"""REST endpoints — geomesa-web parity (GeoMesaStatsEndpoint + catalog).

The reference exposes stats/catalog over Scalatra servlets
(geomesa-web/.../GeoMesaStatsEndpoint); here a stdlib ThreadingHTTPServer
serves the same surface as JSON:

    GET /api/version
    GET /api/schemas                                 -> ["name", ...]
    GET /api/schemas/<name>                          -> spec + count + indices
    GET /api/schemas/<name>/count?cql=...            -> {"count": N}
    GET /api/schemas/<name>/bounds                   -> [xmin, ymin, xmax, ymax]
    GET /api/schemas/<name>/stats?stat=...&cql=...   -> stat JSON
    GET /api/schemas/<name>/histogram?attribute=&bins=&cql=
    GET /api/schemas/<name>/density?bbox=&width=&height=&cql=
    GET /api/schemas/<name>/tiles/<z>/<x>/<y>?detail=&cql=  -> XYZ heatmap
        tile (slippy row order, EPSG:4326 2x1 root; exact per-cell counts
        via the curve-aligned density — no scatter)
    GET /api/schemas/<name>/features?cql=&max=       -> GeoJSON

Observability surface (obs.py; docs/OBSERVABILITY.md — the same routes the
standalone obs server exposes, mounted here so one port serves both):

    GET /metrics        -> prometheus text (histograms included)
    GET /healthz        -> breaker/quarantine/device/SLO health JSON
    GET /debug/queries  -> recent audits + degradations + slow traces
                           (?n=/?user=/?op= filters)
    GET /debug/devices  -> device utilization + slot occupancy + SLO burn
    GET /debug/fleet    -> fleet router ring/health/epoch state (§7)

Write surface (the JVM DataStore's zero-dependency transport; the
reference's DataStore mutates through the same catalog the servlets read):

    POST   /api/schemas                  {"name","spec"} -> create schema
    PATCH  /api/schemas/<name>           {"add_spec"}    -> append attributes
    DELETE /api/schemas/<name>                           -> delete schema
    POST   /api/schemas/<name>/features  GeoJSON FC      -> ingest+flush
    DELETE /api/schemas/<name>/features?cql=...          -> delete by filter
    POST   /api/schemas/<name>/indices   {"attribute"}   -> add attr index
    DELETE /api/schemas/<name>/indices/<attr>            -> drop attr index

Queries pass auths via the ``X-Geomesa-Auths`` header (visibility parity).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np


def _version() -> str:
    try:
        import geomesa_tpu

        return getattr(geomesa_tpu, "__version__", "0.1.0")
    except Exception:
        return "0.1.0"


class _Handler(BaseHTTPRequestHandler):
    dataset = None  # injected by serve()

    # quiet the default stderr chatter
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _send(self, obj, code: int = 200, content_type="application/json"):
        body = (
            obj if isinstance(obj, bytes)
            else json.dumps(obj, default=_jsonable).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, msg: str):
        self._send({"error": msg}, code)

    def do_GET(self):  # noqa: N802
        from geomesa_tpu.api.dataset import Query
        from geomesa_tpu import obs

        ds = self.dataset
        out = obs.handle(self.path, ds,
                         accept=self.headers.get("Accept"))
        if out is not None:  # /metrics, /healthz, /debug/*
            code, ctype, body = out
            return self._send(body, code, content_type=ctype)
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        parts = [p for p in parsed.path.split("/") if p]
        auths_hdr = self.headers.get("X-Geomesa-Auths")
        auths = auths_hdr.split(",") if auths_hdr is not None else None
        try:
            if parts == ["api", "version"]:
                return self._send({"version": _version()})
            if parts == ["api", "schemas"]:
                return self._send(ds.list_schemas())
            if len(parts) >= 3 and parts[:2] == ["api", "schemas"]:
                name = urllib.parse.unquote(parts[2])
                rest = parts[3:]
                cql = q.get("cql", "INCLUDE")
                if not rest:
                    ft = ds.get_schema(name)
                    st = ds._store(name)
                    return self._send({
                        "name": name,
                        "spec": ft.spec(),
                        "count": st.count,
                        "indices": [ks.name for ks in st.keyspaces],
                    })
                op = rest[0]
                if op == "count":
                    exact = q.get("exact", "true").lower() != "false"
                    n = ds.count(name, Query(ecql=cql, auths=auths), exact=exact)
                    return self._send({"count": int(n)})
                if op == "bounds":
                    return self._send(ds.bounds(name))
                if op == "stats":
                    stat = q.get("stat")
                    if not stat:
                        return self._error(400, "missing ?stat=")
                    s = ds.stats(name, stat, Query(ecql=cql, auths=auths))
                    return self._send(json.loads(s.to_json()))
                if op == "histogram":
                    attr = q.get("attribute")
                    if not attr:
                        return self._error(400, "missing ?attribute=")
                    h = ds.histogram(
                        name, attr, bins=int(q.get("bins", "20")),
                        query=Query(ecql=cql, auths=auths),
                    )
                    return self._send(json.loads(h.to_json()))
                if op == "density":
                    bbox = (
                        tuple(float(v) for v in q["bbox"].split(","))
                        if "bbox" in q else None
                    )
                    grid = ds.density(
                        name, Query(ecql=cql, auths=auths), bbox=bbox,
                        width=int(q.get("width", "256")),
                        height=int(q.get("height", "256")),
                    )
                    return self._send({
                        "width": grid.shape[1], "height": grid.shape[0],
                        "nonzero": int(np.count_nonzero(grid)),
                        "grid": grid.tolist(),
                    })
                if op == "tiles" and len(rest) == 4:
                    # XYZ tile-pyramid heatmap: /tiles/<z>/<x>/<y> over the
                    # curve-aligned density (DensityProcess under WMS; the
                    # EPSG:4326 pyramid has 2 root tiles side by side, so
                    # a z/x/y tile spans 180/2^z degrees and maps exactly
                    # onto morton blocks at level z + sub)
                    z, x, y = (int(v) for v in rest[1:4])
                    sub = max(1, min(8, int(q.get("detail", "6"))))
                    if z + 1 > 14:
                        # morton levels cap at 15; deeper tiles would be
                        # WIDER than a block and double-count neighbors
                        return self._error(400, "max tile zoom is 13")
                    if not (0 <= x < (1 << (z + 1)) and 0 <= y < (1 << z)):
                        return self._error(400, "tile out of range")
                    span = 180.0 / (1 << z)
                    level = min(z + sub, 15)
                    # XYZ row order: y=0 is the NORTH edge (WMTS/slippy
                    # convention), so flip to latitude
                    bbox = (
                        -180.0 + x * span, 90.0 - (y + 1) * span,
                        -180.0 + (x + 1) * span, 90.0 - y * span,
                    )
                    # exclusive upper edges: inset by half a morton block
                    # so the inclusive snap never pulls in the neighbor
                    # tile's first row/column
                    hx = 180.0 / (1 << level)
                    hy = 90.0 / (1 << level)
                    grid, snapped = ds.density_curve(
                        name, Query(ecql=cql, auths=auths),
                        level=level,
                        bbox=(bbox[0], bbox[1], bbox[2] - hx, bbox[3] - hy),
                        weight=q.get("weight"),
                    )
                    return self._send({
                        "z": z, "x": x, "y": y, "bbox": list(snapped),
                        "width": grid.shape[1], "height": grid.shape[0],
                        "nonzero": int(np.count_nonzero(grid)),
                        "grid": grid.tolist(),
                    })
                if op == "features":
                    from geomesa_tpu.io import geojson

                    fc = ds.query(name, Query(
                        ecql=cql, auths=auths,
                        max_features=int(q["max"]) if "max" in q else None,
                    ))
                    st = ds._store(name)
                    text = geojson.dumps(st.ft, fc.batch, st.dicts)
                    return self._send(
                        text.encode(), content_type="application/geo+json"
                    )
            return self._error(404, f"unknown path {parsed.path!r}")
        except KeyError as e:
            return self._error(404, str(e))
        except ValueError as e:
            return self._error(400, str(e))
        except Exception as e:  # pragma: no cover - defensive
            return self._error(500, f"{type(e).__name__}: {e}")

    def _read_body(self):
        length = int(self.headers.get("Content-Length", "0"))
        return self.rfile.read(length).decode() if length else ""

    def do_POST(self):  # noqa: N802
        ds = self.dataset
        parsed = urllib.parse.urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if parts == ["api", "schemas"]:
                body = json.loads(self._read_body() or "{}")
                name, spec = body.get("name"), body.get("spec")
                if not name or not spec:
                    return self._error(400, 'body must be {"name", "spec"}')
                if name in ds.list_schemas():
                    return self._error(409, f"schema {name!r} exists")
                ft = ds.create_schema(name, spec)
                return self._send({"name": name, "spec": ft.spec()}, 201)
            if len(parts) == 4 and parts[:2] == ["api", "schemas"] \
                    and parts[3] == "features":
                name = urllib.parse.unquote(parts[2])
                from geomesa_tpu.io import geojson

                ft = ds.get_schema(name)
                data, fids = geojson.from_geojson(ft, self._read_body())
                n = ds.insert(name, data, fids=fids)
                ds.flush(name)
                return self._send(
                    {"inserted": int(n), "fids": list(map(str, fids))}, 201
                )
            if len(parts) == 4 and parts[:2] == ["api", "schemas"] \
                    and parts[3] == "indices":
                name = urllib.parse.unquote(parts[2])
                ds.get_schema(name)  # unknown schema -> 404, before 400s
                body = json.loads(self._read_body() or "{}")
                attr = body.get("attribute")
                if not attr:
                    return self._error(400, 'body must be {"attribute"}')
                ds.add_attribute_index(name, attr)
                return self._send({"index": f"attr:{attr}"}, 201)
            return self._error(404, f"unknown path {parsed.path!r}")
        except KeyError as e:
            return self._error(404, str(e))
        except ValueError as e:
            return self._error(400, str(e))
        except Exception as e:  # pragma: no cover - defensive
            return self._error(500, f"{type(e).__name__}: {e}")

    def do_PATCH(self):  # noqa: N802
        ds = self.dataset
        parsed = urllib.parse.urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if len(parts) == 3 and parts[:2] == ["api", "schemas"]:
                name = urllib.parse.unquote(parts[2])
                ds.get_schema(name)  # unknown schema -> 404, before 400s
                body = json.loads(self._read_body() or "{}")
                add = body.get("add_spec")
                if not add:
                    return self._error(400, 'body must be {"add_spec"}')
                ft = ds.update_schema(name, add)
                return self._send({"name": name, "spec": ft.spec()})
            return self._error(404, f"unknown path {parsed.path!r}")
        except KeyError as e:
            return self._error(404, str(e))
        except ValueError as e:
            return self._error(400, str(e))
        except Exception as e:  # pragma: no cover - defensive
            return self._error(500, f"{type(e).__name__}: {e}")

    def do_DELETE(self):  # noqa: N802
        ds = self.dataset
        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        parts = [p for p in parsed.path.split("/") if p]
        auths_hdr = self.headers.get("X-Geomesa-Auths")
        auths = auths_hdr.split(",") if auths_hdr is not None else None
        try:
            if len(parts) == 3 and parts[:2] == ["api", "schemas"]:
                name = urllib.parse.unquote(parts[2])
                if name not in ds.list_schemas():
                    return self._error(404, f"no schema {name!r}")
                ds.delete_schema(name)
                return self._send({"deleted": name})
            if len(parts) == 4 and parts[:2] == ["api", "schemas"] \
                    and parts[3] == "features":
                name = urllib.parse.unquote(parts[2])
                cql = q.get("cql")
                if not cql:
                    return self._error(400, "missing ?cql= (use the schema "
                                            "DELETE to drop everything)")
                n = ds.delete_features(name, cql, auths=auths)
                return self._send({"deleted": int(n)})
            if len(parts) == 5 and parts[:2] == ["api", "schemas"] \
                    and parts[3] == "indices":
                name = urllib.parse.unquote(parts[2])
                attr = urllib.parse.unquote(parts[4])
                ds.remove_attribute_index(name, attr)
                return self._send({"removed": f"attr:{attr}"})
            return self._error(404, f"unknown path {parsed.path!r}")
        except KeyError as e:
            return self._error(404, str(e))
        except ValueError as e:
            return self._error(400, str(e))
        except Exception as e:  # pragma: no cover - defensive
            return self._error(500, f"{type(e).__name__}: {e}")


def _jsonable(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.datetime64):
        return str(o)
    return str(o)


def serve(dataset, host: str = "127.0.0.1", port: int = 8080,
          background: bool = False) -> ThreadingHTTPServer:
    """Serve the REST surface for a GeoDataset. ``background=True`` runs the
    server in a daemon thread and returns it (tests / notebooks)."""
    handler = type("Handler", (_Handler,), {"dataset": dataset})
    server = ThreadingHTTPServer((host, port), handler)
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return server
