"""geomesa-tpu: a TPU-native geospatial analytics framework.

Re-imagines GeoMesa's capability set (spatio-temporal indexing over space-filling
curves, CQL-filtered scans, pushdown aggregation: density heatmaps, stats sketches,
BIN/Arrow export, kNN/joins) as a JAX/XLA-first system: feature collections are
sharded, sorted columnar arrays in device HBM; curve encoding, predicate evaluation
and aggregation are jit/vmap kernels; cross-device merges are XLA collectives.

Reference behavior map: SURVEY.md (GeoMesa 3.2.x @ /root/reference).
"""

__version__ = "0.1.0"

_LAZY = {
    "FeatureType": "geomesa_tpu.schema.feature_type",
    "AttributeSpec": "geomesa_tpu.schema.feature_type",
    "GeoDataset": "geomesa_tpu.api.dataset",
    "Query": "geomesa_tpu.api.dataset",
    "ArrowDataStore": "geomesa_tpu.io.arrow_store",
    "QueryScheduler": "geomesa_tpu.serving",
    "FleetRouter": "geomesa_tpu.fleet",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        return getattr(mod, name)
    raise AttributeError(f"module 'geomesa_tpu' has no attribute {name!r}")
