"""Analytic process library (geomesa-process parity, SURVEY.md §2.6).

The reference exposes GeoServer WPS processes that push down into scans
(geomesa-process-vector: TubeSelectProcess, Point2PointProcess,
TrackLabelProcess, DateOffsetProcess, HashAttributeProcess,
RouteSearchProcess, JoinProcess, SamplingProcess...). Here each is a library
function over a GeoDataset: a planner-backed prefilter (ECQL derived from the
process geometry/time envelope) followed by a vectorized refine — the same
coarse-scan→fine-kernel split as the query path.

Density / stats / unique / min-max / kNN / proximity / arrow / bin live on
GeoDataset itself; the point-in-polygon spatial join kernel is
``geomesa_tpu.kernels.join`` (exposed here via ``spatial_join``).
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.api.dataset import FeatureCollection, GeoDataset, Query
from geomesa_tpu.kernels import join as kjoin
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.utils import geometry as geo
from geomesa_tpu.utils.geometry import METERS_PER_DEGREE, haversine_m


def _as_query(query) -> Query:
    return Query(ecql=query) if isinstance(query, str) else query


def _and_ecql(base: str, extra: str) -> str:
    if not base or base.strip().upper() == "INCLUDE":
        return extra
    return f"({base}) AND {extra}"


def _xy(fc: FeatureCollection) -> Tuple[np.ndarray, np.ndarray]:
    g = fc.ft.geom_field
    return fc.batch.columns[g + "__x"], fc.batch.columns[g + "__y"]


def _select(fc: FeatureCollection, mask_or_idx) -> FeatureCollection:
    cols = {k: v[mask_or_idx] for k, v in fc.batch.columns.items()}
    n = len(next(iter(cols.values()))) if cols else 0
    return FeatureCollection(fc.ft, ColumnBatch(cols, n), fc.dicts)


# ---------------------------------------------------------------------------
# Tube select (TubeSelectProcess / TubeBuilder analog)
# ---------------------------------------------------------------------------

def tube_select(
    ds: GeoDataset,
    name: str,
    tube_xy: Sequence[Tuple[float, float]],
    tube_times_ms: Sequence[int],
    buffer_m: float,
    query: "str | Query" = "INCLUDE",
    gap_fill: str = "line",
    max_speed_mps: Optional[float] = None,
) -> FeatureCollection:
    """Features inside the spatio-temporal corridor around a track.

    ``gap_fill='line'`` interpolates the track position linearly between
    waypoints (the reference's LineGapFill); ``'none'`` matches only within
    ``buffer_m`` of a waypoint at +/- the waypoint's segment time span
    (NoGapFill). ``max_speed_mps`` widens the buffer by speed * time-gap,
    mirroring the reference's speed-based tube growth.
    """
    pts = np.asarray(tube_xy, np.float64)
    ts = np.asarray(tube_times_ms, np.int64)
    if pts.shape[0] != ts.shape[0] or pts.shape[0] < 1:
        raise ValueError("tube needs equal-length xy and time sequences")
    order = np.argsort(ts, kind="stable")
    pts, ts = pts[order], ts[order]

    q = _as_query(query)
    ft = ds.get_schema(name)
    g, dtg = ft.geom_field, ft.dtg_field
    if g is None or dtg is None:
        raise ValueError("tube_select needs a point geometry and a time field")
    # coarse prefilter: buffered track bbox + time envelope
    pad = buffer_m / METERS_PER_DEGREE * 2
    xmin, ymin = pts.min(axis=0) - pad
    xmax, ymax = pts.max(axis=0) + pad
    import dataclasses

    # second-truncated endpoints, padded outward so the refine sees everything
    t0 = np.datetime_as_string(ts.min().astype("datetime64[ms]"), unit="s") + "Z"
    t1 = (
        np.datetime_as_string(
            (ts.max() + 1000).astype("datetime64[ms]"), unit="s"
        )
        + "Z"
    )
    pre = _and_ecql(
        q.ecql,
        f"BBOX({g}, {xmin}, {ymin}, {xmax}, {ymax}) AND "
        f"{dtg} DURING {t0}/{t1}",
    )
    fc = ds.query(name, dataclasses.replace(q, ecql=pre))
    if fc.batch.n == 0:
        return fc
    x, y = _xy(fc)
    t = fc.batch.columns[dtg].astype(np.int64)

    if len(pts) == 1:
        d = haversine_m(x, y, pts[0, 0], pts[0, 1])
        return _select(fc, d <= buffer_m)

    # segment-wise refine: N features x M segments
    x1, y1, t1s = pts[:-1, 0][None], pts[:-1, 1][None], ts[:-1][None]
    x2, y2, t2s = pts[1:, 0][None], pts[1:, 1][None], ts[1:][None]
    tc = t[:, None]
    span = np.maximum(t2s - t1s, 1)
    in_time = (tc >= t1s) & (tc <= t2s)
    if gap_fill == "none":
        near_a = haversine_m(x[:, None], y[:, None], x1, y1) <= buffer_m
        near_b = haversine_m(x[:, None], y[:, None], x2, y2) <= buffer_m
        ok = in_time & (near_a | near_b)
    else:
        frac = np.clip((tc - t1s) / span, 0.0, 1.0)
        ix = x1 + frac * (x2 - x1)
        iy = y1 + frac * (y2 - y1)
        buf = buffer_m
        if max_speed_mps:
            buf = buffer_m + max_speed_mps * (span[0] / 1000.0)[None, :] * 0.5
        ok = in_time & (haversine_m(x[:, None], y[:, None], ix, iy) <= buf)
    return _select(fc, ok.any(axis=1))


# ---------------------------------------------------------------------------
# Track processes
# ---------------------------------------------------------------------------

def point2point(
    ds: GeoDataset,
    name: str,
    group_by: str,
    query: "str | Query" = "INCLUDE",
    break_on_day: bool = False,
) -> Dict[str, geo.LineString]:
    """Connect each group's points into time-ordered LineStrings
    (Point2PointProcess analog). Returns {track-id: LineString} (tracks with
    < 2 points are dropped; ``break_on_day`` splits tracks at UTC-day
    boundaries into '<id>#<day>' entries)."""
    ft = ds.get_schema(name)
    dtg = ft.dtg_field
    if dtg is None:
        raise ValueError("point2point needs a time field for ordering")
    fc = ds.query(name, query)
    if fc.batch.n == 0:
        return {}
    x, y = _xy(fc)
    t = fc.batch.columns[dtg].astype(np.int64)
    keys = fc.batch.columns[group_by]
    d = fc.dicts.get(group_by)
    out: Dict[str, geo.LineString] = {}
    for code in np.unique(keys):
        m = keys == code
        order = np.argsort(t[m], kind="stable")
        gx, gy, gt = x[m][order], y[m][order], t[m][order]
        label = d.decode(np.asarray([code]))[0] if d is not None else str(code)
        if break_on_day:
            days = gt // 86_400_000
            for day in np.unique(days):
                dm = days == day
                if dm.sum() >= 2:
                    out[f"{label}#{int(day)}"] = geo.LineString(
                        list(zip(gx[dm], gy[dm]))
                    )
        elif len(gx) >= 2:
            out[label] = geo.LineString(list(zip(gx, gy)))
    return out


def track_label(
    ds: GeoDataset,
    name: str,
    track_attr: str,
    query: "str | Query" = "INCLUDE",
) -> FeatureCollection:
    """Most recent feature per track (TrackLabelProcess analog)."""
    ft = ds.get_schema(name)
    dtg = ft.dtg_field
    if dtg is None:
        raise ValueError("track_label needs a time field")
    fc = ds.query(name, query)
    if fc.batch.n == 0:
        return fc
    t = fc.batch.columns[dtg].astype(np.int64)
    keys = fc.batch.columns[track_attr]
    # stable sort by time then take the last row per key
    order = np.argsort(t, kind="stable")
    last: Dict[object, int] = {}
    for i in order:
        last[keys[i]] = int(i)
    return _select(fc, np.array(sorted(last.values()), np.int64))


def date_offset(
    ds: GeoDataset,
    name: str,
    offset_ms: int,
    query: "str | Query" = "INCLUDE",
) -> FeatureCollection:
    """Query results with the time attribute shifted (DateOffsetProcess)."""
    ft = ds.get_schema(name)
    dtg = ft.dtg_field
    if dtg is None:
        raise ValueError("date_offset needs a time field")
    fc = ds.query(name, query)
    if fc.batch.n:
        cols = dict(fc.batch.columns)
        cols[dtg] = cols[dtg] + np.int64(offset_ms)
        fc = FeatureCollection(fc.ft, ColumnBatch(cols, fc.batch.n), fc.dicts)
    return fc


def hash_attribute(
    ds: GeoDataset,
    name: str,
    attribute: str,
    modulo: int,
    query: "str | Query" = "INCLUDE",
) -> np.ndarray:
    """Stable per-feature hash of an attribute, mod N (HashAttributeProcess —
    used for consistent styling colors). Returns int32 [n]."""
    fc = ds.query(name, query)
    if fc.batch.n == 0:
        return np.zeros(0, np.int32)
    col = fc.batch.columns[attribute]
    d = fc.dicts.get(attribute)
    if d is not None:
        values = np.array(
            [zlib.crc32(v.encode()) if v is not None else 0 for v in d.values],
            np.uint32,
        )
        codes = np.clip(col, 0, None)
        h = np.where(col >= 0, values[codes], 0)
    else:
        h = np.array([zlib.crc32(str(v).encode()) for v in col], np.uint32)
    return (h % np.uint32(modulo)).astype(np.int32)


# ---------------------------------------------------------------------------
# Route search (RouteSearchProcess analog)
# ---------------------------------------------------------------------------

def route_search(
    ds: GeoDataset,
    name: str,
    route: "str | geo.LineString",
    buffer_m: float,
    query: "str | Query" = "INCLUDE",
    heading_attr: Optional[str] = None,
    heading_tolerance_deg: float = 45.0,
    bidirectional: bool = True,
) -> FeatureCollection:
    """Features within ``buffer_m`` of a route line, optionally requiring the
    feature's heading to align with the local route bearing."""
    line = geo.parse_wkt(route) if isinstance(route, str) else route
    coords = np.asarray(line.coords, np.float64)
    if coords.shape[0] < 2:
        raise ValueError("route needs >= 2 vertices")
    q = _as_query(query)
    ft = ds.get_schema(name)
    g = ft.geom_field
    pad = buffer_m / METERS_PER_DEGREE * 2
    xmin, ymin = coords.min(axis=0) - pad
    xmax, ymax = coords.max(axis=0) + pad
    import dataclasses

    pre = _and_ecql(q.ecql, f"BBOX({g}, {xmin}, {ymin}, {xmax}, {ymax})")
    fc = ds.query(name, dataclasses.replace(q, ecql=pre))
    if fc.batch.n == 0:
        return fc
    x, y = _xy(fc)
    # planar point-to-segment distance in meter space (local equirectangular)
    lat0 = float(coords[:, 1].mean())
    kx = METERS_PER_DEGREE * np.cos(np.radians(lat0))
    ky = METERS_PER_DEGREE
    px, py = x * kx, y * ky
    ax, ay = coords[:-1, 0] * kx, coords[:-1, 1] * ky
    bx, by = coords[1:, 0] * kx, coords[1:, 1] * ky
    dx, dy = bx - ax, by - ay
    seg_len2 = np.maximum(dx * dx + dy * dy, 1e-9)
    tpar = np.clip(
        ((px[:, None] - ax) * dx + (py[:, None] - ay) * dy) / seg_len2, 0.0, 1.0
    )
    cx = ax + tpar * dx
    cy = ay + tpar * dy
    dist = np.hypot(px[:, None] - cx, py[:, None] - cy)  # [N, M]
    near = dist <= buffer_m
    ok = near.any(axis=1)
    if heading_attr is not None:
        bearing = (np.degrees(np.arctan2(dx, dy)) + 360.0) % 360.0  # [M]
        hd = fc.batch.columns[heading_attr].astype(np.float64)
        diff = np.abs((hd[:, None] - bearing[None, :] + 180.0) % 360.0 - 180.0)
        if bidirectional:
            diff = np.minimum(diff, 180.0 - diff)
        ok &= (near & (diff <= heading_tolerance_deg)).any(axis=1)
    return _select(fc, ok)


# ---------------------------------------------------------------------------
# Joins
# ---------------------------------------------------------------------------

def join(
    ds: GeoDataset,
    left: str,
    right: str,
    left_attr: str,
    right_attr: str,
    left_query: "str | Query" = "INCLUDE",
    right_query: "str | Query" = "INCLUDE",
) -> ColumnBatch:
    """Attribute equi-join of two schemas (JoinProcess analog). Right columns
    are prefixed ``right.``; string joins resolve through both dictionaries."""
    lfc = ds.query(left, left_query)
    rfc = ds.query(right, right_query)
    if lfc.batch.n == 0 or rfc.batch.n == 0:
        return ColumnBatch({}, 0)
    lcol = lfc.batch.columns[left_attr]
    rcol = rfc.batch.columns[right_attr]
    ld, rd = lfc.dicts.get(left_attr), rfc.dicts.get(right_attr)
    if ld is not None or rd is not None:
        if ld is None or rd is None:
            raise ValueError("join attribute types differ (string vs non-string)")
        lcol = np.array(ld.decode(lcol), dtype=object)
        rcol = np.array(rd.decode(rcol), dtype=object)
    rmap: Dict[object, List[int]] = {}
    for j, v in enumerate(rcol):
        rmap.setdefault(v, []).append(j)
    li, rj = [], []
    for i, v in enumerate(lcol):
        for j in rmap.get(v, ()):
            li.append(i)
            rj.append(j)
    li = np.asarray(li, np.int64)
    rj = np.asarray(rj, np.int64)
    cols = {k: v[li] for k, v in lfc.batch.columns.items()}
    for k, v in rfc.batch.columns.items():
        cols["right." + k] = v[rj]
    return ColumnBatch(cols, len(li))


def spatial_join(
    ds: GeoDataset,
    points: str,
    polygons: "Sequence[str] | Sequence[geo.Geometry]",
    query: "str | Query" = "INCLUDE",
    weight: Optional[str] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Point-in-polygon join (BASELINE config #4; GeoMesaJoinRelation /
    st_contains join analog): assign each matching point its first containing
    polygon and count points (or sum ``weight``) per polygon.

    ``polygons``: WKT strings or parsed geometries. Returns
    (assign int32 [n]  — polygon index or -1, counts float32 [P]).
    Runs as one device kernel over the scan (crossing matrix + segment-sum)
    when the store prefers the device path.
    """
    from geomesa_tpu.planning.partitioned_exec import PartitionedExecutor

    st0 = ds._store(points)
    st0.flush()
    if isinstance(ds._executor(st0), PartitionedExecutor):
        raise NotImplementedError(
            "spatial_join on a time-partitioned store is not supported yet; "
            "query the window of interest into a plain store first"
        )
    geoms = [geo.parse_wkt(p) if isinstance(p, str) else p for p in polygons]
    edges = geo.polygon_edge_buffers(
        geo.MultiPolygon(
            tuple(
                poly
                for gm in geoms
                for poly in (gm.polygons if isinstance(gm, geo.MultiPolygon) else (gm,))
            )
        )
    )
    # poly ids above refer to flattened polygons; remap to input indices
    flat_to_input = []
    for i, gm in enumerate(geoms):
        k = len(gm.polygons) if isinstance(gm, geo.MultiPolygon) else 1
        flat_to_input += [i] * k
    remap = np.asarray(flat_to_input, np.int32)

    st, q, plan = ds._plan(points, query)
    g = st.ft.geom_field
    xc, yc = g + "__x", g + "__y"
    agg_cols = [xc, yc] + ([weight] if weight else [])
    edges_f32 = {
        k: (v.astype(np.float32) if k in ("x1", "y1", "x2", "y2") else v)
        for k, v in edges.items()
    }

    def agg(cols, m, xp):
        return kjoin.pip_assign(cols[xc], cols[yc], m, edges_f32, xp)

    ex = ds._executor(st)
    # cache the jitted kernel per polygon-set signature (re-join with the
    # same polygons skips retracing)
    sig = hash((edges["x1"].tobytes(), edges["poly_id"].tobytes()))
    out = ex._run(
        plan, agg, agg, agg_cols, cache_key=("pip_join", sig),
        compactable=False,  # the assignment is addressed in [S*L] layout
    )
    if out is None:
        return np.zeros(0, np.int32), np.zeros(len(geoms), np.float32)
    assign_flat = np.asarray(out)
    assign_input = np.where(assign_flat >= 0, remap[np.clip(assign_flat, 0, None)], -1)

    table = st.tables[plan.index_name]
    L = table.shard_len
    # compress the padded [S*L] assignment down to real rows
    valid = np.zeros(table.n_shards * L, dtype=bool)
    for s in range(table.n_shards):
        sl = table.shard_slice(s)
        valid[s * L : s * L + (sl.stop - sl.start)] = True
    assign_rows = assign_input[valid]
    counts = np.zeros(len(geoms), np.float32)
    if weight:
        w = table.col_sorted(weight).astype(np.float32)
    else:
        w = np.ones(table.n, np.float32)
    hit = assign_rows >= 0
    np.add.at(counts, assign_rows[hit], w[hit])
    return assign_rows, counts


# ---------------------------------------------------------------------------
# Sampling (SamplingProcess analog; thin wrapper over the SAMPLING hint)
# ---------------------------------------------------------------------------

def sample(
    ds: GeoDataset,
    name: str,
    one_in_n: int,
    query: "str | Query" = "INCLUDE",
) -> FeatureCollection:
    import dataclasses

    q = _as_query(query)
    return ds.query(name, dataclasses.replace(q, sampling=one_in_n))
