"""Trace export with tail-based sampling (docs/OBSERVABILITY.md).

PR 4 gave every query a span tree, but finished traces evaporated in the
256-entry slow-query ring. This module streams them out instead — as
OTLP-shaped JSON span batches — with the sampling decision made at trace
COMPLETION (tail-based), when the interesting-or-not verdict is actually
known:

* **always keep**: slow (over ``geomesa.trace.slow.ms``), errored,
  degraded (partitions skipped), shed (typed deadline shed), and
  recompile-carrying traces — the five classes an operator pages on;
* **sample the rest**: healthy traces keep at ``geomesa.trace.sample.rate``,
  decided deterministically from ``(geomesa.trace.sample.seed, trace_id)``
  so a given trace is kept or dropped identically run to run (and tests
  can assert the exact keep set).

Two sinks, either or both:

* **HTTP OTLP** (``geomesa.trace.otlp.endpoint``): POST one OTLP/JSON
  batch per flush, retried via :class:`resilience.RetryPolicy` and fenced
  by the ``trace.otlp`` circuit breaker (a dead collector fails fast, it
  never backs work up into the exporter);
* **JSONL file** (``geomesa.trace.export.path``): one OTLP-shaped batch
  per line — the air-gapped/CI sink the smoke job shape-validates.

**Never blocks the query/dispatch threads.** ``offer()`` classifies,
samples, and ``put_nowait``s onto a bounded queue; a full queue DROPS the
trace and counts it in ``trace.export.dropped``. Conversion and sink I/O
happen on one background flusher thread. Sink targets are captured on the
OFFERING thread (where thread-local config scopes are visible), so scoped
test configuration routes correctly even though the write happens
elsewhere. Every sink write passes the ``trace.export.sink`` fault point,
so chaos tests drive the retry/breaker path deterministically through the
``geomesa.fault.injection`` registry.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional

from geomesa_tpu import config, metrics, resilience

#: fault-point site every sink write passes (chaos tests)
SINK_FAULT_POINT = "trace.export.sink"


# ---------------------------------------------------------------------------
# tail-sampling policy
# ---------------------------------------------------------------------------


def classify(trace) -> Optional[str]:
    """The always-keep class of a completed trace, or None (healthy —
    subject to the sample rate). Flags are set while the query runs
    (tracing.py), so this is a handful of attribute reads."""
    if getattr(trace, "slot_died", False):
        # a serving slot died/drained under this trace's stream — the
        # device-fault post-mortem evidence (docs/RESILIENCE.md §6)
        return "slot_died"
    if trace.shed:
        return "shed"
    if trace.error is not None:
        return "error"
    if trace.degraded:
        return "degraded"
    if trace.recompiles:
        return "recompile"
    if trace.slow_logged:
        return "slow"
    root = trace.root
    try:
        thresh = config.TRACE_SLOW_MS.to_float()
    except (TypeError, ValueError):
        thresh = None
    if thresh is not None and root is not None \
            and root.duration_ms >= thresh:
        return "slow"
    return None


def sampled_in(trace_id: str) -> bool:
    """Deterministic keep/drop for a HEALTHY trace: hash (seed, trace_id)
    to [0, 1) and compare against ``geomesa.trace.sample.rate``. Stable
    across runs and processes for a given seed — the property the seeded-
    determinism tests assert."""
    try:
        rate = config.TRACE_SAMPLE_RATE.to_float()
    except (TypeError, ValueError):
        rate = 1.0
    rate = 1.0 if rate is None else rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    seed = config.TRACE_SAMPLE_SEED.get() or "0"
    h = zlib.crc32(f"{seed}:{trace_id}".encode()) & 0xFFFFFFFF
    return (h / 2**32) < rate


# ---------------------------------------------------------------------------
# OTLP conversion (the span tree is already shaped like an OTLP batch —
# docs/OBSERVABILITY.md §7's observation, now cashed in)
# ---------------------------------------------------------------------------


def _otlp_value(v) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


def _span_id(trace_id: str, idx: int) -> str:
    """Deterministic 8-byte span id from (trace_id, preorder index)."""
    return hashlib.blake2b(
        f"{trace_id}/{idx}".encode(), digest_size=8
    ).hexdigest()


def trace_to_otlp_spans(trace, keep_reason: Optional[str],
                        epoch_offset: float) -> List[Dict[str, Any]]:
    """Flatten one trace's span tree into OTLP/JSON span dicts.
    ``epoch_offset`` maps the monotonic ``perf_counter`` timestamps the
    spans carry onto unix time (computed once per batch). The root span
    additionally carries the sampling verdict, the classification flags,
    and the per-query cost ledger as attributes."""
    out: List[Dict[str, Any]] = []
    tid32 = (trace.trace_id * 2)[:32]  # OTLP wants 16 bytes hex
    counter = [0]

    def walk(span, parent_hex: str) -> None:
        idx = counter[0]
        counter[0] += 1
        with trace.lock:
            attrs = dict(span.attrs)
            children = list(span.children)
        start_ns = int((span.t0 + epoch_offset) * 1e9)
        end_ns = start_ns + int(span.duration_ms * 1e6)
        rec: Dict[str, Any] = {
            "traceId": tid32,
            "spanId": _span_id(trace.trace_id, idx),
            "name": span.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
        }
        if parent_hex:
            rec["parentSpanId"] = parent_hex
        if idx == 0:
            attrs["geomesa.keep"] = keep_reason or "sampled"
            if trace.error is not None:
                attrs["geomesa.error"] = trace.error
            if trace.degraded:
                attrs["geomesa.degraded"] = True
            if trace.recompiles:
                attrs["geomesa.recompiles"] = trace.recompiles
            if trace.dropped:
                attrs["geomesa.dropped_spans"] = trace.dropped
            with trace.lock:
                cost = dict(trace.cost)
            for k, v in sorted(cost.items()):
                attrs[f"geomesa.cost.{k}"] = round(v, 4)
        if attrs:
            rec["attributes"] = _otlp_attrs(attrs)
        if trace.error is not None and idx == 0:
            rec["status"] = {"code": 2, "message": trace.error}  # ERROR
        out.append(rec)
        for c in children:
            walk(c, rec["spanId"])

    if trace.root is not None:
        walk(trace.root, "")
    return out


def otlp_batch(entries: List[tuple]) -> Dict[str, Any]:
    """One OTLP/JSON ExportTraceServiceRequest for ``entries`` of
    ``(trace, keep_reason)``."""
    epoch_offset = time.time() - time.perf_counter()
    spans: List[Dict[str, Any]] = []
    for trace, reason in entries:
        spans.extend(trace_to_otlp_spans(trace, reason, epoch_offset))
    return {
        "resourceSpans": [{
            "resource": {"attributes": _otlp_attrs(
                {"service.name": "geomesa-tpu"}
            )},
            "scopeSpans": [{
                "scope": {"name": "geomesa_tpu.tracing"},
                "spans": spans,
            }],
        }],
    }


def dict_tree_to_otlp_spans(trace_id: str,
                            tree: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flatten a span tree in its DICT form (``Span.to_dict()`` shape:
    ``{"name", "ms", "attrs", "children"}``) into OTLP/JSON span dicts —
    the stitched fleet trace is assembled as a dict tree (router spans +
    ``trace-fetch``ed replica subtrees), so it never had live Span
    objects. Dict trees carry durations but not absolute start times, so
    start times are synthesized: the root ends "now", and each child
    starts when its parent does — slicing stays faithful, sub-span skew
    inside one parent is lost (an accepted stitching approximation)."""
    tid32 = (trace_id * 2)[:32]
    root_ms = float(tree.get("ms") or 0.0)
    root_start_ns = int(time.time() * 1e9) - int(root_ms * 1e6)
    out: List[Dict[str, Any]] = []
    counter = [0]

    def walk(node: Dict[str, Any], parent_hex: str, start_ns: int) -> None:
        idx = counter[0]
        counter[0] += 1
        rec: Dict[str, Any] = {
            "traceId": tid32,
            # a distinct id keyspace from the replicas' own exports: the
            # same trace id legitimately appears twice in a sink (each
            # replica's local subtree + the fleet's stitched whole), and
            # their span ids must not collide
            "spanId": _span_id(f"stitched/{trace_id}", idx),
            "name": str(node.get("name") or "span"),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(
                start_ns + int(float(node.get("ms") or 0.0) * 1e6)
            ),
        }
        if parent_hex:
            rec["parentSpanId"] = parent_hex
        attrs = dict(node.get("attrs") or {})
        if idx == 0:
            attrs["geomesa.stitched"] = True
        if attrs:
            rec["attributes"] = _otlp_attrs(attrs)
        out.append(rec)
        for c in node.get("children") or []:
            walk(c, rec["spanId"], start_ns)

    walk(tree, "", root_start_ns)
    return out


def stitched_batch(trace_id: str, tree: Dict[str, Any]) -> Dict[str, Any]:
    """One OTLP/JSON ExportTraceServiceRequest for one stitched fleet
    trace. The resource is ``geomesa-tpu-fleet`` with ``stitched=true``
    so a backend (and the CI smoke gate) can tell the fleet's assembled
    view from the replicas' own exports of the same trace id."""
    return {
        "resourceSpans": [{
            "resource": {"attributes": _otlp_attrs({
                "service.name": "geomesa-tpu-fleet",
                "geomesa.stitched": True,
            })},
            "scopeSpans": [{
                "scope": {"name": "geomesa_tpu.fleet.obs"},
                "spans": dict_tree_to_otlp_spans(trace_id, tree),
            }],
        }],
    }


def export_stitched(trace_id: str, tree: Dict[str, Any]) -> bool:
    """Write one stitched trace through the configured sinks (same
    JSONL/OTLP targets and breakers the live exporter uses). Runs on the
    fleet stitcher thread only — never the query path. False when no
    sink is configured or every sink failed."""
    sinks = []
    path = config.TRACE_EXPORT_PATH.get()
    if path:
        sinks.append(("file", path))
    endpoint = config.TRACE_OTLP_ENDPOINT.get()
    if endpoint:
        sinks.append(("otlp", endpoint))
    if not sinks:
        return False
    batch = stitched_batch(trace_id, tree)
    ok = False
    for kind, target in sinks:
        if _Sink(kind, target).write(batch, 1):
            ok = True
    return ok


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


def _write_file_sink(path: str, batch: Dict[str, Any]) -> None:
    resilience.fault_point(SINK_FAULT_POINT, sink="file", path=path)
    with open(path, "a") as fh:
        fh.write(json.dumps(batch) + "\n")


def _write_http_sink(endpoint: str, batch: Dict[str, Any]) -> None:
    resilience.fault_point(SINK_FAULT_POINT, sink="otlp", endpoint=endpoint)
    import urllib.request

    req = urllib.request.Request(
        endpoint, data=json.dumps(batch).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()


class _Sink:
    """One sink target: retried writes behind a named circuit breaker.
    A batch that still fails after retries (or finds the breaker open) is
    counted in ``trace.export.failed`` and dropped — export must degrade,
    never back up into the query path."""

    def __init__(self, kind: str, target: str):
        self.kind = kind          # "file" | "otlp"
        self.target = target
        self.breaker_name = f"trace.export.{kind}"

    def write(self, batch: Dict[str, Any], n_traces: int) -> bool:
        br = resilience.breaker(self.breaker_name)
        try:
            br.allow()
        except resilience.CircuitOpenError:
            metrics.inc(metrics.TRACE_EXPORT_FAILED, n_traces)
            return False
        policy = resilience.RetryPolicy.from_config(seed=0)
        try:
            policy.call(lambda: (
                _write_file_sink(self.target, batch) if self.kind == "file"
                else _write_http_sink(self.target, batch)
            ))
        except Exception:
            br.record_failure()
            metrics.inc(metrics.TRACE_EXPORT_FAILED, n_traces)
            return False
        br.record_success()
        return True


# ---------------------------------------------------------------------------
# exporter
# ---------------------------------------------------------------------------


class TraceExporter:
    """Bounded-buffer background exporter. ``offer()`` is the only entry
    point the query path touches and it never blocks: sample -> enqueue
    (or drop+count). One daemon flusher thread drains, converts, and
    writes batches grouped by sink target. Dequeue and sink write happen
    atomically under the flush lock, so :meth:`flush` returning with an
    empty buffer means every offered trace was written (or counted
    failed) — no in-flight limbo for tests to race."""

    def __init__(self, maxsize: Optional[int] = None,
                 autoflush: bool = True):
        #: autoflush=False disables the background thread entirely —
        #: flush() is then the only drain (tests drive the sink path
        #: synchronously so thread-local config scopes stay visible)
        self._autoflush = autoflush
        self._maxsize = maxsize
        self._buf: "deque" = deque()
        self._buf_lock = threading.Lock()
        self._wake = threading.Event()
        self._flush_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _cap(self) -> int:
        if self._maxsize is not None:
            return max(1, self._maxsize)
        return max(1, config.TRACE_EXPORT_QUEUE.to_int() or 1024)

    # -- query-thread half -------------------------------------------------
    def offer(self, trace) -> bool:
        """Classify, sample, and enqueue one completed trace. Returns True
        when the trace was queued for export. Never blocks."""
        reason = classify(trace)
        if reason is None and not sampled_in(trace.trace_id):
            # once per trace: a streamed trace re-finishing on every late
            # child re-offers, and each healthy re-offer must not inflate
            # the sampled counter operators use to validate the rate
            if not trace.sample_counted:
                trace.sample_counted = True
                metrics.inc(metrics.TRACE_EXPORT_SAMPLED)
            return False
        # sink targets resolve HERE (thread-local scopes are visible on
        # the offering thread; the flusher sees only env/defaults)
        sinks = []
        path = config.TRACE_EXPORT_PATH.get()
        if path:
            sinks.append(("file", path))
        endpoint = config.TRACE_OTLP_ENDPOINT.get()
        if endpoint:
            sinks.append(("otlp", endpoint))
        if not sinks:
            return False
        with self._buf_lock:
            if len(self._buf) >= self._cap():
                metrics.inc(metrics.TRACE_EXPORT_DROPPED)
                return False
            self._buf.append((trace, reason, tuple(sinks)))
        trace.exported = True
        metrics.inc(metrics.TRACE_EXPORT_EXPORTED)
        self._wake.set()
        self._ensure_thread()
        return True

    # -- flusher half ------------------------------------------------------
    def _ensure_thread(self) -> None:
        if not self._autoflush:
            return
        t = self._thread
        if t is not None and t.is_alive():
            return
        with self._buf_lock:
            t = self._thread
            if t is not None and t.is_alive():
                return
            self._stop.clear()
            t = threading.Thread(
                target=self._loop, daemon=True, name="geomesa-trace-export"
            )
            self._thread = t
            t.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            self._wake.clear()
            try:
                # drain EVERYTHING buffered, batch by batch: a burst
                # larger than one batch (or offers racing the clear
                # above) must not strand traces until the next offer —
                # the timeout path re-drains too, as the backstop
                while self._flush_once():
                    pass
            except Exception:  # pragma: no cover — a sink conversion bug
                # must not kill the flusher; the batch is already gone
                # from the buffer, count it failed
                metrics.inc(metrics.TRACE_EXPORT_FAILED)

    def _flush_once(self) -> bool:
        """Drain-and-write ONE batch atomically. False = buffer empty."""
        with self._flush_lock:
            batch_max = config.TRACE_EXPORT_BATCH.to_int() or 64
            items: List[tuple] = []
            with self._buf_lock:
                while self._buf and len(items) < batch_max:
                    items.append(self._buf.popleft())
            if not items:
                return False
            self._write(items)
            return True

    def _write(self, items: List[tuple]) -> None:
        # group by sink target set (usually one), one OTLP batch per group
        groups: Dict[tuple, List[tuple]] = {}
        for trace, reason, sinks in items:
            groups.setdefault(sinks, []).append((trace, reason))
        for sinks, entries in groups.items():
            batch = otlp_batch(entries)
            ok = False
            for kind, target in sinks:
                if _Sink(kind, target).write(batch, len(entries)):
                    ok = True
            if ok:
                metrics.inc(metrics.TRACE_EXPORT_BATCHES)

    def flush(self, timeout_s: float = 5.0) -> None:
        """Synchronously drain and write everything queued (tests, bench,
        shutdown). Safe to call concurrently with the flusher; on return
        everything offered before the call has been written or counted."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self._flush_once():
                return

    def shutdown(self, flush: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if flush:
            self.flush()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None


_lock = threading.Lock()
_exporter: Optional[TraceExporter] = None


def exporter() -> TraceExporter:
    """The process-wide exporter (created on first use)."""
    global _exporter
    ex = _exporter
    if ex is None:
        with _lock:
            ex = _exporter
            if ex is None:
                ex = _exporter = TraceExporter()
    return ex


def offer(trace) -> bool:
    """Module-level entry point tracing._finish_trace calls."""
    return exporter().offer(trace)


def flush(timeout_s: float = 5.0) -> None:
    ex = _exporter
    if ex is not None:
        ex.flush(timeout_s)


def reset() -> None:
    """Tear down the exporter (test isolation): stop the flusher WITHOUT
    flushing (queued traces are discarded) and drop the singleton."""
    global _exporter
    with _lock:
        ex, _exporter = _exporter, None
    if ex is not None:
        ex._stop.set()
        ex._wake.set()
        t = ex._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)
