"""Span-tree query tracing (docs/OBSERVABILITY.md).

Answers "where did this query's 40 ms go?": each query opens a root span
(``start``), every stage on the way down — plan, cache cell lookups, per
partition staging, ``device_put``, kernel dispatch, device sync, Flight
hops — opens a child (``span``), and the finished tree is:

* stamped into the query's audit event / explain output by its
  ``trace_id``;
* routed into the fixed-bucket latency histograms (``trace.<stage>`` in
  the metrics registry) so /metrics carries p50/p90/p99 per stage;
* written as one JSONL record through the audit appender when the query
  exceeds ``geomesa.trace.slow.ms`` (the slow-query log), and kept in an
  in-memory ring served by ``/debug/queries``.

Cheap when off: the current span lives in a :mod:`contextvars` ContextVar,
and with no active trace ``span()`` is a single ContextVar read returning a
shared no-op singleton — no allocation, no lock, no clock read (asserted by
``tests/test_tracing.py`` and the bench smoke ``trace_overhead_pct`` gate).

Cross-thread: the partition prefetch worker adopts the query thread's span
context exactly the way it adopts config overrides (:func:`snapshot` /
:func:`adopt`); the sidecar propagates ``trace_id`` as a Flight header so
server-side spans (and the server audit) share the client's trace id.
Span mutation is lock-protected on the owning :class:`Trace` — the
prefetch worker appends staging spans concurrently with the query thread.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
import uuid
import weakref
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from geomesa_tpu import config, metrics

#: live traces by id (weak values — a trace lives exactly as long as its
#: holders do): the serving supervisor looks a stranded ticket's trace up
#: here to flag it slot_died and append the root-span event
_open: "weakref.WeakValueDictionary[str, Trace]" = (
    weakref.WeakValueDictionary()
)

#: the innermost open span of the calling context (None = not tracing)
_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "geomesa_trace_span", default=None
)


class _NoopSpan:
    """Shared do-nothing span: the entire tracing surface when disabled.
    A singleton so the disabled hot path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP = _NoopSpan()


class Trace:
    """One query's span tree: id, root, and the bounded span budget.

    Carries the tail-sampling classification flags (``error``/``shed``/
    ``degraded``/``recompiles`` — set as the query runs, read at
    completion by tracing_export.py) and the per-query cost ledger
    (``cost``: device ms per device, partitions, bytes staged, cache hits
    — accumulated via :func:`add_cost`, rolled into the serving ledger and
    explain's Cost section; docs/OBSERVABILITY.md)."""

    __slots__ = ("trace_id", "root", "max_spans", "n_spans", "dropped",
                 "profiler", "lock", "finished", "slow_logged",
                 "error", "shed", "degraded", "recompiles", "cost",
                 "exported", "sample_counted", "slot_died", "__weakref__")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.root: Optional[Span] = None
        cap = config.TRACE_MAX_SPANS.to_int()
        self.max_spans = 512 if cap is None else max(cap, 1)
        self.n_spans = 0
        self.dropped = 0
        self.profiler = bool(config.TRACE_JAX_PROFILER.to_bool())
        self.lock = threading.Lock()
        self.finished = False
        self.slow_logged = False
        self.error: Optional[str] = None   # exception type name, if raised
        self.shed = False                  # typed deadline shed
        self.degraded = False              # partitions skipped (resilience)
        self.recompiles = 0                # kernel.recompile events seen
        self.cost: Dict[str, float] = {}   # per-query cost ledger
        self.exported = False              # handed to the exporter once
        self.sample_counted = False        # sampled-out counted once
        self.slot_died = False             # serving slot died under it
        # open-trace registry (weak): lets the serving supervisor mark a
        # stranded stream's trace by id when its slot dies — see
        # mark_slot_died (docs/RESILIENCE.md §6)
        _open[self.trace_id] = self

    def admit(self) -> bool:
        """Reserve one span slot (False = budget exhausted, span dropped)."""
        with self.lock:
            if self.n_spans >= self.max_spans:
                self.dropped += 1
                return False
            self.n_spans += 1
            return True


class Span:
    """One timed stage. Context manager; durations are monotonic-clock.

    Children attach under the span that was current when they were
    opened, so trees assemble correctly even when stages run on an
    adopted worker thread (the trace lock orders the appends)."""

    __slots__ = ("name", "trace", "parent", "attrs", "children",
                 "t0", "duration_ms", "_token", "_annotation")

    def __init__(self, name: str, trace: Trace, parent: "Optional[Span]",
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace = trace
        self.parent = parent
        self.attrs = attrs or {}
        self.children: List[Span] = []
        self.t0 = 0.0
        self.duration_ms = 0.0
        self._token = None
        self._annotation = None

    def set(self, **attrs) -> "Span":
        """Attach attributes to an open (or closed) span."""
        with self.trace.lock:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        if self.trace.profiler:
            self._annotation = _jax_annotation(self.name)
            if self._annotation is not None:
                self._annotation.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None and self.parent is None:
            # tail-sampling classification: an OP that raised is an
            # always-keep trace; a typed deadline shed is its own class.
            # Root-only: an exception a child span propagates may be
            # caught and recovered above (a skipped partition under
            # allow_partial succeeds degraded) — only one that escapes
            # the ROOT means the query actually failed.
            self.trace.error = exc[0].__name__
            try:
                from geomesa_tpu.resilience import DeadlineShedError

                if issubclass(exc[0], DeadlineShedError):
                    self.trace.shed = True
            except Exception:  # pragma: no cover — defensive
                pass
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self.finish()
        return False

    def finish(self) -> None:
        """Close the span without touching the context var — for spans
        whose lifetime outlives the opening frame (the streamed
        ``query_batches`` root closes at stream end, possibly from the
        consumer's iteration). ``__exit__`` routes through here."""
        end = time.perf_counter()
        self.duration_ms = (end - self.t0) * 1e3
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        # per-stage latency histogram: p50/p90/p99 derivable from /metrics.
        # The trace id rides along as the bucket's exemplar, so an outlier
        # bucket in the exposition links straight to its exported trace.
        metrics.observe("trace." + self.name, self.duration_ms / 1e3,
                        trace_id=self.trace.trace_id)
        # per-DEVICE attribution (docs/SCALE.md sharded scan): stages that
        # carry a ``device`` attr — partition staging/scans assigned to a
        # device by the sharded fan-out — additionally feed a
        # device-suffixed histogram, so /metrics shows whether one device
        # of the mesh is the straggler. Cardinality is bounded by the
        # local device count.
        dev = self.attrs.get("device") if self.attrs else None
        if dev is not None and isinstance(dev, int):
            metrics.observe(
                f"trace.{self.name}.device.{dev}", self.duration_ms / 1e3,
                trace_id=self.trace.trace_id,
            )
        # per-REPLICA attribution (docs/RESILIENCE.md §7): the fleet
        # router's route spans — and a replica server's root spans — carry
        # a ``replica`` attr, feeding replica-suffixed histograms so
        # /metrics shows which replica of the fleet is the straggler.
        # Cardinality is bounded by the fleet's membership.
        rep = self.attrs.get("replica") if self.attrs else None
        if rep is not None and isinstance(rep, str) and len(rep) <= 64:
            metrics.observe(
                f"trace.{self.name}.replica.{rep}", self.duration_ms / 1e3,
                trace_id=self.trace.trace_id,
            )
        if self.parent is None:
            _finish_trace(self.trace)
        elif self.trace.finished:
            # a span that OUTLIVED its root (a streamed query's scan spans
            # finish at stream end, after the sidecar's do_get root
            # returned the stream object): stretch the root to cover it
            # and re-evaluate the slow-query threshold, so a slow streamed
            # query is still logged (once — _finish_trace is idempotent
            # per trace)
            root = self.trace.root
            if root is not None:
                root.duration_ms = max(
                    root.duration_ms, (end - root.t0) * 1e3
                )
                _finish_trace(self.trace)

    def to_dict(self) -> Dict[str, Any]:
        """The span subtree as plain JSON-able data (slow-query records,
        the CLI ``trace`` command, /debug/queries)."""
        with self.trace.lock:
            children = list(self.children)
            attrs = dict(self.attrs)
        out: Dict[str, Any] = {
            "name": self.name,
            "ms": round(self.duration_ms, 3),
        }
        if attrs:
            out["attrs"] = {k: _jsonable(v) for k, v in attrs.items()}
        if children:
            out["children"] = [c.to_dict() for c in children]
        return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _jax_annotation(name: str):
    try:
        import jax

        return jax.profiler.TraceAnnotation("geomesa:" + name)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def enabled() -> bool:
    return bool(config.TRACE_ENABLED.to_bool())


def start(name: str, trace_id: Optional[str] = None, force: bool = False,
          **attrs):
    """Open a ROOT span (one per query). No-op singleton unless tracing is
    enabled — or ``force`` is set (the sidecar server honors an incoming
    Flight trace header even when its own tracing knob is off, so the
    server audit carries the client's trace id). Called with a trace
    already active on the context (a dataset op inside the sidecar's
    server root, a nested public API call), it JOINS that trace as a
    child instead of shadowing it with a second root."""
    if _current.get() is not None:
        return span(name, **attrs)
    if not (enabled() or (force and trace_id)):
        return NOOP
    trace = Trace(trace_id)
    root = Span(name, trace, None, attrs or None)
    trace.root = root
    trace.n_spans = 1
    return root


def span(name: str, **attrs):
    """Open a child span under the calling context's current span. With no
    active trace this is a single ContextVar read returning the shared
    no-op singleton — the disabled fast path."""
    cur = _current.get()
    if cur is None:
        return NOOP
    trace = cur.trace
    if not trace.admit():
        return NOOP
    child = Span(name, trace, cur, attrs or None)
    with trace.lock:
        cur.children.append(child)
    return child


def event(name: str, **attrs) -> None:
    """A zero-duration marker attached to the current span (e.g. a kernel
    recompile inside the query that paid for it). No-op without a trace."""
    cur = _current.get()
    if cur is None:
        return
    trace = cur.trace
    if name == "kernel.recompile":
        # tail-sampling classification: a recompile-carrying trace is an
        # always-keep class (the warm-path-broke evidence must survive
        # sampling). Flagged here so export never has to walk the tree.
        with trace.lock:
            trace.recompiles += 1
    if not trace.admit():
        return
    child = Span(name, trace, cur, attrs or None)
    with trace.lock:
        cur.children.append(child)


def current_span():
    """The innermost open span, or None."""
    return _current.get()


def current_trace_id() -> Optional[str]:
    cur = _current.get()
    return None if cur is None else cur.trace.trace_id


def snapshot():
    """The calling thread's current span, for cross-thread adoption
    (the partition prefetch worker pairs this with :func:`adopt` exactly
    like ``config.snapshot_overrides``/``adopt_overrides``)."""
    return _current.get()


def adopt(span_) -> None:
    """Install a :func:`snapshot` span as this thread's current span, so
    worker-side ``span()`` calls nest under the query's tree."""
    _current.set(span_)


# ---------------------------------------------------------------------------
# per-query cost ledger + classification hooks (docs/OBSERVABILITY.md)
# ---------------------------------------------------------------------------


def add_cost(key: str, value: float) -> None:
    """Accumulate one cost contribution (``device_ms.<id>``,
    ``partitions_scanned``, ``bytes_staged``, ``cache_hits``, ...) into the
    calling context's trace. No-op without an active trace — the cost
    ledger is trace-scoped, so it shares tracing's off-by-default-cheap
    contract. Contributors cross threads the way spans do (the prefetch
    worker's adopted context routes its staging bytes here too)."""
    cur = _current.get()
    if cur is None:
        return
    tr = cur.trace
    with tr.lock:
        tr.cost[key] = tr.cost.get(key, 0.0) + value


def current_cost() -> Dict[str, float]:
    """Copy of the active trace's cost ledger (empty without a trace).
    Folds the live recompile count in, so mid-trace readers (inline
    serving admission, explain) see the same keys a finished trace
    carries."""
    cur = _current.get()
    if cur is None:
        return {}
    tr = cur.trace
    with tr.lock:
        out = dict(tr.cost)
    if tr.recompiles:
        out.setdefault("recompiles", float(tr.recompiles))
    return out


def mark_degraded() -> None:
    """Flag the active trace degraded (a partition was skipped under the
    resilience contract) — an always-keep class for tail sampling. Called
    by ``resilience.record_skip``."""
    cur = _current.get()
    if cur is not None:
        cur.trace.degraded = True


def mark_slot_died(trace_id: Optional[str], slot: int,
                   reason: str = "died") -> bool:
    """Flag the trace behind ``trace_id`` as stranded by a dying/drained
    serving slot (docs/RESILIENCE.md §6): sets the ``slot_died``
    always-keep class for tail sampling (tracing_export.classify) and
    appends a ``serving.slot.died`` zero-duration event under the ROOT
    span, so the exported/slow-logged tree records which slot took the
    stream down. Called by the serving scheduler for each pinned
    continuation it strands — by id, because the dying dispatcher is not
    in the stream's span context. Returns False when no live trace holds
    that id (tracing off / trace already collected)."""
    if not trace_id:
        return False
    tr = _open.get(trace_id)
    if tr is None:
        return False
    with tr.lock:
        tr.slot_died = True
    root = tr.root
    if root is not None and tr.admit():
        ev = Span("serving.slot.died", tr, root,
                  {"slot": int(slot), "reason": reason})
        with tr.lock:
            root.children.append(ev)
    return True


#: per-thread most recently completed trace — the serving scheduler reads
#: (and clears) it around a dispatched ticket to attribute the ticket's
#: cost ledger to its user without racing other slots on the process-global
#: ``last_trace`` slot
_tls = threading.local()


def pop_thread_trace() -> Optional[Trace]:
    """Return-and-clear THIS thread's most recently completed trace."""
    tr = getattr(_tls, "last", None)
    _tls.last = None
    return tr


# ---------------------------------------------------------------------------
# slow-query log + recent-trace ring
# ---------------------------------------------------------------------------

_slow_lock = threading.Lock()
_slow: "deque" = deque(maxlen=256)
_last: List[Optional[Trace]] = [None]

#: finished traces BY ID (strong refs, bounded by geomesa.trace.retain,
#: oldest-out): the lookup behind /debug/queries?trace=<id> and the
#: sidecar ``trace-fetch`` action the fleet stitcher pulls replica
#: subtrees through (docs/OBSERVABILITY.md §9). Insertion is one ordered-
#: dict put on trace completion; the span-tree walk happens at FETCH
#: time, so query completion pays nothing extra.
_retain_lock = threading.Lock()
_retained: "OrderedDict[str, List[Trace]]" = OrderedDict()

#: traces retained PER ID: a scattered fleet query opens one server root
#: span per owner-group call, all sharing the router's trace id — every
#: one must stay fetchable (the stitcher matches them by parent token)
_RETAIN_PER_ID = 32


def _retain(trace: Trace) -> None:
    cap = config.TRACE_RETAIN.to_int()
    cap = 256 if cap is None else int(cap)
    if cap <= 0:
        return
    with _retain_lock:
        lst = _retained.get(trace.trace_id)
        if lst is None:
            lst = _retained[trace.trace_id] = []
        lst.append(trace)
        del lst[:-_RETAIN_PER_ID]
        _retained.move_to_end(trace.trace_id)
        while len(_retained) > cap:
            _retained.popitem(last=False)


def _trace_record(tr: Trace) -> Dict[str, Any]:
    return {
        "trace_id": tr.trace_id,
        "total_ms": round(tr.root.duration_ms, 3),
        "dropped_spans": tr.dropped,
        "tree": tr.root.to_dict(),
    }


def finished_trace(trace_id: str,
                   parent_span: Optional[str] = None
                   ) -> Optional[Dict[str, Any]]:
    """The retained finished trace behind ``trace_id`` as a JSON-able
    record (``{"trace_id", "total_ms", "dropped_spans", "tree"}``), or
    None when the id never finished here or aged out of the ring. With
    ``parent_span``, selects the retained trace whose root carries that
    ``parent_span`` attribute (several server roots share one trace id
    when a fleet query scatters); otherwise the most recent."""
    with _retain_lock:
        lst = list(_retained.get(trace_id) or ())
    lst = [tr for tr in lst if tr.root is not None]
    if not lst:
        return None
    if parent_span is not None:
        for tr in reversed(lst):
            if tr.root.attrs.get("parent_span") == parent_span:
                return _trace_record(tr)
        return None
    return _trace_record(lst[-1])


def finished_traces(trace_id: str) -> List[Dict[str, Any]]:
    """EVERY retained trace behind ``trace_id`` (oldest first) — the
    ``trace-fetch`` payload: a replica that served several scatter groups
    of one query returns all its subtrees in one round trip."""
    with _retain_lock:
        lst = list(_retained.get(trace_id) or ())
    return [_trace_record(tr) for tr in lst if tr.root is not None]


def clear_retained() -> None:
    with _retain_lock:
        _retained.clear()


def last_trace() -> Optional[Trace]:
    """The most recently completed trace (CLI ``trace`` subcommand,
    tests) — None when tracing never ran."""
    return _last[0]


def _finish_trace(trace: Trace) -> None:
    """Root closed: threshold-check against geomesa.trace.slow.ms and, when
    slow, record the full tree (ring + the audit JSONL appender, so file
    ordering matches the query events around it); then hand the trace to
    the exporter (tracing_export.py) when an export sink is configured —
    the tail-sampling decision happens there, at completion."""
    root = trace.root
    if root is None:
        return
    trace.finished = True
    _last[0] = trace
    _tls.last = trace
    _retain(trace)
    if trace.recompiles:
        # fold the recompile count into the cost ledger, so the serving
        # rollup and exported cost attributes carry it without a second
        # accounting path
        with trace.lock:
            trace.cost["recompiles"] = float(trace.recompiles)
    try:
        thresh = config.TRACE_SLOW_MS.to_float()
    except (TypeError, ValueError):
        thresh = None
    if thresh is None or root.duration_ms < thresh or trace.slow_logged:
        _offer_export(trace)
        return
    trace.slow_logged = True
    rec = {
        "kind": "slow_trace",
        "trace_id": trace.trace_id,
        "total_ms": round(root.duration_ms, 3),
        "threshold_ms": thresh,
        "dropped_spans": trace.dropped,
        "date": time.time(),
        "tree": root.to_dict(),
    }
    with _slow_lock:
        _slow.append(rec)
    from geomesa_tpu import audit

    audit.append_record(rec)
    metrics.inc("trace.slow")
    _offer_export(trace)


def _offer_export(trace: Trace) -> None:
    """Hand a completed trace to the exporter when a sink is configured.
    Re-entrant safe: a late-finishing child re-runs _finish_trace, and a
    trace sampled OUT on its first completion may be re-offered if it
    became slow (an always-keep class) in the meantime — the exporter's
    ``exported`` flag guarantees at-most-once enqueue."""
    if trace.exported:
        return
    if not (config.TRACE_OTLP_ENDPOINT.get()
            or config.TRACE_EXPORT_PATH.get()):
        return
    from geomesa_tpu import tracing_export

    tracing_export.offer(trace)


def slow_traces(n: int = 50) -> List[Dict[str, Any]]:
    """Most recent slow-query span trees (newest last)."""
    with _slow_lock:
        return list(_slow)[-n:]


def clear_slow_traces() -> None:
    with _slow_lock:
        _slow.clear()


def render(tree: Dict[str, Any], indent: int = 0) -> str:
    """Human-readable span tree (CLI ``trace`` subcommand)."""
    pad = "  " * indent
    attrs = tree.get("attrs")
    suffix = (
        " [" + ", ".join(f"{k}={v}" for k, v in attrs.items()) + "]"
        if attrs else ""
    )
    lines = [f"{pad}{tree['name']}: {tree.get('ms', 0.0):.3f} ms{suffix}"]
    for c in tree.get("children", ()):
        lines.append(render(c, indent + 1))
    return "\n".join(lines)
