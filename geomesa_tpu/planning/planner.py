"""Query planner: (filter, hints) -> (index choice, scan windows, compiled
predicate, aggregation program).

Pipeline parity with the reference (SURVEY.md §3.1 call stack):
``configureQuery`` (hints + filter optimize) -> ``FilterSplitter`` (candidate
indices) -> ``CostBasedStrategyDecider`` (stats-estimated counts,
StrategyDecider.scala:79-191) -> key space ranges -> guards
(FullTableScanQueryGuard / TemporalQueryGuard analogs) -> QueryPlan.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from geomesa_tpu import config
from geomesa_tpu.filter import compile_filter, ir, parse_ecql
from geomesa_tpu.filter.compile import CompiledFilter
from geomesa_tpu.index.keyspace import (
    AttributeKeySpace, IdKeySpace, KeyPlan, XZ2KeySpace, XZ3KeySpace,
    Z2KeySpace, Z3KeySpace,
)
from geomesa_tpu.index.store import FeatureStore
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.stats import sketches as sk


@dataclass
class QueryHints:
    """Per-query hints (the reference's QueryHints surface, SURVEY.md §5)."""

    #: force a specific index by name (QUERY_INDEX hint)
    query_index: Optional[str] = None
    #: skip fine predicate when the key filter is sufficient (LOOSE_BBOX)
    loose_bbox: bool = False
    #: 1-in-n sampling (SAMPLING hint)
    sampling: Optional[int] = None
    #: per-key sampling attribute (SAMPLE_BY hint): 1-in-n per key value
    sample_by: Optional[str] = None
    #: max features
    max_features: Optional[int] = None
    #: attribute projection
    properties: Optional[List[str]] = None
    #: sort: list of (attribute, descending)
    sort_by: Optional[List[tuple]] = None


@dataclass
class QueryPlan:
    """Everything the executor needs (reference QueryPlan.scala:30-94)."""

    schema: str
    filter: ir.Filter
    ecql: str
    compiled: CompiledFilter
    key_plan: KeyPlan
    index_name: str
    hints: QueryHints
    explain: Explainer
    est_count: float = 0.0

    @property
    def is_empty(self) -> bool:
        return self.key_plan.disjoint or isinstance(self.filter, ir.Exclude)


class QueryPlanner:
    """Plans queries for one FeatureStore (QueryPlanner.scala:36 analog)."""

    def __init__(self, store: FeatureStore):
        self.store = store

    def plan(
        self,
        ecql: "str | ir.Filter" = "INCLUDE",
        hints: Optional[QueryHints] = None,
        explain: Optional[Explainer] = None,
    ) -> QueryPlan:
        store = self.store
        ft = store.ft
        hints = hints or QueryHints()
        exp = explain or Explainer(enabled=False)

        if isinstance(ecql, ir.Filter):
            f, text = ecql, "<ir>"
        else:
            text = ecql
            f = parse_ecql(ecql)
        exp.push(f"Planning '{ft.name}' query")
        exp.line(f"Filter: {text}")

        # pluggable rewrite hooks (QueryInterceptor.scala:51 analog)
        from geomesa_tpu.planning import interceptors

        f2 = interceptors.apply_rewrite(ft, f)
        if f2 is not f:
            exp.line("Filter rewritten by interceptor")
            f = f2

        # candidate key plans (FilterSplitter.getQueryOptions analog)
        candidates = []
        for ks in store.keyspaces:
            if hints.query_index and ks.name != hints.query_index:
                continue
            kp = ks.plan(ft, f)
            if kp is not None:
                candidates.append(kp)
        if not candidates:
            if hints.query_index:
                raise ValueError(
                    f"index {hints.query_index!r} cannot serve this query"
                )
            # full scan on the first index
            kp = KeyPlan(store.keyspaces[0], full_scan=True)
            candidates = [kp]

        exp.push(f"Candidate indices: {[c.keyspace.name for c in candidates]}")
        chosen, cost = self._decide(candidates, f, exp)
        exp.pop()
        exp.line(
            f"Chosen index: {chosen.keyspace.name} "
            f"(estimated count {cost:.0f}, {len(chosen.ranges)} ranges"
            + (f", {len(chosen.bins)} time bins" if chosen.bins is not None else "")
            + ")"
        )

        self._guard(chosen, f, exp)

        compiled = compile_filter(f, ft, store.dicts)
        exp.line(f"Predicate columns: {compiled.columns}")
        exp.pop()
        plan = QueryPlan(
            schema=ft.name, filter=f, ecql=text, compiled=compiled,
            key_plan=chosen, index_name=chosen.keyspace.name, hints=hints,
            explain=exp, est_count=cost,
        )
        # pluggable guard hooks may veto the chosen plan (raise)
        interceptors.apply_guards(ft, plan)
        return plan

    # -- cost-based decider (StrategyDecider.scala:148-191 analog) ---------
    def _decide(self, candidates: List[KeyPlan], f: ir.Filter, exp: Explainer):
        store = self.store
        total = float(store.count)
        if config.STRATEGY_DECIDER.get() != "cost" and candidates:
            return candidates[0], total
        best, best_cost = None, None
        for kp in candidates:
            cost = self._estimate(kp, f, total)
            # index preference multipliers: id lookups cheapest, then
            # temporal+spatial, spatial, attribute (mirrors the reference's
            # per-index cost multipliers)
            mult = {
                "id": 0.5, "z3": 1.0, "xz3": 1.0, "s3": 1.0,
                "z2": 1.5, "xz2": 1.5, "s2": 1.5, "attr": 2.0,
            }.get(kp.keyspace.kind, 2.0)
            weighted = cost * mult if not kp.disjoint else -1.0
            exp.line(f"{kp.keyspace.name}: estimated {cost:.0f} (weighted {weighted:.0f})")
            if best_cost is None or weighted < best_cost:
                best, best_cost = kp, weighted
        return best, max(best_cost, 0.0)

    def _estimate(self, kp: KeyPlan, f: ir.Filter, total: float) -> float:
        store = self.store
        if kp.disjoint:
            return 0.0
        if kp.full_scan:
            return total
        name = kp.keyspace.kind
        if name in ("z3", "xz3") and kp.bins is not None:
            z3h = store.stats.get("z3-histogram")
            if isinstance(z3h, sk.Z3HistogramStat) and not z3h.is_empty and name == "z3":
                return z3h.estimate_count(kp.bins, kp.ranges)
            return total * kp.coverage
        if name == "z2":
            z2h = store.stats.get("z2-histogram")
            if isinstance(z2h, sk.Z2HistogramStat) and not z2h.is_empty:
                return z2h.estimate_count(kp.ranges)
            return total * min(1.0, kp.coverage * 4)
        if name == "xz2":
            return total * min(1.0, kp.coverage * 4)
        if name == "id":
            return float(len(getattr(kp, "_ids", ())))
        if name == "attr":
            attr = kp.keyspace.attr
            enum = store.stats.get(f"enum-{attr}")
            if isinstance(enum, sk.EnumerationStat) and not enum.is_empty:
                est = 0.0
                d = store.dicts.get(attr)
                for lo, hi in getattr(kp, "_bounds", []):
                    if lo == hi and d is not None:
                        est += enum.counts.get(d.code_of(str(lo)), 0)
                    else:
                        est += total * 0.1
                return est
            mm = store.stats.get(f"minmax-{attr}")
            if isinstance(mm, sk.MinMax) and not mm.is_empty:
                span = float(mm.hi) - float(mm.lo) or 1.0
                est = 0.0
                for lo, hi in getattr(kp, "_bounds", []):
                    lo2 = float(mm.lo) if lo is None else float(lo)
                    hi2 = float(mm.hi) if hi is None else float(hi)
                    est += total * max(0.0, min(hi2, float(mm.hi)) - max(lo2, float(mm.lo))) / span
                return est
            return total * 0.1
        return total * kp.coverage

    # -- guards (QueryInterceptor.guard analogs) ---------------------------
    def _guard(self, kp: KeyPlan, f: ir.Filter, exp: Explainer):
        if kp.full_scan and config.BLOCK_FULL_TABLE_SCANS.to_bool():
            raise ValueError(
                "full-table scan blocked (geomesa.scan.block-full-table=true); "
                "add spatial/temporal/attribute predicates"
            )
        max_days = config.TEMPORAL_GUARD_MAX_DAYS.to_int()
        if max_days and self.store.ft.dtg_field:
            iv = ir.extract_intervals(f, self.store.ft.dtg_field)
            if iv.is_empty:
                raise ValueError(
                    f"temporal guard: query must constrain {self.store.ft.dtg_field!r}"
                )
            span_ms = sum(hi - lo for lo, hi in iv.values)
            if span_ms > max_days * 86_400_000:
                raise ValueError(
                    f"temporal guard: query spans {span_ms / 86_400_000:.1f} days "
                    f"> limit {max_days}"
                )
