"""Batch specs for query-axis megakernels (docs/SERVING.md "Query-axis
batching").

A :class:`BatchSpec` packages everything the executor's ``*_batch`` entry
points need to serve M *distinct* viewports in one device dispatch: the
shared structural template (filter/template.py), the literal-parameterized
compiled mask, and the member literal vectors padded to the registry
batch bucket. :func:`build_spec` is the eligibility gate — it returns
None unless every member plan proves it compiles to the SAME kernel
structure, so the serving layer can always degrade to query-at-a-time
execution without changing any result.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import template as ftpl
from geomesa_tpu.filter.compile import compile_filter
from geomesa_tpu.kernels.registry import bucket_batch


@dataclass
class BatchSpec:
    """One fused group's batched-kernel inputs (see module docstring)."""

    #: structural identity (template key + auths): equal keys <=> one
    #: compiled kernel serves both batches
    key: tuple
    #: version-stable kernel-token component (folded into the registry
    #: key next to shapes + the dictionary fingerprint)
    token: tuple
    #: the literal-parameterized compiled mask
    bf: "ftpl.BatchedFilter"
    #: member literal vectors, padded to the batch bucket
    lits_f: np.ndarray  # [Mp, nf] float32
    lits_i: np.ndarray  # [Mp, ni] int32
    M: int
    Mp: int


def _auths_token(auths) -> Optional[Tuple[str, ...]]:
    return None if auths is None else tuple(auths)


def build_spec(ds, st, plans: List, auths=None) -> Optional[BatchSpec]:
    """Assemble the batch spec for ``plans`` (all over store ``st``), or
    None when they do not share a structural template / cannot ride the
    batched device kernel. ``ds`` supplies the visibility wrap so the
    batched residual enforces exactly the auths each member's serial
    compiled predicate does."""
    if not plans:
        return None
    tpls = []
    for p in plans:
        t = ftpl.split_literals(p.filter, st.ft)
        if t is None:
            return None
        tpls.append(t)
    t0 = tpls[0]
    if any(t.key != t0.key for t in tpls[1:]):
        return None
    if any(p.index_name != plans[0].index_name for p in plans[1:]):
        return None
    # residual compiled once (literals in it are structural — identical
    # across members by key equality), visibility-wrapped like _plan does
    residual = compile_filter(t0.residual, st.ft, st.dicts)
    residual = ds._vis_wrap(st, residual, auths)
    bf = ftpl.compile_batched(t0, st.ft, residual)
    if not bf.device_exact:
        return None
    M = len(plans)
    Mp = bucket_batch(M)
    nf, ni = len(t0.lits_f), len(t0.lits_i)
    lits_f = np.zeros((Mp, nf), np.float32)
    lits_i = np.zeros((Mp, ni), np.int32)
    for m, t in enumerate(tpls):
        lits_f[m] = t.lits_f
        lits_i[m] = t.lits_i
    akey = _auths_token(auths)
    return BatchSpec(
        key=("batch",) + t0.key + (akey,),
        # the FULL template key (not a hash): registry keys must never
        # collide across templates — equality is the correctness contract
        token=("qtpl", t0.key, akey),
        bf=bf, lits_f=lits_f, lits_i=lits_i, M=M, Mp=Mp,
    )
