"""SFC co-partitioned spatial-join executor (docs/JOIN.md).

The device analog of the reference's grid-partitioned Spark join
(GeoMesaJoinRelation + RelationUtils.gridPartition) in the shape "Adaptive
Geospatial Joins for Modern Hardware" (PAPERS.md) shows wins on throughput
hardware: a cheap grid filter prunes candidate pairs, then an exact test
runs on the survivors. Both join sides co-partition by SFC cell — the same
2^level x 2^level lon/lat grid the aggregate cache decomposes to
(cache/cells.py; a cell's identity is its z2 prefix via ``interleave2``) —
so only same-cell (plus boundary-strip) pairs ever reach the device:
candidate work is O(pairs-in-same-cell), never O(N*M).

Build/probe contract:

* the **build** (left) side lands in exactly one cell — the one containing
  its point;
* the **probe** (right) side replicates into every cell its predicate
  reach box ``point ± (reach + margin)`` touches (the *boundary strip*;
  the margin is ``cache.cells.CLASSIFY_MARGIN``, the same f32-safety
  machinery ``classify_cells`` uses, so an f32-rounded pair that passes
  the exact predicate can never hide in an unprobed neighbor cell);
* a candidate pair is tested iff the build row's cell is among the probe
  row's covered cells — each surviving pair is tested exactly ONCE,
  because the build cell is unique. No dedup pass exists or is needed.

Adaptive strategy selection (docs/JOIN.md §5): after co-partitioning, each
joint cell routes to the cheapest executor from its own (n_left, n_right)
statistics — the shape "Adaptive Geospatial Joins for Modern Hardware"
picks per-cell:

* **pairwise** — dense, balanced cells chunk into tiles for the bucketed
  [Cp, Bp, Pp] pairwise kernel (the only strategy when
  ``geomesa.join.adaptive`` is off);
* **brute** — sparse cells (``n_left * n_right`` at most
  ``geomesa.join.adaptive.brute.pairs``) gather into ONE flat 1-D
  candidate-pair list and skip tile padding entirely;
* **split.l / split.r** — skewed cells (one side ≫ the other) land in an
  orientation-specific section whose short-axis padding buckets
  independently, so a 3 x 500 cell pads to (4, tile) instead of the dense
  section's (Bp, Pp).

Strategy routing only decides WHICH executor tests a candidate pair —
every executor runs the SAME ``kernels.join.pair_mask`` f32 arithmetic and
the merged pair set surfaces in canonical row-major order, so the adaptive
join is bit-identical to the single-strategy path and to the numpy N*M
reference by construction (CI-gated).

Device execution: per-cell blocks chunk into **tiles** of at most
``geomesa.join.tile`` rows per side, both tile axes pow2-bucketed and the
tile count bucketed per dispatch, so the bucketed pairwise kernel's
registry key — ``(site, Bp, Pp, Cp, predicate)``, predicate *parameters*
ride as traced f32 scalars — is version-stable: repeated joins over fresh
data of similar size NEVER recompile (CI-gated recompiles==0). The
strategy lives in the key's ``site`` ("join.pairs" / "join.pairs.split" /
"join.brute" / "join.poly"), never in traced data, so strategy mixes
cannot recompile each other.

Sharded fan-out: each section's tile axis splits into one contiguous
slice per usable device (``parallel.devices.scan_devices``); counts merge
via the documented :func:`~geomesa_tpu.parallel.devices.tree_merge` order
and pair blocks concatenate in slice order before the canonical row-major
sort, so the sharded join is bit-identical to the single-device (and
numpy brute-force) result by construction. Per-slice failures degrade
under ``resilience.allow_partial()`` with exact survivor totals (the
skipped tile ranges are recorded; completed tiles' pairs/counts are
exact).

Polygon-dataset joins (docs/JOIN.md §7): :func:`run_polygon_join` joins a
point side against a POLYGON dataset side by classifying each occupied
point cell against each candidate polygon row with
``kernels.join.classify_cells`` + ``CLASSIFY_MARGIN`` — interior cells
match wholesale with ZERO pairwise work, outside cells are skipped, and
only boundary cells pay the polygon kernel.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, tracing, utilization
from geomesa_tpu.cache.cells import CLASSIFY_MARGIN
from geomesa_tpu.kernels import join as kjoin
from geomesa_tpu.kernels.registry import KernelRegistry
from geomesa_tpu.resilience import check_deadline, partial_allowed, record_skip

#: one process-wide registry for join kernels: the pairwise kernel is pure
#: in (shapes, predicate kind) — no store, no dictionary — so it is
#: version-stable trivially and shared across every dataset in the process
_REGISTRY: Optional[KernelRegistry] = None
_REGISTRY_LOCK = threading.Lock()

#: fixed section order — part of the bit-identity contract: sections
#: execute in this order, pairs concatenate in section/slice order, and
#: the canonical row-major sort at the end makes the surfaced set
#: independent of the routing anyway
SECTION_ORDER = ("pairwise", "split.l", "split.r")


def join_registry() -> KernelRegistry:
    """The process-wide join-kernel registry (recompile accounting for the
    bench/CI ``join_recompiles`` gate reads ``.traces('join.pairs')``)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = KernelRegistry()
        return _REGISTRY


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def _tile() -> int:
    t = config.JOIN_TILE.to_int()
    return 64 if t is None else max(int(t), 8)


def _brute_max() -> int:
    v = config.JOIN_ADAPTIVE_BRUTE_PAIRS.to_int()
    return 256 if v is None else max(int(v), 0)


def _skew_ratio() -> int:
    v = config.JOIN_ADAPTIVE_SKEW_RATIO.to_int()
    return 8 if v is None else max(int(v), 2)


@dataclass
class JoinStats:
    """The explain/audit account of one co-partitioned join (docs/JOIN.md):
    how much the grid filter pruned vs the naive N*M, and which strategy
    each joint cell routed to."""

    level: int = 0
    n_left: int = 0
    n_right: int = 0
    cells_left: int = 0
    cells_right: int = 0
    #: cells populated on BOTH sides (only these dispatch)
    cells_joint: int = 0
    #: exact pairwise tests dispatched (same-cell + strip candidates)
    candidate_pairs: int = 0
    #: probe rows replicated beyond their home cell (the boundary strip)
    strip_entries: int = 0
    tiles: int = 0
    matched: int = 0
    devices: int = 1
    #: tile ranges skipped under allow_partial (exact survivor totals)
    skipped: List[str] = field(default_factory=list)
    #: whether per-cell strategy selection ran (vs the single-strategy A/B)
    adaptive: bool = False
    #: adaptive decision trail: joint cells per strategy (pairwise / brute
    #: / split.l / split.r; polygon joins: interior / boundary incidences)
    strategy_cells: Dict[str, int] = field(default_factory=dict)
    #: candidate pairs per strategy as estimated at classification time
    #: (the statistic each routing decision read)
    est_pairs: Dict[str, int] = field(default_factory=dict)
    #: pair slots actually dispatched per strategy AFTER padding — the
    #: estimated-vs-actual gap is exactly the padding the routing saved
    dispatched_pairs: Dict[str, int] = field(default_factory=dict)
    #: polygon-join pairs matched wholesale from INTERIOR cells — zero
    #: pairwise kernel work, by the CLASSIFY_MARGIN contract
    wholesale_pairs: int = 0
    #: lake window-pushdown side-scan account (api.dataset join pushdown):
    #: groups/bytes loaded vs skipped by per-cell footer pruning
    pushdown: Dict[str, int] = field(default_factory=dict)

    @property
    def naive_pairs(self) -> int:
        return self.n_left * self.n_right

    @property
    def candidate_fraction(self) -> float:
        return self.candidate_pairs / max(self.naive_pairs, 1)

    @property
    def strip_fraction(self) -> float:
        """Fraction of probe-side cell memberships that are strip
        replicas (0 = every probe row stayed in its home cell)."""
        total = self.n_right + self.strip_entries
        return self.strip_entries / max(total, 1)


def choose_level(n_left: int, n_right: int, reach: float,
                 bounds: Optional[Tuple[float, float, float, float]]) -> int:
    """Adaptive co-partition level: fine enough that the denser side
    averages ~tile rows per occupied cell over its extent, coarse enough
    that a probe reach box spans at most 2 cells per axis (cell span >=
    2 * reach keeps the boundary strip at most one neighbor ring)."""
    tile = _tile()
    max_level = config.JOIN_MAX_LEVEL.to_int() or 12
    if bounds is None:
        span = 360.0
    else:
        span = max(bounds[2] - bounds[0], (bounds[3] - bounds[1]) * 2, 1e-6)
    target_axis = float(np.sqrt(max(n_left, n_right, 1) / tile))
    target_axis = min(max(target_axis, 1.0), 1024.0)
    want_span = max(span / target_axis, 1e-9)
    level_data = int(np.ceil(np.log2(360.0 / want_span)))
    reach = max(float(reach), 0.0) + CLASSIFY_MARGIN
    level_reach = int(np.floor(np.log2(360.0 / max(2.0 * reach, 1e-9))))
    return int(np.clip(min(level_data, level_reach), 1, max_level))


def _cell_ids(ix: np.ndarray, iy: np.ndarray) -> np.ndarray:
    """Absolute cell identity: the z2 curve prefix (interleave2), the same
    identity the aggregate cache keys cells by (cache/cells.cell_prefix)."""
    from geomesa_tpu.curves.zorder import interleave2

    return interleave2(ix.astype(np.uint64), iy.astype(np.uint64))


@dataclass
class TileSection:
    """One strategy's padded tile blocks: [C, Bp] / [C, Pp] global row
    positions (0-padded; valid counts mask), pow2-bucketed independently
    of every other section — the skew win is exactly that a split
    section's short axis pads to ITS OWN maximum, not the dense
    section's."""

    strategy: str  # "pairwise" | "split.l" | "split.r"
    site: str  # kernel registry site ("join.pairs" / "join.pairs.split")
    l_rows: np.ndarray
    r_rows: np.ndarray
    l_valid: np.ndarray  # [C] int32
    r_valid: np.ndarray  # [C] int32
    Bp: int
    Pp: int

    @property
    def n_tiles(self) -> int:
        return len(self.l_rows)


@dataclass
class JoinPlan:
    """Host-side co-partition product: per-strategy tile sections ready
    for the bucketed pairwise kernel, plus the flat brute-force candidate
    list for sparse cells. All index arrays are int32 positions into the
    caller's left/right row sets."""

    predicate: str
    p0: np.float32
    p1: np.float32
    stats: JoinStats
    sections: List[TileSection] = field(default_factory=list)
    #: flat sparse-cell candidate pairs (global row positions, aligned)
    brute_l: Optional[np.ndarray] = None
    brute_r: Optional[np.ndarray] = None

    @property
    def n_tiles(self) -> int:
        return sum(s.n_tiles for s in self.sections)

    @property
    def n_brute(self) -> int:
        return 0 if self.brute_l is None else len(self.brute_l)

    @property
    def Bp(self) -> int:
        return max((s.Bp for s in self.sections), default=0)

    @property
    def Pp(self) -> int:
        return max((s.Pp for s in self.sections), default=0)


def co_partition(lx, ly, rx, ry, predicate: str, reach_x,
                 reach_y: float, level: Optional[int] = None,
                 p0=None, p1=None, wrap_x: bool = False,
                 adaptive: Optional[bool] = None) -> JoinPlan:
    """Group both sides by SFC cell at ``level`` (adaptive when None),
    classify each joint cell's strategy from its (n_left, n_right), and
    chunk into per-strategy padded tile sections plus the flat brute
    list. Pure host numpy — the grouping is two argsorts plus a bounded
    neighbor expansion.

    ``adaptive`` None reads ``geomesa.join.adaptive``; False forces every
    joint cell through the single "pairwise" section — exactly the
    pre-adaptive plan, the A/B baseline the CI speedup gate compares
    against.

    ``reach_x`` may be a per-probe-row array (``dwithin_meters``: the lon
    reach needed for ``d`` meters grows with |latitude|). ``wrap_x``
    wraps the probe reach box across the antimeridian (modular lon
    cells) — a great-circle predicate matches across lon ±180, so its
    strip must too; the planar predicates keep the clipped grid."""
    lx = np.asarray(lx, np.float64)
    ly = np.asarray(ly, np.float64)
    rx = np.asarray(rx, np.float64)
    ry = np.asarray(ry, np.float64)
    # level choice uses the TYPICAL reach (per-row reach_x arrays rank by
    # their minimum — high-latitude rows widen their own windows instead
    # of coarsening every cell)
    rx_typ = (float(np.min(reach_x)) if np.ndim(reach_x) and len(reach_x)
              else float(reach_x) if not np.ndim(reach_x) else 0.0)
    reach = max(rx_typ, float(reach_y))
    if level is None:
        n_l, n_r = len(lx), len(rx)
        bounds = None
        if n_l and n_r:
            bounds = (
                min(lx.min(), rx.min()), min(ly.min(), ry.min()),
                max(lx.max(), rx.max()), max(ly.max(), ry.max()),
            )
        level = choose_level(n_l, n_r, reach, bounds)
    if adaptive is None:
        adaptive = config.JOIN_ADAPTIVE.to_bool()
        adaptive = True if adaptive is None else bool(adaptive)
    stats = JoinStats(level=level, n_left=len(lx), n_right=len(rx),
                      adaptive=bool(adaptive))
    plan = JoinPlan(predicate=predicate, p0=p0, p1=p1, stats=stats)
    if not len(lx) or not len(rx):
        return plan
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n

    def cell_of(x, y):
        ix = np.clip(np.floor((x + 180.0) / sx), 0, n - 1).astype(np.int64)
        iy = np.clip(np.floor((y + 90.0) / sy), 0, n - 1).astype(np.int64)
        return ix, iy

    lix, liy = cell_of(lx, ly)
    lcell = _cell_ids(lix, liy)
    stats.cells_left = len(np.unique(lcell))

    # probe reach box, inflated by the classify margin (module docstring):
    # every cell the box touches gets a membership
    mx = np.asarray(reach_x, np.float64) + CLASSIFY_MARGIN
    my = float(reach_y) + CLASSIFY_MARGIN
    if wrap_x:
        # modular lon: the window spans [ix0, ix1] mod n, capped at one
        # full wrap (a reach past 180° of longitude covers every column)
        ix0 = np.floor((rx - mx + 180.0) / sx).astype(np.int64)
        ix1 = np.floor((rx + mx + 180.0) / sx).astype(np.int64)
        wx = np.minimum(ix1 - ix0 + 1, n).astype(np.int64)
    else:
        ix0 = np.clip(np.floor((rx - mx + 180.0) / sx), 0, n - 1).astype(np.int64)
        ix1 = np.clip(np.floor((rx + mx + 180.0) / sx), 0, n - 1).astype(np.int64)
        wx = (ix1 - ix0 + 1).astype(np.int64)
    iy0 = np.clip(np.floor((ry - my + 90.0) / sy), 0, n - 1).astype(np.int64)
    iy1 = np.clip(np.floor((ry + my + 90.0) / sy), 0, n - 1).astype(np.int64)
    wy = (iy1 - iy0 + 1).astype(np.int64)
    w = wx * wy
    rid = np.repeat(np.arange(len(rx), dtype=np.int64), w)
    # per-membership (dx, dy) offsets within each row's window, row-major
    off = np.arange(int(w.sum()), dtype=np.int64) - np.repeat(
        np.cumsum(w) - w, w
    )
    gx = ix0[rid] + off % wx[rid]
    if wrap_x:
        gx %= n  # python modulo: non-negative for ix0 < 0
    gy = iy0[rid] + off // wx[rid]
    rcell = _cell_ids(gx, gy)
    rhome = _cell_ids(*cell_of(rx, ry))
    stats.cells_right = len(np.unique(rhome))

    # keep only memberships whose cell holds build rows (the joint cells)
    ucell, linv = np.unique(lcell, return_inverse=True)
    pos = np.searchsorted(ucell, rcell)
    pos_c = np.minimum(pos, len(ucell) - 1)
    keep = ucell[pos_c] == rcell
    rid, rcell_k, pos_c = rid[keep], rcell[keep], pos_c[keep]
    stats.strip_entries = int((rhome[rid] != rcell_k).sum())
    if not len(rid):
        return plan

    # group both sides by joint-cell index (stable order: row order within
    # a cell, cells in ucell order — deterministic for any input)
    lorder = np.argsort(linv, kind="stable")
    lsorted = lorder.astype(np.int32)
    lcounts = np.bincount(linv, minlength=len(ucell))
    rorder = np.argsort(pos_c, kind="stable")
    rsorted = rid[rorder].astype(np.int32)
    rcounts = np.bincount(pos_c, minlength=len(ucell))
    joint = (lcounts > 0) & (rcounts > 0)
    stats.cells_joint = int(joint.sum())
    stats.candidate_pairs = int(
        (lcounts[joint].astype(np.int64) * rcounts[joint]).sum()
    )
    lstart = np.concatenate(([0], np.cumsum(lcounts)))
    rstart = np.concatenate(([0], np.cumsum(rcounts)))

    # per-cell strategy classification (module docstring): sparse cells
    # gather flat, skewed cells bucket in their own orientation section so
    # the short axis pads narrow, dense balanced cells tile as before.
    # Adaptive-mode tile shapes are STATIC per strategy — (Tp, Tp),
    # (Tp, SPLIT_SHORT), (SPLIT_SHORT, Tp) — never derived from data
    # maxima, so fresh data of any distribution re-lands on the warmed
    # kernels (the recompiles==0 contract holds across strategy mixes);
    # single-strategy mode keeps the legacy exact-maxima padding — it IS
    # the A/B baseline and must stay byte-for-byte the old plan
    T = _tile()
    Tp = _pow2(T)
    brute_max = _brute_max() if adaptive else 0
    skew = _skew_ratio()
    # fixed short-axis chunk for split sections: skewed cells chunk their
    # SHORT side at this step too, so the section pads to exactly
    # (Tp, SPLIT_SHORT) — ~Tp/SPLIT_SHORT x less padded work than the
    # dense section would spend on the same cell
    split_short = min(8, Tp)
    bl_list: List[np.ndarray] = []
    br_list: List[np.ndarray] = []
    # strategy -> [tl_rows, tr_rows, tl_valid, tr_valid, max_b, max_p]
    buckets: Dict[str, list] = {}
    for c in np.nonzero(joint)[0]:
        lrows = lsorted[lstart[c]: lstart[c + 1]]
        rrows = rsorted[rstart[c]: rstart[c + 1]]
        nl, nr = len(lrows), len(rrows)
        if adaptive and nl * nr <= brute_max:
            strat = "brute"
            # flat candidate list, left-major (matches the reference's
            # row-major nonzero order; the global sort re-establishes it
            # across strategies anyway)
            bl_list.append(np.repeat(lrows, nr))
            br_list.append(np.tile(rrows, nl))
        elif adaptive and max(nl, nr) >= skew * max(min(nl, nr), 1) \
                and max(nl, nr) > T:
            strat = "split.l" if nl >= nr else "split.r"
        else:
            strat = "pairwise"
        stats.strategy_cells[strat] = stats.strategy_cells.get(strat, 0) + 1
        stats.est_pairs[strat] = stats.est_pairs.get(strat, 0) + nl * nr
        if strat == "brute":
            continue
        if strat == "split.l":
            tb, tp = T, split_short
        elif strat == "split.r":
            tb, tp = split_short, T
        else:
            tb = tp = T
        bucket = buckets.setdefault(strat, [[], [], [], [], 1, 1])
        tl_rows, tr_rows, tl_valid, tr_valid = bucket[0], bucket[1], \
            bucket[2], bucket[3]
        for bl in range(0, nl, tb):
            lchunk = lrows[bl: bl + tb]
            for pl in range(0, nr, tp):
                rchunk = rrows[pl: pl + tp]
                tl_rows.append(lchunk)
                tr_rows.append(rchunk)
                tl_valid.append(len(lchunk))
                tr_valid.append(len(rchunk))
                bucket[4] = max(bucket[4], len(lchunk))
                bucket[5] = max(bucket[5], len(rchunk))
    for strat in SECTION_ORDER:
        if strat not in buckets:
            continue
        tl_rows, tr_rows, tl_valid, tr_valid, max_b, max_p = buckets[strat]
        C = len(tl_rows)
        if not adaptive:
            Bp, Pp = _pow2(max_b), _pow2(max_p)  # legacy exact padding
        elif strat == "split.l":
            Bp, Pp = Tp, split_short
        elif strat == "split.r":
            Bp, Pp = split_short, Tp
        else:
            Bp = Pp = Tp
        l_rows = np.zeros((C, Bp), np.int32)
        r_rows = np.zeros((C, Pp), np.int32)
        for i in range(C):
            l_rows[i, : tl_valid[i]] = tl_rows[i]
            r_rows[i, : tr_valid[i]] = tr_rows[i]
        site = "join.pairs" if strat == "pairwise" else "join.pairs.split"
        plan.sections.append(TileSection(
            strategy=strat, site=site, l_rows=l_rows, r_rows=r_rows,
            l_valid=np.asarray(tl_valid, np.int32),
            r_valid=np.asarray(tr_valid, np.int32), Bp=Bp, Pp=Pp,
        ))
        stats.tiles += C
        stats.dispatched_pairs[strat] = C * Bp * Pp
    if bl_list:
        plan.brute_l = np.concatenate(bl_list)
        plan.brute_r = np.concatenate(br_list)
        stats.dispatched_pairs["brute"] = len(plan.brute_l)
    return plan


# ---------------------------------------------------------------------------
# Bucketed pairwise kernels (the version-stable registry half)
# ---------------------------------------------------------------------------

def _pairs_kernel(site: str, Bp: int, Pp: int, Cp: int, predicate: str):
    """Registry-cached jitted kernel: [Cp, Bp, Pp] bool verdict mask plus
    [Cp] int32 per-tile match counts. Predicate parameters are traced f32
    scalars (kernel data), so distances never recompile. ``site`` is the
    strategy's registry site ("join.pairs" / "join.pairs.split") — the
    strategy lives in the KEY, so mixing strategies never recompiles."""
    reg = join_registry()
    key = (site, Bp, Pp, Cp, predicate)
    go = reg.get(key)
    if go is not None:
        return go
    import jax
    import jax.numpy as jnp

    def _mask(m, lvalid, rvalid):
        iota_b = jnp.arange(Bp, dtype=jnp.int32)[None, :, None]
        iota_p = jnp.arange(Pp, dtype=jnp.int32)[None, None, :]
        m = m & (iota_b < lvalid[:, None, None]) \
              & (iota_p < rvalid[:, None, None])
        return m, m.sum(axis=(1, 2), dtype=jnp.int32)

    if predicate == kjoin.JOIN_DWITHIN_METERS:
        # unit-vector operands: three coordinate planes per side
        @jax.jit
        def go(lxb, lyb, lzb, rxb, ryb, rzb, lvalid, rvalid, p0, p1):
            m = kjoin.pair_mask(
                lxb[:, :, None], lyb[:, :, None],
                rxb[:, None, :], ryb[:, None, :],
                predicate, p0, p1, jnp,
                lz=lzb[:, :, None], rz=rzb[:, None, :],
            )
            return _mask(m, lvalid, rvalid)
    else:
        @jax.jit
        def go(lxb, lyb, rxb, ryb, lvalid, rvalid, p0, p1):
            m = kjoin.pair_mask(
                lxb[:, :, None], lyb[:, :, None],
                rxb[:, None, :], ryb[:, None, :],
                predicate, p0, p1, jnp,
            )
            return _mask(m, lvalid, rvalid)

    reg.put(key, go)
    return go


def _brute_kernel(Kp: int, predicate: str):
    """Registry-cached jitted kernel for the flat sparse-cell strategy:
    1-D [Kp] gathered candidate pairs, bool verdict + int32 match count.
    Same ``pair_mask`` f32 arithmetic as the tiled kernel — elementwise
    instead of broadcast, so each tested pair decides identically."""
    reg = join_registry()
    key = ("join.brute", Kp, predicate)
    go = reg.get(key)
    if go is not None:
        return go
    import jax
    import jax.numpy as jnp

    def _mask(m, kvalid):
        m = m & (jnp.arange(Kp, dtype=jnp.int32) < kvalid)
        return m, m.sum(dtype=jnp.int32)

    if predicate == kjoin.JOIN_DWITHIN_METERS:
        @jax.jit
        def go(lxv, lyv, lzv, rxv, ryv, rzv, kvalid, p0, p1):
            m = kjoin.pair_mask(lxv, lyv, rxv, ryv, predicate, p0, p1,
                                jnp, lz=lzv, rz=rzv)
            return _mask(m, kvalid)
    else:
        @jax.jit
        def go(lxv, lyv, rxv, ryv, kvalid, p0, p1):
            m = kjoin.pair_mask(lxv, lyv, rxv, ryv, predicate, p0, p1, jnp)
            return _mask(m, kvalid)

    reg.put(key, go)
    return go


def _devices(prefer_device: bool):
    """Devices for the join tile fan-out (same stand-down rules as the
    sharded partitioned scan), or None for the single default device."""
    if not prefer_device:
        return None
    from geomesa_tpu.parallel import devices as pdev

    return pdev.scan_devices()


def _pad_tiles(sec: TileSection, lo: int, hi: int, lx32, ly32, rx32, ry32,
               lz32=None, rz32=None):
    """One device slice's padded kernel operands: tile rows [Cp, Bp/Pp]
    gathered into coordinate blocks, Cp = pow2 bucket of the slice.
    ``lz32``/``rz32`` (dwithin_meters unit vectors) gather to z blocks."""
    C = hi - lo
    Cp = _pow2(C)
    lrows = np.zeros((Cp, sec.Bp), np.int32)
    rrows = np.zeros((Cp, sec.Pp), np.int32)
    lval = np.zeros(Cp, np.int32)
    rval = np.zeros(Cp, np.int32)
    lrows[:C] = sec.l_rows[lo:hi]
    rrows[:C] = sec.r_rows[lo:hi]
    lval[:C] = sec.l_valid[lo:hi]
    rval[:C] = sec.r_valid[lo:hi]
    lzb = None if lz32 is None else lz32[lrows]
    rzb = None if rz32 is None else rz32[rrows]
    return (lx32[lrows], ly32[lrows], rx32[rrows], ry32[rrows],
            lval, rval, Cp, C, lzb, rzb)


def _slices(n: int, n_dev: int) -> List[Tuple[int, int]]:
    edges = np.linspace(0, n, n_dev + 1).astype(int)
    return [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:])
            if b > a]


def execute(plan: JoinPlan, lx, ly, rx, ry, prefer_device: bool = True,
            want_pairs: bool = True, lz=None, rz=None):
    """Run every strategy section (and the flat brute list) over the
    device mesh. Returns ``(pairs, total)``: matched global (left, right)
    row positions as int64 [K, 2] sorted row-major (None when
    ``want_pairs`` is False) and the exact match total over completed
    work. Per-slice failures degrade under ``resilience.allow_partial()``
    (recorded in ``plan.stats.skipped``); totals stay exact over
    survivors. For ``dwithin_meters``, the coordinate operands are the
    sides' precomputed f32 unit vectors ((lx, ly, lz) / (rx, ry, rz) —
    kernels.join.unit_vectors)."""
    stats = plan.stats
    if plan.n_tiles == 0 and plan.n_brute == 0:
        return (np.zeros((0, 2), np.int64) if want_pairs else None), 0
    lx32 = np.asarray(lx, np.float32)
    ly32 = np.asarray(ly, np.float32)
    rx32 = np.asarray(rx, np.float32)
    ry32 = np.asarray(ry, np.float32)
    lz32 = None if lz is None else np.asarray(lz, np.float32)
    rz32 = None if rz is None else np.asarray(rz, np.float32)
    use_device = prefer_device and _jax_ok()
    devs = _devices(prefer_device) if use_device else None
    n_dev = len(devs) if devs else 1
    stats.devices = n_dev
    from geomesa_tpu.resilience import QueryTimeoutError

    # contiguous tile slices per section, one per device (bit-identity:
    # pairs concat in section/slice order, then the canonical sort; counts
    # tree-merge in the same order)
    import functools

    jobs = []
    di = 0
    # fan each section out proportionally to its tile share: a full
    # n_dev split of every section multiplies launch count by the
    # number of strategies, and per-launch overhead — not slot math —
    # is what the sparse/skewed strategies are saving. Single-section
    # plans (adaptive off) keep the exact n_dev split.
    total_tiles = sum(s.n_tiles for s in plan.sections)
    for sec in plan.sections:
        fan = max(1, round(n_dev * sec.n_tiles / total_tiles)) \
            if total_tiles else 1
        for lo, hi in _slices(sec.n_tiles, fan):
            dev = devs[di % len(devs)] if devs else None
            di += 1
            jobs.append((f"tiles[{lo}:{hi}]", functools.partial(
                _run_slice, plan, lo, hi, lx32, ly32, rx32, ry32,
                use_device, dev, want_pairs, lz32=lz32, rz32=rz32,
                sec=sec)))
    if plan.n_brute:
        # fixed-size brute chunks: every dispatch (including the final
        # partial one) pads to the SAME pow2 length — four dense tiles'
        # worth of slots — so the registry holds exactly one
        # ("join.brute", Kp, predicate) entry no matter how many sparse
        # pairs fresh data produces (the recompiles==0 contract). The
        # chunk is sized so launch overhead, not padding, sets the cost:
        # a 16k-slot flat kernel is still far cheaper than one tile.
        bchunk = 4 * _pow2(_tile()) ** 2
        for lo in range(0, plan.n_brute, bchunk):
            hi = min(lo + bchunk, plan.n_brute)
            dev = devs[di % len(devs)] if devs else None
            di += 1
            jobs.append((f"brute[{lo}:{hi}]", functools.partial(
                _run_brute_slice, plan, lo, hi, lx32, ly32, rx32, ry32,
                use_device, dev, want_pairs, lz32=lz32, rz32=rz32,
                Kp=bchunk)))
    # multi-device: overlap the per-slice dispatch+fetch across worker
    # threads (each slice blocks on its own device; serializing them
    # leaves n_dev-1 devices idle per launch). Deadline checks and
    # partial-degradation accounting stay on THIS thread — both are
    # thread-local scopes — by collecting results in submission order,
    # which is also what keeps pairs/count merge order deterministic.
    partials = []
    if use_device and n_dev > 1 and len(jobs) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=n_dev,
                                thread_name_prefix="geomesa-join") as pool:
            futs = [(label, pool.submit(fn)) for label, fn in jobs]
            for label, fut in futs:
                try:
                    check_deadline()
                    partials.append(fut.result())
                except BaseException as e:
                    if isinstance(e, QueryTimeoutError) \
                            or not partial_allowed():
                        raise
                    record_skip("join", label, e, phase="pairs")
                    stats.skipped.append(label)
                    partials.append(None)
    else:
        for label, fn in jobs:
            try:
                check_deadline()
                partials.append(fn())
            except BaseException as e:
                if isinstance(e, QueryTimeoutError) or not partial_allowed():
                    raise
                record_skip("join", label, e, phase="pairs")
                stats.skipped.append(label)
                partials.append(None)
    from geomesa_tpu.parallel.devices import tree_merge

    total = tree_merge(
        [None if p is None else p[1] for p in partials],
        lambda a, b: a + b,
    )
    total = int(total or 0)
    stats.matched = total
    if not want_pairs:
        return None, total
    blocks = [p[0] for p in partials if p is not None and len(p[0])]
    if not blocks:
        return np.zeros((0, 2), np.int64), total
    pairs = np.concatenate(blocks, axis=0)
    # canonical row-major order == the brute-force reference's nonzero
    # order: the bit-identity contract is on the SET, surfaced sorted —
    # this is also what makes the adaptive routing invisible in results
    order = np.lexsort((pairs[:, 1], pairs[:, 0]))
    return pairs[order], total


def _run_slice(plan: JoinPlan, lo: int, hi: int, lx32, ly32, rx32, ry32,
               use_device: bool, dev, want_pairs: bool,
               lz32=None, rz32=None, sec: Optional[TileSection] = None):
    """One tile slice: (pairs int64 [k, 2] in tile order, match count)."""
    if sec is None:
        sec = plan.sections[0]
    (lxb, lyb, rxb, ryb, lval, rval, Cp, C, lzb, rzb) = _pad_tiles(
        sec, lo, hi, lx32, ly32, rx32, ry32, lz32, rz32
    )
    if use_device:
        import jax

        go = _pairs_kernel(sec.site, sec.Bp, sec.Pp, Cp, plan.predicate)
        if plan.predicate == kjoin.JOIN_DWITHIN_METERS:
            ops = (lxb, lyb, lzb, rxb, ryb, rzb, lval, rval,
                   np.float32(plan.p0), np.float32(plan.p1))
        else:
            ops = (lxb, lyb, rxb, ryb, lval, rval,
                   np.float32(plan.p0), np.float32(plan.p1))
        if dev is not None:
            ops = tuple(jax.device_put(o, dev) for o in ops)
        with tracing.span("scan.join.pairs", tiles=C, device=getattr(
                dev, "id", None)), \
                utilization.device_busy(getattr(dev, "id", 0) or 0):
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            m, counts = go(*ops)
        m = np.asarray(m)
        counts = np.asarray(counts)
    else:
        m = kjoin.pair_mask(
            lxb[:, :, None], lyb[:, :, None],
            rxb[:, None, :], ryb[:, None, :],
            plan.predicate, plan.p0, plan.p1, np,
            lz=None if lzb is None else lzb[:, :, None],
            rz=None if rzb is None else rzb[:, None, :],
        )
        iota_b = np.arange(sec.Bp, dtype=np.int32)[None, :, None]
        iota_p = np.arange(sec.Pp, dtype=np.int32)[None, None, :]
        m = m & (iota_b < lval[:, None, None]) & (iota_p < rval[:, None, None])
        counts = m.sum(axis=(1, 2), dtype=np.int32)
    n = int(counts[:C].sum())
    if not want_pairs:
        return np.zeros((0, 2), np.int64), n
    c, b, p = np.nonzero(m[:C])
    lrows = sec.l_rows[lo:hi]
    rrows = sec.r_rows[lo:hi]
    pairs = np.stack([
        lrows[c, b].astype(np.int64), rrows[c, p].astype(np.int64)
    ], axis=1)
    return pairs, n


def _run_brute_slice(plan: JoinPlan, lo: int, hi: int, lx32, ly32,
                     rx32, ry32, use_device: bool, dev, want_pairs: bool,
                     lz32=None, rz32=None, Kp: Optional[int] = None):
    """One flat brute-force slice: the sparse-cell candidate pairs
    [lo:hi) gathered into 1-D operands — no tile padding at all, just a
    fixed length bucket (``Kp``, from the caller's chunking; pow2 of the
    slice length when not given). Returns (pairs int64 [k, 2], count)."""
    bl = plan.brute_l[lo:hi]
    br = plan.brute_r[lo:hi]
    K = hi - lo
    if Kp is None:
        Kp = _pow2(K)
    lidx = np.zeros(Kp, np.int32)
    ridx = np.zeros(Kp, np.int32)
    lidx[:K] = bl
    ridx[:K] = br
    lxv, lyv = lx32[lidx], ly32[lidx]
    rxv, ryv = rx32[ridx], ry32[ridx]
    lzv = None if lz32 is None else lz32[lidx]
    rzv = None if rz32 is None else rz32[ridx]
    if use_device:
        import jax

        go = _brute_kernel(Kp, plan.predicate)
        if plan.predicate == kjoin.JOIN_DWITHIN_METERS:
            ops = (lxv, lyv, lzv, rxv, ryv, rzv, np.int32(K),
                   np.float32(plan.p0), np.float32(plan.p1))
        else:
            ops = (lxv, lyv, rxv, ryv, np.int32(K),
                   np.float32(plan.p0), np.float32(plan.p1))
        if dev is not None:
            ops = tuple(jax.device_put(o, dev) for o in ops)
        with tracing.span("scan.join.brute", pairs=K, device=getattr(
                dev, "id", None)), \
                utilization.device_busy(getattr(dev, "id", 0) or 0):
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            m, n = go(*ops)
        m = np.asarray(m)
        n = int(n)
    else:
        m = kjoin.pair_mask(lxv, lyv, rxv, ryv, plan.predicate,
                            plan.p0, plan.p1, np, lz=lzv, rz=rzv)
        m = m & (np.arange(Kp, dtype=np.int32) < K)
        n = int(m.sum())
    if not want_pairs:
        return np.zeros((0, 2), np.int64), n
    k = np.nonzero(m[:K])[0]
    pairs = np.stack([bl[k].astype(np.int64), br[k].astype(np.int64)],
                     axis=1)
    return pairs, n


def _jax_ok() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:  # pragma: no cover — jax is baked into the image
        return False


def meters_reach_deg(distance_m: float, lat) -> Tuple[np.ndarray, float]:
    """Conservative lon/lat reach (degrees) of ``distance_m`` meters of
    great-circle distance around probe rows at latitudes ``lat`` —
    ``(reach_x [per-row], reach_y)`` for the dwithin_meters strip
    (docs/JOIN.md §10: latitude-dependent lon reach). The lat reach is
    the central angle exactly; the lon reach is the maximal longitude
    span of the spherical circle, ``arcsin(sin θ / cos φ)``, going full
    wrap (360°) where the circle reaches a pole (sin θ >= cos φ) — the
    only regime where a partner's longitude is unconstrained."""
    theta = float(distance_m) / kjoin.EARTH_RADIUS_M  # central angle, rad
    reach_y = float(np.degrees(theta))
    if theta >= np.pi / 2:
        return np.full(np.shape(lat), 360.0), reach_y
    cphi = np.cos(np.deg2rad(np.asarray(lat, np.float64)))
    s = np.sin(theta)
    safe = s < cphi
    reach_x = np.where(
        safe,
        np.degrees(np.arcsin(np.minimum(s / np.maximum(cphi, 1e-300), 1.0))),
        360.0,
    )
    return reach_x, reach_y


def run_join(lx, ly, rx, ry, predicate: str, distance=None, dx=None,
             dy=None, level: Optional[int] = None,
             prefer_device: bool = True, want_pairs: bool = True,
             adaptive: Optional[bool] = None):
    """Full co-partitioned join: plan + execute. Returns
    ``(pairs, total, stats)``. ``predicate``: ``"bbox"`` (half-widths
    ``dx``/``dy``), ``"dwithin"`` (planar degree ``distance``), or
    ``"dwithin_meters"`` (haversine great-circle ``distance`` meters) —
    see :func:`geomesa_tpu.kernels.join.pair_mask` for the exact
    semantics. ``adaptive`` None reads ``geomesa.join.adaptive``; False
    is the single-strategy A/B baseline (bit-identical results)."""
    p0, p1 = kjoin.pair_params(predicate, distance=distance, dx=dx, dy=dy)
    wrap_x = False
    if predicate == kjoin.JOIN_BBOX:
        reach_x, reach_y = float(p0), float(p1)
    elif predicate == kjoin.JOIN_DWITHIN_METERS:
        # latitude-dependent lon reach; the great circle wraps the
        # antimeridian, so the strip does too
        reach_x, reach_y = meters_reach_deg(float(distance), ry)
        wrap_x = True
    else:
        reach_x = reach_y = float(distance)
    with tracing.span("scan.join.partition"):
        plan = co_partition(lx, ly, rx, ry, predicate, reach_x, reach_y,
                            level=level, p0=p0, p1=p1, wrap_x=wrap_x,
                            adaptive=adaptive)
    st = plan.stats
    metrics.inc(metrics.JOIN_CELLS, st.cells_joint)
    metrics.inc(metrics.JOIN_CANDIDATE_PAIRS, st.candidate_pairs)
    tracing.add_cost("join_cells", float(st.cells_joint))
    tracing.add_cost("join_candidate_pairs", float(st.candidate_pairs))
    for s, k in st.strategy_cells.items():
        metrics.inc(metrics.JOIN_CELLS_STRATEGY + s, k)
    pairs, total = execute_predicate(plan, lx, ly, rx, ry, predicate,
                                     prefer_device=prefer_device,
                                     want_pairs=want_pairs)
    metrics.inc(metrics.JOIN_PAIRS, total)
    return pairs, total, st


def execute_predicate(plan: JoinPlan, lx, ly, rx, ry, predicate: str,
                      prefer_device: bool = True, want_pairs: bool = True):
    """:func:`execute` with the predicate's operand convention applied:
    ``dwithin_meters`` runs on precomputed f32 unit vectors — host trig
    once, shared by kernel and reference (kernels.join.unit_vectors) —
    every other predicate passes lon/lat straight through. The one
    dispatch both :func:`run_join` and ``explain_join(analyze=True)``
    share, so they cannot drift."""
    if predicate == kjoin.JOIN_DWITHIN_METERS:
        lux, luy, luz = kjoin.unit_vectors(lx, ly)
        rux, ruy, ruz = kjoin.unit_vectors(rx, ry)
        return execute(plan, lux, luy, rux, ruy,
                       prefer_device=prefer_device,
                       want_pairs=want_pairs, lz=luz, rz=ruz)
    return execute(plan, lx, ly, rx, ry, prefer_device=prefer_device,
                   want_pairs=want_pairs)


# ---------------------------------------------------------------------------
# Polygon-dataset joins (docs/JOIN.md §7): point side x POLYGON side
# ---------------------------------------------------------------------------

def _polygon_level(n_points: int, bnds: np.ndarray) -> int:
    """Cell level for a polygon join: the median polygon should span a
    few cells per axis — fine enough that INTERIOR cells exist (the
    wholesale win), coarse enough that per-polygon candidate cell counts
    stay bounded."""
    max_level = config.JOIN_MAX_LEVEL.to_int() or 12
    spans = np.maximum(
        np.maximum(bnds[:, 2] - bnds[:, 0], (bnds[:, 3] - bnds[:, 1]) * 2.0),
        1e-9,
    )
    med = float(np.median(spans))
    level = int(np.round(np.log2(360.0 / max(med / 4.0, 1e-9))))
    return int(np.clip(level, 1, max_level))


def _poly_kernel(Np: int, Ep: int, Pfp: int, Rp: int, predicate: str):
    """Registry-cached jitted polygon-join kernel: [Np, Rp] bool verdict
    matrix for a slice of boundary-cell points against the padded polygon
    tables (kernels.join.polygon_tables/polygon_mask). Every axis is a
    pow2 bucket in the key; the tables ride as traced operands."""
    reg = join_registry()
    key = ("join.poly", Np, Ep, Pfp, Rp, predicate)
    go = reg.get(key)
    if go is not None:
        return go
    import jax
    import jax.numpy as jnp

    @jax.jit
    def go(pxv, pyv, x1, y1, x2, y2, part_id, part_row, boxes):
        t = {"x1": x1, "y1": y1, "x2": x2, "y2": y2,
             "part_id": part_id, "part_row": part_row, "boxes": boxes,
             "n_parts_padded": Pfp, "n_rows_padded": Rp}
        return kjoin.polygon_mask(pxv, pyv, t, predicate, jnp)

    reg.put(key, go)
    return go


def run_polygon_join(px, py, geoms, predicate: str,
                     level: Optional[int] = None,
                     prefer_device: bool = True, want_pairs: bool = True):
    """Join a point side against a polygon-dataset side. Returns
    ``(pairs, total, stats)``: matched (point_row, polygon_row) positions
    in canonical row-major order, bit-identical to
    :func:`kernels.join.polygon_brute_force` by construction.

    The adaptive core: occupied point cells classify against each
    candidate polygon via ``classify_cells`` + ``CLASSIFY_MARGIN`` —

    * INTERIOR cells match **wholesale**: every point in the cell is at
      least the margin inside (exact f64), so the f32 kernel verdict is
      True for all of them — zero pairwise work dispatched;
    * OUTSIDE cells are skipped for the symmetric reason;
    * BOUNDARY cells pay the polygon kernel (the same
      ``polygon_mask`` f32 arithmetic as the reference), so near-edge
      points decide exactly as the reference decides them.

    ``predicate``: ``"pip"`` (even-odd point-in-polygon; holes and
    multipolygon parts per ``polygon_mask``) or ``"poly_bbox"`` (point in
    the row's bounds, inclusive edges — classification runs against the
    bounds rectangle)."""
    from geomesa_tpu.cache import cells as gcells
    from geomesa_tpu.utils import geometry as geo

    px = np.asarray(px, np.float64)
    py = np.asarray(py, np.float64)
    geoms = list(geoms)
    stats = JoinStats(n_left=len(px), n_right=len(geoms), adaptive=True)
    empty = np.zeros((0, 2), np.int64)
    if not len(px) or not len(geoms):
        return (empty if want_pairs else None), 0, stats
    bnds = np.asarray([g.bounds() for g in geoms], np.float64)  # [R, 4]
    if level is None:
        level = _polygon_level(len(px), bnds)
    stats.level = level
    ix, iy = gcells.point_cells(px, py, level)
    cell = _cell_ids(ix, iy)
    order = np.argsort(cell, kind="stable")
    sorted_cells = cell[order]
    ucell, starts = np.unique(sorted_cells, return_index=True)
    ends = np.concatenate([starts[1:], [len(order)]])
    stats.cells_left = len(ucell)
    stats.cells_right = len(geoms)
    boxes = gcells.cell_boxes(level, ix[order][starts], iy[order][starts])
    m = CLASSIFY_MARGIN

    wholesale_blocks: List[np.ndarray] = []
    R = len(geoms)
    boundary_pts = np.zeros(len(px), bool)
    # per-polygon boundary cell lists (classified lazily into the mask
    # AFTER the boundary point set is known)
    boundary_cells: List[np.ndarray] = []
    interior_cells = boundary_count = 0
    for j, g in enumerate(geoms):
        bx0, by0, bx1, by1 = bnds[j]
        cand = np.nonzero(
            (boxes[:, 0] <= bx1 + m) & (boxes[:, 2] >= bx0 - m)
            & (boxes[:, 1] <= by1 + m) & (boxes[:, 3] >= by0 - m)
        )[0]
        if not len(cand):
            boundary_cells.append(cand)
            continue
        stats.cells_joint += len(cand)
        target = g if predicate == kjoin.JOIN_PIP \
            else geo.bbox_polygon(bx0, by0, bx1, by1)
        cls = kjoin.classify_cells(boxes[cand], target, CLASSIFY_MARGIN)
        interior = cand[cls == kjoin.CELL_INTERIOR]
        boundary = cand[cls == kjoin.CELL_BOUNDARY]
        interior_cells += len(interior)
        boundary_count += len(boundary)
        for u in interior:
            rows = order[starts[u]: ends[u]]
            wholesale_blocks.append(np.stack([
                rows.astype(np.int64),
                np.full(len(rows), j, np.int64),
            ], axis=1))
        for u in boundary:
            boundary_pts[order[starts[u]: ends[u]]] = True
        boundary_cells.append(boundary)
    stats.strategy_cells["interior"] = interior_cells
    stats.strategy_cells["boundary"] = boundary_count
    wholesale = (np.concatenate(wholesale_blocks, axis=0)
                 if wholesale_blocks else empty)
    stats.wholesale_pairs = len(wholesale)

    # boundary phase: unique boundary points x candidate polygons through
    # the polygon kernel (the only pairwise work in the whole join)
    brows = np.nonzero(boundary_pts)[0]
    matched_blocks: List[np.ndarray] = []
    kernel_total = 0
    if len(brows):
        # candmask[b, j]: point b's cell is a boundary cell of polygon j —
        # interior cells are EXCLUDED (already matched wholesale)
        bpos = np.full(len(px), -1, np.int64)
        bpos[brows] = np.arange(len(brows))
        candmask = np.zeros((len(brows), R), bool)
        for j, bcells in enumerate(boundary_cells):
            for u in bcells:
                rows = order[starts[u]: ends[u]]
                candmask[bpos[rows], j] = True
        stats.candidate_pairs = int(candmask.sum())
        tables = kjoin.polygon_tables(geoms)
        Ep = _pow2(tables["n_edges"])
        Pfp = _pow2(tables["n_parts"])
        Rp = _pow2(tables["n_rows"])
        tables = kjoin.polygon_tables(geoms, pad_edges=Ep, pad_parts=Pfp,
                                      pad_rows=Rp)
        px32 = px.astype(np.float32)
        py32 = py.astype(np.float32)
        use_device = prefer_device and _jax_ok()
        devs = _devices(prefer_device) if use_device else None
        n_dev = len(devs) if devs else 1
        stats.devices = n_dev
        from geomesa_tpu.resilience import QueryTimeoutError

        for i, (lo, hi) in enumerate(_slices(len(brows), n_dev)):
            check_deadline()
            dev = devs[i % len(devs)] if devs else None
            try:
                verdict = _run_poly_slice(
                    brows[lo:hi], px32, py32, tables, predicate,
                    use_device, dev, Ep, Pfp, Rp,
                )
                hit = verdict[:, :R] & candmask[lo:hi]
                kernel_total += int(hit.sum())
                b, j = np.nonzero(hit)
                if len(b):
                    matched_blocks.append(np.stack([
                        brows[lo:hi][b].astype(np.int64),
                        j.astype(np.int64),
                    ], axis=1))
            except BaseException as e:
                if isinstance(e, QueryTimeoutError) or not partial_allowed():
                    raise
                record_skip("join", f"poly[{lo}:{hi}]", e, phase="pairs")
                stats.skipped.append(f"poly[{lo}:{hi}]")
    total = len(wholesale) + kernel_total
    stats.matched = total
    metrics.inc(metrics.JOIN_CELLS, stats.cells_joint)
    metrics.inc(metrics.JOIN_CANDIDATE_PAIRS, stats.candidate_pairs)
    for s, k in stats.strategy_cells.items():
        metrics.inc(metrics.JOIN_CELLS_STRATEGY + s, k)
    metrics.inc(metrics.JOIN_PAIRS, total)
    if not want_pairs:
        return None, total, stats
    blocks = [b for b in ([wholesale] + matched_blocks) if len(b)]
    if not blocks:
        return empty, total, stats
    pairs = np.concatenate(blocks, axis=0)
    order2 = np.lexsort((pairs[:, 1], pairs[:, 0]))
    return pairs[order2], total, stats


def _run_poly_slice(rows: np.ndarray, px32, py32, tables, predicate: str,
                    use_device: bool, dev, Ep: int, Pfp: int, Rp: int):
    """One boundary-point slice: [len(rows) padded to Np, Rp] verdicts
    from the polygon kernel (device) or the same ``polygon_mask`` on the
    host — identical f32 arithmetic either way."""
    K = len(rows)
    Np = _pow2(K)
    idx = np.zeros(Np, np.int64)
    idx[:K] = rows
    pxv = px32[idx]
    pyv = py32[idx]
    if use_device:
        import jax

        go = _poly_kernel(Np, Ep, Pfp, Rp, predicate)
        ops = (pxv, pyv, tables["x1"], tables["y1"], tables["x2"],
               tables["y2"], tables["part_id"], tables["part_row"],
               tables["boxes"])
        if dev is not None:
            ops = tuple(jax.device_put(o, dev) for o in ops)
        with tracing.span("scan.join.poly", points=K, device=getattr(
                dev, "id", None)), \
                utilization.device_busy(getattr(dev, "id", 0) or 0):
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            verdict = np.asarray(go(*ops))
    else:
        verdict = kjoin.polygon_mask(pxv, pyv, tables, predicate, np)
    return verdict[:K]
