"""SFC co-partitioned spatial-join executor (docs/JOIN.md).

The device analog of the reference's grid-partitioned Spark join
(GeoMesaJoinRelation + RelationUtils.gridPartition) in the shape "Adaptive
Geospatial Joins for Modern Hardware" (PAPERS.md) shows wins on throughput
hardware: a cheap grid filter prunes candidate pairs, then an exact test
runs on the survivors. Both join sides co-partition by SFC cell — the same
2^level x 2^level lon/lat grid the aggregate cache decomposes to
(cache/cells.py; a cell's identity is its z2 prefix via ``interleave2``) —
so only same-cell (plus boundary-strip) pairs ever reach the device:
candidate work is O(pairs-in-same-cell), never O(N*M).

Build/probe contract:

* the **build** (left) side lands in exactly one cell — the one containing
  its point;
* the **probe** (right) side replicates into every cell its predicate
  reach box ``point ± (reach + margin)`` touches (the *boundary strip*;
  the margin is ``cache.cells.CLASSIFY_MARGIN``, the same f32-safety
  machinery ``classify_cells`` uses, so an f32-rounded pair that passes
  the exact predicate can never hide in an unprobed neighbor cell);
* a candidate pair is tested iff the build row's cell is among the probe
  row's covered cells — each surviving pair is tested exactly ONCE,
  because the build cell is unique. No dedup pass exists or is needed.

Device execution: per-cell blocks chunk into **tiles** of at most
``geomesa.join.tile`` rows per side, both tile axes pow2-bucketed and the
tile count bucketed per dispatch, so the bucketed pairwise kernel's
registry key — ``(site, Bp, Pp, Cp, predicate)``, predicate *parameters*
ride as traced f32 scalars — is version-stable: repeated joins over fresh
data of similar size NEVER recompile (CI-gated recompiles==0).

Sharded fan-out: the tile axis splits into one contiguous slice per
usable device (``parallel.devices.scan_devices``); counts merge via the
documented :func:`~geomesa_tpu.parallel.devices.tree_merge` order and
pair blocks concatenate in slice order, so the sharded join is
bit-identical to the single-device (and numpy brute-force) result by
construction. Per-slice failures degrade under
``resilience.allow_partial()`` with exact survivor totals (the skipped
tile ranges are recorded; completed tiles' pairs/counts are exact).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, tracing, utilization
from geomesa_tpu.cache.cells import CLASSIFY_MARGIN
from geomesa_tpu.kernels import join as kjoin
from geomesa_tpu.kernels.registry import KernelRegistry
from geomesa_tpu.resilience import check_deadline, partial_allowed, record_skip

#: one process-wide registry for join kernels: the pairwise kernel is pure
#: in (shapes, predicate kind) — no store, no dictionary — so it is
#: version-stable trivially and shared across every dataset in the process
_REGISTRY: Optional[KernelRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def join_registry() -> KernelRegistry:
    """The process-wide join-kernel registry (recompile accounting for the
    bench/CI ``join_recompiles`` gate reads ``.traces('join.pairs')``)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = KernelRegistry()
        return _REGISTRY


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


def _tile() -> int:
    t = config.JOIN_TILE.to_int()
    return 64 if t is None else max(int(t), 8)


@dataclass
class JoinStats:
    """The explain/audit account of one co-partitioned join (docs/JOIN.md):
    how much the grid filter pruned vs the naive N*M."""

    level: int = 0
    n_left: int = 0
    n_right: int = 0
    cells_left: int = 0
    cells_right: int = 0
    #: cells populated on BOTH sides (only these dispatch)
    cells_joint: int = 0
    #: exact pairwise tests dispatched (same-cell + strip candidates)
    candidate_pairs: int = 0
    #: probe rows replicated beyond their home cell (the boundary strip)
    strip_entries: int = 0
    tiles: int = 0
    matched: int = 0
    devices: int = 1
    #: tile ranges skipped under allow_partial (exact survivor totals)
    skipped: List[str] = field(default_factory=list)

    @property
    def naive_pairs(self) -> int:
        return self.n_left * self.n_right

    @property
    def candidate_fraction(self) -> float:
        return self.candidate_pairs / max(self.naive_pairs, 1)

    @property
    def strip_fraction(self) -> float:
        """Fraction of probe-side cell memberships that are strip
        replicas (0 = every probe row stayed in its home cell)."""
        total = self.n_right + self.strip_entries
        return self.strip_entries / max(total, 1)


def choose_level(n_left: int, n_right: int, reach: float,
                 bounds: Optional[Tuple[float, float, float, float]]) -> int:
    """Adaptive co-partition level: fine enough that the denser side
    averages ~tile rows per occupied cell over its extent, coarse enough
    that a probe reach box spans at most 2 cells per axis (cell span >=
    2 * reach keeps the boundary strip at most one neighbor ring)."""
    tile = _tile()
    max_level = config.JOIN_MAX_LEVEL.to_int() or 12
    if bounds is None:
        span = 360.0
    else:
        span = max(bounds[2] - bounds[0], (bounds[3] - bounds[1]) * 2, 1e-6)
    target_axis = float(np.sqrt(max(n_left, n_right, 1) / tile))
    target_axis = min(max(target_axis, 1.0), 1024.0)
    want_span = max(span / target_axis, 1e-9)
    level_data = int(np.ceil(np.log2(360.0 / want_span)))
    reach = max(float(reach), 0.0) + CLASSIFY_MARGIN
    level_reach = int(np.floor(np.log2(360.0 / max(2.0 * reach, 1e-9))))
    return int(np.clip(min(level_data, level_reach), 1, max_level))


def _cell_ids(ix: np.ndarray, iy: np.ndarray) -> np.ndarray:
    """Absolute cell identity: the z2 curve prefix (interleave2), the same
    identity the aggregate cache keys cells by (cache/cells.cell_prefix)."""
    from geomesa_tpu.curves.zorder import interleave2

    return interleave2(ix.astype(np.uint64), iy.astype(np.uint64))


@dataclass
class JoinPlan:
    """Host-side co-partition product: padded tile blocks ready for the
    bucketed pairwise kernel. All index arrays are int32 positions into
    the caller's left/right row sets."""

    predicate: str
    p0: np.float32
    p1: np.float32
    stats: JoinStats
    #: [C, Bp] / [C, Pp] global row positions (0-padded; valid counts mask)
    l_rows: np.ndarray = None  # type: ignore[assignment]
    r_rows: np.ndarray = None  # type: ignore[assignment]
    l_valid: np.ndarray = None  # type: ignore[assignment]  # [C] int32
    r_valid: np.ndarray = None  # type: ignore[assignment]  # [C] int32
    Bp: int = 0
    Pp: int = 0

    @property
    def n_tiles(self) -> int:
        return 0 if self.l_rows is None else len(self.l_rows)


def co_partition(lx, ly, rx, ry, predicate: str, reach_x,
                 reach_y: float, level: Optional[int] = None,
                 p0=None, p1=None, wrap_x: bool = False) -> JoinPlan:
    """Group both sides by SFC cell at ``level`` (adaptive when None) and
    chunk joint cells into padded tile blocks. Pure host numpy — the
    grouping is two argsorts plus a bounded neighbor expansion.

    ``reach_x`` may be a per-probe-row array (``dwithin_meters``: the lon
    reach needed for ``d`` meters grows with |latitude|). ``wrap_x``
    wraps the probe reach box across the antimeridian (modular lon
    cells) — a great-circle predicate matches across lon ±180, so its
    strip must too; the planar predicates keep the clipped grid."""
    lx = np.asarray(lx, np.float64)
    ly = np.asarray(ly, np.float64)
    rx = np.asarray(rx, np.float64)
    ry = np.asarray(ry, np.float64)
    # level choice uses the TYPICAL reach (per-row reach_x arrays rank by
    # their minimum — high-latitude rows widen their own windows instead
    # of coarsening every cell)
    rx_typ = (float(np.min(reach_x)) if np.ndim(reach_x) and len(reach_x)
              else float(reach_x) if not np.ndim(reach_x) else 0.0)
    reach = max(rx_typ, float(reach_y))
    if level is None:
        n_l, n_r = len(lx), len(rx)
        bounds = None
        if n_l and n_r:
            bounds = (
                min(lx.min(), rx.min()), min(ly.min(), ry.min()),
                max(lx.max(), rx.max()), max(ly.max(), ry.max()),
            )
        level = choose_level(n_l, n_r, reach, bounds)
    stats = JoinStats(level=level, n_left=len(lx), n_right=len(rx))
    plan = JoinPlan(predicate=predicate, p0=p0, p1=p1, stats=stats)
    if not len(lx) or not len(rx):
        return plan
    n = 1 << level
    sx, sy = 360.0 / n, 180.0 / n

    def cell_of(x, y):
        ix = np.clip(np.floor((x + 180.0) / sx), 0, n - 1).astype(np.int64)
        iy = np.clip(np.floor((y + 90.0) / sy), 0, n - 1).astype(np.int64)
        return ix, iy

    lix, liy = cell_of(lx, ly)
    lcell = _cell_ids(lix, liy)
    stats.cells_left = len(np.unique(lcell))

    # probe reach box, inflated by the classify margin (module docstring):
    # every cell the box touches gets a membership
    mx = np.asarray(reach_x, np.float64) + CLASSIFY_MARGIN
    my = float(reach_y) + CLASSIFY_MARGIN
    if wrap_x:
        # modular lon: the window spans [ix0, ix1] mod n, capped at one
        # full wrap (a reach past 180° of longitude covers every column)
        ix0 = np.floor((rx - mx + 180.0) / sx).astype(np.int64)
        ix1 = np.floor((rx + mx + 180.0) / sx).astype(np.int64)
        wx = np.minimum(ix1 - ix0 + 1, n).astype(np.int64)
    else:
        ix0 = np.clip(np.floor((rx - mx + 180.0) / sx), 0, n - 1).astype(np.int64)
        ix1 = np.clip(np.floor((rx + mx + 180.0) / sx), 0, n - 1).astype(np.int64)
        wx = (ix1 - ix0 + 1).astype(np.int64)
    iy0 = np.clip(np.floor((ry - my + 90.0) / sy), 0, n - 1).astype(np.int64)
    iy1 = np.clip(np.floor((ry + my + 90.0) / sy), 0, n - 1).astype(np.int64)
    wy = (iy1 - iy0 + 1).astype(np.int64)
    w = wx * wy
    rid = np.repeat(np.arange(len(rx), dtype=np.int64), w)
    # per-membership (dx, dy) offsets within each row's window, row-major
    off = np.arange(int(w.sum()), dtype=np.int64) - np.repeat(
        np.cumsum(w) - w, w
    )
    gx = ix0[rid] + off % wx[rid]
    if wrap_x:
        gx %= n  # python modulo: non-negative for ix0 < 0
    gy = iy0[rid] + off // wx[rid]
    rcell = _cell_ids(gx, gy)
    rhome = _cell_ids(*cell_of(rx, ry))
    stats.cells_right = len(np.unique(rhome))

    # keep only memberships whose cell holds build rows (the joint cells)
    ucell, linv = np.unique(lcell, return_inverse=True)
    pos = np.searchsorted(ucell, rcell)
    pos_c = np.minimum(pos, len(ucell) - 1)
    keep = ucell[pos_c] == rcell
    rid, rcell_k, pos_c = rid[keep], rcell[keep], pos_c[keep]
    stats.strip_entries = int((rhome[rid] != rcell_k).sum())
    if not len(rid):
        return plan

    # group both sides by joint-cell index (stable order: row order within
    # a cell, cells in ucell order — deterministic for any input)
    lorder = np.argsort(linv, kind="stable")
    lsorted = lorder.astype(np.int32)
    lcounts = np.bincount(linv, minlength=len(ucell))
    rorder = np.argsort(pos_c, kind="stable")
    rsorted = rid[rorder].astype(np.int32)
    rcounts = np.bincount(pos_c, minlength=len(ucell))
    joint = (lcounts > 0) & (rcounts > 0)
    stats.cells_joint = int(joint.sum())
    stats.candidate_pairs = int(
        (lcounts[joint].astype(np.int64) * rcounts[joint]).sum()
    )
    lstart = np.concatenate(([0], np.cumsum(lcounts)))
    rstart = np.concatenate(([0], np.cumsum(rcounts)))

    # tile chunking: skewed cells split into ceil(nb/T) x ceil(np/T)
    # tile pairs instead of inflating every cell's padding
    T = _tile()
    tl_rows: List[np.ndarray] = []
    tr_rows: List[np.ndarray] = []
    tl_valid: List[int] = []
    tr_valid: List[int] = []
    max_b = max_p = 1
    for c in np.nonzero(joint)[0]:
        lrows = lsorted[lstart[c]: lstart[c + 1]]
        rrows = rsorted[rstart[c]: rstart[c + 1]]
        for bl in range(0, len(lrows), T):
            lchunk = lrows[bl: bl + T]
            for pl in range(0, len(rrows), T):
                rchunk = rrows[pl: pl + T]
                tl_rows.append(lchunk)
                tr_rows.append(rchunk)
                tl_valid.append(len(lchunk))
                tr_valid.append(len(rchunk))
                max_b = max(max_b, len(lchunk))
                max_p = max(max_p, len(rchunk))
    C = len(tl_rows)
    stats.tiles = C
    Bp, Pp = _pow2(max_b), _pow2(max_p)
    l_rows = np.zeros((C, Bp), np.int32)
    r_rows = np.zeros((C, Pp), np.int32)
    for i in range(C):
        l_rows[i, : tl_valid[i]] = tl_rows[i]
        r_rows[i, : tr_valid[i]] = tr_rows[i]
    plan.l_rows, plan.r_rows = l_rows, r_rows
    plan.l_valid = np.asarray(tl_valid, np.int32)
    plan.r_valid = np.asarray(tr_valid, np.int32)
    plan.Bp, plan.Pp = Bp, Pp
    return plan


# ---------------------------------------------------------------------------
# Bucketed pairwise kernels (the version-stable registry half)
# ---------------------------------------------------------------------------

def _pairs_kernel(Bp: int, Pp: int, Cp: int, predicate: str):
    """Registry-cached jitted kernel: [Cp, Bp, Pp] bool verdict mask plus
    [Cp] int32 per-tile match counts. Predicate parameters are traced f32
    scalars (kernel data), so distances never recompile."""
    reg = join_registry()
    key = ("join.pairs", Bp, Pp, Cp, predicate)
    go = reg.get(key)
    if go is not None:
        return go
    import jax
    import jax.numpy as jnp

    def _mask(m, lvalid, rvalid):
        iota_b = jnp.arange(Bp, dtype=jnp.int32)[None, :, None]
        iota_p = jnp.arange(Pp, dtype=jnp.int32)[None, None, :]
        m = m & (iota_b < lvalid[:, None, None]) \
              & (iota_p < rvalid[:, None, None])
        return m, m.sum(axis=(1, 2), dtype=jnp.int32)

    if predicate == kjoin.JOIN_DWITHIN_METERS:
        # unit-vector operands: three coordinate planes per side
        @jax.jit
        def go(lxb, lyb, lzb, rxb, ryb, rzb, lvalid, rvalid, p0, p1):
            m = kjoin.pair_mask(
                lxb[:, :, None], lyb[:, :, None],
                rxb[:, None, :], ryb[:, None, :],
                predicate, p0, p1, jnp,
                lz=lzb[:, :, None], rz=rzb[:, None, :],
            )
            return _mask(m, lvalid, rvalid)
    else:
        @jax.jit
        def go(lxb, lyb, rxb, ryb, lvalid, rvalid, p0, p1):
            m = kjoin.pair_mask(
                lxb[:, :, None], lyb[:, :, None],
                rxb[:, None, :], ryb[:, None, :],
                predicate, p0, p1, jnp,
            )
            return _mask(m, lvalid, rvalid)

    reg.put(key, go)
    return go


def _devices(prefer_device: bool):
    """Devices for the join tile fan-out (same stand-down rules as the
    sharded partitioned scan), or None for the single default device."""
    if not prefer_device:
        return None
    from geomesa_tpu.parallel import devices as pdev

    return pdev.scan_devices()


def _pad_tiles(plan: JoinPlan, lo: int, hi: int, lx32, ly32, rx32, ry32,
               lz32=None, rz32=None):
    """One device slice's padded kernel operands: tile rows [Cp, Bp/Pp]
    gathered into coordinate blocks, Cp = pow2 bucket of the slice.
    ``lz32``/``rz32`` (dwithin_meters unit vectors) gather to z blocks."""
    C = hi - lo
    Cp = _pow2(C)
    lrows = np.zeros((Cp, plan.Bp), np.int32)
    rrows = np.zeros((Cp, plan.Pp), np.int32)
    lval = np.zeros(Cp, np.int32)
    rval = np.zeros(Cp, np.int32)
    lrows[:C] = plan.l_rows[lo:hi]
    rrows[:C] = plan.r_rows[lo:hi]
    lval[:C] = plan.l_valid[lo:hi]
    rval[:C] = plan.r_valid[lo:hi]
    lzb = None if lz32 is None else lz32[lrows]
    rzb = None if rz32 is None else rz32[rrows]
    return (lx32[lrows], ly32[lrows], rx32[rrows], ry32[rrows],
            lval, rval, Cp, C, lzb, rzb)


def execute(plan: JoinPlan, lx, ly, rx, ry, prefer_device: bool = True,
            want_pairs: bool = True, lz=None, rz=None):
    """Run the bucketed pairwise kernel over the plan's tiles, sharded
    over the device mesh. Returns ``(pairs, total)``: matched global
    (left, right) row positions as int64 [K, 2] sorted row-major (None
    when ``want_pairs`` is False) and the exact match total over
    completed tiles. Per-slice failures degrade under
    ``resilience.allow_partial()`` (recorded in ``plan.stats.skipped``);
    totals stay exact over survivors. For ``dwithin_meters``, the
    coordinate operands are the sides' precomputed f32 unit vectors
    ((lx, ly, lz) / (rx, ry, rz) — kernels.join.unit_vectors)."""
    stats = plan.stats
    if plan.n_tiles == 0:
        return (np.zeros((0, 2), np.int64) if want_pairs else None), 0
    lx32 = np.asarray(lx, np.float32)
    ly32 = np.asarray(ly, np.float32)
    rx32 = np.asarray(rx, np.float32)
    ry32 = np.asarray(ry, np.float32)
    lz32 = None if lz is None else np.asarray(lz, np.float32)
    rz32 = None if rz is None else np.asarray(rz, np.float32)
    use_device = prefer_device and _jax_ok()
    devs = _devices(prefer_device) if use_device else None
    n_dev = len(devs) if devs else 1
    stats.devices = n_dev
    # contiguous tile slices, one per device (bit-identity: slice order ==
    # tile order; counts tree-merge in slice order)
    edges = np.linspace(0, plan.n_tiles, n_dev + 1).astype(int)
    slices = [(int(a), int(b)) for a, b in zip(edges[:-1], edges[1:])
              if b > a]
    partials = []
    for i, (lo, hi) in enumerate(slices):
        check_deadline()
        dev = devs[i % len(devs)] if devs else None
        try:
            partials.append(
                _run_slice(plan, lo, hi, lx32, ly32, rx32, ry32,
                           use_device, dev, want_pairs,
                           lz32=lz32, rz32=rz32)
            )
        except BaseException as e:
            from geomesa_tpu.resilience import QueryTimeoutError

            if isinstance(e, QueryTimeoutError) or not partial_allowed():
                raise
            record_skip("join", f"tiles[{lo}:{hi}]", e, phase="pairs")
            stats.skipped.append(f"tiles[{lo}:{hi}]")
            partials.append(None)
    from geomesa_tpu.parallel.devices import tree_merge

    total = tree_merge(
        [None if p is None else p[1] for p in partials],
        lambda a, b: a + b,
    )
    total = int(total or 0)
    stats.matched = total
    if not want_pairs:
        return None, total
    blocks = [p[0] for p in partials if p is not None and len(p[0])]
    if not blocks:
        return np.zeros((0, 2), np.int64), total
    pairs = np.concatenate(blocks, axis=0)
    # canonical row-major order == the brute-force reference's nonzero
    # order: the bit-identity contract is on the SET, surfaced sorted
    order = np.lexsort((pairs[:, 1], pairs[:, 0]))
    return pairs[order], total


def _run_slice(plan: JoinPlan, lo: int, hi: int, lx32, ly32, rx32, ry32,
               use_device: bool, dev, want_pairs: bool,
               lz32=None, rz32=None):
    """One tile slice: (pairs int64 [k, 2] in tile order, match count)."""
    (lxb, lyb, rxb, ryb, lval, rval, Cp, C, lzb, rzb) = _pad_tiles(
        plan, lo, hi, lx32, ly32, rx32, ry32, lz32, rz32
    )
    if use_device:
        import jax

        go = _pairs_kernel(plan.Bp, plan.Pp, Cp, plan.predicate)
        if plan.predicate == kjoin.JOIN_DWITHIN_METERS:
            ops = (lxb, lyb, lzb, rxb, ryb, rzb, lval, rval,
                   np.float32(plan.p0), np.float32(plan.p1))
        else:
            ops = (lxb, lyb, rxb, ryb, lval, rval,
                   np.float32(plan.p0), np.float32(plan.p1))
        if dev is not None:
            ops = tuple(jax.device_put(o, dev) for o in ops)
        with tracing.span("scan.join.pairs", tiles=C, device=getattr(
                dev, "id", None)), \
                utilization.device_busy(getattr(dev, "id", 0) or 0):
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            m, counts = go(*ops)
        m = np.asarray(m)
        counts = np.asarray(counts)
    else:
        m = kjoin.pair_mask(
            lxb[:, :, None], lyb[:, :, None],
            rxb[:, None, :], ryb[:, None, :],
            plan.predicate, plan.p0, plan.p1, np,
            lz=None if lzb is None else lzb[:, :, None],
            rz=None if rzb is None else rzb[:, None, :],
        )
        iota_b = np.arange(plan.Bp, dtype=np.int32)[None, :, None]
        iota_p = np.arange(plan.Pp, dtype=np.int32)[None, None, :]
        m = m & (iota_b < lval[:, None, None]) & (iota_p < rval[:, None, None])
        counts = m.sum(axis=(1, 2), dtype=np.int32)
    n = int(counts[:C].sum())
    if not want_pairs:
        return np.zeros((0, 2), np.int64), n
    c, b, p = np.nonzero(m[:C])
    lrows = plan.l_rows[lo:hi]
    rrows = plan.r_rows[lo:hi]
    pairs = np.stack([
        lrows[c, b].astype(np.int64), rrows[c, p].astype(np.int64)
    ], axis=1)
    return pairs, n


def _jax_ok() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except Exception:  # pragma: no cover — jax is baked into the image
        return False


def meters_reach_deg(distance_m: float, lat) -> Tuple[np.ndarray, float]:
    """Conservative lon/lat reach (degrees) of ``distance_m`` meters of
    great-circle distance around probe rows at latitudes ``lat`` —
    ``(reach_x [per-row], reach_y)`` for the dwithin_meters strip
    (docs/JOIN.md §10: latitude-dependent lon reach). The lat reach is
    the central angle exactly; the lon reach is the maximal longitude
    span of the spherical circle, ``arcsin(sin θ / cos φ)``, going full
    wrap (360°) where the circle reaches a pole (sin θ >= cos φ) — the
    only regime where a partner's longitude is unconstrained."""
    theta = float(distance_m) / kjoin.EARTH_RADIUS_M  # central angle, rad
    reach_y = float(np.degrees(theta))
    if theta >= np.pi / 2:
        return np.full(np.shape(lat), 360.0), reach_y
    cphi = np.cos(np.deg2rad(np.asarray(lat, np.float64)))
    s = np.sin(theta)
    safe = s < cphi
    reach_x = np.where(
        safe,
        np.degrees(np.arcsin(np.minimum(s / np.maximum(cphi, 1e-300), 1.0))),
        360.0,
    )
    return reach_x, reach_y


def run_join(lx, ly, rx, ry, predicate: str, distance=None, dx=None,
             dy=None, level: Optional[int] = None,
             prefer_device: bool = True, want_pairs: bool = True):
    """Full co-partitioned join: plan + execute. Returns
    ``(pairs, total, stats)``. ``predicate``: ``"bbox"`` (half-widths
    ``dx``/``dy``), ``"dwithin"`` (planar degree ``distance``), or
    ``"dwithin_meters"`` (haversine great-circle ``distance`` meters) —
    see :func:`geomesa_tpu.kernels.join.pair_mask` for the exact
    semantics."""
    p0, p1 = kjoin.pair_params(predicate, distance=distance, dx=dx, dy=dy)
    wrap_x = False
    if predicate == kjoin.JOIN_BBOX:
        reach_x, reach_y = float(p0), float(p1)
    elif predicate == kjoin.JOIN_DWITHIN_METERS:
        # latitude-dependent lon reach; the great circle wraps the
        # antimeridian, so the strip does too
        reach_x, reach_y = meters_reach_deg(float(distance), ry)
        wrap_x = True
    else:
        reach_x = reach_y = float(distance)
    with tracing.span("scan.join.partition"):
        plan = co_partition(lx, ly, rx, ry, predicate, reach_x, reach_y,
                            level=level, p0=p0, p1=p1, wrap_x=wrap_x)
    st = plan.stats
    metrics.inc(metrics.JOIN_CELLS, st.cells_joint)
    metrics.inc(metrics.JOIN_CANDIDATE_PAIRS, st.candidate_pairs)
    tracing.add_cost("join_cells", float(st.cells_joint))
    tracing.add_cost("join_candidate_pairs", float(st.candidate_pairs))
    pairs, total = execute_predicate(plan, lx, ly, rx, ry, predicate,
                                     prefer_device=prefer_device,
                                     want_pairs=want_pairs)
    metrics.inc(metrics.JOIN_PAIRS, total)
    return pairs, total, st


def execute_predicate(plan: JoinPlan, lx, ly, rx, ry, predicate: str,
                      prefer_device: bool = True, want_pairs: bool = True):
    """:func:`execute` with the predicate's operand convention applied:
    ``dwithin_meters`` runs on precomputed f32 unit vectors — host trig
    once, shared by kernel and reference (kernels.join.unit_vectors) —
    every other predicate passes lon/lat straight through. The one
    dispatch both :func:`run_join` and ``explain_join(analyze=True)``
    share, so they cannot drift."""
    if predicate == kjoin.JOIN_DWITHIN_METERS:
        lux, luy, luz = kjoin.unit_vectors(lx, ly)
        rux, ruy, ruz = kjoin.unit_vectors(rx, ry)
        return execute(plan, lux, luy, rux, ruy,
                       prefer_device=prefer_device,
                       want_pairs=want_pairs, lz=luz, rz=ruz)
    return execute(plan, lx, ly, rx, ry, prefer_device=prefer_device,
                   want_pairs=want_pairs)
