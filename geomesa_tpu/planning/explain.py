"""Explain tree — the query-debugging UX.

Parity with the reference's ``Explainer`` (geomesa-index-api/.../utils/
Explainer.scala:16-50): an indented push/pop log emitted during planning,
surfaced by ``GeoDataset.explain`` and the CLI ``explain`` command.
"""

from __future__ import annotations

from typing import List


class Explainer:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lines: List[str] = []
        self._depth = 0

    def line(self, msg: str) -> "Explainer":
        if self.enabled:
            self._lines.append("  " * self._depth + str(msg))
        return self

    def push(self, msg: str) -> "Explainer":
        self.line(msg)
        self._depth += 1
        return self

    def pop(self) -> "Explainer":
        self._depth = max(0, self._depth - 1)
        return self

    def kv(self, key: str, value) -> "Explainer":
        """One `key: value` line — the idiom sections like the cache
        participation block are built from."""
        return self.line(f"{key}: {value}")

    def __str__(self) -> str:
        return "\n".join(self._lines)
