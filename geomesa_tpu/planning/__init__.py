from geomesa_tpu.planning.planner import QueryPlanner, QueryPlan, QueryHints  # noqa: F401
from geomesa_tpu.planning.explain import Explainer  # noqa: F401
