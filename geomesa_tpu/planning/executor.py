"""Query executor: run a QueryPlan against an IndexTable.

The runtime role of the reference's scan/reduce pipeline
(QueryPlanner.runQuery -> plan.scan -> resultsToFeatures -> reducer,
QueryPlan.scala:30-94): resolve scan windows, build the fused mask (coarse
window mask & compiled predicate & validity), and run the aggregation kernel —
all inside one jit when the predicate's columns are device-resident, falling
back to vectorized numpy when the filter needs host-only columns (feature-id
strings, exact 64-bit values).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from geomesa_tpu.index.store import FeatureStore, IndexTable
from geomesa_tpu.kernels import density as kdensity
from geomesa_tpu.kernels import knn as kknn
from geomesa_tpu.kernels import masks as kmasks
from geomesa_tpu.kernels import stats_scan as kstats
from geomesa_tpu.planning.planner import QueryPlan
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk


class QueryTimeoutError(RuntimeError):
    """Raised when a scan exceeds ``geomesa.query.timeout`` (the reference's
    ThreadManagement query killer, index/utils/ThreadManagement.scala:28-80)."""


_deadline = threading.local()


@contextlib.contextmanager
def query_deadline(timeout_s: "Optional[float]"):
    """Scope a wall-clock deadline over a query's scan phases. Checked
    between per-shard host passes and around device dispatches — kernels
    themselves are not interruptible, so enforcement is at phase granularity
    (the same guarantee the reference's killer thread gives a blocking scan)."""
    if timeout_s is None:
        yield
        return
    prev = getattr(_deadline, "t", None)
    _deadline.t = time.monotonic() + timeout_s
    try:
        yield
    finally:
        _deadline.t = prev


def check_deadline():
    t = getattr(_deadline, "t", None)
    if t is not None and time.monotonic() > t:
        raise QueryTimeoutError(
            "query exceeded geomesa.query.timeout; narrow the filter or "
            "raise the timeout"
        )


class Executor:
    def __init__(self, store: FeatureStore, mesh=None, prefer_device: bool = True,
                 kernel_fns: Optional[Dict] = None, version_source=None):
        self.store = store
        self.mesh = mesh
        self.prefer_device = prefer_device
        #: jitted-kernel cache shared ACROSS stores (time partitions of one
        #: parent store execute the same plan: one trace/compile, many tables)
        self.kernel_fns = kernel_fns
        #: object whose ``.version`` keys kernel caches (the parent store for
        #: partition children — any partition mutation bumps it)
        self.version_source = version_source or store

    # -- helpers -----------------------------------------------------------
    def _table(self, plan: QueryPlan) -> IndexTable:
        return self.store.tables[plan.index_name]

    def _scan_setup(self, plan: QueryPlan, extra_cols=()):
        """Resolve windows + choose device/host path. Returns a dict bundle."""
        table = self._table(plan)
        if table.n == 0 or plan.is_empty:
            return None
        starts, ends = table.windows(plan.key_plan)
        counts = np.diff(table.shard_bounds).astype(np.int32)
        L = table.shard_len
        needed = list(dict.fromkeys(list(plan.compiled.columns) + list(extra_cols)))
        host_only = [
            c for c in needed
            if not table.has_column(c) or table.is_host_only(c)
        ]
        # per-key sampling needs an exact running counter per key value —
        # host path only (the reference runs it inside the iterator loop).
        # sample_by is meaningless without a sampling rate.
        if plan.hints.sample_by and not plan.hints.sampling:
            raise ValueError("sample_by requires sampling (the 1-in-n rate)")
        # extent-geometry refinement (exact spatial predicates) runs on the
        # host __wkt columns, so the whole mask must be host-resident before
        # aggregation — route such plans through the host path
        use_device = (
            self.prefer_device and not host_only
            and not plan.hints.sample_by
            and plan.compiled.refine is None
        )
        # refine-bearing plans (extent geometries, >2^24 int64 predicates)
        # can still run their COARSE mask on device: the heavy dense scan
        # stays a TPU kernel, the host only refines coarse-true candidates
        # (AggregatingScan.scala:82-116 validate-then-aggregate, split
        # across the device/host boundary)
        coarse_device = (
            self.prefer_device and not host_only
            and plan.compiled.refine is not None
        )
        # selectivity instrumentation: rows the coarse windows admit vs the
        # table size. The audit event pairs this with `hits` so over-scan
        # (candidates >> matches) is visible per query instead of silent.
        plan.__dict__["scanned_rows"] = int(
            np.maximum(ends - starts, 0).sum()
        )
        plan.__dict__["table_rows"] = int(table.n)
        return {
            "table": table, "starts": starts, "ends": ends, "counts": counts,
            "L": L, "needed": needed, "use_device": use_device,
            "coarse_device": coarse_device,
        }

    def _device_coarse_mask(self, plan: QueryPlan, setup) -> np.ndarray:
        """Window mask ∧ coarse predicate as ONE device kernel, packed
        8 rows/byte on device so the host download is n/8 bytes. Returns
        the unpacked [S, L] numpy mask for host refinement."""
        import time as _time

        L = setup["L"]
        Lp = -(-L // 8) * 8

        def agg(cols, m, xp):
            import jax.numpy as jnp

            mp = jnp.pad(m, ((0, 0), (0, Lp - L))) if Lp != L else m
            bits = mp.reshape(m.shape[0], Lp // 8, 8).astype(jnp.uint8)
            w = (2 ** jnp.arange(8, dtype=jnp.uint8))[None, None, :]
            return (bits * w).sum(axis=-1).astype(jnp.uint8)

        t0 = _time.perf_counter()
        packed = np.asarray(
            self._device_mask_and_agg(plan, setup, agg,
                                      cache_key=("coarse_mask",),
                                      apply_sampling=False)
        )
        plan.__dict__["device_coarse_ms"] = (
            plan.__dict__.get("device_coarse_ms", 0.0)
            + (_time.perf_counter() - t0) * 1e3
        )
        bits = np.unpackbits(packed, axis=1, bitorder="little")
        return bits[:, :L].astype(bool)

    def _coarse_or_none(self, plan: QueryPlan, setup) -> Optional[np.ndarray]:
        """Device coarse mask when the plan is eligible, else None (host
        computes the full mask). Falls back loudly, honoring STRICT_DEVICE."""
        if not setup.get("coarse_device"):
            return None
        try:
            return self._device_coarse_mask(plan, setup)
        except Exception as e:
            if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                raise
            logging.getLogger(__name__).warning(
                "device coarse scan failed, computing mask on host: %r", e
            )
            return None

    def _host_mask(self, plan: QueryPlan, setup,
                   coarse: Optional[np.ndarray] = None) -> np.ndarray:
        """[S, L] mask on the host (numpy). ``coarse`` short-circuits the
        window+predicate passes with a device-computed coarse mask."""
        table = setup["table"]
        if coarse is not None:
            mask = coarse
        else:
            wm = kmasks.window_mask_np(
                setup["starts"], setup["ends"], setup["counts"], setup["L"]
            )
            S, L = wm.shape
            pm = np.zeros((S, L), dtype=bool)
            needed = setup["needed"]
            for s in range(table.n_shards):
                check_deadline()
                sl = table.shard_slice(s)
                cols = table.shard_cols(needed, s)
                pm[s, : sl.stop - sl.start] = np.asarray(plan.compiled(cols, np))
            mask = wm & pm
        mask = self._apply_refine(plan, setup, mask)
        S, L = mask.shape
        if plan.hints.sampling and plan.hints.sample_by:
            key = plan.hints.sample_by
            if not table.has_column(key):
                raise KeyError(f"sample-by attribute {key!r} not found")
            col = table.col_sorted(key)
            # exact distinct-value codes for ANY dtype (float truncation or
            # object hashing would merge distinct keys)
            _, codes = np.unique(col, return_inverse=True)
            stacked = np.zeros((S, L), dtype=np.int64)
            for s in range(table.n_shards):
                sl = table.shard_slice(s)
                stacked[s, : sl.stop - sl.start] = codes[sl]
            mask = kmasks.sampling_mask_by_key(
                mask, plan.hints.sampling, stacked
            )
        elif plan.hints.sampling:
            mask = kmasks.sampling_mask(mask, plan.hints.sampling, np)
        return mask

    def _apply_refine(self, plan: QueryPlan, setup, mask: np.ndarray) -> np.ndarray:
        """Exact-predicate refinement pass (FastFilterFactory.scala:395
        parity): re-evaluate the exact filter tree on coarse-true candidate
        rows using the host ``__wkt`` columns. Only clears mask bits, so
        fused visibility/window masks are preserved. Runs before sampling —
        the 1-in-n counter must see exact matches only."""
        ref = plan.compiled.refine
        if ref is None:
            return mask
        table = setup["table"]
        names = list(dict.fromkeys(
            list(plan.compiled.columns) + list(plan.compiled.refine_columns or [])
        ))
        for s in range(table.n_shards):
            check_deadline()
            sl = table.shard_slice(s)
            row = mask[s, : sl.stop - sl.start]
            if not row.any():
                continue
            idx = np.nonzero(row)[0]
            cols = table.shard_rows_cols(names, s, idx)
            keep = plan.compiled.refine_rows(cols, len(idx))
            row[idx[~keep]] = False
        return mask

    def _device_mask_and_agg(self, plan: QueryPlan, setup, agg_fn, agg_cols=(),
                             cache_key=None, apply_sampling=True, extra=()):
        """Run mask + aggregation in one jit. ``agg_fn(cols, mask, xp,
        *extra)`` — ``extra`` values are TRACED jit arguments (scalar query
        parameters like a kNN origin), so one compiled kernel serves every
        value instead of baking them in as constants.

        ``cache_key`` caches the jitted kernel on the plan so re-running the
        same plan (benchmarks, pagination) skips retracing."""
        import jax
        import jax.numpy as jnp

        table = setup["table"]
        dev_cols = table.device_columns(
            tuple(setup["needed"]) + tuple(agg_cols), self._sharding()
        )
        L = setup["L"]
        compiled = plan.compiled
        # coarse-mask kernels must NOT sample: sampling runs once on the
        # host, AFTER refinement (the 1-in-n counter sees exact matches)
        sampling = plan.hints.sampling if apply_sampling else None

        # Two caches with different lifetimes:
        # 1. the jitted kernel — reusable across API calls (same predicate
        #    text + auths, via cache_token) AND across time-partition tables
        #    of one store (same plan, same shapes). Keyed by the version of
        #    `version_source` (the parent store for partition children) so a
        #    predicate recompiled under grown dictionaries never reuses a
        #    stale closure.
        # 2. the device-resident window arrays — strictly per (store,
        #    version): windows differ per partition and per mutation.
        token = plan.__dict__.get("cache_token")
        fn_cache = fn_key = None
        if cache_key is not None:
            K = setup["starts"].shape[1]
            if token is not None:
                fn_cache = (
                    self.kernel_fns
                    if self.kernel_fns is not None
                    else self.version_source.__dict__.setdefault("_kernel_fns", {})
                )
                fn_key = (cache_key, L, K, sampling, token, plan.index_name,
                          self.version_source.version)
            else:  # raw-IR plan: cache on the plan (shared across partitions)
                fn_cache = plan.__dict__.setdefault("_kernel_fns", {})
                fn_key = (cache_key, L, K, sampling)
        go = fn_cache.get(fn_key) if fn_cache is not None else None
        if go is None:

            @jax.jit
            def go(cols, starts, ends, counts, extra):
                m = kmasks.window_mask(starts, ends, counts, L)
                m = m & compiled(cols, jnp)
                if sampling:
                    m = kmasks.sampling_mask(m, sampling, jnp)
                return agg_fn(cols, m, jnp, *extra)

            if fn_cache is not None:
                if len(fn_cache) >= 64:  # bound compiled-kernel growth
                    fn_cache.clear()
                fn_cache[fn_key] = go
        # pre-placed window arrays: repeated same-plan runs (pagination,
        # benchmarks) shouldn't re-upload per call — host link latency can
        # dwarf the kernel. Unlike the jitted fn, window DATA is plan- and
        # store-specific: token-less fn_keys carry no plan identity, so
        # their windows must live on the plan (keyed by store uid), never
        # in a store-level cache another plan could hit.
        win = None
        if fn_key is not None:
            # window_token lets plans that share a kernel but differ in
            # their scan windows (knn radius expansion) key window arrays
            # separately without forcing a retrace
            wtoken = plan.__dict__.get("window_token", token)
            if token is not None:
                wcache = self.store.__dict__.setdefault("_win_cache", {})
            else:
                wcache = plan.__dict__.setdefault("_win_cache", {})
            wkey = (fn_key, wtoken, self.store.uid, self.store.version)
            win = wcache.get(wkey)
        if win is None:
            win = (
                jax.device_put(setup["starts"]),
                jax.device_put(setup["ends"]),
                jax.device_put(setup["counts"]),
            )
            if fn_key is not None:
                if len(wcache) >= 64:
                    wcache.clear()
                wcache[wkey] = win
        d_starts, d_ends, d_counts = win
        from geomesa_tpu.kernels import pallas_kernels as pk

        # trace-time context: under a sharded mesh, polygon pallas kernels
        # re-dispatch through an inner shard_map over the mesh (bare
        # pallas_call has no GSPMD partitioning rule)
        with pk.sharded_execution(self.mesh):
            return go(dev_cols, d_starts, d_ends, d_counts, tuple(extra))

    def _sharding(self):
        if self.mesh is None:
            return None
        # cached: device_columns keys its upload cache by id(sharding), so a
        # fresh NamedSharding per call would re-upload every column per query
        sh = self.__dict__.get("_sharding_cache")
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec

            sh = NamedSharding(self.mesh, PartitionSpec("shard", None))
            self.__dict__["_sharding_cache"] = sh
        return sh

    # -- bin-space (sequence) parallelism ---------------------------------
    def _binspace_mesh(self):
        """The mesh, when it has a 'bin' axis (time-bin sequence axis)."""
        m = self.mesh
        if m is not None and "bin" in m.axis_names and "shard" in m.axis_names:
            return m
        return None

    def _binspace_run(self, plan: QueryPlan, setup, agg_fn, agg_cols,
                      cache_key):
        """Additive aggregate over the 2-D (shard, bin) mesh; None if the
        layout does not fit (caller falls through to the GSPMD path)."""
        from geomesa_tpu.parallel import binspace

        mesh = self._binspace_mesh()
        table = setup["table"]
        if (
            mesh is None
            or plan.hints.sampling  # sampling's running index is global
            or table.n_shards % mesh.shape["shard"] != 0
        ):
            return None
        import jax

        stream = int(os.environ.get("GEOMESA_BIN_STREAM_CHUNKS", "1"))
        n_bin = mesh.shape["bin"]
        starts, ends = binspace.pad_windows(
            setup["starts"], setup["ends"], n_bin * stream
        )
        # cached shardings: device_columns keys its upload cache by
        # id(sharding) — fresh NamedShardings would re-upload per query
        sh = self.__dict__.get("_binspace_placements")
        if sh is None:
            sh = binspace.placements(mesh)
            self.__dict__["_binspace_placements"] = sh
        col_sh, win_sh, cnt_sh = sh
        names = tuple(dict.fromkeys(list(setup["needed"]) + list(agg_cols)))
        dev_cols = table.device_columns(names, col_sh)
        L = setup["L"]
        token = plan.__dict__.get("cache_token")
        if token is not None and cache_key is not None:
            cache = self.store.__dict__.setdefault("_kernel_cache", {})
            key = ("binspace", cache_key, L, starts.shape[1], stream, token,
                   plan.index_name, self.store.version)
        else:  # token-less plan: cache on the plan (pagination, benchmarks)
            cache = plan.__dict__.setdefault("_kernel_cache", {})
            key = ("binspace", cache_key, L, starts.shape[1], stream)
        fn = cache.get(key)
        if fn is None:
            fn = binspace.build_bin_parallel(
                mesh, sorted(dev_cols), L, plan.compiled, agg_fn, stream
            )
            if len(cache) >= 64:
                cache.clear()
            cache[key] = fn
        return fn(
            {k: dev_cols[k] for k in sorted(dev_cols)},
            jax.device_put(starts.astype(np.int32), win_sh),
            jax.device_put(ends.astype(np.int32), win_sh),
            jax.device_put(setup["counts"].astype(np.int32), cnt_sh),
        )

    def _run(self, plan: QueryPlan, agg_fn_dev, agg_fn_host, agg_cols=(),
             cache_key=None, additive=False, extra=()):
        check_deadline()
        setup = self._scan_setup(plan, agg_cols)
        if setup is None:
            return None
        if setup["use_device"]:
            if additive:
                try:
                    out = self._binspace_run(
                        plan, setup, agg_fn_dev, agg_cols, cache_key
                    )
                    if out is not None:
                        return out
                except Exception as e:
                    if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                        raise
                    # binspace-specific failure: the 1-D GSPMD device path
                    # below is still viable — don't drop to the host runner
                    logging.getLogger(__name__).warning(
                        "binspace scan failed, trying GSPMD path: %r", e
                    )
            try:
                return self._device_mask_and_agg(
                    plan, setup, agg_fn_dev, agg_cols, cache_key, extra=extra
                )
            except Exception as e:
                if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                    raise
                # graceful degradation (the reference's remoteFilter=false /
                # Bigtable path): fall back to the host runner — loudly, so a
                # permanent fallback is never an invisible perf cliff
                logging.getLogger(__name__).warning(
                    "device scan failed, falling back to host: %r", e
                )
        mask = self._host_mask(plan, setup, self._coarse_or_none(plan, setup))
        table = setup["table"]
        cols = {}
        for c in set(list(setup["needed"]) + list(agg_cols)):
            if table.has_column(c):
                L = setup["L"]
                full = table.col_sorted(c)
                stacked = np.zeros((table.n_shards, L), dtype=full.dtype)
                for s in range(table.n_shards):
                    sl = table.shard_slice(s)
                    stacked[s, : sl.stop - sl.start] = full[sl]
                cols[c] = stacked
        return agg_fn_host(cols, mask, np, *extra)

    # -- public operations --------------------------------------------------
    def count(self, plan: QueryPlan) -> int:
        out = self._run(
            plan,
            lambda cols, m, xp: m.sum(),
            lambda cols, m, xp: m.sum(),
            cache_key=("count",),
            additive=True,
        )
        return 0 if out is None else int(out)

    def features(self, plan: QueryPlan) -> ColumnBatch:
        """Matching rows as a host ColumnBatch (sort/limit applied by caller)."""
        setup = self._scan_setup(plan)
        if setup is None:
            return ColumnBatch({}, 0)
        mask = None
        if setup["use_device"]:
            try:
                mask = np.asarray(
                    self._device_mask_and_agg(
                        plan, setup, lambda cols, m, xp: m, cache_key=("mask",)
                    )
                )
            except Exception as e:
                if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                    raise
                # same graceful degradation as _run(): loud host fallback
                logging.getLogger(__name__).warning(
                    "device scan failed, falling back to host: %r", e
                )
        if mask is None:
            mask = self._host_mask(
                plan, setup, self._coarse_or_none(plan, setup)
            )
        return setup["table"].host_gather(mask.reshape(-1))

    def features_iter(self, plan: QueryPlan, batch_rows: Optional[int] = None):
        """Matching rows as a stream of ColumnBatch chunks (ArrowScan's
        batched-yield contract, AggregatingScan.scala:82-116). A single
        table materializes its result once and re-slices it — the streaming
        value on an unpartitioned store is wire chunking, not peak memory."""
        batch_rows = batch_rows or int(
            os.environ.get("GEOMESA_ARROW_BATCH_ROWS", 1_000_000)
        )
        out = self.features(plan)
        n = out.n
        if plan.hints.max_features is not None and not plan.hints.sort_by:
            n = min(n, plan.hints.max_features)
        for lo in range(0, n, batch_rows):
            hi = min(lo + batch_rows, n)
            yield ColumnBatch(
                {k: v[lo:hi] for k, v in out.columns.items()}, hi - lo
            )

    def density(self, plan: QueryPlan, bbox, width: int, height: int,
                weight: Optional[str] = None, as_numpy: bool = True):
        """Density grid. ``as_numpy=False`` leaves the grid on device (no
        host transfer) — for benchmark loops and device-side composition."""
        geom = self.store.ft.geom_field
        xc, yc = geom + "__x", geom + "__y"
        agg_cols = [xc, yc] + ([weight] if weight else [])

        def agg(cols, m, xp):
            w = cols.get(weight) if weight else None
            return kdensity.density_grid(
                cols[xc], cols[yc], m, bbox, width, height, w, xp
            )

        out = self._run(
            plan, agg, agg, agg_cols,
            cache_key=("density", tuple(bbox), width, height, weight),
            additive=True,
        )
        if out is None:
            return np.zeros((height, width), np.float32)
        return np.asarray(out) if as_numpy else out

    def stats(self, plan: QueryPlan, stat: sk.Stat) -> sk.Stat:
        table = self._table(plan)
        host_only = {
            c for c in table.column_names() if table.is_host_only(c)
        }
        vocab_sizes = {a: max(len(d), 1) for a, d in self.store.dicts.items()}
        leaf_attrs = []
        for leaf in kstats._leaf_stats(stat):
            if isinstance(leaf, sk.DescriptiveStats):
                leaf_attrs.extend(leaf.attributes)
            elif getattr(leaf, "attribute", None) is not None:
                leaf_attrs.append(leaf.attribute)
        agg_cols = []
        for a in leaf_attrs:
            if table.has_column(a + "__x"):
                agg_cols += [a + "__x", a + "__y"]
            elif table.has_column(a):
                agg_cols.append(a)
        enum_ok = all(
            leaf.attribute in self.store.dicts
            for leaf in kstats._leaf_stats(stat)
            if leaf.kind in ("enumeration", "topk")
        )
        if kstats.device_supported(stat, host_only) and enum_ok:
            partials = self._run(
                plan,
                lambda cols, m, xp: kstats.device_update(stat, cols, m, xp, vocab_sizes),
                lambda cols, m, xp: kstats.device_update(stat, cols, m, xp, vocab_sizes),
                agg_cols,
            )
            if partials is not None:
                kstats.absorb_partials(stat, partials, self.store.dicts)
            return stat
        batch = self.features(plan)
        if batch.n:
            stat.observe(batch.columns)
            kstats.decode_enum_keys(stat, self.store.dicts)
        return stat

    def knn(self, plan: QueryPlan, qx: float, qy: float, k: int, boxes=None):
        """k nearest to (qx, qy) among plan matches. ``boxes`` (optional):
        up to two (x0, y0, x1, y1) restriction boxes applied INSIDE the
        aggregation as traced scalars — the expanding-radius search passes
        its search box here (and via the plan's windows) instead of baking
        it into the compiled predicate, so one kernel serves every location
        and radius."""
        geom = self.store.ft.geom_field
        xc, yc = geom + "__x", geom + "__y"

        def agg(cols, m, xp, qx_, qy_, *bb):
            if bb:
                x, y = cols[xc], cols[yc]
                inb = None
                for i in range(0, len(bb), 4):
                    x0, y0, x1, y1 = bb[i:i + 4]
                    mi = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
                    inb = mi if inb is None else (inb | mi)
                m = m & inb
            return kknn.knn_indices(cols[xc], cols[yc], m, qx_, qy_, k, xp)

        extra = [np.float32(qx), np.float32(qy)]
        nb = 0
        if boxes:
            for b in boxes:
                extra.extend(np.float32(v) for v in b)
            nb = len(boxes)
        out = self._run(
            plan, agg, agg, [xc, yc], cache_key=("knn", int(k), nb),
            extra=tuple(extra),
        )
        if out is None:
            return np.zeros(0, np.int64), np.zeros(0)
        idx, d = np.asarray(out[0]), np.asarray(out[1])
        keep = np.isfinite(d)
        return idx[keep], d[keep]
