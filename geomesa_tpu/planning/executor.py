"""Query executor: run a QueryPlan against an IndexTable.

The runtime role of the reference's scan/reduce pipeline
(QueryPlanner.runQuery -> plan.scan -> resultsToFeatures -> reducer,
QueryPlan.scala:30-94): resolve scan windows, build the fused mask (coarse
window mask & compiled predicate & validity), and run the aggregation kernel —
all inside one jit when the predicate's columns are device-resident, falling
back to vectorized numpy when the filter needs host-only columns (feature-id
strings, exact 64-bit values).
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Any, Dict, Optional

import numpy as np

from geomesa_tpu import config, metrics, tracing, utilization
from geomesa_tpu.index.store import FeatureStore, IndexTable
from geomesa_tpu.kernels import density as kdensity
from geomesa_tpu.kernels import knn as kknn
from geomesa_tpu.kernels import masks as kmasks
from geomesa_tpu.kernels import stats_scan as kstats
from geomesa_tpu.kernels.registry import (
    KernelRegistry, dict_fingerprint, enable_persistent_cache,
)
from geomesa_tpu.planning.planner import QueryPlan
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk


# QueryTimeoutError is defined in the resilience layer (resilience.py) and
# re-exported here: the deadline primitive moved there so remote edges can
# propagate the remaining budget, while existing callers keep importing the
# error (and query_deadline) from this module.
from geomesa_tpu.resilience import (  # noqa: E402  (re-export)
    QueryTimeoutError, check_deadline, deadline_scope,
)


# -- window-compacted scan layout -------------------------------------------
# Device scatter costs ~6.7 ns per TOUCHED row regardless of masking
# (docs/SCALE.md cost model), so a density scan over the full padded table
# pays for every row even when the z-windows admit a few percent. The
# compacted path gathers ONLY the window rows — as chunked slabs, because
# slice-sized gathers run at HBM bandwidth (~100 GiB/s measured) while
# per-element gathers crawl at ~7.5 ns/element — and aggregates over the
# [C, B] compact layout. Selective queries then scale with rows *scanned*,
# not rows *stored* (the same property the reference gets from range scans:
# AbstractBatchScan.scala:32 only ever reads the planned ranges).
_SLAB_GATHER_FNS: Dict[int, Any] = {}


def _slab_gather_fn(B: int):
    """jit'd [C]-chunk slab gather (vmapped dynamic_slice of length B)."""
    fn = _SLAB_GATHER_FNS.get(B)
    if fn is None:
        import jax

        @jax.jit
        def fn(flat, gstart):
            return jax.vmap(
                lambda s: jax.lax.dynamic_slice(flat, (s,), (B,))
            )(gstart)

        _SLAB_GATHER_FNS[B] = fn
    return fn


@contextlib.contextmanager
def query_deadline(timeout_s: "Optional[float]"):
    """Scope a wall-clock deadline over a query's scan phases (built on
    ``resilience.deadline_scope``). Checked between per-shard host passes,
    around device dispatches, and per partition — kernels themselves are not
    interruptible, so enforcement is at phase granularity (the same guarantee
    the reference's killer thread gives a blocking scan). Remote edges
    (sidecar client) read ``resilience.current_deadline()`` to tighten their
    per-call timeouts to the remaining budget."""
    with deadline_scope(timeout_s):
        yield


class Executor:
    def __init__(self, store: FeatureStore, mesh=None, prefer_device: bool = True,
                 kernel_fns: Optional[Dict] = None, version_source=None,
                 device=None):
        self.store = store
        self.mesh = mesh
        self.prefer_device = prefer_device
        #: optional jax device PIN (mutually exclusive with ``mesh``): every
        #: column/window/schedule placement commits to this one device, so
        #: the sharded partitioned scan can run partition i on device d and
        #: the serving pool can give each dispatch thread its own device
        #: (one jit thread per device — docs/SCALE.md, docs/SERVING.md).
        #: Kernel registry keys stay device-free: one traced callable
        #: serves every device (jax specializes the executable per device
        #: internally without re-tracing), so pinning never recompiles.
        self.device = device
        #: jitted-kernel LRU shared ACROSS stores (time partitions of one
        #: parent store execute the same plan: one trace/compile, many tables)
        self.kernel_fns = kernel_fns
        #: object hosting the shared kernel registry and version-keyed host
        #: caches (the parent store for partition children). Kernel KEYS are
        #: version-stable (a mutation never recompiles — docs/PERF.md);
        #: window/verdict DATA caches stay keyed by ``.version``.
        self.version_source = version_source or store
        enable_persistent_cache()  # geomesa.compile.cache.dir (idempotent)

    # -- helpers -----------------------------------------------------------
    def _table(self, plan: QueryPlan) -> IndexTable:
        return self.store.tables[plan.index_name]

    def kernel_registry(self) -> KernelRegistry:
        """The shared compiled-kernel LRU: one per parent store, shared by
        every partition child and every aggregate-cache cell query (the
        ROADMAP per-cell kernel-token item)."""
        if self.kernel_fns is not None:
            return self.kernel_fns
        reg = self.version_source.__dict__.get("_kernel_registry")
        if reg is None:
            reg = KernelRegistry()
            self.version_source.__dict__["_kernel_registry"] = reg
        return reg

    @staticmethod
    def _plan_registry(plan: QueryPlan) -> KernelRegistry:
        """Token-less (raw-IR) plans cache kernels on the plan itself —
        still LRU-managed so pagination/benchmark loops never hit the old
        clear-on-overflow wipe."""
        reg = plan.__dict__.get("_kernel_fns")
        if reg is None:
            reg = plan.__dict__["_kernel_fns"] = KernelRegistry()
        return reg

    def _dict_fp(self):
        """Dictionary-growth fingerprint: the ONLY store change that can
        invalidate a compiled predicate closure (string codes are resolved
        at compile time). Replaces the store version in kernel keys."""
        return dict_fingerprint(self.store.dicts)

    def _scan_setup(self, plan: QueryPlan, extra_cols=()):
        """Resolve windows + choose device/host path. Returns a dict bundle."""
        table = self._table(plan)
        if table.n == 0 or plan.is_empty:
            return None
        # Resolved windows are pure in (key_plan, table contents): cache
        # them so re-running the query — same plan object (pagination,
        # benchmarks, kNN radius loop) or a fresh plan of the same text
        # (cache_token) — skips the per-shard searchsorted sweep, which at
        # 20M rows costs ~90 ms/query, dwarfing the device kernel it feeds.
        rkey = ("win", self.store.uid, self.store.version, plan.index_name,
                plan.__dict__.get("window_token"),
                config.COMPACT_BUCKETING.to_bool(),
                config.COMPACT_BUCKET_FLOOR.to_int())
        cache, rkey = self._resolve_cache(plan, rkey)
        hit = cache.get(rkey)
        if hit is not None:
            starts, ends = hit
        else:
            starts, ends = table.windows(plan.key_plan)
            if len(cache) >= 64:
                cache.clear()
            cache[rkey] = (starts, ends)
        counts = np.diff(table.shard_bounds).astype(np.int32)
        L = table.shard_len
        needed = list(dict.fromkeys(list(plan.compiled.columns) + list(extra_cols)))
        # sample_by is meaningless without a sampling rate.
        if plan.hints.sample_by and not plan.hints.sampling:
            raise ValueError("sample_by requires sampling (the 1-in-n rate)")
        # per-key sampling device modes (sort-free by design — device sort
        # compiles pathologically on this TPU toolchain):
        #   "exact": dictionary-coded key with a small vocabulary — one
        #     cumsum pass per code, exact per-key counters;
        #   "hash":  any other device-resident int32 key (large vocab,
        #     Integer attrs) — keys hash into SAMPLE_HASH_BUCKETS groups
        #     sharing counters (documented approximation; the host twin
        #     hashes identically so results are backend-independent).
        # float/int64/object keys stay on the host's exact counter (float
        # keys would merge distinct values at f32).
        sb = plan.hints.sample_by
        sb_mode, sb_off, sb_span_vocab = None, 0, 0
        if sb and table.has_column(sb) and not table.is_host_only(sb) \
                and table.dtype_of(sb) == np.int32:
            if sb in self.store.dicts:
                if 0 < len(self.store.dicts[sb]) <= 256:
                    sb_mode = "exact"
                elif self.prefer_device \
                        and (config.SAMPLE_HASH_BUCKETS.to_int() or 0) > 0:
                    # the approximation only buys anything when a device
                    # scan runs; host-only stores keep the exact counter
                    sb_mode = "hash"
            else:
                # raw int keys: a small VALUE SPAN runs the exact
                # per-code kernel on offset values (preserving the
                # reference's exact per-key counters); wide key spaces
                # hash-bucket. min/max cached per store version.
                span_cache = self.store.__dict__.setdefault("_sb_span", {})
                skey = (sb, plan.index_name, self.version_source.version)
                rng = span_cache.get(skey)
                if rng is None:
                    col = table.col_sorted(sb)
                    rng = ((int(col.min()), int(col.max()))
                           if len(col) else (0, -1))
                    if len(span_cache) >= 64:
                        span_cache.clear()
                    span_cache[skey] = rng
                lo_v, hi_v = rng
                if 0 <= hi_v - lo_v < 256:
                    sb_mode, sb_off = "exact-span", lo_v
                    sb_span_vocab = hi_v - lo_v + 1
                elif self.prefer_device \
                        and (config.SAMPLE_HASH_BUCKETS.to_int() or 0) > 0:
                    sb_mode = "hash"
        sb_device = sb_mode is not None
        if sb_device:
            needed = list(dict.fromkeys(needed + [sb]))
        host_only = [
            c for c in needed
            if not table.has_column(c) or table.is_host_only(c)
        ]
        # extent-geometry refinement (exact spatial predicates) runs on the
        # host __wkt columns, so the whole mask must be host-resident before
        # aggregation — route such plans through the host path
        use_device = (
            self.prefer_device and not host_only
            and (sb is None or sb_device)
            and (
                plan.compiled.refine is None
                or plan.compiled.refine_only_if_band
            )
        )
        # refine-bearing plans (extent geometries, >2^24 int64 predicates)
        # can still run their COARSE mask on device: the heavy dense scan
        # stays a TPU kernel, the host only refines coarse-true candidates
        # (AggregatingScan.scala:82-116 validate-then-aggregate, split
        # across the device/host boundary)
        coarse_device = (
            self.prefer_device and not host_only
            and plan.compiled.refine is not None
        )
        # selectivity instrumentation: rows the coarse windows admit vs the
        # table size. The audit event pairs this with `hits` so over-scan
        # (candidates >> matches) is visible per query instead of silent.
        plan.__dict__["scanned_rows"] = int(
            np.maximum(ends - starts, 0).sum()
        )
        plan.__dict__["table_rows"] = int(table.n)
        # the partition prefetcher stages exactly this column set for the
        # NEXT partition while this one executes (partitioned_exec.py)
        plan.__dict__["needed_cols"] = tuple(needed)
        return {
            "table": table, "starts": starts, "ends": ends, "counts": counts,
            "L": L, "needed": needed, "use_device": use_device,
            "coarse_device": coarse_device, "sb_mode": sb_mode,
            "sb_off": sb_off, "sb_span_vocab": sb_span_vocab,
        }

    def _compact_candidates(self, plan: QueryPlan, setup):
        """Window set + chunk size for a compacted scan: (starts, ends, B,
        lens), or None when no window set admits chunking.

        Steady-state cost is per PADDED row, so the chunk size minimizes
        padding (preferring the largest B within 10% — fewer, larger slabs
        gather faster on the one-time pass), over BOTH window resolutions:
        the fine (gap-union-free) set usually admits fewer rows AND gives
        spatially tight chunks (the density pair lists depend on that), so
        it wins any near-tie (the 0.77 bias). Shared by the single-chip
        and mesh compaction descriptors."""
        L = setup["L"]
        ladder = [b for b in (128, 256, 512, 1024, 2048, 4096) if b <= L]

        def _choose(starts, ends):
            """(B, rows, lens) minimizing padded rows for one window set."""
            lens = np.maximum(ends - starts, 0).astype(np.int64)
            if int(lens.sum()) == 0 or not ladder:
                return None
            flat = lens.reshape(-1)
            rows_at = {
                Bc: int((-(-flat // Bc)).sum()) * Bc for Bc in ladder
            }
            override = config.COMPACT_B.to_int() or 0
            if override:
                # clamp the knob into the legal ladder (values off the
                # ladder or > L would break the slab clamp arithmetic)
                B = min(ladder, key=lambda b: abs(b - override))
            else:
                floor_rows = min(rows_at.values())
                B = max(
                    b for b, r in rows_at.items() if r <= 1.10 * floor_rows
                )
            return B, rows_at[B], lens

        cands = []
        coarse = _choose(setup["starts"], setup["ends"])
        if coarse is not None:
            cands.append(
                (coarse[1], 1, setup["starts"], setup["ends"], coarse[0],
                 coarse[2])
            )
        fs, fe = self._fine_windows(plan, setup)
        if fs is not None:
            fine = _choose(fs, fe)
            if fine is not None:
                cands.append(
                    (int(fine[1] * 0.77), 0, fs, fe, fine[0], fine[2])
                )
        if not cands:
            return None
        cands.sort(key=lambda c: (c[0], c[1]))
        _, _, starts, ends, B, lens = cands[0]
        return starts, ends, B, lens

    def _maybe_compact(self, plan: QueryPlan, setup, allowed: bool) -> None:
        """Decide the window-compacted layout for this scan. Sets
        ``setup['compact']`` to a chunk-descriptor dict (or None).

        Chunks are B-row slabs (B = pow2 bucket of the typical window
        length) covering every window, ordered by global position so the
        deterministic sampling counter sees matches in the same order as
        the padded path. ``lo`` handles the end-of-table dynamic_slice
        clamp: valid rows of chunk c live at [lo, lo+valid) and map to
        global rows cstart + lo + i."""
        if "compact" in setup:
            return
        setup["compact"] = None
        if (
            not allowed
            or not setup["use_device"]
            or self.mesh is not None
            or not config.COMPACT_ENABLED.to_bool()
        ):
            return
        table = setup["table"]
        if table.n < (config.COMPACT_MIN_ROWS.to_int() or 0):
            return
        # the descriptor is pure in (resolved windows, table, knobs):
        # memoize it so repeat queries skip the ~1.5 ms argsort/repeat
        # rebuild (it dwarfs the per-call jit dispatch on cached plans)
        ckey = ("compact_desc", self.store.uid, self.store.version,
                plan.index_name, plan.__dict__.get("window_token"),
                config.COMPACT_B.to_int(), config.COMPACT_FRACTION.to_float(),
                config.COMPACT_COVER.to_int())
        ccache, ckey = self._resolve_cache(plan, ckey)
        chit = ccache.get(ckey)
        if chit is not None:
            setup["compact"] = chit or None
            return
        L = setup["L"]
        chosen = self._compact_candidates(plan, setup)
        if chosen is None:
            if len(ccache) >= 64:
                ccache.clear()
            ccache[ckey] = False
            return
        starts, ends, B, lens = chosen
        S, K = starts.shape
        # content-addressed descriptor share (docs/PERF.md "Shared
        # descriptors"): the built descriptor is pure in (resolved window
        # BYTES, B bucket, padded layout), so any other jit site / query
        # text / plan token that resolves the same windows reuses the
        # ~1.5 ms argsort/repeat build instead of duplicating it. Keyed
        # by the bytes, never their hash — a collision would silently
        # scan another query's rows, and equality is the correctness
        # contract (the arrays are small next to the slabs they index).
        share = self.store.__dict__.setdefault("_desc_share", {})
        skey = ("flat", B, S, L, starts.tobytes(), ends.tobytes())
        shit = share.get(skey)
        if shit is not None:
            metrics.inc(metrics.COMPACT_DESC_SHARED)
            if len(ccache) >= 64:
                ccache.clear()
            ccache[ckey] = shit
            setup["compact"] = shit or None
            return
        flat_lens = lens.reshape(-1)
        nc = -(-flat_lens // B)
        C = int(nc.sum())
        frac = config.COMPACT_FRACTION.to_float()
        if C * B >= table.n * (0.5 if frac is None else frac):
            # windows admit most of the table: compaction can't win
            if len(ccache) >= 64:
                ccache.clear()
            ccache[ckey] = False
            if len(share) >= 64:
                share.clear()
            share[skey] = False
            return
        win = np.repeat(np.arange(S * K), nc)
        j = np.arange(C) - np.repeat(np.cumsum(nc) - nc, nc)
        s_of = win // K
        gstart = (
            s_of * L + starts.reshape(-1)[win] + j * B
        ).astype(np.int64)
        valid = np.minimum(flat_lens[win] - j * B, B).astype(np.int32)
        order = np.argsort(gstart, kind="stable")
        gstart, valid = gstart[order], valid[order]
        cstart = np.minimum(gstart, S * L - B)
        lo = (gstart - cstart).astype(np.int32)
        # bucket the chunk count (shared ladder with the MXU pair padding),
        # so partitions of one store reuse few kernel shapes without pow2's
        # 2x row padding (scatter pays per padded row, masked or not)
        from geomesa_tpu.kernels.density_mxu import ladder8

        Cp = ladder8(C)
        if Cp != C:
            pad = Cp - C
            cstart = np.concatenate([cstart, np.zeros(pad, np.int64)])
            lo = np.concatenate([lo, np.zeros(pad, np.int32)])
            valid = np.concatenate([valid, np.zeros(pad, np.int32)])
        desc = {
            "B": B,
            "C": Cp,
            "cstart": cstart.astype(np.int32),
            "lo": lo,
            "valid": valid,
            "whash": hash((starts.tobytes(), ends.tobytes())),
        }
        if len(ccache) >= 64:
            ccache.clear()
        ccache[ckey] = desc
        if len(share) >= 64:
            share.clear()
        share[skey] = desc
        setup["compact"] = desc

    # -- mesh-sharded window compaction -----------------------------------
    def _plain_shard_mesh(self):
        """The mesh, when 'shard' is its only non-trivial axis (the
        binspace 2-D layout has its own path)."""
        m = self.mesh
        if m is None or "shard" not in m.axis_names:
            return None
        other = int(np.prod([
            m.shape[a] for a in m.axis_names if a != "shard"
        ])) if len(m.axis_names) > 1 else 1
        return m if other == 1 else None

    def _mesh_compact_desc(self, plan: QueryPlan, setup, D: int):
        """Per-device compact descriptors for a 'shard'-meshed scan:
        [D, Cp] (cstart, lo, valid) arrays with a UNIFORM padded chunk
        count Cp, chunk starts local to each device's [S/D, L] block —
        every device slab-gathers only its own windows' rows, so a
        multi-chip selective scan costs per row SCANNED per chip, exactly
        like the single-chip compact path. False = compaction can't win
        for these windows (cached)."""
        ckey = ("compact_mesh", self.store.uid, self.store.version,
                plan.index_name, plan.__dict__.get("window_token"), D,
                config.COMPACT_B.to_int(), config.COMPACT_FRACTION.to_float(),
                config.COMPACT_COVER.to_int())
        cache, ckey = self._resolve_cache(plan, ckey)
        hit = cache.get(ckey)
        if hit is not None:
            return hit or None
        table = setup["table"]
        L = setup["L"]
        chosen = self._compact_candidates(plan, setup)
        out = False
        share = self.store.__dict__.setdefault("_desc_share", {})
        skey = None
        if chosen is not None:
            starts, ends, B, lens = chosen
            S, K = starts.shape
            # content-addressed share, bucket-aware (docs/PERF.md "Shared
            # descriptors"): same resolved windows + same (B, S, D)
            # layout => same [D, Cp] descriptor, whatever site/plan asked
            # (keyed by the window BYTES — equality is the correctness
            # contract; S pins the (S, K) factorization of those bytes)
            skey = ("mesh", B, D, S, L, starts.tobytes(), ends.tobytes())
            shit = share.get(skey)
            if shit is not None:
                metrics.inc(metrics.COMPACT_DESC_SHARED)
                if len(cache) >= 64:
                    cache.clear()
                cache[ckey] = shit
                return shit or None
            Sd = S // D
            flat_lens = lens.reshape(-1)
            nc = -(-flat_lens // B)
            C = int(nc.sum())
            c_dev = nc.reshape(D, Sd * K).sum(axis=1)
            from geomesa_tpu.kernels.density_mxu import ladder8

            Cp = ladder8(int(c_dev.max())) if C else 0
            frac = config.COMPACT_FRACTION.to_float()
            frac = 0.5 if frac is None else frac
            if C and Cp * B * D < table.n * frac:
                win = np.repeat(np.arange(S * K), nc)
                j = np.arange(C) - np.repeat(np.cumsum(nc) - nc, nc)
                s_of = win // K
                d_of = s_of // Sd
                gstart = (
                    (s_of - d_of * Sd) * L + starts.reshape(-1)[win] + j * B
                ).astype(np.int64)
                valid = np.minimum(flat_lens[win] - j * B, B).astype(np.int32)
                cstart = np.minimum(gstart, Sd * L - B)
                lo = (gstart - cstart).astype(np.int32)
                # pack into [D, Cp]: chunks of device d land at row d in
                # their global (shard-major) order
                slot = np.arange(C) - np.repeat(
                    np.concatenate(([0], np.cumsum(c_dev)[:-1])), c_dev
                )
                a_cstart = np.zeros((D, Cp), np.int32)
                a_lo = np.zeros((D, Cp), np.int32)
                a_valid = np.zeros((D, Cp), np.int32)
                a_cstart[d_of, slot] = cstart.astype(np.int32)
                a_lo[d_of, slot] = lo
                a_valid[d_of, slot] = valid
                out = {
                    "B": B, "Cp": Cp,
                    "cstart": a_cstart, "lo": a_lo, "valid": a_valid,
                    "whash": hash((starts.tobytes(), ends.tobytes())),
                }
        if len(cache) >= 64:
            cache.clear()
        cache[ckey] = out
        if skey is not None:
            if len(share) >= 64:
                share.clear()
            share[skey] = out
        return out or None

    def _compact_mesh_run(self, plan: QueryPlan, setup, agg_fn, agg_cols,
                          cache_key, extra):
        """Additive aggregate over per-device compacted windows on the
        plain-'shard' mesh (shard_map slab-gather + fused mask + psum).
        None when the layout does not apply (caller falls through to the
        padded GSPMD path)."""
        mesh = self._plain_shard_mesh()
        table = setup["table"]
        if (
            mesh is None
            or not config.COMPACT_ENABLED.to_bool()
            or plan.hints.sampling  # the 1-in-n counter is global
            or table.n < (config.COMPACT_MIN_ROWS.to_int() or 0)
            or table.n_shards % mesh.shape["shard"] != 0
        ):
            return None
        D = mesh.shape["shard"]
        d = self._mesh_compact_desc(plan, setup, D)
        if d is None:
            return None
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:  # older jax: experimental module
            from jax.experimental.shard_map import shard_map

        B, Cp = d["B"], d["Cp"]
        compiled = plan.compiled
        names = tuple(dict.fromkeys(list(setup["needed"]) + list(agg_cols)))
        dev_cols = table.device_columns(names, self._sharding())
        token = plan.__dict__.get("cache_token")
        if token is not None and cache_key is not None:
            fn_cache = self.kernel_registry()
            fn_key = ("compact_mesh", cache_key, B, Cp, D, token,
                      plan.index_name, self._dict_fp())
        else:
            fn_cache = self._plan_registry(plan)
            fn_key = ("compact_mesh", cache_key, B, Cp, D)
        go = fn_cache.get(fn_key)
        if go is None:
            col_names = sorted(names)

            def local(cols, cstart, lo, valid, extra):
                gather = jax.vmap(
                    lambda flat, s: jax.lax.dynamic_slice(flat, (s,), (B,)),
                    in_axes=(None, 0),
                )
                ccols = {
                    k: gather(cols[k].reshape(-1), cstart[0])
                    for k in col_names
                }
                iota = jnp.arange(B, dtype=jnp.int32)[None, :]
                m = (iota >= lo[0][:, None]) & (iota < (lo[0] + valid[0])[:, None])
                m = m & compiled(ccols, jnp)
                if compiled.band is not None:
                    m = m & ~compiled.band(ccols, jnp)
                return jax.lax.psum(agg_fn(ccols, m, jnp, *extra), "shard")

            sm = shard_map(
                local, mesh=mesh,
                in_specs=(
                    {k: P("shard", None) for k in col_names},
                    P("shard", None), P("shard", None), P("shard", None),
                    P(),
                ),
                out_specs=P(),
            )
            go = jax.jit(sm)
            fn_cache.put(fn_key, go)
        wcache = self.store.__dict__.setdefault("_win_cache", {})
        wkey = ("mesh_win", d["whash"], B, Cp, D, self.store.uid,
                self.store.version)
        win = wcache.get(wkey)
        if win is None:
            sh = self._sharding()
            win = tuple(
                jax.device_put(d[k], sh) for k in ("cstart", "lo", "valid")
            )
            if len(wcache) >= 64:
                wcache.clear()
            wcache[wkey] = win
        metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
        with utilization.device_busy(self._devkey() or 0):
            return go(
                {k: dev_cols[k] for k in sorted(names)}, *win, tuple(extra)
            )

    def _resolve_cache(self, plan: QueryPlan, key):
        """Window-resolution cache host: store-level keyed by the plan's
        cache token when the plan is reproducible from query text (so a
        fresh plan of the same query hits), else the plan itself."""
        token = plan.__dict__.get("cache_token")
        if token is not None:
            return (
                self.store.__dict__.setdefault("_win_resolve_cache", {}),
                key + (token,),
            )
        return plan.__dict__.setdefault("_win_resolve_cache", {}), key

    def _fine_windows(self, plan: QueryPlan, setup):
        """Scan windows re-resolved from a RE-COVERED key plan under a much
        larger range budget, with the per-shard window cap lifted to match.

        The planner's default cover (~2000 ranges) leaves each range a
        degrees-wide span of the curve — fine for the padded path, whose
        cost is per stored row, but the compacted path costs per ADMITTED
        row and the MXU density kernel wants spatially TIGHT chunks, so a
        16-64x finer cover pays for itself immediately. Cover + resolve
        run once per (plan, store version) and are cached on the plan.
        (None, None) when disabled or the keyspace can't re-plan."""
        cover = config.COMPACT_COVER.to_int() or 0
        from geomesa_tpu.index import keyspace as ksmod

        if cover <= (config.SCAN_RANGES_TARGET.to_int() or 2000):
            return None, None
        rkey = ("fine", cover, self.store.uid, self.store.version,
                plan.index_name, plan.__dict__.get("window_token"),
                config.COMPACT_BUCKETING.to_bool(),
                config.COMPACT_BUCKET_FLOOR.to_int())
        cache, rkey = self._resolve_cache(plan, rkey)
        hit = cache.get(rkey)
        if hit is not None:
            return hit
        out = (None, None)
        try:
            table = setup["table"]
            with config.SCAN_RANGES_TARGET.scoped(cover), \
                    ksmod.window_cap(cover):
                fine_kp = table.keyspace.plan(self.store.ft, plan.filter)
                if fine_kp is not None:
                    out = table.windows(fine_kp)
        except Exception:
            logging.getLogger(__name__).warning(
                "fine window resolution failed; using the planner windows",
                exc_info=True,
            )
        if len(cache) >= 64:
            cache.clear()
        cache[rkey] = out
        return out

    def _compact_cols(self, setup, names):
        """Window rows of ``names`` as device [C, B] slabs, gathered from
        the (cached) padded device columns and cached per (windows, store
        version, device pin)."""
        d = setup["compact"]
        B, Cp = d["B"], d["C"]
        cache = self.store.__dict__.setdefault("_compact_cache", {})
        key0 = (d["whash"], self.store.uid, self.store.version, B, Cp,
                self._devkey())
        out, missing = {}, []
        for n in names:
            hit = cache.get(key0 + (n,))
            (out.__setitem__(n, hit) if hit is not None else missing.append(n))
        if missing:
            with tracing.span("scan.device_put", compact=True):
                full = setup["table"].device_columns(
                    tuple(missing), self._sharding()
                )
                g = self._put(d["cstart"])
                gather = _slab_gather_fn(B)
                if len(cache) >= 64:
                    cache.clear()
                for n in missing:
                    out[n] = cache[key0 + (n,)] = gather(
                        full[n].reshape(-1), g
                    )
        return out

    def _device_compact_agg(self, plan: QueryPlan, setup, agg_fn, agg_cols=(),
                            cache_key=None, extra=()):
        """Mask + aggregation in one jit over the compacted [C, B] layout.
        Same caching contract as :meth:`_device_mask_and_agg`; band rows are
        always excised (the compact path only serves the exact device
        path), their correction is additive host-side."""
        import jax
        import jax.numpy as jnp

        d = setup["compact"]
        B, Cp = d["B"], d["C"]
        compiled = plan.compiled
        sampling = plan.hints.sampling
        sample_by = plan.hints.sample_by
        sb_mode = setup["sb_mode"]
        sb_off = setup["sb_off"]
        if sb_mode == "exact-span":
            sb_vocab = setup["sb_span_vocab"]
        else:
            sb_vocab = (
                len(self.store.dicts[sample_by])
                if sample_by and sample_by in self.store.dicts else 0
            )
        sb_buckets = config.SAMPLE_HASH_BUCKETS.to_int() or int(config.SAMPLE_HASH_BUCKETS.default)
        names = tuple(dict.fromkeys(list(setup["needed"]) + list(agg_cols)))
        cols = self._compact_cols(setup, names)
        token = plan.__dict__.get("cache_token")
        fn_cache = fn_key = None
        if cache_key is not None:
            if token is not None:
                fn_cache = self.kernel_registry()
                # sb_vocab is baked static below: it belongs in the key now
                # that the store version no longer stands in for it
                fn_key = ("compact", cache_key, B, Cp, sampling, sample_by,
                          sb_mode, sb_off, sb_vocab, sb_buckets, token,
                          plan.index_name, self._dict_fp())
            else:
                fn_cache = self._plan_registry(plan)
                fn_key = ("compact", cache_key, B, Cp, sampling, sample_by,
                          sb_mode, sb_off, sb_vocab, sb_buckets)
        go = fn_cache.get(fn_key) if fn_cache is not None else None
        if go is None:

            @jax.jit
            def go(cols, lo, valid, extra):
                iota = jnp.arange(B, dtype=jnp.int32)[None, :]
                m = (iota >= lo[:, None]) & (iota < (lo + valid)[:, None])
                m = m & compiled(cols, jnp)
                if compiled.band is not None:
                    m = m & ~compiled.band(cols, jnp)
                if sampling and sample_by and sb_mode == "hash":
                    m = kmasks.sampling_mask_by_key_hash(
                        m, sampling, cols[sample_by], sb_buckets, jnp
                    )
                elif sampling and sample_by:
                    m = kmasks.sampling_mask_by_key_device(
                        m, sampling, cols[sample_by] - sb_off, sb_vocab,
                        jnp
                    )
                elif sampling:
                    m = kmasks.sampling_mask(m, sampling, jnp)
                return agg_fn(cols, m, jnp, *extra)

            if fn_cache is not None:
                fn_cache.put(fn_key, go)
                self._note(plan, kernel="trace")
        elif fn_cache is not None:
            self._note(plan, kernel="hit")
        wcache = self.store.__dict__.setdefault("_win_cache", {})
        wkey = ("compact_win", d["whash"], B, Cp, self.store.uid,
                self.store.version, self._devkey())
        win = wcache.get(wkey)
        if win is None:
            win = (self._put(d["lo"]), self._put(d["valid"]))
            if len(wcache) >= 64:
                wcache.clear()
            wcache[wkey] = win
        with tracing.span("scan.kernel", compact=True,
                          site=str(cache_key[0]) if cache_key else None), \
                utilization.device_busy(self._devkey() or 0):
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            return go(cols, win[0], win[1], tuple(extra))

    def _expand_compact_mask(self, setup, cmask) -> np.ndarray:
        """[C, B] compact mask -> [S, L] padded mask (host, vectorized —
        the chunk count can reach tens of thousands under the fine cover,
        so a per-chunk Python loop would cost more than the scan)."""
        d = setup["compact"]
        table = setup["table"]
        S, L = table.n_shards, setup["L"]
        B = d["B"]
        out = np.zeros(S * L, bool)
        cm = np.asarray(cmask)
        cstart = d["cstart"].astype(np.int64)
        lo, valid = d["lo"].astype(np.int64), d["valid"].astype(np.int64)
        n = int(valid.sum())
        if n == 0:
            return out.reshape(S, L)
        # flat positions of every valid (chunk, row) cell, in chunk order
        c_of = np.repeat(np.arange(len(valid)), valid)
        r_of = np.arange(n) - np.repeat(np.cumsum(valid) - valid, valid)
        out[cstart[c_of] + lo[c_of] + r_of] = cm[c_of, lo[c_of] + r_of]
        return out.reshape(S, L)

    def _device_coarse_mask(self, plan: QueryPlan, setup) -> np.ndarray:
        """Window mask ∧ coarse predicate as ONE device kernel, packed
        8 rows/byte on device so the host download is n/8 bytes. Returns
        the unpacked [S, L] numpy mask for host refinement."""
        import time as _time

        L = setup["L"]
        Lp = -(-L // 8) * 8

        def agg(cols, m, xp):
            import jax.numpy as jnp

            mp = jnp.pad(m, ((0, 0), (0, Lp - L))) if Lp != L else m
            bits = mp.reshape(m.shape[0], Lp // 8, 8).astype(jnp.uint8)
            w = (2 ** jnp.arange(8, dtype=jnp.uint8))[None, None, :]
            return (bits * w).sum(axis=-1).astype(jnp.uint8)

        t0 = _time.perf_counter()
        packed = np.asarray(
            self._device_mask_and_agg(plan, setup, agg,
                                      cache_key=("coarse_mask",),
                                      apply_sampling=False,
                                      excise_band=False)
        )
        plan.__dict__["device_coarse_ms"] = (
            plan.__dict__.get("device_coarse_ms", 0.0)
            + (_time.perf_counter() - t0) * 1e3
        )
        bits = np.unpackbits(packed, axis=1, bitorder="little")
        return bits[:, :L].astype(bool)

    def _band_info(self, plan: QueryPlan, setup):
        """f32-uncertainty resolution for the device path. The device
        kernel always runs on ``mask ∧ ¬band`` (band rows excised), which
        is exact for every non-band row. This host pass — one vectorized
        sweep per (plan token, store version), cached — finds the band
        rows inside the scan windows, evaluates the EXACT f64 predicate on
        them, and returns the kept rows' master indices (usually an empty
        array: at 20M uniform doubles a round query bound collides with
        ~2-3 rows). Additive aggregates add these rows' contribution to
        the device partial; other ops fall back when any survive."""
        compiled = plan.compiled
        if compiled.band is None:
            return None
        token = plan.__dict__.get("cache_token")
        vc = (
            self.version_source.__dict__.setdefault("_band_verdicts", {})
            if token is not None
            else plan.__dict__.setdefault("_band_verdicts", {})
        )
        # the verdict depends on the SCAN WINDOWS too (kNN reuses one token
        # across expanding boxes): fingerprint them into the key
        vkey = (
            token, self.store.uid, self.store.version,
            hash((setup["starts"].tobytes(), setup["ends"].tobytes())),
        )
        hit = vc.get(vkey)
        if hit is not None:
            return hit
        table = setup["table"]
        names = list(dict.fromkeys(
            list(compiled.columns) + list(compiled.refine_columns or [])
        ))
        full = {
            n: table.col_sorted(n) for n in names if table.has_column(n)
        }
        band = np.asarray(compiled.band(full, np)).reshape(-1)
        idx = np.nonzero(band)[0]
        if len(idx):
            # inside the scan windows? (vectorized: [n_band, K] broadcast —
            # equality predicates can band millions of rows)
            s_of = np.clip(
                np.searchsorted(table.shard_bounds, idx, side="right") - 1,
                0, table.n_shards - 1,
            )
            local = (idx - table.shard_bounds[s_of])[:, None]
            starts, ends = setup["starts"], setup["ends"]
            inw = (
                (starts[s_of] <= local) & (local < ends[s_of])
            ).any(axis=1)
            idx = idx[inw]
        if len(idx):
            rows = {n: v[idx] for n, v in full.items()}
            # master columns for names stored only via the permutation
            keep = np.asarray(
                (compiled.refine or compiled.fn)(rows, np)
            ).reshape(-1)
            if keep.ndim == 0:
                keep = np.full(len(idx), bool(keep))
            idx = idx[keep.astype(bool)]
        info = idx.astype(np.int64)  # sorted-order row positions, maybe empty
        if len(vc) >= 256:
            vc.clear()
        vc[vkey] = info
        return info

    def _band_correction(self, plan: QueryPlan, setup, info, agg_fn_host,
                         agg_cols, extra):
        """Exact contribution of the surviving band rows, shaped for
        additive combination with the device partial."""
        if info is None or len(info) == 0:
            return None
        table = setup["table"]
        names = dict.fromkeys(
            list(setup["needed"]) + list(agg_cols)
        )
        rows = {}
        master_rows = table.order[info]
        for n in names:
            kc = table.key_columns.get(n)
            if kc is not None:
                rows[n] = kc[info][None, :]
            elif table.has_column(n):
                rows[n] = table._master[n][master_rows][None, :]
        mask = np.ones((1, len(info)), bool)
        return agg_fn_host(rows, mask, np, *extra)

    def _coarse_or_none(self, plan: QueryPlan, setup) -> Optional[np.ndarray]:
        """Device coarse mask when the plan is eligible, else None (host
        computes the full mask). Falls back loudly, honoring STRICT_DEVICE."""
        if not setup.get("coarse_device"):
            return None
        try:
            return self._device_coarse_mask(plan, setup)
        except Exception as e:
            if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                raise
            logging.getLogger(__name__).warning(
                "device coarse scan failed, computing mask on host: %r", e
            )
            return None

    def _host_mask(self, plan: QueryPlan, setup,
                   coarse: Optional[np.ndarray] = None) -> np.ndarray:
        """[S, L] mask on the host (numpy). ``coarse`` short-circuits the
        window+predicate passes with a device-computed coarse mask."""
        table = setup["table"]
        if coarse is not None:
            mask = coarse
        else:
            wm = kmasks.window_mask_np(
                setup["starts"], setup["ends"], setup["counts"], setup["L"]
            )
            S, L = wm.shape
            pm = np.zeros((S, L), dtype=bool)
            needed = setup["needed"]
            for s in range(table.n_shards):
                check_deadline()
                sl = table.shard_slice(s)
                cols = table.shard_cols(needed, s)
                pm[s, : sl.stop - sl.start] = np.asarray(plan.compiled(cols, np))
            mask = wm & pm
        # band-bearing coarse masks evaluate at f32 on BOTH backends (so
        # device and host mean the same thing); the exact-f64 refine pass
        # always restores boundary exactness on candidates
        mask = self._apply_refine(plan, setup, mask)
        S, L = mask.shape
        if plan.hints.sampling and plan.hints.sample_by:
            key = plan.hints.sample_by
            if not table.has_column(key):
                raise KeyError(f"sample-by attribute {key!r} not found")
            col = table.col_sorted(key)
            if setup.get("sb_mode") == "hash":
                # backend parity: keys the DEVICE would hash-bucket are
                # hash-bucketed here too (same mixer, xp=numpy), so a
                # host fallback never changes which rows are sampled
                stacked = np.zeros((S, L), dtype=np.int32)
                for s in range(table.n_shards):
                    sl = table.shard_slice(s)
                    stacked[s, : sl.stop - sl.start] = col[sl]
                mask = kmasks.sampling_mask_by_key_hash(
                    mask, plan.hints.sampling, stacked,
                    config.SAMPLE_HASH_BUCKETS.to_int() or int(config.SAMPLE_HASH_BUCKETS.default), np,
                )
            else:
                # exact distinct-value codes for ANY dtype (float
                # truncation or object hashing would merge distinct keys)
                _, codes = np.unique(col, return_inverse=True)
                stacked = np.zeros((S, L), dtype=np.int64)
                for s in range(table.n_shards):
                    sl = table.shard_slice(s)
                    stacked[s, : sl.stop - sl.start] = codes[sl]
                mask = kmasks.sampling_mask_by_key(
                    mask, plan.hints.sampling, stacked
                )
        elif plan.hints.sampling:
            mask = kmasks.sampling_mask(mask, plan.hints.sampling, np)
        return mask

    def _apply_refine(self, plan: QueryPlan, setup, mask: np.ndarray) -> np.ndarray:
        """Exact-predicate refinement pass (FastFilterFactory.scala:395
        parity): re-evaluate the exact filter tree on coarse-true candidate
        rows using the host ``__wkt`` columns. Only clears mask bits, so
        fused visibility/window masks are preserved. Runs before sampling —
        the 1-in-n counter must see exact matches only."""
        ref = plan.compiled.refine
        if ref is None:
            return mask
        table = setup["table"]
        names = list(dict.fromkeys(
            list(plan.compiled.columns) + list(plan.compiled.refine_columns or [])
        ))
        for s in range(table.n_shards):
            check_deadline()
            sl = table.shard_slice(s)
            row = mask[s, : sl.stop - sl.start]
            if not row.any():
                continue
            idx = np.nonzero(row)[0]
            cols = table.shard_rows_cols(names, s, idx)
            keep = plan.compiled.refine_rows(cols, len(idx))
            row[idx[~keep]] = False
        return mask

    def _device_mask_and_agg(self, plan: QueryPlan, setup, agg_fn, agg_cols=(),
                             cache_key=None, apply_sampling=True, extra=(),
                             excise_band=True):
        """Run mask + aggregation in one jit. ``agg_fn(cols, mask, xp,
        *extra)`` — ``extra`` values are TRACED jit arguments (scalar query
        parameters like a kNN origin), so one compiled kernel serves every
        value instead of baking them in as constants.

        ``cache_key`` caches the jitted kernel on the plan so re-running the
        same plan (benchmarks, pagination) skips retracing."""
        import jax
        import jax.numpy as jnp

        table = setup["table"]
        with tracing.span("scan.device_put"):
            dev_cols = table.device_columns(
                tuple(setup["needed"]) + tuple(agg_cols), self._sharding()
            )
        L = setup["L"]
        compiled = plan.compiled
        # coarse-mask kernels must NOT sample: sampling runs once on the
        # host, AFTER refinement (the 1-in-n counter sees exact matches)
        sampling = plan.hints.sampling if apply_sampling else None
        sample_by = plan.hints.sample_by if apply_sampling else None
        sb_mode = setup["sb_mode"] if apply_sampling else None
        sb_off = setup["sb_off"]
        if sb_mode == "exact-span":
            sb_vocab = setup["sb_span_vocab"]
        else:
            sb_vocab = (
                len(self.store.dicts[sample_by])
                if sample_by and sample_by in self.store.dicts else 0
            )
        sb_buckets = config.SAMPLE_HASH_BUCKETS.to_int() or int(config.SAMPLE_HASH_BUCKETS.default)

        # Two caches with different lifetimes:
        # 1. the jitted kernel — reusable across API calls (same predicate
        #    text + auths, via cache_token), across time-partition tables
        #    of one store (same plan, same bucketed shapes), and across
        #    aggregate-cache cell queries. Keys are VERSION-STABLE: the
        #    compiled closure depends only on structure (shapes, predicate,
        #    sampling mode) plus the dictionary fingerprint (string codes
        #    are baked at compile time), so a store mutation never forces a
        #    recompile.
        # 2. the device-resident window arrays — strictly per (store,
        #    version): windows differ per partition and per mutation.
        token = plan.__dict__.get("cache_token")
        fn_cache = fn_key = None
        if cache_key is not None:
            K = setup["starts"].shape[1]
            if token is not None:
                fn_cache = self.kernel_registry()
                fn_key = (cache_key, L, K, sampling, sample_by, sb_mode,
                          sb_off, sb_vocab, sb_buckets, token,
                          plan.index_name, self._dict_fp())
            else:  # raw-IR plan: cache on the plan (shared across partitions)
                fn_cache = self._plan_registry(plan)
                fn_key = (cache_key, L, K, sampling, sample_by, sb_mode,
                          sb_off, sb_vocab, sb_buckets)
            self._note(plan, shape_bucket=(L, K))
        go = fn_cache.get(fn_key) if fn_cache is not None else None
        if go is None:

            @jax.jit
            def go(cols, starts, ends, counts, extra):
                m = kmasks.window_mask(starts, ends, counts, L)
                m = m & compiled(cols, jnp)
                if compiled.band is not None and excise_band:
                    # excise f32-uncertain rows: the kernel result is then
                    # exact over every row it counts; the few band rows are
                    # added back host-side from their f64 values. COARSE
                    # masks keep them (they are the refinement candidates).
                    m = m & ~compiled.band(cols, jnp)
                if sampling and sample_by and sb_mode == "hash":
                    m = kmasks.sampling_mask_by_key_hash(
                        m, sampling, cols[sample_by], sb_buckets, jnp
                    )
                elif sampling and sample_by:
                    m = kmasks.sampling_mask_by_key_device(
                        m, sampling, cols[sample_by] - sb_off, sb_vocab,
                        jnp
                    )
                elif sampling:
                    m = kmasks.sampling_mask(m, sampling, jnp)
                return agg_fn(cols, m, jnp, *extra)

            if fn_cache is not None:
                fn_cache.put(fn_key, go)
                self._note(plan, kernel="trace")
        elif fn_cache is not None:
            self._note(plan, kernel="hit")
        # pre-placed window arrays: repeated same-plan runs (pagination,
        # benchmarks) shouldn't re-upload per call — host link latency can
        # dwarf the kernel. Unlike the jitted fn, window DATA is plan- and
        # store-specific: token-less fn_keys carry no plan identity, so
        # their windows must live on the plan (keyed by store uid), never
        # in a store-level cache another plan could hit.
        win = None
        if fn_key is not None:
            # window_token lets plans that share a kernel but differ in
            # their scan windows (knn radius expansion) key window arrays
            # separately without forcing a retrace
            wtoken = plan.__dict__.get("window_token", token)
            if token is not None:
                wcache = self.store.__dict__.setdefault("_win_cache", {})
            else:
                wcache = plan.__dict__.setdefault("_win_cache", {})
            wkey = (fn_key, wtoken, self.store.uid, self.store.version,
                    self._devkey())
            win = wcache.get(wkey)
        if win is None:
            win = (
                self._put(setup["starts"]),
                self._put(setup["ends"]),
                self._put(setup["counts"]),
            )
            if fn_key is not None:
                if len(wcache) >= 64:
                    wcache.clear()
                wcache[wkey] = win
        d_starts, d_ends, d_counts = win
        from geomesa_tpu.kernels import pallas_kernels as pk

        # trace-time context: under a sharded mesh, polygon pallas kernels
        # re-dispatch through an inner shard_map over the mesh (bare
        # pallas_call has no GSPMD partitioning rule)
        with pk.sharded_execution(self.mesh), \
                tracing.span("scan.kernel",
                             site=str(cache_key[0]) if cache_key else None), \
                utilization.device_busy(self._devkey() or 0):
            # one observable unit of device work (the serving bench's
            # fusion-actually-fused gate counts these; docs/SERVING.md).
            # The busy interval covers dispatch (async backends may still
            # be executing past it) and feeds the device.busy.<id> gauge
            # plus the per-query device_ms cost attribution.
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            return go(dev_cols, d_starts, d_ends, d_counts, tuple(extra))

    def _sharding(self):
        if self.mesh is None:
            if self.device is None:
                return None
            # process-wide singleton per device: the prefetch thread's
            # device_put overlap must present the SAME sharding object
            # (device_columns keys its cache by id(sharding))
            from geomesa_tpu.parallel.devices import device_sharding

            return device_sharding(self.device)
        # cached: device_columns keys its upload cache by id(sharding), so a
        # fresh NamedSharding per call would re-upload every column per query
        sh = self.__dict__.get("_sharding_cache")
        if sh is None:
            from jax.sharding import NamedSharding, PartitionSpec

            sh = NamedSharding(self.mesh, PartitionSpec("shard", None))
            self.__dict__["_sharding_cache"] = sh
        return sh

    def _put(self, x):
        """``jax.device_put`` honoring the executor's device pin (window
        arrays, compact descriptors, density schedules — operands that are
        NOT mesh-sharded; mesh placements keep their own shardings)."""
        import jax

        if self.mesh is None and self.device is not None:
            return jax.device_put(x, self._sharding())
        return jax.device_put(x)

    def _devkey(self):
        """Cache-key component for device-RESIDENT data (window arrays,
        compact slabs, schedules): a pinned executor must never hit
        another device's arrays — mixing committed devices in one jit is
        an error. Compiled-KERNEL keys deliberately omit it (one trace
        serves every device)."""
        return None if self.device is None else self.device.id

    # -- bin-space (sequence) parallelism ---------------------------------
    def _binspace_mesh(self):
        """The mesh, when it has a 'bin' axis (time-bin sequence axis)."""
        m = self.mesh
        if m is not None and "bin" in m.axis_names and "shard" in m.axis_names:
            return m
        return None

    def _binspace_run(self, plan: QueryPlan, setup, agg_fn, agg_cols,
                      cache_key):
        """Additive aggregate over the 2-D (shard, bin) mesh; None if the
        layout does not fit (caller falls through to the GSPMD path)."""
        from geomesa_tpu.parallel import binspace

        mesh = self._binspace_mesh()
        table = setup["table"]
        if (
            mesh is None
            or plan.hints.sampling  # sampling's running index is global
            or table.n_shards % mesh.shape["shard"] != 0
        ):
            return None
        import jax

        stream = config.BIN_STREAM_CHUNKS.to_int() or 1
        n_bin = mesh.shape["bin"]
        starts, ends = binspace.pad_windows(
            setup["starts"], setup["ends"], n_bin * stream
        )
        # cached shardings: device_columns keys its upload cache by
        # id(sharding) — fresh NamedShardings would re-upload per query
        sh = self.__dict__.get("_binspace_placements")
        if sh is None:
            sh = binspace.placements(mesh)
            self.__dict__["_binspace_placements"] = sh
        col_sh, win_sh, cnt_sh = sh
        names = tuple(dict.fromkeys(list(setup["needed"]) + list(agg_cols)))
        dev_cols = table.device_columns(names, col_sh)
        L = setup["L"]
        token = plan.__dict__.get("cache_token")
        if token is not None and cache_key is not None:
            cache = self.kernel_registry()
            key = ("binspace", cache_key, L, starts.shape[1], stream, token,
                   plan.index_name, self._dict_fp())
        else:  # token-less plan: cache on the plan (pagination, benchmarks)
            cache = self._plan_registry(plan)
            key = ("binspace", cache_key, L, starts.shape[1], stream)
        fn = cache.get(key)
        if fn is None:
            compiled = plan.compiled
            if compiled.band is not None:
                # same band excision as the GSPMD kernel: binspace counts
                # only f32-certain rows; the correction adds the rest
                inner_fn, inner_band = compiled.fn, compiled.band

                def predicate(cols, xp):
                    return inner_fn(cols, xp) & ~inner_band(cols, xp)
            else:
                predicate = compiled
            fn = binspace.build_bin_parallel(
                mesh, sorted(dev_cols), L, predicate, agg_fn, stream
            )
            cache.put(key, fn)
        metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
        with utilization.device_busy(self._devkey() or 0):
            return fn(
                {k: dev_cols[k] for k in sorted(dev_cols)},
                jax.device_put(starts.astype(np.int32), win_sh),
                jax.device_put(ends.astype(np.int32), win_sh),
                jax.device_put(setup["counts"].astype(np.int32), cnt_sh),
            )

    def _cached_density_schedule(self, setup, bbox, width, height,
                                 cache_name, key_extras, build, device_keys):
        """Shared cache host for the host-built density pair schedules
        (pallas grouped / MXU einsum): build once per (windows, grid,
        store version, device pin), device_put the array members, remember
        a False sentinel for negative results."""
        d = setup["compact"]
        table = setup["table"]
        cache = self.store.__dict__.setdefault(cache_name, {})
        key = (cache_name, d["whash"], tuple(bbox), width, height, d["B"],
               d["C"]) + tuple(key_extras) + (
                   self.store.uid, self.store.version, self._devkey())
        hit = cache.get(key)
        if hit is None:
            pr = build(
                d, table, table.keyspace, bbox, width, height,
                box_cache=self.store.__dict__.setdefault(
                    "_chunk_box_cache", {}
                ),
                version=self.store.version,
            )
            if pr is not None:
                for k in device_keys:
                    pr[k] = self._put(pr[k])
            if len(cache) >= 64:
                cache.clear()
            hit = cache[key] = pr if pr is not None else False
        return hit or None

    def _density_grouped(self, plan: QueryPlan, setup, bbox, width, height):
        """Pair schedule for the pallas grouped density kernel, cached on
        device per (windows, grid, store version). None when pallas is
        unavailable, the kernel is disabled, or the index has no morton
        key (callers fall through to the einsum/scatter paths)."""
        from geomesa_tpu.kernels import density_pallas as _dp
        from geomesa_tpu.kernels import pallas_kernels as pk

        if not config.DENSITY_PALLAS.to_bool() or not pk.use_pallas():
            return None
        return self._cached_density_schedule(
            setup, bbox, width, height, "_grouped_cache",
            (config.DENSITY_PALLAS_MAX_DUP.to_float(),),
            _dp.build_grouped,
            ("sc", "row", "tile", "ox", "oy", "seen"),
        )

    def _density_pairs(self, plan: QueryPlan, setup, bbox, width, height):
        """(chunk, tile) pair arrays for the MXU density kernel, cached on
        device per (windows, grid, store version). None when the index has
        no morton key or the kernel is disabled."""
        from geomesa_tpu.kernels import density_mxu as _dm

        if not config.DENSITY_MXU.to_bool():
            return None
        return self._cached_density_schedule(
            setup, bbox, width, height, "_pair_cache",
            (_dm.tile_shape(),),
            _dm.build_pairs,
            ("chunk", "px0", "py0", "tile", "pvalid"),
        )

    @staticmethod
    def _note(plan: QueryPlan, **kw) -> None:
        """Record which execution path served (part of) this query in
        ``plan.exec_path`` — surfaced by explain(analyze=True) and the
        audit log so silent fallbacks (device -> host, pallas -> XLA,
        mesh -> single-chip) are visible per query instead of only as a
        perf cliff."""
        plan.__dict__.setdefault("exec_path", {}).update(kw)

    def _run(self, plan: QueryPlan, agg_fn_dev, agg_fn_host, agg_cols=(),
             cache_key=None, additive=False, extra=(), compactable=True,
             compact_agg=None):
        check_deadline()
        setup = self._scan_setup(plan, agg_cols)
        if setup is None:
            return None
        self._note(
            plan,
            sampling=setup["sb_mode"] if plan.hints.sample_by else None,
            mesh=(None if self.mesh is None
                  else dict(zip(self.mesh.axis_names,
                                self.mesh.devices.shape))),
        )
        from geomesa_tpu.kernels import pallas_kernels as _pk

        _pk.take_dispatch()  # drop records a prior query's trace left
        try:
            return self._run_inner(
                plan, setup, agg_fn_dev, agg_fn_host, agg_cols, cache_key,
                additive, extra, compactable, compact_agg,
            )
        finally:
            disp = _pk.take_dispatch()
            if disp:
                self._note(plan, **{f"kernel:{k}": v
                                    for k, v in disp.items()})

    def _run_inner(self, plan, setup, agg_fn_dev, agg_fn_host, agg_cols,
                   cache_key, additive, extra, compactable, compact_agg):
        corr = None
        band_rows = 0
        if setup["use_device"] and plan.compiled.band is not None:
            info = self._band_info(plan, setup)
            band_rows = 0 if info is None else len(info)
            if band_rows:
                if additive and not plan.hints.sampling:
                    # device aggregates the certain rows; the band rows'
                    # exact f64 contribution combines additively
                    corr = self._band_correction(
                        plan, setup, info, agg_fn_host, agg_cols, extra
                    )
                else:
                    setup["use_device"] = False  # exact host evaluation
        if setup["use_device"]:
            if additive:
                try:
                    out = self._binspace_run(
                        plan, setup, agg_fn_dev, agg_cols, cache_key
                    )
                    if out is not None:
                        self._note(plan, scan="device-binspace",
                                   band_rows=band_rows)
                        return out if corr is None else out + corr
                except Exception as e:
                    if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                        raise
                    # binspace-specific failure: the 1-D GSPMD device path
                    # below is still viable — don't drop to the host runner
                    logging.getLogger(__name__).warning(
                        "binspace scan failed, trying GSPMD path: %r", e
                    )
            try:
                if additive and compactable and self.mesh is not None:
                    out = self._compact_mesh_run(
                        plan, setup, agg_fn_dev, agg_cols, cache_key, extra
                    )
                    if out is not None:
                        self._note(plan, scan="device-compact-mesh",
                                   band_rows=band_rows)
                        return out if corr is None else out + corr
                self._maybe_compact(plan, setup, compactable)
                if setup["compact"] is not None:
                    agg_use, extra_use, ckey = agg_fn_dev, extra, cache_key
                    if compact_agg is not None:
                        alt = compact_agg(setup)
                        if alt is not None:
                            agg_use, alt_extra, suffix = alt
                            extra_use = tuple(extra) + tuple(alt_extra)
                            ckey = (cache_key or ()) + suffix
                    out = self._device_compact_agg(
                        plan, setup, agg_use, agg_cols, ckey,
                        extra=extra_use,
                    )
                    self._note(plan, scan="device-compact",
                               B=setup["compact"]["B"], band_rows=band_rows)
                else:
                    out = self._device_mask_and_agg(
                        plan, setup, agg_fn_dev, agg_cols, cache_key,
                        extra=extra,
                    )
                    self._note(plan, scan="device-padded",
                               band_rows=band_rows)
                return out if corr is None else out + corr
            except Exception as e:
                if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                    raise
                # graceful degradation (the reference's remoteFilter=false /
                # Bigtable path): fall back to the host runner — loudly, so a
                # permanent fallback is never an invisible perf cliff
                logging.getLogger(__name__).warning(
                    "device scan failed, falling back to host: %r", e
                )
                self._note(plan, device_error=repr(e)[:200])
        coarse = self._coarse_or_none(plan, setup)
        self._note(
            plan,
            scan=("host+device-coarse" if coarse is not None else "host"),
            band_rows=band_rows,
        )
        with tracing.span("scan.host"):
            mask = self._host_mask(plan, setup, coarse)
            table = setup["table"]
            cols = {}
            for c in set(list(setup["needed"]) + list(agg_cols)):
                if table.has_column(c):
                    L = setup["L"]
                    full = table.col_sorted(c)
                    stacked = np.zeros((table.n_shards, L), dtype=full.dtype)
                    for s in range(table.n_shards):
                        sl = table.shard_slice(s)
                        stacked[s, : sl.stop - sl.start] = full[sl]
                    cols[c] = stacked
            return agg_fn_host(cols, mask, np, *extra)

    # -- public operations --------------------------------------------------
    def count_partial(self, plan: QueryPlan):
        """:meth:`count` WITHOUT the device sync: the additive partial
        (device scalar or host value; None = empty scan) the sharded
        partitioned scan merges after every device has been dispatched."""
        return self._run(
            plan,
            lambda cols, m, xp: m.sum(),
            lambda cols, m, xp: m.sum(),
            cache_key=("count",),
            additive=True,
        )

    def count(self, plan: QueryPlan) -> int:
        out = self.count_partial(plan)
        if out is None:
            return 0
        with tracing.span("scan.sync"):
            return int(out)

    def features(self, plan: QueryPlan) -> ColumnBatch:
        """Matching rows as a host ColumnBatch (sort/limit applied by caller)."""
        setup = self._scan_setup(plan)
        if setup is None:
            return ColumnBatch({}, 0)
        mask = None
        band_clean = True
        if setup["use_device"] and plan.compiled.band is not None:
            info = self._band_info(plan, setup)
            band_clean = info is None or len(info) == 0
        if setup["use_device"] and band_clean:
            try:
                self._maybe_compact(plan, setup, True)
                if setup["compact"] is not None:
                    cmask = self._device_compact_agg(
                        plan, setup, lambda cols, m, xp: m,
                        cache_key=("mask",),
                    )
                    with tracing.span("scan.sync"):
                        mask = self._expand_compact_mask(setup, cmask)
                else:
                    dmask = self._device_mask_and_agg(
                        plan, setup, lambda cols, m, xp: m,
                        cache_key=("mask",),
                    )
                    with tracing.span("scan.sync"):
                        mask = np.asarray(dmask)
            except Exception as e:
                if os.environ.get("GEOMESA_TPU_STRICT_DEVICE"):
                    raise
                # same graceful degradation as _run(): loud host fallback
                logging.getLogger(__name__).warning(
                    "device scan failed, falling back to host: %r", e
                )
        if mask is None:
            mask = self._host_mask(
                plan, setup, self._coarse_or_none(plan, setup)
            )
        names = None
        if plan.hints.properties:
            # projection pushdown into the gather (ColumnGroups analog):
            # sort keys must survive for the caller's post-sort
            names = list(plan.hints.properties) + [
                a for a, _ in (plan.hints.sort_by or [])
            ]
        return setup["table"].host_gather(mask.reshape(-1), names)

    def features_iter(self, plan: QueryPlan, batch_rows: Optional[int] = None):
        """Matching rows as a stream of ColumnBatch chunks (ArrowScan's
        batched-yield contract, AggregatingScan.scala:82-116). A single
        table materializes its result once and re-slices it — the streaming
        value on an unpartitioned store is wire chunking, not peak memory."""
        batch_rows = batch_rows or int(
            os.environ.get("GEOMESA_ARROW_BATCH_ROWS", 1_000_000)
        )
        out = self.features(plan)
        n = out.n
        if plan.hints.max_features is not None and not plan.hints.sort_by:
            n = min(n, plan.hints.max_features)
        for lo in range(0, n, batch_rows):
            hi = min(lo + batch_rows, n)
            yield ColumnBatch(
                {k: v[lo:hi] for k, v in out.columns.items()}, hi - lo
            )

    def density(self, plan: QueryPlan, bbox, width: int, height: int,
                weight: Optional[str] = None, as_numpy: bool = True):
        """Density grid. ``as_numpy=False`` leaves the grid on device (no
        host transfer) — for benchmark loops and device-side composition."""
        geom = self.store.ft.geom_field
        xc, yc = geom + "__x", geom + "__y"
        agg_cols = [xc, yc] + ([weight] if weight else [])

        def agg(cols, m, xp):
            w = cols.get(weight) if weight else None
            return kdensity.density_grid(
                cols[xc], cols[yc], m, bbox, width, height, w, xp
            )

        def mxu_agg(setup):
            # device kernel ladder over the compacted layout: pallas
            # grouped one-hot matmul (kernels/density_pallas.py) when the
            # backend has pallas, else the XLA einsum pair kernel
            # (kernels/density_mxu.py), else the scatter agg (returns
            # None when the index has no morton key column)
            gr = self._density_grouped(plan, setup, bbox, width, height)
            if gr is not None:
                self._note(plan, density_kernel="pallas-grouped-mxu")
                from geomesa_tpu.kernels import density_pallas as kdp

                Bc, n_pairs = gr["B"], gr["n_pairs"]
                gntx, gnty = gr["ntx"], gr["nty"]

                def gagg(cols, m, xp, sc, row, tile, ox, oy, seen):
                    return kdp.density_grid_grouped(
                        cols[xc], cols[yc], m, bbox, width, height,
                        cols.get(weight) if weight else None,
                        sc, row, tile, ox, oy, seen,
                        Bc, gntx, gnty, n_pairs,
                    )

                extra = (gr["sc"], gr["row"], gr["tile"], gr["ox"],
                         gr["oy"], gr["seen"])
                return gagg, extra, ("grouped", n_pairs, Bc, gntx, gnty)
            pr = self._density_pairs(plan, setup, bbox, width, height)
            if pr is None:
                self._note(plan, density_kernel="scatter")
                return None
            self._note(plan, density_kernel="mxu-einsum")
            from geomesa_tpu.kernels import density_mxu as kmxu

            PB, ntx, nty = pr["PB"], pr["ntx"], pr["nty"]
            TY, TX = pr["TY"], pr["TX"]

            def pagg(cols, m, xp, pc, p0, p1, pt, pv):
                return kmxu.density_grid_pairs(
                    cols[xc], cols[yc], m, bbox, width, height,
                    cols.get(weight) if weight else None,
                    pc, p0, p1, pt, pv, PB, ntx, nty, TY, TX, xp,
                )

            extra = (pr["chunk"], pr["px0"], pr["py0"], pr["tile"],
                     pr["pvalid"])
            return pagg, extra, ("mxu", pr["P"], PB, TX, TY)

        out = self._run(
            plan, agg, agg, agg_cols,
            cache_key=("density", tuple(bbox), width, height, weight),
            additive=True,
            compact_agg=mxu_agg,
        )
        if out is None:
            return np.zeros((height, width), np.float32)
        if not as_numpy:
            return out
        with tracing.span("scan.sync"):
            return np.asarray(out)

    # -- curve-aligned density (the index-native heatmap) ------------------
    def _curve_positions(self, plan: QueryPlan, level: int, block_window):
        """Host-side: padded-flat CDF positions of every morton block in the
        crop window. Each level-``level`` block is ONE contiguous range of
        the z2-sorted order, so its masked count is a 2-gather CDF
        difference — no scatter. Cached per (store version, level, crop)."""
        table = self._table(plan)
        key = ("curve_pos", table.keyspace.name, self.store.version, level,
               tuple(block_window))
        cache = self.store.__dict__.setdefault("_curve_pos_cache", {})
        hit = cache.get(key)
        if hit is not None:
            return hit
        from geomesa_tpu.curves.zorder import interleave2

        ix0, iy0, ix1, iy1 = block_window
        nx, ny = ix1 - ix0 + 1, iy1 - iy0 + 1
        jj, ii = np.meshgrid(
            np.arange(iy0, iy1 + 1, dtype=np.uint64),
            np.arange(ix0, ix1 + 1, dtype=np.uint64),
            indexing="ij",
        )
        codes = interleave2(ii.ravel(), jj.ravel())
        shift_bits = 2 * (31 - level)
        z_lo = codes << np.uint64(shift_bits)
        z_hi = (codes + np.uint64(1)) << np.uint64(shift_bits)
        z_col = table.key_columns["__z2"]
        sh = 0 if table.key_shifts is None else table.key_shifts.get("__z2", 0)
        if sh > shift_bits:
            raise ValueError(
                f"z2 keys quantized below level {level} blocks "
                f"(shift {sh} > {shift_bits}); use the scatter density path"
            )
        g0 = np.searchsorted(z_col, (z_lo >> np.uint64(sh)).astype(z_col.dtype))
        g1 = np.searchsorted(z_col, (z_hi >> np.uint64(sh)).astype(z_col.dtype))
        # global sorted position -> padded [S, L] flat position
        bounds = table.shard_bounds
        L = table.shard_len

        def pad_pos(g):
            s = np.clip(
                np.searchsorted(bounds, g, side="right") - 1,
                0, table.n_shards - 1,
            )
            return (s * L + (g - bounds[s])).astype(np.int32)

        p0, p1 = pad_pos(g0), pad_pos(g1)
        # pad the block count to a pow2 bucket so one compiled kernel
        # serves every crop of similar size (padding diffs are 0)
        B = len(p0)
        Bp = 1 << max(B - 1, 0).bit_length()
        if Bp != B:
            p0 = np.concatenate([p0, np.zeros(Bp - B, np.int32)])
            p1 = np.concatenate([p1, np.zeros(Bp - B, np.int32)])
        out = (p0, p1, B, nx, ny)
        if len(cache) >= 32:
            cache.clear()
        cache[key] = out
        return out

    def density_curve_raw(self, plan: QueryPlan, level: int, block_window,
                          weight: Optional[str] = None):
        """:meth:`density_curve` WITHOUT the final host transfer:
        ``(partial_or_None, B, nx, ny)``. The sharded partitioned scan
        dispatches one of these per partition (each async, on its own
        device) and decodes via :meth:`decode_curve` only after every
        device is busy."""
        p0, p1, B, nx, ny = self._curve_positions(plan, level, block_window)
        agg_cols = [weight] if weight else []

        def agg(cols, m, xp, p0_, p1_):
            if weight is None:
                w = m.reshape(-1).astype(xp.int32)
            else:
                w = xp.where(
                    m.reshape(-1),
                    cols[weight].reshape(-1).astype(xp.float32),
                    xp.float32(0),
                )
            c = xp.concatenate([xp.zeros(1, w.dtype), xp.cumsum(w)])
            # counts stay int32 end-to-end: an f32 cast here would round
            # blocks holding >2^24 rows
            return c[p1_] - c[p0_]

        out = self._run(
            plan, agg, agg, agg_cols,
            cache_key=("density_curve", level, len(p0), weight),
            extra=(p0, p1),
            compactable=False,  # CDF positions index the padded layout
        )
        return out, B, nx, ny

    @staticmethod
    def decode_curve(raw) -> np.ndarray:
        """One :meth:`density_curve_raw` partial as the host f64 grid
        (zeros for an empty partial) — the per-partition decode the
        partitioned merge runs in pruned-bin order, identically on the
        serial and sharded paths."""
        out, B, nx, ny = raw
        if out is None:
            return np.zeros((ny, nx), np.float64)
        # float64 grid: cell counts are exact to 2^53 (an f32 grid would
        # round cells beyond 2^24 rows); weighted cells carry the f32
        # accumulation documented in density_curve_raw
        flat = np.asarray(out)[:B].astype(np.float64)
        # blocks were generated row-major over (j, i): reshape directly;
        # row 0 = ymin edge (RenderingGrid convention)
        return flat.reshape(ny, nx)

    def density_curve(self, plan: QueryPlan, level: int, block_window,
                      weight: Optional[str] = None) -> np.ndarray:
        """Exact density over a morton-block-aligned grid (XYZ/EPSG:4326
        tile pyramids align by construction): masked counts via one cumsum
        over the z2-sorted scan + two gathers per block. At 20M rows this
        is ~25x faster than the scatter path, because TPU scatter costs
        ~6.7 ns/row while cumsum runs at bandwidth (docs/SCALE.md).
        Unweighted counts accumulate in int32 (exact to 2^31 rows);
        weighted densities accumulate in f32."""
        return self.decode_curve(
            self.density_curve_raw(plan, level, block_window, weight)
        )

    def density_curve_batch_raw(self, plan: QueryPlan, level: int,
                                block_windows, weight: Optional[str] = None):
        """N curve-aligned density crops of ONE (plan, level) in a single
        device pass — the cross-query fusion entry point (docs/SERVING.md):
        concurrent tile clients share the mask + cumsum (the expensive
        O(rows) work) and each member costs only its own CDF gathers,
        stacked over the query axis as ``[M, P]`` position operands.

        Per-member results are bit-identical to :meth:`density_curve` run
        serially: the shared cumsum is the same array either way, and
        ``c[p1] - c[p0]`` gathers are exact. The kernel registry key pads
        the member axis to a power of two (``registry.bucket_batch``) next
        to the usual version-stable token, so batch sizes in one bucket
        share a compiled kernel. Returns the UNSYNCED ``(partial, infos)``
        pair (the sharded partitioned scan merges these across devices);
        :meth:`density_curve_batch` is the synchronous public form."""
        from geomesa_tpu.kernels.registry import bucket_batch

        infos = [
            self._curve_positions(plan, level, bw) for bw in block_windows
        ]
        if not infos:
            return None, []
        # stack the per-member CDF positions: members pad to a common P
        # (each is already pow2-padded, so P = max is a pow2) and the
        # member axis pads to its batch bucket. Padded cells gather
        # c[0] - c[0] = 0 and are sliced away below.
        P = max(len(i[0]) for i in infos)
        M = len(infos)
        Mp = bucket_batch(M)
        p0s = np.zeros((Mp, P), np.int32)
        p1s = np.zeros((Mp, P), np.int32)
        for i, (p0, p1, _B, _nx, _ny) in enumerate(infos):
            p0s[i, : len(p0)] = p0
            p1s[i, : len(p1)] = p1
        agg_cols = [weight] if weight else []

        def agg(cols, m, xp, p0_, p1_):
            if weight is None:
                w = m.reshape(-1).astype(xp.int32)
            else:
                w = xp.where(
                    m.reshape(-1),
                    cols[weight].reshape(-1).astype(xp.float32),
                    xp.float32(0),
                )
            # ONE cumsum serves every member; the [M, P] gather pair is
            # the only per-member work (same int32 exactness contract as
            # density_curve)
            c = xp.concatenate([xp.zeros(1, w.dtype), xp.cumsum(w)])
            return c[p1_] - c[p0_]

        out = self._run(
            plan, agg, agg, agg_cols,
            cache_key=("density_curve_batch", level, P, Mp, weight),
            extra=(p0s, p1s),
            compactable=False,  # CDF positions index the padded layout
        )
        return out, infos

    @staticmethod
    def decode_curve_batch(raw):
        """One :meth:`density_curve_batch_raw` partial as per-member host
        f64 grids (the per-partition decode of the sharded merge)."""
        out, infos = raw
        results = []
        arr = None if out is None else np.asarray(out)
        for i, (_p0, _p1, B, nx, ny) in enumerate(infos):
            if arr is None:
                results.append(np.zeros((ny, nx), np.float64))
            else:
                results.append(
                    arr[i, :B].astype(np.float64).reshape(ny, nx)
                )
        return results

    def density_curve_batch(self, plan: QueryPlan, level: int,
                            block_windows, weight: Optional[str] = None):
        """See :meth:`density_curve_batch_raw` — this is the synchronous
        public form, one ``[ny, nx]`` float64 grid per window, in order."""
        return self.decode_curve_batch(
            self.density_curve_batch_raw(plan, level, block_windows, weight)
        )

    def density_curve_filter_batch_raw(self, plans, spec, level: int,
                                       block_windows,
                                       weight: Optional[str] = None):
        """M DISTINCT-filter curve crops of one structural template in a
        single device dispatch (docs/SERVING.md "Query-axis batching",
        extended to the curve path): each member carries its OWN viewport
        literals (kernel data via ``spec``) AND its own crop window
        (stacked CDF gather positions). Unlike :meth:`density_curve_batch`
        — which shares one mask + cumsum across crops of ONE filter —
        every member here pays its own masked cumsum, but all M ride one
        kernel launch and one column residency. Per-member math is
        op-for-op the serial :meth:`density_curve` kernel (batched
        window_mask + literal-parameterized compare, then the identical
        int32/f32 cumsum + 2-gather CDF), so de-interleaved grids are
        bit-identical to query-at-a-time execution. Returns the unsynced
        ``(partials_or_None, infos)`` pair, or None when ineligible
        (caller degrades to per-member serial execution); members with
        surviving f32 band rows keep the serial path (band corrections
        are per-block additive host work the batch does not carry)."""
        check_deadline()
        agg_cols = [weight] if weight else []
        bs = self._batch_setups(plans, spec, agg_cols)
        if bs is None:
            return None
        infos = [
            self._curve_positions(plans[0], level, bw)
            for bw in block_windows
        ]
        if bs["empty"]:
            return (None, infos)
        # any member with SURVIVING f32 band rows keeps the serial path:
        # its correction is per-block additive host work this batch does
        # not carry (same posture as stats_batch)
        for plan, su in zip(plans, bs["setups"]):
            if su is None or plan.compiled.band is None:
                continue
            info = self._band_info(plan, su)
            if info is not None and len(info):
                return None
        P = max(len(i[0]) for i in infos)
        Mp = bs["Mp"]
        p0s = np.zeros((Mp, P), np.int32)
        p1s = np.zeros((Mp, P), np.int32)
        for m, (p0, p1, _B, _nx, _ny) in enumerate(infos):
            p0s[m, : len(p0)] = p0
            p1s[m, : len(p1)] = p1

        def member_agg(m, cols, mm, xp, p0_, p1_):
            if weight is None:
                w = mm.reshape(-1).astype(xp.int32)
            else:
                w = xp.where(
                    mm.reshape(-1),
                    cols[weight].reshape(-1).astype(xp.float32),
                    xp.float32(0),
                )
            # per-member cumsum (distinct masks), same exactness contract
            # as the serial density_curve kernel
            c = xp.concatenate([xp.zeros(1, w.dtype), xp.cumsum(w)])
            return c[p1_[m]] - c[p0_[m]]

        out = self._batch_device_agg(
            plans, spec, bs, member_agg, agg_cols,
            "density_curve_filter_batch", key_extras=(level, P, weight),
            extra_arrays=(p0s, p1s),
        )
        return (out, infos)

    @staticmethod
    def decode_curve_filter_batch(raw):
        """One :meth:`density_curve_filter_batch_raw` partial as
        per-member host f64 grids (the partitioned merge's decode)."""
        got, infos = raw
        results = []
        for m, (_p0, _p1, B, nx, ny) in enumerate(infos):
            if got is None:
                results.append(np.zeros((ny, nx), np.float64))
            else:
                results.append(
                    np.asarray(got[m])[:B].astype(np.float64).reshape(ny, nx)
                )
        return results

    def density_curve_filter_batch(self, plans, spec, level: int,
                                   block_windows,
                                   weight: Optional[str] = None):
        """M distinct-filter curve grids in one device dispatch (None =
        ineligible). Each member's grid equals its serial
        :meth:`density_curve` exactly — the CI-gated contract."""
        got = self.density_curve_filter_batch_raw(
            plans, spec, level, block_windows, weight
        )
        if got is None:
            return None
        return self.decode_curve_filter_batch(got)

    # -- query-axis batched aggregates (docs/SERVING.md "Query-axis
    # batching"): M *distinct* viewports in ONE device dispatch. The
    # batched kernel bakes the predicate SHAPE (the structural template's
    # residual + slot layout) but not the viewport literals — those ride
    # as [Mp, nf]/[Mp, ni] traced arrays — and the member axis pads to its
    # registry bucket (registry.bucket_batch), so batch sizes 3, 5, 7
    # share one compiled kernel at Mp=8 and a panning client never
    # recompiles. Each member's mask is op-for-op its serial kernel
    # (unrolled member loop, batched window_mask + literal-parameterized
    # compare with the identical f32/int32 values), so de-interleaved
    # results are bit-identical to query-at-a-time execution — the
    # CI-gated contract.
    def _batch_setups(self, plans, spec, agg_cols=()):
        """Per-member scan setups + stacked windows for one batch, or
        None when the batch cannot ride the device kernel (caller falls
        back to per-member serial execution). ``spec`` is the
        planning/batch.BatchSpec the API layer built."""
        if self.mesh is not None or not self.prefer_device:
            return None
        setups = []
        table = None
        for plan in plans:
            if plan.hints.sampling or plan.hints.sample_by:
                return None
            su = self._scan_setup(plan, agg_cols)
            if su is None:
                # empty member (disjoint key plan) or empty table: zero
                # windows, zero partial — uniform with serial zeros
                plan.__dict__.setdefault("scanned_rows", 0)
                plan.__dict__.setdefault("table_rows", 0)
                setups.append(None)
                continue
            if not su["use_device"] or su["sb_mode"] is not None:
                return None
            t = su["table"]
            if table is None:
                table = t
            elif t is not table:
                return None
            setups.append(su)
        if table is None:  # every member empty
            return {"empty": True, "setups": setups}
        if any(p.__dict__.get("cache_token") is None for p in plans):
            return None
        from geomesa_tpu.kernels.registry import bucket_batch

        S, L = table.n_shards, table.shard_len
        K = max(
            (su["starts"].shape[1] for su in setups if su is not None),
            default=1,
        )
        Mp = bucket_batch(len(plans))
        starts = np.zeros((Mp, S, K), np.int32)
        ends = np.zeros((Mp, S, K), np.int32)
        for m, su in enumerate(setups):
            if su is None:
                continue
            k = su["starts"].shape[1]
            starts[m, :, :k] = su["starts"]
            ends[m, :, :k] = su["ends"]
        counts = np.diff(table.shard_bounds).astype(np.int32)
        return {
            "empty": False, "setups": setups, "table": table, "L": L,
            "K": K, "Mp": Mp, "starts": starts, "ends": ends,
            "counts": counts,
        }

    def _batch_band_corrs(self, plans, bs, agg_fn_host, agg_cols,
                          extras=None):
        """Per-member exact f32-band corrections (None = member clean).
        The batched device kernel excises each member's band rows exactly
        like the serial kernel; this is the serial host-side correction,
        run per member off its own plan's compiled band."""
        corrs = []
        for m, (plan, su) in enumerate(zip(plans, bs["setups"])):
            if su is None or plan.compiled.band is None:
                corrs.append(None)
                continue
            info = self._band_info(plan, su)
            if info is None or len(info) == 0:
                corrs.append(None)
                continue
            extra = () if extras is None else extras[m]
            corrs.append(self._band_correction(
                plan, su, info, agg_fn_host, agg_cols, extra
            ))
        return corrs

    def _batch_device_agg(self, plans, spec, bs, member_agg, agg_cols,
                          site, key_extras=(), extra_arrays=()):
        """Mask + per-member aggregation in ONE jit over the stacked
        query axis. ``member_agg(m, cols, mm, xp, *extra_arrays)`` builds
        member ``m``'s partial from its mask (the loop unrolls at trace
        time — Mp is part of the kernel shape). Returns the UNSYNCED
        tuple of Mp partials."""
        import jax
        import jax.numpy as jnp

        table, L, K, Mp = bs["table"], bs["L"], bs["K"], bs["Mp"]
        bfn, bband = spec.bf.fn, spec.bf.band
        names = tuple(dict.fromkeys(
            list(spec.bf.columns) + list(agg_cols)
        ))
        fn_cache = self.kernel_registry()
        fn_key = ((site,) + tuple(key_extras), L, K, Mp, spec.token,
                  plans[0].index_name, self._dict_fp())
        go = fn_cache.get(fn_key)
        if go is None:

            @jax.jit
            def go(cols, starts, ends, counts, lf, li, extra):
                outs = []
                for m in range(Mp):
                    wm = kmasks.window_mask_batch(starts, ends, counts,
                                                  L, m)
                    mm = wm & bfn(cols, jnp, lf[m], li[m])
                    if bband is not None:
                        mm = mm & ~bband(cols, jnp, lf[m], li[m])
                    outs.append(member_agg(m, cols, mm, jnp, *extra))
                return tuple(outs)

            fn_cache.put(fn_key, go)
            for p in plans:
                self._note(p, kernel="trace")
        else:
            for p in plans:
                self._note(p, kernel="hit")
        with tracing.span("scan.device_put", batch=len(plans)):
            dev_cols = table.device_columns(names, self._sharding())
        wcache = self.store.__dict__.setdefault("_win_cache", {})
        # keyed by the window BYTES, not their hash: a collision here
        # would silently serve another batch's scan ranges, and equality
        # is the correctness contract (the [Mp, S, K] arrays are far
        # smaller than the device windows the 64-entry cache holds)
        wkey = ("batch_win", site, self.store.uid, self.store.version,
                K, Mp, bs["starts"].tobytes(), bs["ends"].tobytes(),
                self._devkey())
        win = wcache.get(wkey)
        if win is None:
            win = (self._put(bs["starts"]), self._put(bs["ends"]),
                   self._put(bs["counts"]))
            if len(wcache) >= 64:
                wcache.clear()
            wcache[wkey] = win
        for p in plans:
            self._note(p, scan="device-batch", batch=len(plans))
        with tracing.span("scan.kernel", site=site, batch=len(plans)), \
                utilization.device_busy(self._devkey() or 0):
            # ONE observable unit of device work for the whole batch —
            # the distinct-fusion bench/CI gate counts these
            metrics.inc(metrics.EXEC_DEVICE_DISPATCH)
            return go(dev_cols, *win, spec.lits_f, spec.lits_i,
                      tuple(extra_arrays))

    def count_batch_partial(self, plans, spec):
        """Unsynced batched count: ``(partials_or_None, corrs)`` — one
        device scalar per member plus each member's exact band-row
        correction — or None when the batch is ineligible here (caller
        degrades to query-at-a-time)."""
        check_deadline()
        bs = self._batch_setups(plans, spec)
        if bs is None:
            return None
        corrs = [None] * len(plans)
        if bs["empty"]:
            return (None, corrs)
        corrs = self._batch_band_corrs(
            plans, bs, lambda cols, m, xp: m.sum(), ()
        )
        out = self._batch_device_agg(
            plans, spec, bs,
            lambda m, cols, mm, xp: mm.sum(),
            (), "count_batch",
        )
        return (out, corrs)

    def count_batch(self, plans, spec):
        """M distinct counts in one device dispatch (None = ineligible).
        Each member's value equals its serial :meth:`count` exactly."""
        got = self.count_batch_partial(plans, spec)
        if got is None:
            return None
        return self.decode_count_batch(got, len(plans))

    @staticmethod
    def decode_count_batch(got, n: int):
        """One :meth:`count_batch_partial` result as per-member host ints
        (the per-partition decode of the partitioned merge)."""
        out, corrs = got
        totals = []
        arr = None if out is None else [np.asarray(o) for o in out]
        for m in range(n):
            v = 0 if arr is None else int(arr[m])
            if corrs[m] is not None:
                v += int(corrs[m])
            totals.append(v)
        return totals

    def density_batch_partial(self, plans, spec, bboxes, width: int,
                              height: int, weight=None):
        """Unsynced batched density: ``(grids_or_None, corrs)`` — one
        device [height, width] f32 grid per member over that member's OWN
        bbox (traced grid parameters: one compiled kernel serves every
        viewport) — or None when ineligible."""
        check_deadline()
        geom = self.store.ft.geom_field
        xc, yc = geom + "__x", geom + "__y"
        agg_cols = [xc, yc] + ([weight] if weight else [])
        bs = self._batch_setups(plans, spec, agg_cols)
        if bs is None:
            return None
        corrs = [None] * len(plans)
        if bs["empty"]:
            return (None, corrs)
        Mp = bs["Mp"]
        gp = np.zeros((Mp, 4), np.float32)
        gp[:, 2:] = 1.0  # padded members: benign nonzero spans
        for m, bb in enumerate(bboxes):
            gp[m] = kdensity.grid_params(bb)

        def host_agg(m):
            def agg(cols, msk, xp):
                w = cols.get(weight) if weight else None
                return kdensity.density_grid(
                    cols[xc], cols[yc], msk, tuple(bboxes[m]),
                    width, height, w, xp,
                )

            return agg

        corrs = self._batch_band_corrs(
            plans, bs,
            # the member index rides through extras so each band
            # correction rasterizes into ITS member's grid
            lambda cols, msk, xp, m: host_agg(m)(cols, msk, xp),
            agg_cols,
            extras=[(m,) for m in range(len(plans))],
        )

        def member_agg(m, cols, mm, xp, gp_):
            w = cols.get(weight) if weight else None
            return kdensity.density_grid_at(
                cols[xc], cols[yc], mm,
                gp_[m, 0], gp_[m, 1], gp_[m, 2], gp_[m, 3],
                width, height, w, xp,
            )

        out = self._batch_device_agg(
            plans, spec, bs, member_agg, agg_cols, "density_batch",
            key_extras=(width, height, weight), extra_arrays=(gp,),
        )
        return (out, corrs)

    def density_batch(self, plans, spec, bboxes, width: int, height: int,
                      weight=None):
        """M distinct heatmaps in one device dispatch (None = ineligible).
        Unweighted grids are bit-identical to serial :meth:`density` (the
        cell values are exact integer counts); weighted grids match the
        serial padded-scatter path op-for-op."""
        got = self.density_batch_partial(plans, spec, bboxes, width,
                                         height, weight)
        if got is None:
            return None
        return self.decode_density_batch(got, len(plans), width, height)

    @staticmethod
    def decode_density_batch(got, n: int, width: int, height: int):
        """One :meth:`density_batch_partial` result as per-member host
        f32 grids."""
        out, corrs = got
        grids = []
        for m in range(n):
            g = (np.zeros((height, width), np.float32) if out is None
                 else np.asarray(out[m]))
            if corrs[m] is not None:
                g = g + np.asarray(corrs[m], np.float32)
            grids.append(g)
        return grids

    def stats_batch_partials(self, plans, spec, stats):
        """Unsynced batched stats partials: one
        :func:`~geomesa_tpu.kernels.stats_scan.device_update` pytree list
        per member — or None when ineligible. Stats never take additive
        band corrections (the serial path reroutes band-bearing scans to
        the host), so ANY member with surviving band rows makes the batch
        ineligible here; descriptive leaves are excluded by
        :func:`~geomesa_tpu.kernels.stats_scan.batch_supported`."""
        check_deadline()
        if any(not kstats.batch_supported(s) for s in stats):
            return None
        bundle = self._stats_bundle(plans[0], stats[0])
        if bundle is None:
            return None
        agg_cols, vocab_sizes = bundle
        bs = self._batch_setups(plans, spec, agg_cols)
        if bs is None:
            return None
        if bs["empty"]:
            return (None,)
        for plan, su in zip(plans, bs["setups"]):
            if su is None or plan.compiled.band is None:
                continue
            info = self._band_info(plan, su)
            if info is not None and len(info):
                return None  # serial would run this member on host

        def member_agg(m, cols, mm, xp):
            # padded members reuse member 0's structure (same spec text)
            st = stats[m] if m < len(stats) else stats[0]
            return kstats.device_update(st, cols, mm, xp, vocab_sizes)

        out = self._batch_device_agg(
            plans, spec, bs, member_agg, agg_cols, "stats_batch",
            # the stat STRUCTURE is baked into the traced update (leaf
            # kinds, bins, attributes): it must key the kernel, or a
            # Count() batch and a MinMax() batch of one template would
            # collide on one compiled kernel
            key_extras=(self._stat_signature(stats[0]),),
        )
        return (out,)

    @staticmethod
    def _stat_signature(stat: sk.Stat) -> tuple:
        """Trace-shape signature of a stat tree: everything
        :func:`~geomesa_tpu.kernels.stats_scan.device_update` bakes."""
        sig = []
        for leaf in kstats._leaf_stats(stat):
            if isinstance(leaf, sk.DescriptiveStats):
                attrs = tuple(leaf.attributes)
            else:
                attrs = (getattr(leaf, "attribute", None),)
            extra = ()
            if leaf.kind == "histogram":
                extra = (leaf.bins, leaf.lo, leaf.hi)
            elif leaf.kind == "topk":
                extra = (getattr(leaf, "k", None),)
            sig.append((leaf.kind, attrs, extra))
        return tuple(sig)

    def stats_batch(self, plans, spec, stats):
        """M distinct stats scans in one device dispatch (None =
        ineligible). Mutates and returns ``stats`` in member order."""
        got = self.stats_batch_partials(plans, spec, stats)
        if got is None:
            return None
        self.absorb_stats_batch(got, stats, self.store.dicts)
        return stats

    @staticmethod
    def absorb_stats_batch(got, stats, dicts) -> None:
        """Fold one :meth:`stats_batch_partials` result into the member
        Stat objects (the per-partition absorb of the partitioned merge,
        in member order)."""
        (out,) = got
        if out is None:
            return
        for m, st in enumerate(stats):
            kstats.absorb_partials(st, out[m], dicts)

    def _stats_bundle(self, plan: QueryPlan, stat: sk.Stat):
        """(agg_cols, vocab_sizes) when every leaf of ``stat`` can update
        on device over this table, else None (the gather path serves)."""
        table = self._table(plan)
        host_only = {
            c for c in table.column_names() if table.is_host_only(c)
        }
        vocab_sizes = {a: max(len(d), 1) for a, d in self.store.dicts.items()}
        leaf_attrs = []
        for leaf in kstats._leaf_stats(stat):
            if isinstance(leaf, sk.DescriptiveStats):
                leaf_attrs.extend(leaf.attributes)
            elif getattr(leaf, "attribute", None) is not None:
                leaf_attrs.append(leaf.attribute)
        agg_cols = []
        for a in leaf_attrs:
            if table.has_column(a + "__x"):
                agg_cols += [a + "__x", a + "__y"]
            elif table.has_column(a):
                agg_cols.append(a)
        enum_ok = all(
            leaf.attribute in self.store.dicts
            for leaf in kstats._leaf_stats(stat)
            if leaf.kind in ("enumeration", "topk")
        )
        if not (kstats.device_supported(stat, host_only) and enum_ok):
            return None
        return agg_cols, vocab_sizes

    def stats_partials(self, plan: QueryPlan, stat: sk.Stat):
        """``(supported, partials)`` — the async device partial-update
        pytree for ``stat`` (the sharded partitioned scan absorbs these in
        pruned-bin order AFTER every device has been dispatched). Does NOT
        mutate ``stat``. ``supported=False`` means the stat tree needs the
        host gather path; ``partials`` may be None on an empty scan."""
        bundle = self._stats_bundle(plan, stat)
        if bundle is None:
            return False, None
        agg_cols, vocab_sizes = bundle

        def agg(cols, m, xp):
            return kstats.device_update(stat, cols, m, xp, vocab_sizes)

        return True, self._run(plan, agg, agg, agg_cols)

    def stats(self, plan: QueryPlan, stat: sk.Stat) -> sk.Stat:
        supported, partials = self.stats_partials(plan, stat)
        if supported:
            if partials is not None:
                kstats.absorb_partials(stat, partials, self.store.dicts)
            return stat
        batch = self.features(plan)
        if batch.n:
            stat.observe(batch.columns)
            kstats.decode_enum_keys(stat, self.store.dicts)
        return stat

    def top_rows(self, plan: QueryPlan, attr: str, descending: bool,
                 k: int, include_ties: bool = False):
        """Flattened [S*L] positions of a SUPERSET of the top-k matched
        rows by one attribute (every boundary tie included) — the device
        half of a sorted+limited query (reference
        SortingSimpleFeatureIterator, done without a device sort, which
        compiles pathologically on this TPU toolchain). The caller sorts
        the gathered candidates exactly on host, so: for single-key
        sorts the final order is exact; for MULTI-key sorts this is
        called with the primary key, and tie inclusion guarantees every
        lexicographic top-k row is among the candidates.

        Two device strategies:
        - k <= 32, native f32 column: exact argmin iteration (r4 path);
        - otherwise: THRESHOLD SELECT — binary-search the k-th key value
          with masked count reductions (48 bandwidth-bound passes, one
          dispatch), then compact the <=threshold row positions into a
          k + tie-slack buffer with a sized nonzero. f64/int32 columns
          ride at f32: monotone rounding makes the selection a provable
          superset; the host's exact sort of the candidates restores f64
          order. Returns None when the column can't rank on device or
          the tie group overflows the buffer (caller sorts on host)."""
        table = self._table(plan)
        if (
            not table.has_column(attr)
            or table.is_host_only(attr)
            or attr in self.store.dicts  # codes rank by insertion order
            or table.dtype_of(attr) == np.bool_
        ):
            return None
        if include_ties or table.dtype_of(attr) != np.float32 or k > 32:
            # multi-key sorts REQUIRE tie inclusion: the argmin path
            # returns exactly k rows and would drop a boundary tie that
            # wins on a secondary key
            return self._top_rows_threshold(plan, attr, descending, k)

        def agg(cols, m, xp, *extra):
            v = cols[attr].reshape(-1).astype(xp.float32)
            # NaN keys are excluded here (argmin would select them first);
            # if that leaves fewer than k rows the caller falls back to the
            # host sort, which orders NaNs last — exact parity either way
            ok = m.reshape(-1) & ~xp.isnan(v)
            d = xp.where(ok, -v if descending else v, xp.inf)
            # argmin iteration (same tradeoff as kernels/knn.py): both
            # lax.top_k and sort-based top-k compile pathologically on
            # this TPU toolchain, so large k stays on the host
            idxs, vals = [], []
            for _ in range(k):
                i = xp.argmin(d)
                idxs.append(i)
                vals.append(-d[i] if descending else d[i])
                d = d.at[i].set(xp.inf)
            return xp.stack(idxs), xp.stack(vals)

        def agg_host(cols, m, xp, *extra):
            v = cols[attr].reshape(-1).astype(np.float64)
            v = np.where(m.reshape(-1), v if descending else -v, -np.inf)
            idx = np.argsort(-v, kind="stable")[:k]
            return idx, v[idx]

        out = self._run(
            plan, agg, agg_host, [attr],
            cache_key=("top", attr, bool(descending), int(k)),
            compactable=False,  # returned indices address the padded layout
        )
        if out is None:
            return np.zeros(0, np.int64)
        idx, vals = np.asarray(out[0]), np.asarray(out[1])
        idx = idx[np.isfinite(vals)].astype(np.int64)
        if len(idx) < k:
            # fewer finite matches than k: NaN-keyed or sparse matches may
            # exist that the device path excluded — let the host decide
            return None
        return idx

    def _top_rows_threshold(self, plan: QueryPlan, attr: str,
                            descending: bool, k: int):
        """Threshold-select top-k candidates (see :meth:`top_rows`)."""
        slack = config.TOPK_TIE_SLACK.to_int()
        if slack is None:
            slack = int(config.TOPK_TIE_SLACK.default)
        B = int(k + slack)
        desc = bool(descending)

        def agg(cols, m, xp, *extra):
            from jax import lax

            v = cols[attr].reshape(-1).astype(xp.float32)
            key = -v if desc else v
            ok = m.reshape(-1) & ~xp.isnan(v)
            kv = xp.where(ok, key, xp.inf)
            n_ok = ok.sum()
            lo = xp.min(kv)
            hi = xp.max(xp.where(ok, key, -xp.inf))

            # smallest t with count(key <= t) >= k: 48 halvings reach f32
            # resolution from any normal range
            def body(_, lohi):
                lo, hi = lohi
                mid = (lo + hi) * 0.5
                c = xp.sum(kv <= mid)
                ge = c >= k
                return xp.where(ge, lo, mid), xp.where(ge, mid, hi)

            lo, hi = lax.fori_loop(0, 48, body, (lo, hi))
            t = xp.where(n_ok <= k, xp.inf, hi)  # few matches: take all
            sel = ok & (kv <= t)
            cnt = sel.sum()
            idx = xp.nonzero(sel, size=B, fill_value=sel.shape[0])[0]
            return idx, cnt

        def agg_host(cols, m, xp, *extra):
            # host twin with the same superset-with-ties contract
            v = cols[attr].reshape(-1).astype(np.float64)
            ok = m.reshape(-1) & ~np.isnan(v)
            key = np.where(ok, -v if desc else v, np.inf)
            n_ok = int(ok.sum())
            out = np.full(B, len(key), np.int64)
            if n_ok == 0:
                return out, 0
            kk = min(k, n_ok)
            t = np.partition(key, kk - 1)[kk - 1]
            sel = np.nonzero(key <= t)[0]
            out[: min(len(sel), B)] = sel[:B]
            return out, len(sel)

        out = self._run(
            plan, agg, agg_host, [attr],
            cache_key=("topt", attr, desc, int(k), B),
            compactable=False,  # returned indices address the padded layout
        )
        if out is None:
            return np.zeros(0, np.int64)
        idx, cnt = np.asarray(out[0]), int(out[1])
        if cnt > B:
            return None  # tie group overflowed the buffer: host sorts
        if cnt < k:
            # fewer non-NaN matches than k: NaN-keyed matches (which sort
            # LAST, but still belong in an under-filled result) were
            # excluded here — let the host decide
            return None
        table = self._table(plan)
        total = int(table.n_shards * table.shard_len)
        return idx[idx < total].astype(np.int64)

    def knn(self, plan: QueryPlan, qx: float, qy: float, k: int, boxes=None):
        """k nearest to (qx, qy) among plan matches. ``boxes`` (optional):
        up to two (x0, y0, x1, y1) restriction boxes applied INSIDE the
        aggregation as traced scalars — the expanding-radius search passes
        its search box here (and via the plan's windows) instead of baking
        it into the compiled predicate, so one kernel serves every location
        and radius."""
        geom = self.store.ft.geom_field
        xc, yc = geom + "__x", geom + "__y"

        def agg(cols, m, xp, qx_, qy_, *bb):
            if bb:
                x, y = cols[xc], cols[yc]
                inb = None
                for i in range(0, len(bb), 4):
                    x0, y0, x1, y1 = bb[i:i + 4]
                    mi = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
                    inb = mi if inb is None else (inb | mi)
                m = m & inb
            return kknn.knn_indices(cols[xc], cols[yc], m, qx_, qy_, k, xp)

        extra = [np.float32(qx), np.float32(qy)]
        nb = 0
        if boxes:
            for x0, y0, x1, y1 in boxes:
                # round the box OUTWARD at f32: a nearest-rounded bound can
                # shrink the box half an ulp and drop an edge neighbor the
                # f64 termination proof assumed was inside
                extra.extend((
                    np.nextafter(np.float32(x0), np.float32(-np.inf)),
                    np.nextafter(np.float32(y0), np.float32(-np.inf)),
                    np.nextafter(np.float32(x1), np.float32(np.inf)),
                    np.nextafter(np.float32(y1), np.float32(np.inf)),
                ))
            nb = len(boxes)
        out = self._run(
            plan, agg, agg, [xc, yc], cache_key=("knn", int(k), nb),
            extra=tuple(extra),
            compactable=False,  # returned indices address the padded layout
        )
        if out is None:
            return np.zeros(0, np.int64), np.zeros(0)
        idx, d = np.asarray(out[0]), np.asarray(out[1])
        keep = np.isfinite(d)
        return idx[keep], d[keep]
