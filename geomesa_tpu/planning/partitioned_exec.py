"""Partition-at-a-time query execution over a PartitionedFeatureStore.

The runtime role of the reference's per-partition range scans + client merge
(TablePartition tables scanned per partition, AbstractBatchScan.scala:32
bounded-queue streaming; FeatureReducer merge in QueryPlanner.runQuery):
prune partitions by the plan's time bounds, stream each pruned partition
through RAM/HBM (loading spilled ones from disk, evicting over budget), run
the ordinary :class:`Executor` against it, and merge the additive results.
One plan → one traced kernel shared by every partition (kernel shapes are
bucketed in IndexTable.shard_len / windows).

**Sharded scan** (docs/SCALE.md): with more than one local device and
``geomesa.mesh.devices`` not disabled, additive aggregates (count /
density / density_curve / stats) fan the pruned partitions out
ROUND-ROBIN over the devices — partition i (in pruned-bin order) pins to
device i % D, its scan dispatches asynchronously (jax dispatch returns
before execution, so device d runs partition i while the one query thread
dispatches partition i+1 to the next device — the jit discipline is
untouched), and the per-device partials merge in the fixed order
:func:`geomesa_tpu.parallel.devices.tree_merge` documents. The merge
order depends only on the pruned-bin order, never on device assignment or
completion timing, and the serial path uses the SAME tree merge — so the
sharded scan is bit-identical to the single-device path by construction.
Non-additive ops (features/top/knn) keep the serial partition stream."""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience, tracing
from geomesa_tpu.parallel import health as phealth
from geomesa_tpu.filter import ir
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.kernels.registry import KernelRegistry
from geomesa_tpu.kernels import stats_scan as kstats
from geomesa_tpu.parallel import devices as pdev
from geomesa_tpu.planning.executor import Executor, check_deadline
from geomesa_tpu.planning.planner import QueryPlan
from geomesa_tpu.resilience import QueryTimeoutError
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk

_SKIPPED = object()  # sentinel: partition degraded away (fn may return None)
_UNSET = object()


def _coalesce_boxes(boxes: List[Tuple[float, float, float, float]]
                    ) -> List[Tuple[float, float, float, float]]:
    """Coalesce exactly-tiling boxes into a compact cover — the
    group-scoped plan-bounds pass for fleet-scattered sub-queries
    (docs/RESILIENCE.md §7): a scatter group's filter carries one BBOX
    per owned SFC cell (dozens of boxes in row-major runs), and every
    lake row group would otherwise test disjointness against each one.
    Two boxes merge only when their union is (up to one float ulp) a
    box: identical y-span and x-ranges that touch, overlap, or are one
    ulp apart — cell boxes are CLOSED realizations of half-open cells,
    so adjacent cells sit exactly one ulp apart — then the transpose
    pass for columns of identical x-span. Closing an ulp seam can only
    WIDEN the cover, which is always safe for pruning (a row group is
    dropped only when disjoint from every box; a wider box never drops
    more). Adjacent cell boxes in a row collapse to one strip, stacked
    strips to one window."""
    def _pass(bs, flip):
        def key(b):
            return (b[1], b[3], b[0]) if not flip else (b[0], b[2], b[1])

        bs = sorted(bs, key=key)
        out = [bs[0]]
        for b in bs[1:]:
            p = out[-1]
            if not flip and p[1] == b[1] and p[3] == b[3] \
                    and b[0] <= np.nextafter(p[2], np.inf):
                out[-1] = (p[0], p[1], max(p[2], b[2]), p[3])
            elif flip and p[0] == b[0] and p[2] == b[2] \
                    and b[1] <= np.nextafter(p[3], np.inf):
                out[-1] = (p[0], p[1], p[2], max(p[3], b[3]))
            else:
                out.append(b)
        return out

    if len(boxes) < 2:
        return boxes
    return _pass(_pass(boxes, flip=False), flip=True)


class PartitionedExecutor:
    def __init__(self, store: PartitionedFeatureStore, mesh=None,
                 prefer_device: bool = True, device=None):
        self.store = store
        self.mesh = mesh
        self.prefer_device = prefer_device
        #: serving-pool device pin: a slot executor streams every partition
        #: through ITS device (the pool owns one device per dispatch
        #: thread), which also disables the sharded fan-out below — two
        #: threads must never dispatch to one device (docs/SERVING.md)
        self.device = device
        #: jitted-kernel LRU shared across every partition child AND every
        #: aggregate-cache cell query (version-stable keys — docs/PERF.md).
        #: Also shared across the sharded scan's per-device executors AND
        #: every serving-pool slot's PartitionedExecutor over this store:
        #: hosted on the STORE (the same ``_kernel_registry`` slot plain
        #: Executors use via version_source), because keys are device-free
        #: — D devices or N pool slots cost ONE trace per kernel shape.
        reg = store.__dict__.get("_kernel_registry")
        if reg is None:
            reg = store.__dict__["_kernel_registry"] = KernelRegistry()
        self._kernel_fns = reg
        self._execs: Dict[int, Executor] = {}

    def kernel_registry(self) -> KernelRegistry:
        return self._kernel_fns

    # -- partition pruning (the TimePartition.partitions() analog) ---------
    def prune(self, plan: QueryPlan) -> List[int]:
        store = self.store
        bins = store.partition_bins()
        if plan.is_empty:
            return []
        kp = plan.key_plan
        if (
            kp.bins is not None
            and store.partition_period == store.ft.time_period
        ):
            sel = {int(x) for x in np.asarray(kp.bins).ravel()}
            return [b for b in bins if b in sel]
        dtg = store.ft.dtg_field
        iv = ir.extract_intervals(plan.filter, dtg) if dtg else None
        if iv is not None and not iv.is_empty:
            sel = set()
            for lo, hi in iv.values:
                if lo is None or hi is None:
                    return bins
                sel.update(
                    int(x) for x in store.binned.bins_between(int(lo), int(hi))
                )
            return [b for b in bins if b in sel]
        return bins

    def _executor_for(self, b: int, child, device=_UNSET) -> Executor:
        if device is _UNSET:
            device = self.device
        ex = self._execs.get(b)
        if ex is None or ex.store is not child \
                or getattr(ex, "device", None) is not device:
            ex = Executor(
                child, self.mesh, self.prefer_device,
                kernel_fns=self._kernel_fns, version_source=self.store,
                device=device,
            )
            self._execs[b] = ex
        return ex

    # -- multi-device sharded scan (docs/SCALE.md) -------------------------
    def _scan_devices(self):
        """Devices for the sharded fan-out, or None when it cannot engage:
        an explicit GSPMD mesh shards WITHIN partitions instead; a pinned
        (serving-pool slot) executor owns exactly one device; the host
        path has nothing to fan out; and ``geomesa.mesh.devices`` can turn
        it off (parallel/devices.py also stands down while a >1-executor
        pool runs)."""
        if self.mesh is not None or self.device is not None \
                or not self.prefer_device:
            return None
        return pdev.scan_devices()

    # -- double-buffered partition pipeline --------------------------------
    def _stage(self, child, plan: QueryPlan) -> None:
        """Prefetch-thread half of the double buffer: pull the partition's
        columns off disk (lazy snapshot members) and assemble the stacked
        [S, L] HOST arrays the device upload will consume. Pure host work —
        no jax calls, so all compile/dispatch stays on the query thread
        (the PR 1 one-query-thread jit discipline)."""
        names = plan.__dict__.get("needed_cols")
        if child is None or not names:
            return
        t = child.tables.get(plan.index_name)
        if t is not None and t.n:
            staged = t.stage_host(names)
            if staged:
                # per-query cost ledger: host bytes assembled for upload.
                # The prefetch worker adopted the query's span context, so
                # this lands on the right trace (docs/OBSERVABILITY.md)
                tracing.add_cost("bytes_staged", float(staged))
            metrics.inc(metrics.PIPELINE_PREFETCH)

    # -- lake row-group pushdown (docs/LAKE.md) ----------------------------
    def _push_window(self, plan: QueryPlan) -> Optional[Dict]:
        """The plan's conservative spatial/temporal bounds as a lake
        pruning window, or None when pushdown cannot engage (disabled,
        sampling hints — the 1-in-n counter is row-set dependent — or a
        filter that constrains neither axis). Extraction reuses the same
        ``ir.extract_*`` machinery partition/file pruning already trusts:
        a row group whose statistics are disjoint from every extracted
        bound provably holds no matching row."""
        if not config.LAKE_PUSHDOWN.to_bool():
            return None
        h = plan.hints
        if h.sampling is not None or h.sample_by is not None:
            return None
        ft = self.store.ft
        boxes = times = None
        geom = ft.geom_field
        if geom is not None and ft.attr(geom).is_point:
            fv = ir.extract_geometries(plan.filter, geom)
            if fv.disjoint:
                boxes = []
            elif not fv.is_empty:
                boxes = _coalesce_boxes([
                    tuple(float(v) for v in g.bounds())
                    for g in fv.values
                ])
        dtg = ft.dtg_field
        if dtg is not None:
            iv = ir.extract_intervals(plan.filter, dtg)
            if iv.disjoint:
                times = []
            elif not iv.is_empty:
                inf = float("inf")
                times = [
                    (-inf if lo is None else float(lo),
                     inf if hi is None else float(hi))
                    for lo, hi in iv.values
                ]
        if boxes is None and times is None:
            return None
        window = {"index": plan.index_name, "boxes": boxes, "times": times}
        # cross-chunk residency cache (docs/JOIN.md §11): the join's chunk
        # loop plants one cache on each re-planned side plan so boundary
        # row groups shared by adjacent chunk windows decode once
        residency = plan.__dict__.get("residency")
        if residency is not None:
            window["residency"] = residency
        return window

    def _get_child(self, b: int, window: Optional[Dict]):
        """Load one partition for the scan: statistics-pruned ephemeral
        child when a window is pushed down, the ordinary resident load
        otherwise (and always on plain FeatureStore children)."""
        if window is not None:
            sc = getattr(self.store, "scan_child", None)
            if sc is not None:
                return sc(b, window)
        return self.store.child(b)

    def _note_lake(self, plan: QueryPlan, note: Dict) -> None:
        """Fold one pruned partial load's account into the plan (explain
        ``exec_path``, the audit event, and the per-query cost ledger)."""
        acct = plan.__dict__.setdefault("lake_acct", {
            "groups_total": 0, "groups_loaded": 0, "groups_pruned": 0,
            "bytes_payload": 0, "bytes_loaded": 0, "bytes_skipped": 0,
        })
        for k in acct:
            acct[k] += int(note.get(k, 0))
        plan.__dict__.setdefault("exec_path", {})["lake"] = (
            f"{acct['groups_loaded']}/{acct['groups_total']} rowgroups, "
            f"{acct['bytes_loaded']}/{acct['bytes_payload']} bytes"
        )
        tracing.add_cost("lake_bytes_read", float(note["bytes_loaded"]))
        tracing.add_cost("lake_bytes_skipped",
                         float(note["bytes_skipped"]))
        metrics.inc(metrics.LAKE_PUSHDOWN_SCANS)

    def _children(self, plan: QueryPlan, bins: Optional[List[int]] = None,
                  window: Optional[Dict] = None):
        """(bin, child) over pruned partitions through the serial
        (one-staging-slot) prefetch pipeline — see :meth:`_pipeline`.
        ``bins`` overrides the plan's own pruning (the query-axis batch
        path scans the UNION of its members' pruned bins)."""
        if bins is None:
            bins = self.prune(plan)
        for _i, b, child in self._pipeline(plan, bins, window=window):
            yield b, child

    def _stage_device(self, child, plan: QueryPlan, dev) -> None:
        """device_put half of the sharded prefetch overlap (docs/PERF.md):
        upload the staged host arrays for the partition's assigned device
        FROM THE PREFETCH THREAD, overlapping the previous partition's
        execution on another device. Safe under the one-jit-thread-per-
        device discipline: device_put is a pure transfer — it never traces
        or compiles (the PR 1 wedge was jit compilation on foreign
        threads) — and it populates the same device cache, through the
        same per-device sharding singleton, the query thread would have
        populated itself, so results are bit-identical with the overlap
        off (gated by ``geomesa.pipeline.device-put``)."""
        names = plan.__dict__.get("needed_cols")
        if not names or child is None:
            return
        t = child.tables.get(plan.index_name)
        if t is None or not t.n:
            return
        t.device_columns(tuple(names), pdev.device_sharding(dev))
        metrics.inc(metrics.PIPELINE_DEVICE_PUT)

    def _pipeline(self, plan: QueryPlan, bins: List[int], devs=None,
                  window: Optional[Dict] = None):
        """(i, bin, child) over pruned partitions — THE prefetch
        pipeline, serial and sharded in one body. With
        ``geomesa.pipeline.prefetch`` (default on), a single worker
        thread stages partition host columns ahead of the consumer,
        granted ONE STAGING SLOT PER DEVICE (serial ``devs=None`` = one
        slot = the classic double buffer: partition i+1's load overlaps
        partition i's execution). With ``devs`` and
        ``geomesa.pipeline.device-put``, the worker also uploads each
        staged partition to its assigned device (a pure transfer — never
        traces or compiles — through the shared per-device sharding
        singleton; docs/PERF.md §3), so every device has its next
        partition's columns resident the moment its current scan drains.

        Consumption order is pruned-bin order in both modes; a load
        error re-raises on the query thread at the same point it would
        have sequentially; config overrides and the span context cross
        the thread boundary via snapshot/adopt (staged (name, L) keys
        and trace nesting must match the query thread exactly)."""
        # cost ledger: partition pruning effectiveness for this scan
        # (pruned = bins the plan's time bounds excluded outright)
        total_bins = len(self.store.partition_bins())
        tracing.add_cost("partitions_scanned", float(len(bins)))
        tracing.add_cost("partitions_pruned",
                         float(max(total_bins - len(bins), 0)))
        if len(bins) < 2 or not config.PIPELINE_PREFETCH.to_bool():
            for i, b in enumerate(bins):
                try:
                    child = self._get_child(b, window)
                except BaseException as e:
                    self._contain_load(plan, b, e)
                    continue
                if child is not None:
                    note = child.__dict__.get("_lake_note")
                    if note is not None:
                        self._note_lake(plan, note)
                yield i, b, child
            return
        out: "queue.Queue" = queue.Queue()
        stop = threading.Event()
        slot = threading.Semaphore(0)  # one permit per granted load
        overlap = devs is not None \
            and bool(config.PIPELINE_DEVICE_PUT.to_bool())
        ov = config.snapshot_overrides()
        tspan = tracing.snapshot()

        def worker():
            config.adopt_overrides(ov)
            tracing.adopt(tspan)
            try:
                for i, b in enumerate(bins):
                    while not slot.acquire(timeout=0.1):
                        if stop.is_set():
                            return
                    if stop.is_set():
                        return
                    attrs = {"part": int(b)}
                    dev = None
                    if devs is not None:
                        dev = devs[i % len(devs)]
                        attrs["device"] = int(dev.id)
                    child = err = None
                    try:
                        child = self._get_child(b, window)
                    except BaseException as e:
                        err = e  # a LOAD failure: _contain_load decides
                    if err is None and child is not None:
                        # staging (host assembly + device upload) is a
                        # best-effort OVERLAP, never the dispatch: a
                        # staging failure must not fail — or mislabel as
                        # a spill-load skip — a partition the dispatch
                        # can still serve by assembling on demand, and a
                        # fenced lane must stop receiving uploads
                        try:
                            with tracing.span("scan.stage", **attrs):
                                self._stage(child, plan)
                                if overlap:
                                    if phealth.registry().usable(dev.id):
                                        self._stage_device(child, plan,
                                                           dev)
                        except Exception:
                            pass  # dispatch re-stages on demand
                        except BaseException as e:
                            err = e  # interpreter teardown etc.: surface
                    out.put((i, b, child if err is None else None, err))
            finally:
                out.put(None)

        t = threading.Thread(
            target=worker, daemon=True,
            name="geomesa-part-prefetch" if devs is None
            else "geomesa-shard-prefetch",
        )
        t.start()
        for _ in range(1 if devs is None else len(devs)):
            slot.release()  # the first load(s) start immediately
        try:
            while True:
                item = out.get()
                if item is None:
                    return
                # grant the NEXT load now: it overlaps this partition's
                # execution — at most one in-flight partition per slot
                slot.release()
                i, b, child, err = item
                if err is not None:
                    self._contain_load(plan, b, err)
                    continue
                if child is not None:
                    # lake accounting folds on the CONSUMER thread — the
                    # plan dict is single-thread-mutated like every other
                    # counter (the worker only loads)
                    note = child.__dict__.get("_lake_note")
                    if note is not None:
                        self._note_lake(plan, note)
                yield i, b, child
        finally:
            stop.set()
            # JOIN, not fire-and-forget: an early consumer exit
            # (max_features, deadline) must not leave the worker mutating
            # the partition map under a follow-up query's unlocked readers
            # (partition_bins, flush loops). The wait is bounded by the
            # in-flight loads (worker observes `stop` right after each).
            t.join()
            # free staged host arrays of prefetched-but-never-executed
            # partitions (their loop-body cleanup never ran)
            while True:
                try:
                    item = out.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                _, _, child, _ = item
                if child is not None:
                    tb = child.tables.get(plan.index_name)
                    if tb is not None:
                        tb._host_stage.clear()

    def _dispatch_reassign(self, plan: QueryPlan, b: int, child, i: int,
                           op: str, dispatch, live: List, state: Dict):
        """One partition's dispatch under the device fault-tolerance
        contract (docs/RESILIENCE.md §6). The partition pins to
        ``live[i % len(live)]`` — pruned-bin round-robin over the devices
        still SURVIVING this scan (cordoned/broken lanes are skipped; a
        lane that fails here is dropped, so its pending partitions requeue
        onto the survivors). Each attempt passes the
        ``scan.device.dispatch`` fault point; a failed attempt feeds the
        device's breaker (``parallel/health.py``) and retries on the next
        survivor under a seeded RetryPolicy (``geomesa.retry.*``, seed =
        the partition bin — a chaos run replays identically). Exhausted
        retries, or no survivors, re-raise into ``_scan_part``'s
        degradation contract: exact survivor totals under
        ``allow_partial()``, typed failure otherwise, never a wedge.

        Bit-identity holds by construction: whichever device computes a
        partial, it enters the tree reduction in pruned-bin order — the
        only order :func:`~geomesa_tpu.parallel.devices.tree_merge` ever
        sees — so a recovered run is bit-identical to a healthy one
        (asserted by tests/test_chaos.py)."""
        hreg = phealth.registry()
        policy = resilience.RetryPolicy.from_config(seed=int(b))
        attempts = max(policy.attempts, 1)
        delays = policy.delays_ms()
        last: Optional[BaseException] = None
        removed_here: List = []  # lanes this PARTITION's attempts removed
        for attempt in range(attempts):
            # rotate past lanes health has fenced since the scan started
            while live and not hreg.usable(live[i % len(live)].id):
                live.pop(i % len(live))
            if not live:
                break
            dev = live[i % len(live)]
            try:
                resilience.fault_point(
                    "scan.device.dispatch", bin=int(b),
                    device=int(dev.id), op=op, attempt=attempt,
                )
                ex = self._executor_for(b, child, device=dev)
                r = dispatch(ex)
            except QueryTimeoutError:
                raise
            except Exception as e:
                last = e
                hreg.record_failure(dev.id, e)
                try:
                    live.remove(dev)
                    removed_here.append(dev)
                except ValueError:
                    pass
                if attempt + 1 >= attempts or not live:
                    break
                # requeue onto the next survivor (round-robin continues
                # over the shrunken rotation)
                hreg.note_reassigned(dev.id)
                metrics.inc(metrics.SCAN_REASSIGNED)
                tracing.event("scan.reassigned", part=int(b),
                              device=int(dev.id), error=type(e).__name__)
                d = delays[attempt] if attempt < len(delays) else 0.0
                if d > 0:
                    policy.sleep(d / 1000.0)
                check_deadline()
                continue
            # success on a survivor: lanes removed above STAY removed —
            # the same partition worked elsewhere, so the evidence is
            # lane-scoped. The device's own breaker success is recorded
            # at SYNC time (_finish_oldest), where execution errors
            # actually surface — an enqueue is not evidence of health.
            state["device"] = dev
            return r
        # the partition failed on EVERY lane it tried: the evidence is
        # PARTITION-scoped (bad data / oversized staging), not lane-
        # scoped — restore the lanes it removed so one poison partition
        # cannot fence the whole mesh off for the rest of the scan
        # (their breakers keep the charge; genuinely dead lanes still
        # accumulate consecutive failures across partitions)
        for dev in removed_here:
            if hreg.usable(dev.id) and dev not in live:
                live.append(dev)
        if last is not None:
            raise last
        raise RuntimeError(
            "no surviving devices for the sharded scan (all cordoned or "
            "broken mid-scan)"
        )

    def _contain_load(self, plan: QueryPlan, b: int, err: BaseException):
        """Degradation contract for a partition LOAD failure (a corrupt
        or unreadable spill snapshot — ``index/partitioned.py``'s
        ``index.spill.load`` edge): under ``allow_partial()`` the
        partition is skipped with a recorded degradation (exact survivor
        totals, same as a scan failure); strict mode — and any deadline
        expiry or non-Exception — re-raises at the point the sequential
        load would have. Before this, a spill-load failure took the whole
        query down even in degraded mode (ROADMAP resilience item)."""
        if isinstance(err, QueryTimeoutError) \
                or not isinstance(err, Exception) \
                or not resilience.partial_allowed():
            raise err
        rec = resilience.record_skip(
            "index.spill.load", f"bin:{b}", err, phase="load"
        )
        plan.__dict__.setdefault("degraded", []).append(rec)

    def _sharded_scan(self, plan: QueryPlan, op: str, dispatch, finish,
                      devs, bins: List[int],
                      window: Optional[Dict] = None) -> None:
        """Round-robin fan-out of one additive op over ``devs``:
        ``dispatch(ex)`` runs per pruned partition against an executor
        pinned to the partition's device (it must return WITHOUT forcing
        a device sync). Each partial is handed to ``finish(bin, partial,
        merge_device)`` in pruned-bin order — the only order the merge
        ever sees — but DEFERRED until D further partitions have been
        dispatched (or the scan ends), so every device keeps executing
        while older partials sync/merge and at most D partials plus the
        reducer spine are ever outstanding (never all P). finish runs
        under the same degradation guard as the scan, attributing a
        sync-time device failure to its partition; its sync wall time
        feeds the device's latency-outlier detector (a straggler lane is
        fenced like a failing one — parallel/health.py). Dispatch
        failures requeue the partition onto surviving devices
        (:meth:`_dispatch_reassign`)."""
        metrics.inc(metrics.SCAN_SHARDED)
        from collections import deque

        pending: "deque" = deque()  # (bin, partial, device) awaiting finish
        mdev = devs[0]  # the device the serial path computes on
        hreg = phealth.registry()
        #: devices still surviving THIS scan (failed lanes drop out and
        #: their pending partitions requeue round-robin onto the rest)
        live: List = list(devs)

        def _finish_oldest():
            fb, fr, fdev, fshape = pending.popleft()
            t0 = time.perf_counter()

            def _fin():
                # jax dispatch is async: execution errors surface HERE,
                # at the blocking sync — so health verdicts are recorded
                # at sync time, not enqueue time (an enqueue that
                # "succeeded" on a dead device is not evidence of
                # health, and must not reset its breaker)
                try:
                    out = finish(fb, fr, mdev)
                except QueryTimeoutError:
                    raise
                except Exception as e:
                    if fdev is not None:
                        hreg.record_failure(fdev.id, e)
                    raise
                if fdev is not None:
                    hreg.record_success(fdev.id)
                return out

            self._scan_part(plan, fb, op, _fin,
                            probe=False, spanned=False)
            if fdev is not None:
                # baseline keyed by kernel shape (op + padded-length
                # bucket): heterogeneous ops/partition sizes each compare
                # against their own trailing median (RESILIENCE.md §6)
                hreg.record_latency(fdev.id, time.perf_counter() - t0,
                                    shape=fshape)

        tot_scanned = tot_rows = 0
        try:
            for i, b, child in self._pipeline(plan, bins, devs,
                                              window=window):
                check_deadline()
                if child is None or child.count == 0:
                    continue
                plan.__dict__.pop("scanned_rows", None)
                plan.__dict__.pop("table_rows", None)
                state: Dict = {}
                r = self._scan_part(
                    plan, b, op,
                    lambda b=b, i=i, child=child, state=state:
                        self._dispatch_reassign(plan, b, child, i, op,
                                                dispatch, live, state),
                    device=live[i % len(live)] if live else None,
                )
                tot_scanned += plan.__dict__.pop("scanned_rows", 0)
                tot_rows += plan.__dict__.pop("table_rows", 0)
                dev = state.get("device")
                if dev is not None:
                    metrics.inc(f"{metrics.SCAN_SHARDED_DEVICE}.{dev.id}")
                if r is not _SKIPPED and r is not None:
                    # kernel-shape key: the op plus the partition's padded-
                    # length bucket (geomesa.partition.shard.bucket rounds
                    # child tables to multiples, so equal buckets share a
                    # compiled kernel shape)
                    lbucket = config.SHARD_LEN_BUCKET.to_int() or 65536
                    shape = (op, -(-child.count // max(lbucket, 1)))
                    pending.append((b, r, dev, shape))
                # dispatched work holds its own buffer references: staged
                # host arrays and evicted children free safely here even
                # while the device is still executing
                t = child.tables.get(plan.index_name)
                if t is not None:
                    t._host_stage.clear()
                self.store.evict()
                resident = self.store.partitions
                for bb in list(self._execs):
                    if self._execs[bb].store is not resident.get(bb):
                        del self._execs[bb]
                while len(pending) > len(devs):
                    _finish_oldest()
            while pending:
                _finish_oldest()
        finally:
            plan.__dict__["scanned_rows"] = tot_scanned
            plan.__dict__["table_rows"] = tot_rows
        self._note_sharded(plan, len(bins), len(devs))

    def _note_sharded(self, plan: QueryPlan, n_parts: int, n_devs: int):
        plan.__dict__.setdefault("exec_path", {}).update(
            sharded=f"{n_parts} partitions over {n_devs} devices"
        )

    def _additive_scan(self, plan: QueryPlan, op: str, dispatch,
                       finish, bins: Optional[List[int]] = None,
                       push: bool = False) -> None:
        """Drive one additive op over the pruned partitions, delivering
        each partition's partial to ``finish(bin, partial, merge_device)``
        in pruned-bin order. The sharded fan-out serves when it engages
        (merge_device = the first local device — where the serial path
        computes — so the merge is bit-identical); otherwise the serial
        partition stream runs finish immediately after each partition
        (merge_device None), exactly the pre-sharding cadence. Both
        paths guard finish with the _scan_part degradation contract, so
        a device failure surfacing at sync time skips that partition
        with exact survivor totals instead of failing the query under
        ``allow_partial()``. ``bins`` overrides the plan's pruning (the
        query-axis batch path scans its members' pruned-bin UNION).

        ``push=True``: the op's partial merge is exact over any superset
        of the matching rows (count / unweighted density / unweighted
        density_curve / stats), so spilled lake partitions may serve a
        statistics-pruned PARTIAL load (docs/LAKE.md) — row groups whose
        bbox/time statistics are disjoint from the plan's bounds never
        leave disk, and the surviving groups decode into the same
        prefetch pipeline bit-identically."""
        window = self._push_window(plan) if push else None
        try:
            devs = self._scan_devices()
            if devs is not None:
                if bins is None:
                    bins = self.prune(plan)
                if len(bins) >= 2:
                    self._sharded_scan(plan, op, dispatch, finish, devs,
                                       bins, window=window)
                    return
            for b, ex in self._each(plan, bins=bins, window=window):
                r = self._scan_part(plan, b, op, lambda: dispatch(ex))
                if r is not _SKIPPED and r is not None:
                    self._scan_part(plan, b, op,
                                    lambda: finish(b, r, None),
                                    probe=False, spanned=False)
        finally:
            self._note_pushdown_fallbacks(plan, window)

    @staticmethod
    def _note_pushdown_fallbacks(plan: QueryPlan,
                                 window: Optional[Dict]) -> None:
        """Fold the partitions pushdown could NOT serve pruned (exotic /
        unbuildable keyspace, pre-lake snapshot — recorded on the window
        by ``scan_child``) into explain/audit ``exec_path``, so a full
        load never reads as "pushdown covered everything"
        (docs/LAKE.md §10)."""
        fallbacks = (window or {}).get("fallbacks") if window else None
        if not fallbacks:
            return
        reasons: Dict[str, int] = {}
        for _b, reason in fallbacks:
            reasons[reason] = reasons.get(reason, 0) + 1
        plan.__dict__.setdefault("exec_path", {})["lake_fallback"] = (
            f"{len(fallbacks)} partition(s) full-loaded: "
            + ", ".join(f"{r} x{n}" for r, n in sorted(reasons.items()))
        )

    def _each(self, plan: QueryPlan,
              bins: Optional[List[int]] = None,
              window: Optional[Dict] = None) -> Iterator[Tuple[int, Executor]]:
        """Stream (bin, executor) over pruned partitions under the residency
        budget; accumulates the selectivity counters across partitions."""
        tot_scanned = tot_rows = 0
        try:
            for b, child in self._children(plan, bins, window=window):
                check_deadline()
                if child is None or child.count == 0:
                    continue
                plan.__dict__.pop("scanned_rows", None)
                plan.__dict__.pop("table_rows", None)
                yield b, self._executor_for(b, child)
                tot_scanned += plan.__dict__.pop("scanned_rows", 0)
                tot_rows += plan.__dict__.pop("table_rows", 0)
                # free staged host arrays the scan didn't consume (host
                # path, projection change): staging is per-partition-pass,
                # never a resident duplicate of the device columns
                t = child.tables.get(plan.index_name)
                if t is not None:
                    t._host_stage.clear()
                self.store.evict()
                resident = self.store.partitions
                for bb in list(self._execs):
                    if self._execs[bb].store is not resident.get(bb):
                        del self._execs[bb]  # frees the child's device arrays
        finally:
            # an early consumer exit (features() hitting max_features)
            # closes the generator AT the yield: the just-scanned
            # partition's counters are still on the plan — fold them in
            tot_scanned += plan.__dict__.get("scanned_rows", 0)
            tot_rows += plan.__dict__.get("table_rows", 0)
            plan.__dict__["scanned_rows"] = tot_scanned
            plan.__dict__["table_rows"] = tot_rows

    def _scan_part(self, plan: QueryPlan, b: int, op: str, fn, device=None,
                   probe: bool = True, spanned: bool = True):
        """One partition's scan under the degradation contract
        (docs/RESILIENCE.md): strict mode re-raises; under
        ``resilience.allow_partial()`` / ``geomesa.scan.partial`` a failing
        partition is recorded (collector + audit trail + the plan, for the
        query audit event) and skipped — returns the ``_SKIPPED`` sentinel.
        Deadline expiry always propagates: a timed-out scan must never
        masquerade as a degraded-but-complete one. ``device``: the sharded
        scan's assigned device — stamped on the span (per-device
        attribution, docs/OBSERVABILITY.md); on that path the span covers
        dispatch only (execution is async by design). ``probe=False`` /
        ``spanned=False``: the finish (sync/merge) half of a partition —
        same degradation handling, but no second fault-injection probe
        (one probe per partition keeps seeded chaos tests deterministic)
        and no second scan.partition span (sync time attributes to the
        op's parent span, as the pre-sharding merges did)."""
        try:
            if probe:
                resilience.fault_point("exec.partition.scan", bin=b, op=op)
            if not spanned:
                return fn()
            attrs = {"part": int(b), "op": op}
            if device is not None:
                attrs["device"] = int(device.id)
            with tracing.span("scan.partition", **attrs):
                return fn()
        except QueryTimeoutError:
            raise
        except Exception as e:
            if not resilience.partial_allowed():
                raise
            rec = resilience.record_skip(
                "exec.partition.scan", f"bin:{b}", e, phase=op
            )
            plan.__dict__.setdefault("degraded", []).append(rec)
            return _SKIPPED

    # -- public operations (Executor surface) ------------------------------
    # Additive aggregates collect per-partition partials (async-dispatched
    # round-robin over the local devices when the sharded scan engages)
    # and merge in pruned-bin order via the fixed tree reduction
    # parallel/devices.tree_merge documents — serial and sharded paths
    # share the merge code, so they are bit-identical by construction.
    def count(self, plan: QueryPlan) -> int:
        # counts merge as exact host integers (a device tree-add would
        # accumulate in int32 and overflow past 2^31 total rows); on the
        # sharded path each int() waits on a partial whose device was
        # dispatched D partitions ago, so the devices stay concurrent
        totals: List[int] = []
        self._additive_scan(
            plan, "count", lambda ex: ex.count_partial(plan),
            lambda b, p, mdev: totals.append(int(p)),
            push=True,
        )
        return sum(totals)

    def density(self, plan: QueryPlan, bbox, width: int, height: int,
                weight: Optional[str] = None, as_numpy: bool = True):
        import jax

        # merge ON DEVICE (per-partition grid downloads would ride the
        # host link once per partition per call) through the streaming
        # tree reduction — bit-identical to tree_merge over all partials,
        # holding O(log P) grids instead of P; sharded partials first
        # transfer to the merge device (jax.devices()[0], where the
        # serial path computes)
        red = pdev.TreeReducer(lambda a, b: a + b)

        def finish(b, p, mdev):
            if mdev is not None:
                p = jax.device_put(p, pdev.device_sharding(mdev))
            red.push(p)

        self._additive_scan(
            plan, "density",
            lambda ex: ex.density(plan, bbox, width, height, weight,
                                  as_numpy=False),
            finish,
            # unweighted grids are integer-valued (exact adds); weighted
            # grids keep full loads — a NaN/-0.0 weight on a pruned-away
            # non-matching row could still perturb the masked scatter
            push=weight is None,
        )
        out = red.result()
        if out is None:
            return np.zeros((height, width), np.float32)
        return np.asarray(out) if as_numpy else out

    def density_curve(self, plan: QueryPlan, level: int, block_window,
                      weight=None) -> np.ndarray:
        # decode syncs each partition's partial (deferred D partitions on
        # the sharded path) and the f64 host grids reduce in pruned-bin
        # tree order (integer counts are exact to 2^53; identical bits on
        # both paths)
        red = pdev.TreeReducer(lambda a, b: a + b)
        self._additive_scan(
            plan, "density_curve",
            lambda ex: ex.density_curve_raw(plan, level, block_window,
                                            weight),
            lambda b, p, mdev: red.push(Executor.decode_curve(p)),
            push=weight is None,  # see density: integer block counts only
        )
        out = red.result()
        if out is None:
            ix0, iy0, ix1, iy1 = block_window
            out = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
        return out

    def density_curve_batch(self, plan: QueryPlan, level: int,
                            block_windows, weight=None):
        """Fused tile batch over the partitioned store: each pruned
        partition executes ONE stacked device pass for every member crop
        (Executor.density_curve_batch), and per-member grids tree-merge
        across partitions — M concurrent tile queries cost one scan of the
        pruned partitions, not M (docs/SERVING.md)."""
        # one streaming reduction over the per-partition member LISTS:
        # elementwise combine keeps every member's association identical
        # to a per-member tree_merge over the same partials
        red = pdev.TreeReducer(
            lambda A, B: [a + b for a, b in zip(A, B)]
        )
        self._additive_scan(
            plan, "density_curve",
            lambda ex: ex.density_curve_batch_raw(
                plan, level, block_windows, weight
            ),
            lambda b, p, mdev: red.push(Executor.decode_curve_batch(p)),
        )
        merged = red.result()
        outs = []
        for i, (ix0, iy0, ix1, iy1) in enumerate(block_windows):
            g = merged[i] if merged is not None else None
            if g is None:
                g = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
            outs.append(g)
        return outs

    def density_curve_filter_batch(self, plans: List[QueryPlan], spec,
                                   level: int, block_windows, weight=None):
        """M DISTINCT-filter curve crops over the partitioned store in
        one stacked device pass per pruned partition (None = ineligible;
        docs/SERVING.md "Query-axis batching", curve extension). Members'
        pruned-bin UNION scans once; per-member grids tree-merge across
        partitions exactly like :meth:`density_curve_batch`."""
        if spec is None:
            return None
        agg_cols = [weight] if weight else []
        bins = self._union_bins(plans)
        if not self._batch_ok(plans, spec, bins, agg_cols):
            return None
        red = pdev.TreeReducer(
            lambda A, B: [a + b for a, b in zip(A, B)]
        )

        def dispatch(ex):
            r = ex.density_curve_filter_batch_raw(
                plans, spec, level, block_windows, weight
            )
            if r is None:
                # partition-local ineligibility (e.g. surviving f32 band
                # rows in THIS partition): degrade this partition to
                # per-member serial curves — exact, never dropped — while
                # the other partitions keep the batched pass
                return ("serial", [
                    Executor.decode_curve(
                        ex.density_curve_raw(p, level, bw, weight)
                    )
                    for p, bw in zip(plans, block_windows)
                ])
            return r

        def finish(b, p, mdev):
            if isinstance(p, tuple) and len(p) == 2 and p[0] == "serial":
                red.push(p[1])
            else:
                red.push(Executor.decode_curve_filter_batch(p))

        self._additive_scan(plans[0], "density_curve", dispatch, finish,
                            bins=bins)
        merged = red.result()
        outs = []
        for i, (ix0, iy0, ix1, iy1) in enumerate(block_windows):
            g = merged[i] if merged is not None else None
            if g is None:
                g = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
            outs.append(g)
        return outs

    # -- query-axis batched aggregates (docs/SERVING.md "Query-axis
    # batching"): each pruned partition executes ONE stacked device pass
    # for every member viewport, and per-member partials accumulate
    # through the SAME pruned-bin tree-merge order the serial and sharded
    # paths share — so the batch composes with the device mesh and a
    # degraded partition skips for every member alike (exact per-member
    # survivor totals).
    def _union_bins(self, plans: List[QueryPlan]) -> List[int]:
        """Members' pruned-bin UNION, in store partition order. A member
        whose own pruning excludes a bin contributes an all-empty window
        set there — a zero partial, which is the additive identity, so
        per-member results equal their serial (member-pruned) runs."""
        sel = set()
        for p in plans:
            sel.update(self.prune(p))
        return [b for b in self.store.partition_bins() if b in sel]

    def _batch_ok(self, plans: List[QueryPlan], spec, bins: List[int],
                  agg_cols=()) -> bool:
        """Partition-invariant batch eligibility, decided once from the
        first non-empty pruned partition (children share the schema,
        dictionaries, and column layout). ``bins`` is the caller's
        already-computed union (pruning M plans is not free — compute it
        once, probe and scan with the same list); ``agg_cols`` must be
        the op's aggregation columns — a host-only weight column flips
        ``use_device`` off, and the probe must see it or the per-
        partition dispatches would fail where the caller expects the
        None degrade."""
        if self.mesh is not None or not self.prefer_device:
            return False
        for b in bins:
            child = self.store.child(b)
            if child is None or child.count == 0:
                continue
            ex = self._executor_for(b, child)
            bs = ex._batch_setups(plans, spec, agg_cols)
            return bs is not None
        return True  # nothing to scan: zeros for everyone

    def count_batch(self, plans: List[QueryPlan], spec):
        """M distinct counts over the partitioned store in one device
        dispatch per pruned partition (None = ineligible)."""
        bins = self._union_bins(plans)
        if not self._batch_ok(plans, spec, bins):
            return None
        M = len(plans)
        totals = [0] * M
        carrier = plans[0]

        def finish(b, p, mdev):
            for m, v in enumerate(Executor.decode_count_batch(p, M)):
                totals[m] += v

        def dispatch(ex):
            r = ex.count_batch_partial(plans, spec)
            if r is None:
                # eligibility is partition-invariant (checked up front):
                # a None here is a bug, and returning it would silently
                # DROP this partition's contribution — fail loudly into
                # the degradation contract instead
                raise RuntimeError("batched count ineligible mid-scan")
            return r

        self._additive_scan(
            carrier, "count", dispatch,
            finish, bins=bins,
        )
        return totals

    def density_batch(self, plans: List[QueryPlan], spec, bboxes,
                      width: int, height: int, weight=None):
        """M distinct heatmaps over the partitioned store (None =
        ineligible). Per-member grids reduce across partitions in the
        shared tree-merge order; a member's extra (member-pruned-away)
        partitions contribute exact-zero grids — the additive identity."""
        geom = self.store.ft.geom_field
        agg_cols = [geom + "__x", geom + "__y"] \
            + ([weight] if weight else [])
        bins = self._union_bins(plans)
        if not self._batch_ok(plans, spec, bins, agg_cols):
            return None
        M = len(plans)
        red = pdev.TreeReducer(lambda A, B: [a + b for a, b in zip(A, B)])

        def finish(b, p, mdev):
            red.push(Executor.decode_density_batch(p, M, width, height))

        def dispatch(ex):
            r = ex.density_batch_partial(plans, spec, bboxes, width,
                                         height, weight)
            if r is None:  # see count_batch: never drop silently
                raise RuntimeError("batched density ineligible mid-scan")
            return r

        self._additive_scan(
            plans[0], "density", dispatch,
            finish, bins=bins,
        )
        merged = red.result()
        if merged is None:
            return [np.zeros((height, width), np.float32)
                    for _ in range(M)]
        return merged

    def stats_batch(self, plans: List[QueryPlan], spec, stats):
        """M distinct stats scans over the partitioned store (None =
        ineligible). Per-member partials absorb in pruned-bin order —
        the exact absorb sequence each member's serial scan performs."""
        if any(not kstats.batch_supported(s) for s in stats):
            return None
        bins = self._union_bins(plans)
        if not self._batch_ok(plans, spec, bins):
            return None
        saw_ineligible = [False]

        def finish(b, p, mdev):
            Executor.absorb_stats_batch(p, stats, self.store.dicts)

        def dispatch(ex):
            if saw_ineligible[0]:
                # the batch is already doomed to the query-at-a-time
                # fallback: don't burn device passes on partitions whose
                # partials will be discarded
                return None
            r = ex.stats_batch_partials(plans, spec, stats)
            if r is None:
                # a partition whose band rows force the host path: the
                # whole batch must degrade to query-at-a-time (raising
                # here would only skip the partition under allow_partial)
                saw_ineligible[0] = True
                return None
            return r

        self._additive_scan(
            plans[0], "stats", dispatch, finish,
            bins=bins,
        )
        if saw_ineligible[0]:
            return None
        return stats

    def _stats_device_ok(self, plan: QueryPlan, stat: sk.Stat) -> bool:
        """Can every leaf of ``stat`` update on device? Decided once from
        the first non-empty pruned partition (children share the schema
        and dictionaries, so the answer is partition-invariant)."""
        for b in self.prune(plan):
            child = self.store.child(b)
            if child is None or child.count == 0:
                continue
            ex = self._executor_for(b, child)
            return ex._stats_bundle(plan, stat) is not None
        return False

    def stats(self, plan: QueryPlan, stat: sk.Stat) -> sk.Stat:
        if self._scan_devices() is not None \
                and self._stats_device_ok(plan, stat):
            # absorb in pruned-bin order — the exact sequence of
            # absorb_partials calls the serial loop performs (deferred D
            # partitions behind dispatch on the fan-out)
            self._additive_scan(
                plan, "stats",
                lambda ex: ex.stats_partials(plan, stat)[1],
                lambda b, p, mdev: kstats.absorb_partials(
                    stat, p, self.store.dicts
                ),
                push=True,  # sketches observe only matching rows
            )
            return stat
        window = self._push_window(plan)
        try:
            for b, ex in self._each(plan, window=window):
                self._scan_part(plan, b, "stats",
                                lambda: ex.stats(plan, stat))
        finally:
            self._note_pushdown_fallbacks(plan, window)
        return stat

    def features_iter(self, plan: QueryPlan, batch_rows: Optional[int] = None,
                      window: Optional[Dict] = None):
        """Stream matching rows partition-at-a-time: peak memory is one
        partition's gather, never the whole result (AbstractBatchScan /
        ArrowScan streaming contract). ``window``: an optional lake
        pruning window (``_push_window``) — spilled partitions then load
        only the row groups whose footer statistics intersect it; the
        residual filter still runs on every loaded row, so the yielded
        rows are exactly the plan's matches (``features_pushdown`` is
        the materializing wrapper that builds the window)."""
        got = 0
        limit = plan.hints.max_features if not plan.hints.sort_by else None
        for b, ex in self._each(plan, window=window):
            if resilience.partial_allowed():
                # degraded mode: materialize the partition before any yield,
                # so a failing partition drops WHOLE — never half-streamed
                batches = self._scan_part(
                    plan, b, "features",
                    lambda: list(ex.features_iter(plan, batch_rows)),
                )
                if batches is _SKIPPED:
                    continue
            else:
                # strict mode streams chunk-at-a-time (the ArrowScan
                # contract): max_features can return mid-partition without
                # gathering the rest
                resilience.fault_point("exec.partition.scan", bin=b,
                                       op="features")
                batches = ex.features_iter(plan, batch_rows)
            for batch in batches:
                if not batch.n:
                    continue
                if limit is not None:
                    if got >= limit:
                        return
                    if got + batch.n > limit:
                        keep = limit - got
                        yield ColumnBatch(
                            {k: v[:keep] for k, v in batch.columns.items()},
                            keep,
                        )
                        return
                got += batch.n
                yield batch
            if limit is not None and got >= limit:
                return

    def features(self, plan: QueryPlan) -> ColumnBatch:
        batches = list(self.features_iter(plan))
        return ColumnBatch.concat(batches) if batches else ColumnBatch({}, 0)

    def features_pushdown(self, plan: QueryPlan) -> ColumnBatch:
        """Materialize matching rows with the lake statistics window
        engaged: spilled partitions load only the row groups whose
        footer bbox/time statistics intersect the plan's extracted
        bounds (docs/LAKE.md). EXACT for row retrieval — a pruned
        group's statistics prove it holds no row inside the plan's
        bounds, so the surviving groups contain every matching row and
        the residual filter runs bit-identically on the loaded subset.
        Falls back to the plain full load whenever the window cannot
        engage (``_push_window`` returns None) or a partition cannot
        serve pruned (``_note_pushdown_fallbacks`` records those). The
        adaptive join's side scan streams the probe side through this
        per cell-group window instead of materializing it whole
        (docs/JOIN.md §10)."""
        window = self._push_window(plan)
        try:
            batches = list(self.features_iter(plan, window=window))
        finally:
            self._note_pushdown_fallbacks(plan, window)
        return ColumnBatch.concat(batches) if batches else ColumnBatch({}, 0)

    def top_batch(self, plan: QueryPlan, attr: str, descending: bool,
                  k: int, names=None,
                  include_ties: bool = False) -> Optional[ColumnBatch]:
        """Candidate rows for a sorted+limited query over the partitioned
        store: each pruned partition contributes ITS OWN device-selected
        top-k candidates (threshold select, boundary ties included when
        asked), so the union provably contains the global top-k — the
        caller's exact host sort + truncate finishes the job. Partitions
        whose device selection declines (tie overflow, NaN-keyed
        underfill) contribute their full match set instead, which is
        still a superset. The reference sorts client-side after merging
        per-partition scans (QueryPlanner.runQuery); here each partition
        ships at most k + tie-slack rows to the host."""
        parts: List[ColumnBatch] = []
        pushed = 0
        for b, ex in self._each(plan):
            def one_part(ex=ex):
                idx = ex.top_rows(plan, attr, descending, k,
                                  include_ties=include_ties)
                if idx is None:
                    return None, ex.features(plan)
                if len(idx) == 0:
                    return True, None  # device ran and found nothing
                table = ex.store.tables[plan.index_name]
                return True, table.host_gather_positions(idx, names)

            got = self._scan_part(plan, b, "top", one_part)
            if got is _SKIPPED:
                continue
            dev, batch = got
            if dev:
                pushed += 1
            if batch is not None and batch.n:
                parts.append(batch)
        if pushed == 0:
            # no partition device-selected anything: report None so the
            # caller runs (and its audit records) the plain gather path
            return None
        if not parts:
            return ColumnBatch({}, 0)
        return ColumnBatch.concat(parts)

    def knn_features(self, plan: QueryPlan, x: float, y: float,
                     k: int, boxes=None) -> ColumnBatch:
        """Per-partition top-k gathered and merged; the union of partition
        top-ks contains the global top-k (caller orders and truncates)."""
        parts = []
        for b, ex in self._each(plan):
            def one_part(ex=ex):
                idx, _ = ex.knn(plan, x, y, k, boxes=boxes)
                if len(idx) == 0:
                    return None
                table = ex.store.tables[plan.index_name]
                mask = np.zeros(table.n_shards * table.shard_len, bool)
                mask[idx] = True
                return table.host_gather(mask)

            batch = self._scan_part(plan, b, "knn", one_part)
            if batch is not _SKIPPED and batch is not None:
                parts.append(batch)
        return ColumnBatch.concat(parts) if parts else ColumnBatch({}, 0)
