"""Partition-at-a-time query execution over a PartitionedFeatureStore.

The runtime role of the reference's per-partition range scans + client merge
(TablePartition tables scanned per partition, AbstractBatchScan.scala:32
bounded-queue streaming; FeatureReducer merge in QueryPlanner.runQuery):
prune partitions by the plan's time bounds, stream each pruned partition
through RAM/HBM (loading spilled ones from disk, evicting over budget), run
the ordinary :class:`Executor` against it, and merge the additive results.
One plan → one traced kernel shared by every partition (kernel shapes are
bucketed in IndexTable.shard_len / windows)."""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.planning.executor import Executor, check_deadline
from geomesa_tpu.planning.planner import QueryPlan
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk


class PartitionedExecutor:
    def __init__(self, store: PartitionedFeatureStore, mesh=None,
                 prefer_device: bool = True):
        self.store = store
        self.mesh = mesh
        self.prefer_device = prefer_device
        #: jitted kernels shared across every partition child
        self._kernel_fns: Dict = {}
        self._execs: Dict[int, Executor] = {}

    # -- partition pruning (the TimePartition.partitions() analog) ---------
    def prune(self, plan: QueryPlan) -> List[int]:
        store = self.store
        bins = store.partition_bins()
        if plan.is_empty:
            return []
        kp = plan.key_plan
        if (
            kp.bins is not None
            and store.partition_period == store.ft.time_period
        ):
            sel = {int(x) for x in np.asarray(kp.bins).ravel()}
            return [b for b in bins if b in sel]
        dtg = store.ft.dtg_field
        iv = ir.extract_intervals(plan.filter, dtg) if dtg else None
        if iv is not None and not iv.is_empty:
            sel = set()
            for lo, hi in iv.values:
                if lo is None or hi is None:
                    return bins
                sel.update(
                    int(x) for x in store.binned.bins_between(int(lo), int(hi))
                )
            return [b for b in bins if b in sel]
        return bins

    def _executor_for(self, b: int, child) -> Executor:
        ex = self._execs.get(b)
        if ex is None or ex.store is not child:
            ex = Executor(
                child, self.mesh, self.prefer_device,
                kernel_fns=self._kernel_fns, version_source=self.store,
            )
            self._execs[b] = ex
        return ex

    def _each(self, plan: QueryPlan) -> Iterator[Tuple[int, Executor]]:
        """Stream (bin, executor) over pruned partitions under the residency
        budget; accumulates the selectivity counters across partitions."""
        tot_scanned = tot_rows = 0
        try:
            for b in self.prune(plan):
                check_deadline()
                child = self.store.child(b)
                if child is None or child.count == 0:
                    continue
                plan.__dict__.pop("scanned_rows", None)
                plan.__dict__.pop("table_rows", None)
                yield b, self._executor_for(b, child)
                tot_scanned += plan.__dict__.pop("scanned_rows", 0)
                tot_rows += plan.__dict__.pop("table_rows", 0)
                self.store.evict()
                resident = self.store.partitions
                for bb in list(self._execs):
                    if self._execs[bb].store is not resident.get(bb):
                        del self._execs[bb]  # frees the child's device arrays
        finally:
            # an early consumer exit (features() hitting max_features)
            # closes the generator AT the yield: the just-scanned
            # partition's counters are still on the plan — fold them in
            tot_scanned += plan.__dict__.get("scanned_rows", 0)
            tot_rows += plan.__dict__.get("table_rows", 0)
            plan.__dict__["scanned_rows"] = tot_scanned
            plan.__dict__["table_rows"] = tot_rows

    # -- public operations (Executor surface) ------------------------------
    def count(self, plan: QueryPlan) -> int:
        total = 0
        for _, ex in self._each(plan):
            total += ex.count(plan)
        return total

    def density(self, plan: QueryPlan, bbox, width: int, height: int,
                weight: Optional[str] = None, as_numpy: bool = True):
        out = None
        for _, ex in self._each(plan):
            g = ex.density(plan, bbox, width, height, weight, as_numpy=False)
            # accumulate ON DEVICE: per-partition grid downloads would ride
            # the host link once per partition per call
            out = g if out is None else out + g
        if out is None:
            return np.zeros((height, width), np.float32)
        return np.asarray(out) if as_numpy else out

    def density_curve(self, plan: QueryPlan, level: int, block_window,
                      weight=None) -> np.ndarray:
        out = None
        for _, ex in self._each(plan):
            g = ex.density_curve(plan, level, block_window, weight)
            out = g if out is None else out + g
        if out is None:
            ix0, iy0, ix1, iy1 = block_window
            out = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
        return out

    def stats(self, plan: QueryPlan, stat: sk.Stat) -> sk.Stat:
        for _, ex in self._each(plan):
            ex.stats(plan, stat)
        return stat

    def features_iter(self, plan: QueryPlan, batch_rows: Optional[int] = None):
        """Stream matching rows partition-at-a-time: peak memory is one
        partition's gather, never the whole result (AbstractBatchScan /
        ArrowScan streaming contract)."""
        got = 0
        limit = plan.hints.max_features if not plan.hints.sort_by else None
        for _, ex in self._each(plan):
            for batch in ex.features_iter(plan, batch_rows):
                if not batch.n:
                    continue
                if limit is not None:
                    if got >= limit:
                        return
                    if got + batch.n > limit:
                        keep = limit - got
                        yield ColumnBatch(
                            {k: v[:keep] for k, v in batch.columns.items()},
                            keep,
                        )
                        return
                got += batch.n
                yield batch
            if limit is not None and got >= limit:
                return

    def features(self, plan: QueryPlan) -> ColumnBatch:
        batches = list(self.features_iter(plan))
        return ColumnBatch.concat(batches) if batches else ColumnBatch({}, 0)

    def top_batch(self, plan: QueryPlan, attr: str, descending: bool,
                  k: int, names=None,
                  include_ties: bool = False) -> Optional[ColumnBatch]:
        """Candidate rows for a sorted+limited query over the partitioned
        store: each pruned partition contributes ITS OWN device-selected
        top-k candidates (threshold select, boundary ties included when
        asked), so the union provably contains the global top-k — the
        caller's exact host sort + truncate finishes the job. Partitions
        whose device selection declines (tie overflow, NaN-keyed
        underfill) contribute their full match set instead, which is
        still a superset. The reference sorts client-side after merging
        per-partition scans (QueryPlanner.runQuery); here each partition
        ships at most k + tie-slack rows to the host."""
        parts: List[ColumnBatch] = []
        pushed = 0
        for b, ex in self._each(plan):
            idx = ex.top_rows(plan, attr, descending, k,
                              include_ties=include_ties)
            if idx is None:
                batch = ex.features(plan)
            elif len(idx) == 0:
                pushed += 1  # device ran and found nothing: still pushdown
                continue
            else:
                pushed += 1
                table = ex.store.tables[plan.index_name]
                batch = table.host_gather_positions(idx, names)
            if batch.n:
                parts.append(batch)
        if pushed == 0:
            # no partition device-selected anything: report None so the
            # caller runs (and its audit records) the plain gather path
            return None
        if not parts:
            return ColumnBatch({}, 0)
        return ColumnBatch.concat(parts)

    def knn_features(self, plan: QueryPlan, x: float, y: float,
                     k: int, boxes=None) -> ColumnBatch:
        """Per-partition top-k gathered and merged; the union of partition
        top-ks contains the global top-k (caller orders and truncates)."""
        parts = []
        for _, ex in self._each(plan):
            idx, _ = ex.knn(plan, x, y, k, boxes=boxes)
            if len(idx) == 0:
                continue
            table = ex.store.tables[plan.index_name]
            mask = np.zeros(table.n_shards * table.shard_len, bool)
            mask[idx] = True
            parts.append(table.host_gather(mask))
        return ColumnBatch.concat(parts) if parts else ColumnBatch({}, 0)
