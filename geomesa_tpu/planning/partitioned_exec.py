"""Partition-at-a-time query execution over a PartitionedFeatureStore.

The runtime role of the reference's per-partition range scans + client merge
(TablePartition tables scanned per partition, AbstractBatchScan.scala:32
bounded-queue streaming; FeatureReducer merge in QueryPlanner.runQuery):
prune partitions by the plan's time bounds, stream each pruned partition
through RAM/HBM (loading spilled ones from disk, evicting over budget), run
the ordinary :class:`Executor` against it, and merge the additive results.
One plan → one traced kernel shared by every partition (kernel shapes are
bucketed in IndexTable.shard_len / windows)."""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience, tracing
from geomesa_tpu.filter import ir
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.kernels.registry import KernelRegistry
from geomesa_tpu.planning.executor import Executor, check_deadline
from geomesa_tpu.planning.planner import QueryPlan
from geomesa_tpu.resilience import QueryTimeoutError
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.stats import sketches as sk

_SKIPPED = object()  # sentinel: partition degraded away (fn may return None)


class PartitionedExecutor:
    def __init__(self, store: PartitionedFeatureStore, mesh=None,
                 prefer_device: bool = True):
        self.store = store
        self.mesh = mesh
        self.prefer_device = prefer_device
        #: jitted-kernel LRU shared across every partition child AND every
        #: aggregate-cache cell query (version-stable keys — docs/PERF.md)
        self._kernel_fns = KernelRegistry()
        self._execs: Dict[int, Executor] = {}

    def kernel_registry(self) -> KernelRegistry:
        return self._kernel_fns

    # -- partition pruning (the TimePartition.partitions() analog) ---------
    def prune(self, plan: QueryPlan) -> List[int]:
        store = self.store
        bins = store.partition_bins()
        if plan.is_empty:
            return []
        kp = plan.key_plan
        if (
            kp.bins is not None
            and store.partition_period == store.ft.time_period
        ):
            sel = {int(x) for x in np.asarray(kp.bins).ravel()}
            return [b for b in bins if b in sel]
        dtg = store.ft.dtg_field
        iv = ir.extract_intervals(plan.filter, dtg) if dtg else None
        if iv is not None and not iv.is_empty:
            sel = set()
            for lo, hi in iv.values:
                if lo is None or hi is None:
                    return bins
                sel.update(
                    int(x) for x in store.binned.bins_between(int(lo), int(hi))
                )
            return [b for b in bins if b in sel]
        return bins

    def _executor_for(self, b: int, child) -> Executor:
        ex = self._execs.get(b)
        if ex is None or ex.store is not child:
            ex = Executor(
                child, self.mesh, self.prefer_device,
                kernel_fns=self._kernel_fns, version_source=self.store,
            )
            self._execs[b] = ex
        return ex

    # -- double-buffered partition pipeline --------------------------------
    def _stage(self, child, plan: QueryPlan) -> None:
        """Prefetch-thread half of the double buffer: pull the partition's
        columns off disk (lazy snapshot members) and assemble the stacked
        [S, L] HOST arrays the device upload will consume. Pure host work —
        no jax calls, so all compile/dispatch stays on the query thread
        (the PR 1 one-query-thread jit discipline)."""
        names = plan.__dict__.get("needed_cols")
        if child is None or not names:
            return
        t = child.tables.get(plan.index_name)
        if t is not None and t.n:
            t.stage_host(names)
            metrics.inc(metrics.PIPELINE_PREFETCH)

    def _children(self, plan: QueryPlan):
        """(bin, child) over pruned partitions. With
        ``geomesa.pipeline.prefetch`` (default on), partition i+1's host
        load/column assembly overlaps partition i's device execution on a
        single prefetch thread, bounded to ONE in-flight partition (the
        consumer grants each load). Load errors re-raise on the query
        thread at the same point they would have sequentially; order and
        merge semantics are unchanged, so results stay bit-identical."""
        bins = self.prune(plan)
        if len(bins) < 2 or not config.PIPELINE_PREFETCH.to_bool():
            for b in bins:
                yield b, self.store.child(b)
            return
        out: "queue.Queue" = queue.Queue()
        stop = threading.Event()
        slot = threading.Semaphore(0)  # one permit per granted load
        # config overrides are thread-local: the worker must resolve every
        # property (bucketed shard length above all) exactly as the query
        # thread does, or staged (name, L) keys would silently mismatch
        ov = config.snapshot_overrides()
        # the span context crosses the same boundary the same way: staging
        # spans the worker opens nest under the query's current span, so a
        # trace shows partition i+1's host load overlapping partition i's
        # device execution (docs/OBSERVABILITY.md)
        tspan = tracing.snapshot()

        def worker():
            config.adopt_overrides(ov)
            tracing.adopt(tspan)
            try:
                for b in bins:
                    while not slot.acquire(timeout=0.1):
                        if stop.is_set():
                            return
                    if stop.is_set():
                        return
                    try:
                        child = self.store.child(b)
                        with tracing.span("scan.stage", part=int(b)):
                            self._stage(child, plan)
                    except BaseException as e:
                        out.put((b, None, e))
                    else:
                        out.put((b, child, None))
            finally:
                out.put(None)

        t = threading.Thread(
            target=worker, name="geomesa-part-prefetch", daemon=True
        )
        t.start()
        slot.release()  # the first load starts immediately
        try:
            while True:
                item = out.get()
                if item is None:
                    return
                # grant the NEXT load now: it overlaps this partition's
                # execution — exactly one partition ever in flight
                slot.release()
                b, child, err = item
                if err is not None:
                    raise err
                yield b, child
        finally:
            stop.set()
            # JOIN, not fire-and-forget: an early consumer exit
            # (max_features, deadline) must not leave the worker mutating
            # the partition map under a follow-up query's unlocked readers
            # (partition_bins, flush loops). The wait is bounded by the
            # one in-flight load (worker observes `stop` right after it).
            t.join()
            # free staged host arrays of prefetched-but-never-executed
            # partitions (their loop-body cleanup never ran)
            while True:
                try:
                    item = out.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                _, child, _ = item
                if child is not None:
                    tb = child.tables.get(plan.index_name)
                    if tb is not None:
                        tb._host_stage.clear()

    def _each(self, plan: QueryPlan) -> Iterator[Tuple[int, Executor]]:
        """Stream (bin, executor) over pruned partitions under the residency
        budget; accumulates the selectivity counters across partitions."""
        tot_scanned = tot_rows = 0
        try:
            for b, child in self._children(plan):
                check_deadline()
                if child is None or child.count == 0:
                    continue
                plan.__dict__.pop("scanned_rows", None)
                plan.__dict__.pop("table_rows", None)
                yield b, self._executor_for(b, child)
                tot_scanned += plan.__dict__.pop("scanned_rows", 0)
                tot_rows += plan.__dict__.pop("table_rows", 0)
                # free staged host arrays the scan didn't consume (host
                # path, projection change): staging is per-partition-pass,
                # never a resident duplicate of the device columns
                t = child.tables.get(plan.index_name)
                if t is not None:
                    t._host_stage.clear()
                self.store.evict()
                resident = self.store.partitions
                for bb in list(self._execs):
                    if self._execs[bb].store is not resident.get(bb):
                        del self._execs[bb]  # frees the child's device arrays
        finally:
            # an early consumer exit (features() hitting max_features)
            # closes the generator AT the yield: the just-scanned
            # partition's counters are still on the plan — fold them in
            tot_scanned += plan.__dict__.get("scanned_rows", 0)
            tot_rows += plan.__dict__.get("table_rows", 0)
            plan.__dict__["scanned_rows"] = tot_scanned
            plan.__dict__["table_rows"] = tot_rows

    def _scan_part(self, plan: QueryPlan, b: int, op: str, fn):
        """One partition's scan under the degradation contract
        (docs/RESILIENCE.md): strict mode re-raises; under
        ``resilience.allow_partial()`` / ``geomesa.scan.partial`` a failing
        partition is recorded (collector + audit trail + the plan, for the
        query audit event) and skipped — returns the ``_SKIPPED`` sentinel.
        Deadline expiry always propagates: a timed-out scan must never
        masquerade as a degraded-but-complete one."""
        try:
            resilience.fault_point("exec.partition.scan", bin=b, op=op)
            with tracing.span("scan.partition", part=int(b), op=op):
                return fn()
        except QueryTimeoutError:
            raise
        except Exception as e:
            if not resilience.partial_allowed():
                raise
            rec = resilience.record_skip(
                "exec.partition.scan", f"bin:{b}", e, phase=op
            )
            plan.__dict__.setdefault("degraded", []).append(rec)
            return _SKIPPED

    # -- public operations (Executor surface) ------------------------------
    def count(self, plan: QueryPlan) -> int:
        total = 0
        for b, ex in self._each(plan):
            n = self._scan_part(plan, b, "count", lambda: ex.count(plan))
            if n is not _SKIPPED:
                total += n
        return total

    def density(self, plan: QueryPlan, bbox, width: int, height: int,
                weight: Optional[str] = None, as_numpy: bool = True):
        out = None
        for b, ex in self._each(plan):
            g = self._scan_part(
                plan, b, "density",
                lambda: ex.density(plan, bbox, width, height, weight,
                                   as_numpy=False),
            )
            if g is _SKIPPED:
                continue
            # accumulate ON DEVICE: per-partition grid downloads would ride
            # the host link once per partition per call
            out = g if out is None else out + g
        if out is None:
            return np.zeros((height, width), np.float32)
        return np.asarray(out) if as_numpy else out

    def density_curve(self, plan: QueryPlan, level: int, block_window,
                      weight=None) -> np.ndarray:
        out = None
        for b, ex in self._each(plan):
            g = self._scan_part(
                plan, b, "density_curve",
                lambda: ex.density_curve(plan, level, block_window, weight),
            )
            if g is _SKIPPED:
                continue
            out = g if out is None else out + g
        if out is None:
            ix0, iy0, ix1, iy1 = block_window
            out = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
        return out

    def density_curve_batch(self, plan: QueryPlan, level: int,
                            block_windows, weight=None):
        """Fused tile batch over the partitioned store: each pruned
        partition executes ONE stacked device pass for every member crop
        (Executor.density_curve_batch), and per-member grids accumulate
        across partitions — M concurrent tile queries cost one scan of the
        pruned partitions, not M (docs/SERVING.md)."""
        outs = None
        for b, ex in self._each(plan):
            g = self._scan_part(
                plan, b, "density_curve",
                lambda: ex.density_curve_batch(
                    plan, level, block_windows, weight
                ),
            )
            if g is _SKIPPED:
                continue
            outs = g if outs is None else [a + p for a, p in zip(outs, g)]
        if outs is None:
            outs = []
            for ix0, iy0, ix1, iy1 in block_windows:
                outs.append(
                    np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
                )
        return outs

    def stats(self, plan: QueryPlan, stat: sk.Stat) -> sk.Stat:
        for b, ex in self._each(plan):
            self._scan_part(plan, b, "stats", lambda: ex.stats(plan, stat))
        return stat

    def features_iter(self, plan: QueryPlan, batch_rows: Optional[int] = None):
        """Stream matching rows partition-at-a-time: peak memory is one
        partition's gather, never the whole result (AbstractBatchScan /
        ArrowScan streaming contract)."""
        got = 0
        limit = plan.hints.max_features if not plan.hints.sort_by else None
        for b, ex in self._each(plan):
            if resilience.partial_allowed():
                # degraded mode: materialize the partition before any yield,
                # so a failing partition drops WHOLE — never half-streamed
                batches = self._scan_part(
                    plan, b, "features",
                    lambda: list(ex.features_iter(plan, batch_rows)),
                )
                if batches is _SKIPPED:
                    continue
            else:
                # strict mode streams chunk-at-a-time (the ArrowScan
                # contract): max_features can return mid-partition without
                # gathering the rest
                resilience.fault_point("exec.partition.scan", bin=b,
                                       op="features")
                batches = ex.features_iter(plan, batch_rows)
            for batch in batches:
                if not batch.n:
                    continue
                if limit is not None:
                    if got >= limit:
                        return
                    if got + batch.n > limit:
                        keep = limit - got
                        yield ColumnBatch(
                            {k: v[:keep] for k, v in batch.columns.items()},
                            keep,
                        )
                        return
                got += batch.n
                yield batch
            if limit is not None and got >= limit:
                return

    def features(self, plan: QueryPlan) -> ColumnBatch:
        batches = list(self.features_iter(plan))
        return ColumnBatch.concat(batches) if batches else ColumnBatch({}, 0)

    def top_batch(self, plan: QueryPlan, attr: str, descending: bool,
                  k: int, names=None,
                  include_ties: bool = False) -> Optional[ColumnBatch]:
        """Candidate rows for a sorted+limited query over the partitioned
        store: each pruned partition contributes ITS OWN device-selected
        top-k candidates (threshold select, boundary ties included when
        asked), so the union provably contains the global top-k — the
        caller's exact host sort + truncate finishes the job. Partitions
        whose device selection declines (tie overflow, NaN-keyed
        underfill) contribute their full match set instead, which is
        still a superset. The reference sorts client-side after merging
        per-partition scans (QueryPlanner.runQuery); here each partition
        ships at most k + tie-slack rows to the host."""
        parts: List[ColumnBatch] = []
        pushed = 0
        for b, ex in self._each(plan):
            def one_part(ex=ex):
                idx = ex.top_rows(plan, attr, descending, k,
                                  include_ties=include_ties)
                if idx is None:
                    return None, ex.features(plan)
                if len(idx) == 0:
                    return True, None  # device ran and found nothing
                table = ex.store.tables[plan.index_name]
                return True, table.host_gather_positions(idx, names)

            got = self._scan_part(plan, b, "top", one_part)
            if got is _SKIPPED:
                continue
            dev, batch = got
            if dev:
                pushed += 1
            if batch is not None and batch.n:
                parts.append(batch)
        if pushed == 0:
            # no partition device-selected anything: report None so the
            # caller runs (and its audit records) the plain gather path
            return None
        if not parts:
            return ColumnBatch({}, 0)
        return ColumnBatch.concat(parts)

    def knn_features(self, plan: QueryPlan, x: float, y: float,
                     k: int, boxes=None) -> ColumnBatch:
        """Per-partition top-k gathered and merged; the union of partition
        top-ks contains the global top-k (caller orders and truncates)."""
        parts = []
        for b, ex in self._each(plan):
            def one_part(ex=ex):
                idx, _ = ex.knn(plan, x, y, k, boxes=boxes)
                if len(idx) == 0:
                    return None
                table = ex.store.tables[plan.index_name]
                mask = np.zeros(table.n_shards * table.shard_len, bool)
                mask[idx] = True
                return table.host_gather(mask)

            batch = self._scan_part(plan, b, "knn", one_part)
            if batch is not _SKIPPED and batch is not None:
                parts.append(batch)
        return ColumnBatch.concat(parts) if parts else ColumnBatch({}, 0)
