"""Query interceptors — pluggable rewrite + guard hooks.

Parity with the reference's ``QueryInterceptor`` (geomesa-index-api/.../
planning/QueryInterceptor.scala:51): per-schema hooks loaded from the
schema's user-data key ``geomesa.query.interceptors`` (comma-separated dotted
paths, same configuration surface) or registered programmatically. Each
interceptor may implement:

    rewrite(filter: ir.Filter, ft) -> ir.Filter   # before planning
    guard(plan) -> None                            # raise to veto the plan

Built-in guards (full-table-scan block, temporal span limit) run regardless;
these hooks add schema-specific policy on top — the reference's
``GraduatedQueryGuard`` pattern is expressible as a guard.
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Dict, List

_lock = threading.Lock()
_registry: Dict[str, List[Any]] = {}
# keyed by the user-data spec STRING (id(ft) would recycle across GC'd
# schemas); identical specs share loaded interceptor instances
_loaded_userdata: Dict[str, List[Any]] = {}

USER_DATA_KEY = "geomesa.query.interceptors"


_version = 0


def version() -> int:
    """Bumped on every registry mutation — cache key for anything derived
    from a planned query (plans are pure in (filter, hints, interceptors))."""
    return _version


def register(type_name: str, interceptor: Any):
    """Programmatic registration for one schema name."""
    global _version
    with _lock:
        _registry.setdefault(type_name, []).append(interceptor)
        _version += 1


def clear(type_name: "str | None" = None):
    global _version
    with _lock:
        if type_name is None:
            _registry.clear()
            _loaded_userdata.clear()
        else:
            _registry.pop(type_name, None)
        _version += 1


def _load_path(path: str) -> Any:
    mod, _, attr = path.rpartition(".")
    obj = getattr(importlib.import_module(mod), attr)
    return obj() if isinstance(obj, type) else obj


def for_schema(ft) -> List[Any]:
    """Interceptors for a schema: user-data dotted paths + registered."""
    out: List[Any] = []
    spec = (ft.user_data or {}).get(USER_DATA_KEY)
    if spec:
        key = str(spec)
        with _lock:
            cached = _loaded_userdata.get(key)
        if cached is None:
            cached = []
            for p in key.split(","):
                p = p.strip()
                if not p:
                    continue
                try:
                    cached.append(_load_path(p))
                except Exception as e:
                    # a typo'd path must not brick the schema (the reference's
                    # QueryInterceptorFactory logs and continues the same way)
                    import logging

                    logging.getLogger(__name__).warning(
                        "failed to load query interceptor %r: %r", p, e
                    )
            with _lock:
                if len(_loaded_userdata) >= 256:
                    _loaded_userdata.clear()
                _loaded_userdata[key] = cached
        out.extend(cached)
    with _lock:
        out.extend(_registry.get(ft.name, ()))
    return out


def apply_rewrite(ft, f):
    for ic in for_schema(ft):
        rw = getattr(ic, "rewrite", None)
        if rw is not None:
            f = rw(f, ft)
    return f


def apply_guards(ft, plan):
    for ic in for_schema(ft):
        g = getattr(ic, "guard", None)
        if g is not None:
            g(plan)
