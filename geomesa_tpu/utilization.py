"""Per-device utilization + executor-slot occupancy accounting
(docs/OBSERVABILITY.md).

"How busy is device 2?" is the question the many-core evaluations in
PAPERS.md show scaled geospatial scans lose their headroom on — occupancy,
not kernel speed. This module records busy-time intervals at the existing
dispatch sites (the executor's device kernel dispatches, the sharded
scan's per-device partition scans, the serving pool's per-slot ticket
execution) and rolls them into:

* ``device.busy.<id>`` gauges — busy fraction of each device over the
  trailing ``geomesa.device.busy.window`` seconds;
* ``serving.slot.occupancy.<slot>`` gauges — same, per pool slot;
* the ``/debug/devices`` payload (obs.py): per-device/per-slot busy
  seconds, fractions, and interval counts, plus the queue-wait vs
  device-time breakdown (total seconds queries spent WAITING vs total
  seconds devices spent WORKING — the saturation-vs-starvation signal).

Recording is a perf_counter pair + one lock per interval at dispatch
granularity (never per row), and :func:`device_busy` also feeds the
per-query cost ledger (``tracing.add_cost("device_ms.<id>", …)``) so the
same measurement backs fleet gauges AND per-user cost attribution — one
source of truth, like the serving ledger.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Any, Dict

from geomesa_tpu import config, metrics, tracing

#: injectable clock (tests advance time deterministically)
_clock = time.monotonic


class _Usage:
    """Busy intervals for one key: cumulative totals plus a trailing-window
    deque of (end_time, duration) the busy-fraction gauge reads."""

    __slots__ = ("busy_s", "count", "recent", "lock")

    def __init__(self):
        self.busy_s = 0.0
        self.count = 0
        self.recent: "deque" = deque()
        self.lock = threading.Lock()

    def add(self, seconds: float, now: float) -> None:
        with self.lock:
            self.busy_s += seconds
            self.count += 1
            self.recent.append((now, seconds))
            self._trim(now)

    def _trim(self, now: float) -> None:
        win = _window_s()
        while self.recent and self.recent[0][0] < now - win:
            self.recent.popleft()

    def fraction(self) -> float:
        """Busy fraction over the trailing window: sum of interval
        durations clipped to the window, over the window length. Clamped
        to 1.0 (overlapping intervals from concurrent dispatch can sum
        past the wall clock)."""
        now = _clock()
        win = _window_s()
        with self.lock:
            self._trim(now)
            total = 0.0
            for end, dur in self.recent:
                start = end - dur
                total += end - max(start, now - win)
        return min(total / win, 1.0) if win > 0 else 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            busy, n = self.busy_s, self.count
        return {
            "busy_s": round(busy, 6),
            "busy_fraction": round(self.fraction(), 4),
            "intervals": n,
        }


def _window_s() -> float:
    try:
        w = config.DEVICE_BUSY_WINDOW.to_float()
    except (TypeError, ValueError):
        w = None
    return 60.0 if w is None or w <= 0 else w


_lock = threading.Lock()
_devices: Dict[int, _Usage] = {}
_slots: Dict[int, _Usage] = {}
_gauged = set()
#: queue-wait half of the breakdown (seconds queries spent queued, fed by
#: the serving scheduler at dispatch time)
_wait = _Usage()


def _usage(table: Dict[int, _Usage], key: int, gauge_name: str) -> _Usage:
    u = table.get(key)
    if u is None:
        with _lock:
            u = table.get(key)
            if u is None:
                u = table[key] = _Usage()
    if gauge_name not in _gauged:
        with _lock:
            if gauge_name not in _gauged:
                # one bound method per key backs the gauge; replace=True
                # because reset() (tests, metrics.clear survivors) leaves
                # a stale backing the fresh _Usage must take over from
                metrics.registry().gauge(gauge_name, u.fraction,
                                         replace=True)
                _gauged.add(gauge_name)
    return u


def record_device(device_id: int, seconds: float) -> None:
    """One device busy interval (a kernel dispatch / sharded partition
    scan). Also attributes the time to the active trace's cost ledger."""
    did = int(device_id)
    _usage(_devices, did,
           f"{metrics.DEVICE_BUSY_PREFIX}.{did}").add(seconds, _clock())
    tracing.add_cost(f"device_ms.{did}", seconds * 1e3)


def record_slot(slot: int, seconds: float) -> None:
    """One serving-pool slot busy interval (a dispatched ticket group)."""
    s = int(slot)
    _usage(_slots, s,
           f"{metrics.SLOT_OCCUPANCY_PREFIX}.{s}").add(seconds, _clock())


def record_wait(seconds: float) -> None:
    """One query's queue wait (the other half of the wait-vs-work
    breakdown in /debug/devices)."""
    _wait.add(seconds, _clock())


@contextlib.contextmanager
def device_busy(device_id: int):
    """Time one device dispatch as a busy interval."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_device(device_id, time.perf_counter() - t0)


@contextlib.contextmanager
def slot_busy(slot: int):
    """Time one pool-slot dispatch as a busy interval."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_slot(slot, time.perf_counter() - t0)


def snapshot() -> Dict[str, Any]:
    """The /debug/devices payload: per-device and per-slot usage plus the
    queue-wait vs device-time breakdown."""
    with _lock:
        devs = dict(_devices)
        slots = dict(_slots)
    device_busy_s = sum(u.busy_s for u in devs.values())
    return {
        "window_s": _window_s(),
        "devices": {str(k): u.snapshot() for k, u in sorted(devs.items())},
        "slots": {str(k): u.snapshot() for k, u in sorted(slots.items())},
        "breakdown": {
            "queue_wait_s": round(_wait.busy_s, 6),
            "device_time_s": round(device_busy_s, 6),
            "waits": _wait.count,
        },
    }


def reset() -> None:
    """Drop all usage state (test isolation). Gauges registered against
    previous _Usage objects are re-pointed on next use via replace."""
    global _wait
    with _lock:
        _devices.clear()
        _slots.clear()
        _gauged.clear()
        _wait = _Usage()
