"""geomesa-tpu CLI (geomesa-tools analog).

Command parity with the reference's JCommander CLI (geomesa-tools/.../Runner,
SURVEY.md §2.7): create-schema, delete-schema, describe-schema,
get-type-names, ingest, export, stats-*, explain, compact, version. The
catalog is a directory managed by GeoDataset.save/load (the shard-manifest
checkpoint) — pass ``-c/--catalog <dir>`` like the reference's catalog table.

Usage examples::

    geomesa-tpu create-schema -c /data/cat -f gdelt \\
        -s "name:String,dtg:Date,*geom:Point"
    geomesa-tpu ingest -c /data/cat -f gdelt -C conv.conf data.csv
    geomesa-tpu ingest -c /data/cat -f auto --infer data.csv
    geomesa-tpu export -c /data/cat -f gdelt -q "BBOX(geom,-100,30,-80,45)" \\
        -F geojson -o out.json
    geomesa-tpu stats-count -c /data/cat -f gdelt -q "INCLUDE"
    geomesa-tpu explain -c /data/cat -f gdelt -q "name = 'x'"
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

import numpy as np

__version__ = "0.1.0"


def _load(catalog: str):
    from geomesa_tpu import GeoDataset

    if os.path.exists(os.path.join(catalog, "manifest.json")):
        return GeoDataset.load(catalog)
    from geomesa_tpu.fs import journal as journal_mod

    if journal_mod.journal_exists(catalog):
        # a crash before the first checkpoint leaves a journal-only root:
        # still a loadable catalog (docs/RESILIENCE.md §8)
        return GeoDataset.load(catalog)
    return GeoDataset()


def _save(ds, catalog: str):
    ds.save(catalog)


def cmd_create_schema(args):
    ds = _load(args.catalog)
    ft = ds.create_schema(args.feature_name, args.spec)
    _save(ds, args.catalog)
    print(f"created schema {ft.name!r}")
    print(ft.describe())


def cmd_update_schema(args):
    ds = _load(args.catalog)
    ft = ds.update_schema(args.feature_name, args.add)
    _save(ds, args.catalog)
    print(f"updated schema {ft.name!r}")
    print(ft.describe())


def cmd_add_index(args):
    """Enable an attribute index on a live schema without recreating it
    (reference updateSchema index transitions,
    GeoMesaDataStore.scala:288-336)."""
    ds = _load(args.catalog)
    ds.add_attribute_index(args.feature_name, args.attribute)
    _save(ds, args.catalog)
    print(f"added attr:{args.attribute} to {args.feature_name!r}")


def cmd_remove_index(args):
    ds = _load(args.catalog)
    ds.remove_attribute_index(args.feature_name, args.attribute)
    _save(ds, args.catalog)
    print(f"removed attr:{args.attribute} from {args.feature_name!r}")


def cmd_manage_partitions(args):
    """List / age off time partitions of a partitioned store (reference
    geomesa-tools manage-partitions; TimePartition.scala:35)."""
    from geomesa_tpu.index.partitioned import PartitionedFeatureStore

    ds = _load(args.catalog)
    st = ds._store(args.feature_name)
    if not isinstance(st, PartitionedFeatureStore):
        print(f"schema {args.feature_name!r} is not time-partitioned")
        return
    if args.action == "list":
        for b in st.partition_bins():
            lo = int(st.binned.bin_start_ms(np.asarray([b]))[0])
            hi = int(st.binned.bin_start_ms(np.asarray([b + 1]))[0])
            state = "resident" if b in st.partitions else "spilled"
            rows = st.part_counts.get(b, 0)
            print(f"bin {b}  [{_iso(lo)} .. {_iso(hi)})  {rows} rows  {state}")
    elif args.action == "delete":
        if not args.older_than:
            raise SystemExit(
                "manage-partitions delete requires --older-than <ISO date>"
            )
        n = ds.age_off(args.feature_name, args.older_than)
        _save(ds, args.catalog)
        print(f"removed {n} features older than {args.older_than}")


def _iso(ms: int) -> str:
    import datetime as _dt

    return _dt.datetime.fromtimestamp(
        ms / 1000.0, _dt.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")


def cmd_delete_schema(args):
    ds = _load(args.catalog)
    ds.delete_schema(args.feature_name)
    _save(ds, args.catalog)
    # remove orphan data file
    npz = os.path.join(args.catalog, f"{args.feature_name}.npz")
    if os.path.exists(npz):
        os.remove(npz)
    print(f"deleted schema {args.feature_name!r}")


def cmd_get_type_names(args):
    ds = _load(args.catalog)
    for n in ds.list_schemas():
        print(n)


def cmd_describe_schema(args):
    ds = _load(args.catalog)
    print(ds.describe(args.feature_name))


def cmd_ingest(args):
    from geomesa_tpu.convert import ConverterConfig, converter_for, infer_schema

    ds = _load(args.catalog)
    total_ok = total_fail = 0
    if args.infer:
        with open(args.files[0]) as fh:
            sample = "".join(fh.readline() for _ in range(101))
        ft, cfg = infer_schema(sample, name=args.feature_name or "inferred")
        if ft.name not in ds.list_schemas():
            ds.create_schema(ft)
            print(f"inferred schema: {ft.spec()}", file=sys.stderr)
    else:
        if not args.converter:
            raise SystemExit("ingest requires -C/--converter or --infer")
        with open(args.converter) as fh:
            cfg = ConverterConfig.parse(fh.read())
        if args.feature_name is None:
            raise SystemExit("ingest requires -f/--feature-name")
    name = args.feature_name or ft.name
    for path in args.files:
        if path.endswith(".parquet"):
            import pyarrow.parquet as pq

            from geomesa_tpu.io import arrow_io

            table = pq.read_table(path)
            st_ft = ds.get_schema(name)
            data, fids = arrow_io.table_to_data(st_ft, table)
            ds.insert(name, data, fids)
            total_ok += table.num_rows
            continue
        with open(path) as fh:
            ctx = ds.ingest(name, fh, cfg)
        total_ok += ctx.success
        total_fail += ctx.failure
        for e in ctx.errors[:5]:
            print(f"  warn: {e}", file=sys.stderr)
    ds.flush()
    _save(ds, args.catalog)
    print(f"ingested {total_ok} features ({total_fail} failed)")


def cmd_export(args):
    from geomesa_tpu.api.dataset import Query

    ds = _load(args.catalog)
    q = Query(
        ecql=args.cql, max_features=args.max_features,
        properties=args.attributes.split(",") if args.attributes else None,
    )
    fmt = args.format.lower()
    out = args.output
    if fmt == "arrow":
        ds.export_arrow(args.feature_name, out or "export.arrow", q)
        print(f"wrote {out or 'export.arrow'}")
        return
    if fmt == "bin":
        payload = ds.export_bin(args.feature_name, q, track=args.track,
                                label=args.label)
        path = out or "export.bin"
        with open(path, "wb") as fh:
            fh.write(payload)
        print(f"wrote {path} ({len(payload)} bytes)")
        return
    if fmt == "parquet":
        import pyarrow.parquet as pq

        table = ds.to_arrow(args.feature_name, q)
        path = out or "export.parquet"
        pq.write_table(table, path)
        print(f"wrote {path} ({table.num_rows} rows)")
        return
    if fmt == "orc":
        import pyarrow as pa
        import pyarrow.orc as orc

        table = ds.to_arrow(args.feature_name, q)
        # ORC has no dictionary type: decode dictionary-encoded strings
        cols = []
        for i, f in enumerate(table.schema):
            col = table.column(i)
            if pa.types.is_dictionary(f.type):
                col = col.cast(f.type.value_type)
            cols.append(col)
        table = pa.table(cols, names=table.schema.names)
        path = out or "export.orc"
        orc.write_table(table, path)
        print(f"wrote {path} ({table.num_rows} rows)")
        return
    fc = ds.query(args.feature_name, q)
    if fmt in ("geojson", "json"):
        from geomesa_tpu.io import geojson

        st = ds._store(args.feature_name)
        text = geojson.dumps(st.ft, fc.batch, st.dicts)
        _write_text(out, text)
        return
    if fmt in ("csv", "tsv"):
        sep = "," if fmt == "csv" else "\t"
        d = fc.to_dict()
        if not d:
            _write_text(out, "")
            return
        cols = list(d)
        lines = [sep.join(cols)]
        n = len(d[cols[0]])
        for i in range(n):
            lines.append(sep.join(_csv_cell(d[c][i]) for c in cols))
        _write_text(out, "\n".join(lines) + "\n")
        return
    if fmt == "leaflet":
        from geomesa_tpu.io import geojson

        st = ds._store(args.feature_name)
        gj = geojson.dumps(st.ft, fc.batch, st.dicts)
        _write_text(out, _LEAFLET_TMPL.replace("__GEOJSON__", gj))
        return
    if fmt == "gml":
        from geomesa_tpu.io import gml

        st = ds._store(args.feature_name)
        _write_text(out, gml.dumps(st.ft, fc.batch, st.dicts))
        return
    if fmt == "shp":
        from geomesa_tpu.io import shapefile

        st = ds._store(args.feature_name)
        base = shapefile.write_shapefile(
            out or "export.shp", st.ft, fc.batch, st.dicts
        )
        print(f"wrote {base}.shp/.shx/.dbf ({fc.batch.n} features)")
        return
    if fmt == "avro":
        from geomesa_tpu.io import avro_io

        st = ds._store(args.feature_name)
        path = out or "export.avro"
        avro_io.write_avro(path, st.ft, fc.batch, st.dicts)
        print(f"wrote {path} ({fc.batch.n} features)")
        return
    raise SystemExit(f"unknown export format {args.format!r}")


def _csv_cell(v) -> str:
    if v is None:
        return ""
    if isinstance(v, tuple):
        return f"POINT ({v[0]} {v[1]})"
    s = str(v)
    if "," in s or '"' in s:
        s = '"' + s.replace('"', '""') + '"'
    return s


def _write_text(out: Optional[str], text: str):
    if out:
        with open(out, "w") as fh:
            fh.write(text)
        print(f"wrote {out}")
    else:
        sys.stdout.write(text)


def cmd_explain(args):
    ds = _load(args.catalog)
    print(ds.explain(args.feature_name, args.cql))


def cmd_stats_count(args):
    ds = _load(args.catalog)
    exact = not args.no_cache_ok
    print(ds.count(args.feature_name, args.cql, exact=exact))


def cmd_stats_bounds(args):
    ds = _load(args.catalog)
    if args.attribute:
        print(ds.min_max(args.feature_name, args.attribute, args.cql))
    else:
        print(ds.bounds(args.feature_name))


def cmd_stats_histogram(args):
    ds = _load(args.catalog)
    mm = ds.min_max(args.feature_name, args.attribute, args.cql)
    lo, hi = (mm if isinstance(mm, tuple) else (0, 1))
    stat = ds.stats(
        args.feature_name,
        f"Histogram({args.attribute},{args.bins},{float(lo)},{float(hi)})",
        args.cql,
    )
    print(stat.to_json())


def cmd_stats_top_k(args):
    ds = _load(args.catalog)
    stat = ds.stats(args.feature_name, f"TopK({args.attribute})", args.cql)
    for v, c in list(stat.value())[: args.k]:
        print(f"{v}\t{c}")


def cmd_stats_analyze(args):
    ds = _load(args.catalog)
    st = ds._store(args.feature_name)
    st.flush()
    print(f"count: {st.count}")
    for key, stat in sorted(st.stats.items()):
        v = stat.value()
        s = str(v)
        print(f"{key}: {s[:200] + '...' if len(s) > 200 else s}")


def cmd_compact(args):
    from geomesa_tpu.fs import FileSystemStorage

    fs = FileSystemStorage(args.catalog)
    removed = fs.compact(args.feature_name)
    print(f"compacted: removed {removed} files")


def cmd_web(args):
    """Run the REST endpoint (geomesa-web GeoMesaStatsEndpoint analog)."""
    from geomesa_tpu import web

    ds = _load(args.catalog)
    print(f"geomesa-tpu web listening on http://{args.host}:{args.port}/api")
    web.serve(ds, args.host, args.port)


def cmd_serve(args):
    """Run the Arrow Flight sidecar over a catalog (SURVEY.md §5 comm
    backend; the coprocessor-endpoint analog)."""
    from geomesa_tpu.sidecar import GeoFlightServer

    ds = _load(args.catalog)
    srv = GeoFlightServer(ds, f"grpc+tcp://{args.host}:{args.port}")
    print(f"geomesa-tpu sidecar listening on grpc+tcp://{args.host}:{srv.port}")
    try:
        srv.serve()
    except KeyboardInterrupt:
        pass
    finally:
        if args.persist:
            _save(ds, args.catalog)


def cmd_metrics(args):
    """Print the metrics exposition (tools analog of the Dropwizard
    reporters; docs/OBSERVABILITY.md). Three sources:

    * ``--url http://host:port/metrics`` — scrape a running obs/web
      endpoint (prometheus text passthrough);
    * ``--host/--port`` — fetch a running sidecar's registry snapshot via
      the Flight ``metrics`` action (JSON);
    * neither — this process's own registry (prometheus text; mostly
      relevant when invoked after in-process work, e.g. under test).
    """
    if args.url:
        import urllib.request

        with urllib.request.urlopen(args.url, timeout=10) as resp:
            sys.stdout.write(resp.read().decode())
        return
    if args.sidecar_host:
        from geomesa_tpu.sidecar import GeoFlightClient

        port = args.sidecar_port or 8815
        with GeoFlightClient(f"grpc+tcp://{args.sidecar_host}:{port}") as c:
            print(json.dumps(c.metrics(), indent=2, sort_keys=True, default=str))
        return
    from geomesa_tpu import metrics

    sys.stdout.write(metrics.registry().prometheus())


def cmd_trace(args):
    """Run one query with tracing enabled and print its span tree — the
    operator's "where did this query's 40 ms go?" loop without touching
    config (docs/OBSERVABILITY.md)."""
    from geomesa_tpu import config, tracing
    from geomesa_tpu.api.dataset import Query

    ds = _load(args.catalog)
    q = Query(ecql=args.cql)
    with config.TRACE_ENABLED.scoped("true"):
        if args.op == "count":
            out = ds.count(args.feature_name, q)
        elif args.op == "density":
            out = f"grid nonzero={int((ds.density(args.feature_name, q) > 0).sum())}"
        elif args.op == "query":
            out = len(ds.query(args.feature_name, q))
        else:
            raise SystemExit(f"unknown --op {args.op!r}")
    tr = tracing.last_trace()
    if tr is None:
        raise SystemExit("no trace captured (query produced no root span)")
    tree = tr.root.to_dict()
    if args.json:
        print(json.dumps({"trace_id": tr.trace_id, "result": str(out),
                          "tree": tree}, indent=2, default=str))
    else:
        print(f"trace_id: {tr.trace_id}")
        print(f"result: {out}")
        print(tracing.render(tree))


def cmd_obs(args):
    """Run the standalone observability endpoint (/metrics, /healthz,
    /debug/queries, /debug/devices) over a catalog."""
    from geomesa_tpu import obs

    ds = _load(args.catalog)
    print(f"geomesa-tpu obs listening on http://{args.host}:{args.port}"
          "/metrics /healthz /debug/queries /debug/devices")
    obs.serve(ds, args.host, args.port)


def cmd_devices(args):
    """``devices`` prints the /debug/devices payload — per-device busy
    fractions + HEALTH (ok/cordoned/broken, reassignment counts, last
    failure), pool slot occupancy, the queue-wait vs device-time
    breakdown, and the SLO burn summary (docs/OBSERVABILITY.md,
    docs/RESILIENCE.md §6). ``devices cordon <id>`` / ``devices uncordon
    <id>`` remove/re-admit a device from scheduling without a restart —
    against a running sidecar with ``--host/--port`` (the
    ``cordon-device`` action), or this process's registry otherwise.
    ``--url`` scrapes a running obs/web endpoint's payload."""
    if args.action:
        if args.device is None:
            print("devices cordon/uncordon needs a device id",
                  file=sys.stderr)
            return 2
        did = int(args.device)
        if args.sidecar_host:
            from geomesa_tpu.sidecar import GeoFlightClient

            port = args.sidecar_port or 8815
            with GeoFlightClient(
                f"grpc+tcp://{args.sidecar_host}:{port}"
            ) as c:
                out = c.cordon_device(did, reason=args.reason) \
                    if args.action == "cordon" else c.uncordon_device(did)
            print(json.dumps(out, indent=2, sort_keys=True, default=str))
            return
        from geomesa_tpu.parallel import health as phealth

        reg = phealth.registry()
        if args.action == "cordon":
            reg.cordon(did, reason=args.reason or "operator")
        else:
            reg.uncordon(did)
        print(json.dumps({"devices": reg.snapshot()}, indent=2,
                         sort_keys=True, default=str))
        return
    if args.url:
        import urllib.request

        url = args.url.rstrip("/")
        if not url.endswith("/debug/devices"):
            url += "/debug/devices"
        with urllib.request.urlopen(url, timeout=10) as resp:
            sys.stdout.write(resp.read().decode() + "\n")
        return
    from geomesa_tpu import obs

    print(json.dumps(obs.debug_devices(), indent=2, sort_keys=True,
                     default=str))


def _parse_replicas(spec: str):
    """``id=host:port,id=host:port`` -> {id: flight location}."""
    out = {}
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" not in tok:
            raise ValueError(
                f"bad --replicas entry {tok!r} (want id=host:port)"
            )
        rid, addr = tok.split("=", 1)
        if not addr.startswith("grpc"):
            addr = f"grpc+tcp://{addr}"
        out[rid.strip()] = addr
    if not out:
        raise ValueError("--replicas is empty")
    return out


def cmd_fleet(args):
    """``fleet`` subcommands (docs/RESILIENCE.md §7):

    * ``fleet replica`` — run ONE replica sidecar over the shared fleet
      root: loads the catalog, serves Flight with the replica id + epoch
      headers, honors stamped writes (apply + save + epoch advance);
    * ``fleet status`` — probe every replica (replica-status action):
      identity, drain flag, epochs, serving snapshot;
    * ``fleet drain`` / ``fleet undrain`` — replica-side drain: new
      non-admin requests answer [GM-DRAINING] until undrained, so every
      router fails the traffic over;
    * ``fleet count`` — route one count through an ad-hoc router (smoke/
      operator sanity check of affinity + failover);
    * ``fleet leave`` — warm-handoff drain through an ad-hoc router:
      drain the replica, push its hottest cache entries to the new ring
      owners (cache-export/cache-import), report the handoff summary;
    * ``fleet handoff`` — operator-driven direct handoff: export one
      replica's hottest entries for a schema and import them into
      another (no router involved);
    * ``fleet heat`` — the fleet cell-heat table: per-(schema, SFC cell)
      hits/misses/device-ms merged across replicas
      (docs/OBSERVABILITY.md §9);
    * ``fleet trace`` — one trace id's retained span tree(s) from every
      replica (the stitcher's raw inputs).
    """
    if args.fleet_cmd == "replica":
        from geomesa_tpu import GeoDataset
        from geomesa_tpu.sidecar import GeoFlightServer

        ds = (GeoDataset.load(args.root)
              if os.path.exists(os.path.join(args.root, "manifest.json"))
              else GeoDataset())
        srv = GeoFlightServer(
            ds, f"grpc+tcp://{args.host}:{args.port}",
            replica_id=args.replica_id, fleet_root=args.root,
        )
        print(f"geomesa-tpu fleet replica {args.replica_id!r} listening on "
              f"grpc+tcp://{args.host}:{srv.port} (root {args.root})",
              flush=True)
        try:
            srv.serve()
        except KeyboardInterrupt:
            pass
        return 0
    if args.fleet_cmd == "status":
        from geomesa_tpu.fleet import FleetRouter

        with FleetRouter(_parse_replicas(args.replicas)) as router:
            out = {"probes": router.probe_all(), "fleet": router.snapshot()}
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd in ("drain", "undrain"):
        from geomesa_tpu.sidecar import GeoFlightClient

        with GeoFlightClient(f"grpc+tcp://{args.host}:{args.port}") as c:
            out = (c.drain(reason=args.reason)
                   if args.fleet_cmd == "drain" else c.undrain())
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd == "count":
        from geomesa_tpu.fleet import FleetRouter

        with FleetRouter(_parse_replicas(args.replicas)) as router:
            n = router.count(args.feature_name, args.cql)
            snap = router.snapshot()
        print(json.dumps({"count": int(n), "counters": snap["counters"],
                          "scatter": snap["scatter"],
                          "replicas": snap["replicas"]},
                         indent=2, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd == "leave":
        from geomesa_tpu.fleet import FleetRouter

        with FleetRouter(_parse_replicas(args.replicas)) as router:
            out = router.deregister_replica(
                args.replica_id, handoff=not args.no_handoff
            )
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd == "heat":
        from geomesa_tpu.fleet import FleetRouter

        with FleetRouter(_parse_replicas(args.replicas)) as router:
            out = router.observability().fleet_heat(top=args.top)
        if args.json:
            print(json.dumps(out, indent=2, sort_keys=True, default=str))
            return 0
        for schema in sorted(out["schemas"]):
            print(f"schema {schema}:")
            print(f"  {'cell':<16} {'touches':>8} {'hits':>8} "
                  f"{'misses':>8} {'device_ms':>10}  replicas")
            for row in out["schemas"][schema]:
                split = ",".join(
                    f"{r}={n}" for r, n in sorted(row["replicas"].items())
                )
                print(f"  {row['cell']:<16} {row['touches']:>8} "
                      f"{row['hits']:>8} {row['misses']:>8} "
                      f"{row['device_ms']:>10.3f}  {split}")
        if out.get("errors"):
            print(f"federation errors: {out['errors']}", file=sys.stderr)
        return 0
    if args.fleet_cmd == "trace":
        from geomesa_tpu.fleet import FleetRouter

        with FleetRouter(_parse_replicas(args.replicas)) as router:
            out = {
                rid: router._client(rid).trace_fetch(args.trace_id)
                for rid in router.registry.members()
            }
        print(json.dumps(out, indent=2, sort_keys=True, default=str))
        return 0
    if args.fleet_cmd == "handoff":
        from geomesa_tpu.sidecar import GeoFlightClient

        with GeoFlightClient(args.source) as src, \
                GeoFlightClient(args.dest) as dst:
            exported = src.cache_export(args.feature_name,
                                        limit=args.limit)
            got = dst.cache_import(
                args.feature_name, exported.get("guard") or {},
                exported.get("entries") or [],
            )
        print(json.dumps({
            "exported": len(exported.get("entries") or []),
            "restored": got.get("restored", 0),
            **({"skipped": got["skipped"]} if got.get("skipped") else {}),
        }, indent=2, sort_keys=True))
        return 0
    print(f"unknown fleet command {args.fleet_cmd!r}", file=sys.stderr)
    return 2


def _summarize_standing(spec_agg, encoded):
    """Human-readable one-liner for a wire-encoded standing result."""
    from geomesa_tpu.cache.store import decode_wire_value

    try:
        val = decode_wire_value(encoded)
    except Exception:
        return str(encoded)[:120]
    if spec_agg == "count":
        return f"count={int(val)}"
    if spec_agg == "density":
        return (f"density sum={float(val.sum()):.0f} "
                f"nonzero={int((val > 0).sum())} shape={val.shape}")
    if spec_agg == "pyramid":
        grids = val if isinstance(val, tuple) else (val,)
        return (f"pyramid levels={len(grids)} "
                f"leaf_sum={float(grids[0].sum()):.0f}")
    return f"stats={str(val)[:160]}"


def cmd_subscribe(args):
    """``subscribe`` — register a standing viewport against a sidecar
    (or a fleet of replicas via an ad-hoc router) and stream its update
    records: the server maintains the aggregate incrementally per
    applied ingest batch (docs/STANDING.md; PROTOCOL §5 v1.6), so each
    poll carries only the update records past the client's cursor."""
    import time as _time

    bbox = None
    if args.bbox:
        bbox = [float(v) for v in args.bbox.split(",")]
        if len(bbox) != 4:
            raise SystemExit("--bbox wants xmin,ymin,xmax,ymax")

    if args.replicas:
        from geomesa_tpu.fleet import FleetRouter

        target = FleetRouter(_parse_replicas(args.replicas))

        def register():
            return target.subscribe(
                args.feature_name, args.aggregate, bbox=bbox,
                region=args.region, width=args.width, height=args.height,
                levels=args.levels, stat_spec=args.stat,
            )

        poll = target.subscription_poll
        unsub = target.unsubscribe
    else:
        from geomesa_tpu.sidecar import GeoFlightClient

        target = GeoFlightClient(
            f"grpc+tcp://{args.host}:{args.port}"
        )

        def register():
            return target.subscribe(
                args.feature_name, args.aggregate, bbox=bbox,
                region=args.region, width=args.width, height=args.height,
                levels=args.levels, stat_spec=args.stat,
            )

        poll = target.subscribe_poll
        unsub = target.unsubscribe
    try:
        sub_id = register()
        got = poll(sub_id, 0)
        cursor = int(got["version"])
        print(json.dumps({
            "sub_id": sub_id, "version": cursor,
            "epoch": got.get("epoch"),
            "subscribers": got.get("subscribers"),
            "result": _summarize_standing(args.aggregate, got["result"]),
        }, sort_keys=True), flush=True)
        if args.once:
            return 0
        seen = 0
        while args.max_updates is None or seen < args.max_updates:
            _time.sleep(args.poll_interval)
            got = poll(sub_id, cursor)
            for u in got.get("updates") or []:
                print(json.dumps({
                    "version": u["version"], "kind": u["kind"],
                    "rows": u.get("rows"), "epoch": u.get("epoch"),
                    "result": _summarize_standing(
                        args.aggregate, got["result"]
                    ),
                }, sort_keys=True), flush=True)
                seen += 1
            cursor = int(got["version"])
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        try:
            unsub(sub_id)
        except Exception:
            pass
        target.close()


def cmd_journal(args):
    """``journal`` subcommands (docs/RESILIENCE.md §8):

    * ``journal status`` — the catalog's mutation-journal summary:
      segments, sequence range, per-schema checkpointed positions,
      torn bytes, pending frames;
    * ``journal replay`` — recover the catalog (load replays records
      past each schema's checkpoint, truncating any torn tail), report
      how many records re-applied, and checkpoint via ``save`` so the
      next load starts clean.
    """
    from geomesa_tpu.fs import journal as journal_mod

    if args.journal_cmd == "status":
        out: dict = {"root": args.catalog,
                     "journal": journal_mod.journal_exists(args.catalog)}
        if out["journal"]:
            j = journal_mod.MutationJournal(args.catalog)
            try:
                out.update(j.status())
            finally:
                j.close()
        mpath = os.path.join(args.catalog, "manifest.json")
        if os.path.exists(mpath):
            with open(mpath) as fh:
                out["checkpoints"] = {
                    name: int(meta.get("journal_seq", 0))
                    for name, meta in
                    json.load(fh).get("schemas", {}).items()
                }
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    if args.journal_cmd == "replay":
        from geomesa_tpu import GeoDataset

        ds = GeoDataset.load(args.catalog)
        replayed = ds._journal_replayed
        ds.save(args.catalog)
        print(json.dumps({
            "root": args.catalog, "replayed": int(replayed),
            "schemas": sorted(ds._stores),
            "checkpointed": True,
        }, indent=2, sort_keys=True))
        return 0
    print(f"unknown journal command {args.journal_cmd!r}", file=sys.stderr)
    return 2


def cmd_version(args):
    print(f"geomesa-tpu {__version__}")


def cmd_version_remote(args):
    """Query a running sidecar's version (tools `version-remote`)."""
    from geomesa_tpu.sidecar import GeoFlightClient

    with GeoFlightClient(f"grpc+tcp://{args.host}:{args.port}") as c:
        info = c.check_version()
    print(f"remote geomesa-tpu {info['version']} (protocol {info['protocol']})")


def cmd_env(args):
    """Print every config tunable with its effective value (tools `env`)."""
    import os

    from geomesa_tpu import config

    for name, prop in sorted(config.registry().items()):
        val = prop.get()
        if name in config._overrides():
            src = "override"
        elif prop.env_name in os.environ:
            src = "env"
        else:
            src = "default"
        print(f"{name} = {val!r}  [{src}]")


def cmd_convert(args):
    """Dry-run a converter config against input (tools `convert`): parse,
    transform, validate, and print the first rows — nothing is ingested."""
    import json as _json

    from geomesa_tpu.convert import EvaluationContext, converter_for
    from geomesa_tpu.schema.feature_type import FeatureType

    from geomesa_tpu.convert.converter import ConverterConfig

    ft = FeatureType.from_spec(args.feature_name, args.spec)
    with open(args.config) as fh:
        conf = fh.read()
    cfg = ConverterConfig.parse(conf)
    conv = converter_for(ft, cfg)
    if cfg.type in ("parquet", "avro"):
        source: "str | bytes" = args.input  # binary formats take the path
    else:
        with open(args.input) as fh:
            source = fh.read()
    ctx = EvaluationContext()
    shown = 0
    for data, fids in conv.convert(source, ctx):
        n = len(next(iter(data.values()), ()))
        for i in range(n):
            if shown >= args.max:
                break
            row = {k: _to_py(v[i]) for k, v in data.items()}
            if fids is not None:
                row["__fid__"] = str(fids[i])
            print(_json.dumps(row, default=str))
            shown += 1
    print(f"converted: {ctx.success} ok, {ctx.failure} failed", file=sys.stderr)
    for e in ctx.errors[:10]:
        print(f"  error: {e}", file=sys.stderr)


def _to_py(v):
    import numpy as _np

    if isinstance(v, _np.generic):
        return v.item()
    return v


def cmd_playback(args):
    """Replay a catalog dataset in dtg order onto a live streaming window
    (tools `playback`)."""
    from geomesa_tpu.schema.columns import decode_batch
    from geomesa_tpu.stream.live import StreamingDataset, playback

    ds = _load(args.catalog)
    st = ds._store(args.feature_name)
    st.flush()
    if st._all is None or st._all.n == 0:
        raise SystemExit("nothing to play back")
    d = decode_batch(st.ft, st._all, st.dicts)
    dtg = st.ft.dtg_field
    if dtg is None:
        raise SystemExit("playback requires a date attribute")
    sds = StreamingDataset()
    sds.create_schema(st.ft.name, st.ft.spec())
    data = {
        a.name: d[a.name] for a in st.ft.attributes if a.name in d
    }
    fids = [str(v) for v in d["__fid__"]]
    dtg_ms = np.asarray(st._all.columns[dtg], np.int64)
    playback(
        sds, st.ft.name, data, fids, dtg_ms,
        rate=args.rate, batch_ms=args.batch_ms, sleep=not args.fast,
    )
    n = sds.count(st.ft.name)
    print(f"played back {n} features at {args.rate}x")


_LEAFLET_TMPL = """<!DOCTYPE html>
<html><head>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>#map { height: 100vh; }</style></head>
<body><div id="map"></div><script>
var map = L.map('map');
L.tileLayer('https://{s}.tile.openstreetmap.org/{z}/{x}/{y}.png').addTo(map);
var layer = L.geoJSON(__GEOJSON__).addTo(map);
map.fitBounds(layer.getBounds());
</script></body></html>
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="geomesa-tpu",
        description="GeoMesa-TPU command-line tools",
    )
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp, feature=True, cql=False):
        sp.add_argument("-c", "--catalog", required=True, help="catalog directory")
        if feature:
            sp.add_argument("-f", "--feature-name", help="schema name")
        if cql:
            sp.add_argument("-q", "--cql", default="INCLUDE", help="ECQL filter")

    sp = sub.add_parser("create-schema", help="create a feature schema")
    common(sp)
    sp.add_argument("-s", "--spec", required=True, help="schema spec string")
    sp.set_defaults(fn=cmd_create_schema)

    sp = sub.add_parser("update-schema", help="add attributes to a schema")
    common(sp)
    sp.add_argument("--add", required=True,
                    help="spec of attributes to append, e.g. 'tag:String'")
    sp.set_defaults(fn=cmd_update_schema)

    sp = sub.add_parser("add-attribute-index",
                        help="enable an attribute index on a live schema")
    common(sp)
    sp.add_argument("--attribute", required=True)
    sp.set_defaults(fn=cmd_add_index)

    sp = sub.add_parser("remove-attribute-index",
                        help="drop an attribute index (data untouched)")
    common(sp)
    sp.add_argument("--attribute", required=True)
    sp.set_defaults(fn=cmd_remove_index)

    sp = sub.add_parser(
        "manage-partitions", help="list or age off time partitions"
    )
    common(sp)
    sp.add_argument("action", choices=["list", "delete"])
    sp.add_argument("--older-than", help="ISO date for delete")
    sp.set_defaults(fn=cmd_manage_partitions)

    sp = sub.add_parser("delete-schema", help="delete a schema and its data")
    common(sp)
    sp.set_defaults(fn=cmd_delete_schema)

    sp = sub.add_parser("get-type-names", help="list schemas")
    common(sp, feature=False)
    sp.set_defaults(fn=cmd_get_type_names)

    sp = sub.add_parser("describe-schema", help="describe a schema")
    common(sp)
    sp.set_defaults(fn=cmd_describe_schema)

    sp = sub.add_parser("ingest", help="ingest files via a converter")
    common(sp)
    sp.add_argument("-C", "--converter", help="converter config file (HOCON/JSON)")
    sp.add_argument("--infer", action="store_true",
                    help="infer schema+converter from the input")
    sp.add_argument("files", nargs="+")
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser("export", help="export features")
    common(sp, cql=True)
    sp.add_argument("-F", "--format", default="csv",
                    help="csv|tsv|geojson|arrow|bin|parquet|leaflet")
    sp.add_argument("-o", "--output")
    sp.add_argument("-m", "--max-features", type=int)
    sp.add_argument("-a", "--attributes", help="comma-separated projection")
    sp.add_argument("--track", help="BIN track attribute")
    sp.add_argument("--label", help="BIN label attribute")
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser("explain", help="explain query planning")
    common(sp, cql=True)
    sp.set_defaults(fn=cmd_explain)

    sp = sub.add_parser("stats-count", help="feature count")
    common(sp, cql=True)
    sp.add_argument("--no-cache-ok", action="store_true",
                    help="allow estimated (sketch-based) counts")
    sp.set_defaults(fn=cmd_stats_count)

    sp = sub.add_parser("stats-bounds", help="geometry or attribute bounds")
    common(sp, cql=True)
    sp.add_argument("-a", "--attribute")
    sp.set_defaults(fn=cmd_stats_bounds)

    sp = sub.add_parser("stats-histogram", help="attribute histogram")
    common(sp, cql=True)
    sp.add_argument("-a", "--attribute", required=True)
    sp.add_argument("--bins", type=int, default=10)
    sp.set_defaults(fn=cmd_stats_histogram)

    sp = sub.add_parser("stats-top-k", help="top-k attribute values")
    common(sp, cql=True)
    sp.add_argument("-a", "--attribute", required=True)
    sp.add_argument("-k", type=int, default=10)
    sp.set_defaults(fn=cmd_stats_top_k)

    sp = sub.add_parser("stats-analyze", help="recompute & print cached stats")
    common(sp)
    sp.set_defaults(fn=cmd_stats_analyze)

    sp = sub.add_parser("compact", help="compact filesystem partitions")
    common(sp)
    sp.set_defaults(fn=cmd_compact)

    sp = sub.add_parser("serve", help="run the Arrow Flight sidecar")
    common(sp)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8815)
    sp.add_argument("--persist", action="store_true",
                    help="save the catalog on shutdown")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("web", help="run the REST endpoint (geomesa-web analog)")
    common(sp)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8081)
    sp.set_defaults(fn=cmd_web)

    sp = sub.add_parser("metrics", help="print the metrics exposition")
    sp.add_argument("--url", help="scrape a running /metrics endpoint")
    sp.add_argument("--host", dest="sidecar_host",
                    help="fetch a sidecar's registry via Flight")
    sp.add_argument("--port", dest="sidecar_port", type=int)
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser("trace",
                        help="run one query with tracing on; print the span tree")
    common(sp, cql=True)
    sp.add_argument("--op", default="count", choices=["count", "density", "query"])
    sp.add_argument("--json", action="store_true", help="emit JSON")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("obs", help="run the observability endpoint "
                                    "(/metrics /healthz /debug/queries)")
    common(sp, feature=False)
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=9090)
    sp.set_defaults(fn=cmd_obs)

    sp = sub.add_parser("devices", help="per-device utilization + health, "
                        "slot occupancy, and SLO burn (JSON); "
                        "cordon/uncordon removes/re-admits a device")
    sp.add_argument("action", nargs="?", choices=["cordon", "uncordon"],
                    help="mutate device health instead of printing it")
    sp.add_argument("device", nargs="?", type=int,
                    help="device id for cordon/uncordon")
    sp.add_argument("--reason", help="cordon reason (recorded in "
                    "/debug/devices)")
    sp.add_argument("--url", help="base URL of a running obs/web endpoint")
    sp.add_argument("--host", dest="sidecar_host",
                    help="apply cordon/uncordon on a running sidecar")
    sp.add_argument("--port", dest="sidecar_port", type=int)
    sp.set_defaults(fn=cmd_devices)

    sp = sub.add_parser("fleet", help="replica-fleet operations: run a "
                        "replica, probe status, drain/undrain, routed "
                        "count, warm-handoff leave, direct cache handoff "
                        "(docs/RESILIENCE.md §7)")
    fsub = sp.add_subparsers(dest="fleet_cmd", required=True)
    fp = fsub.add_parser("replica", help="run one replica sidecar over "
                         "the shared fleet root")
    fp.add_argument("--root", required=True,
                    help="shared storage root (GeoDataset.save layout)")
    fp.add_argument("--replica-id", required=True)
    fp.add_argument("--host", default="127.0.0.1")
    fp.add_argument("--port", type=int, default=0)
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("status", help="probe every replica")
    fp.add_argument("--replicas", required=True,
                    help="id=host:port,id=host:port")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("drain", help="drain one replica (new requests "
                         "answer [GM-DRAINING] until undrain)")
    fp.add_argument("--host", default="127.0.0.1")
    fp.add_argument("--port", type=int, default=8815)
    fp.add_argument("--reason")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("undrain", help="re-admit a drained replica")
    fp.add_argument("--host", default="127.0.0.1")
    fp.add_argument("--port", type=int, default=8815)
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("count", help="route one count through an "
                         "ad-hoc fleet router")
    fp.add_argument("--replicas", required=True,
                    help="id=host:port,id=host:port")
    fp.add_argument("-f", "--feature-name", required=True)
    fp.add_argument("-q", "--cql", default="INCLUDE")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("leave", help="warm-handoff drain: drain the "
                         "replica, push its hottest cache entries to the "
                         "new ring owners, remove it from the ring")
    fp.add_argument("--replicas", required=True,
                    help="id=host:port,... (must include the leaver)")
    fp.add_argument("--replica-id", required=True,
                    help="the replica to drain and remove")
    fp.add_argument("--no-handoff", action="store_true",
                    help="skip the cache handoff (plain drain + remove)")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("heat", help="fleet cell-heat table: per-"
                         "(schema, SFC cell) hits/misses/device-ms "
                         "merged across replicas with per-replica touch "
                         "splits (docs/OBSERVABILITY.md §9)")
    fp.add_argument("--replicas", required=True,
                    help="id=host:port,id=host:port")
    fp.add_argument("--top", type=int, default=None,
                    help="hottest rows per schema (default "
                    "geomesa.heat.top)")
    fp.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table rendering")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("trace", help="fetch one trace id's retained "
                         "span tree(s) from every replica (the stitcher's "
                         "raw inputs; docs/OBSERVABILITY.md §9)")
    fp.add_argument("--replicas", required=True,
                    help="id=host:port,id=host:port")
    fp.add_argument("trace_id")
    fp.set_defaults(fn=cmd_fleet)
    fp = fsub.add_parser("handoff", help="direct cache handoff between "
                         "two replicas: export one's hottest entries for "
                         "a schema, import into the other")
    fp.add_argument("--source", required=True,
                    help="grpc+tcp://host:port of the exporting replica")
    fp.add_argument("--dest", required=True,
                    help="grpc+tcp://host:port of the importing replica")
    fp.add_argument("-f", "--feature-name", required=True)
    fp.add_argument("--limit", type=int, default=None,
                    help="hottest-entry cap (default: all current-epoch "
                    "entries)")
    fp.set_defaults(fn=cmd_fleet)

    sp = sub.add_parser("subscribe", help="register a standing viewport "
                        "on a sidecar (or fleet) and stream its "
                        "incremental updates (docs/STANDING.md)")
    sp.add_argument("-f", "--feature-name", required=True)
    sp.add_argument("--aggregate", default="count",
                    choices=["count", "density", "pyramid", "stats"])
    sp.add_argument("--bbox", help="xmin,ymin,xmax,ymax viewport")
    sp.add_argument("--region", help="WKT polygon viewport (exact "
                    "membership, like region= queries)")
    sp.add_argument("--width", type=int, default=256)
    sp.add_argument("--height", type=int, default=256)
    sp.add_argument("--levels", type=int, default=None,
                    help="pyramid depth (aggregate=pyramid)")
    sp.add_argument("--stat", help="stats spec, e.g. Count() "
                    "(aggregate=stats; exact-merge sketches only)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8815)
    sp.add_argument("--replicas", help="id=host:port,... — route via an "
                    "ad-hoc fleet router instead of one sidecar")
    sp.add_argument("--poll-interval", type=float, default=1.0)
    sp.add_argument("--max-updates", type=int, default=None,
                    help="exit after N update records (default: stream "
                    "until interrupted)")
    sp.add_argument("--once", action="store_true",
                    help="print the registration snapshot and exit")
    sp.set_defaults(fn=cmd_subscribe)

    sp = sub.add_parser("journal", help="durable mutation journal: "
                        "status + crash recovery (docs/RESILIENCE.md §8)")
    jsub = sp.add_subparsers(dest="journal_cmd", required=True)
    jp = jsub.add_parser("status", help="segments, sequence range, "
                         "per-schema checkpoints, pending frames")
    jp.add_argument("catalog")
    jp.set_defaults(fn=cmd_journal)
    jp = jsub.add_parser("replay", help="recover: replay records past "
                         "each checkpoint, then checkpoint via save")
    jp.add_argument("catalog")
    jp.set_defaults(fn=cmd_journal)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)

    sp = sub.add_parser("version-remote", help="query a sidecar's version")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8815)
    sp.set_defaults(fn=cmd_version_remote)

    sp = sub.add_parser("env", help="print config tunables + effective values")
    sp.set_defaults(fn=cmd_env)

    sp = sub.add_parser("convert", help="dry-run a converter config")
    sp.add_argument("-f", "--feature-name", required=True)
    sp.add_argument("-s", "--spec", required=True)
    sp.add_argument("-C", "--config", required=True, help="converter config file")
    sp.add_argument("-i", "--input", required=True)
    sp.add_argument("--max", type=int, default=10, help="rows to print")
    sp.set_defaults(fn=cmd_convert)

    sp = sub.add_parser("playback", help="replay a dataset onto a live stream")
    common(sp)
    sp.add_argument("--rate", type=float, default=10.0)
    sp.add_argument("--batch-ms", type=int, default=1000)
    sp.add_argument("--fast", action="store_true", help="no real-time sleeps")
    sp.set_defaults(fn=cmd_playback)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        # a command may return its own non-zero exit code (e.g. a usage
        # error in `devices cordon`); None keeps the success default
        rc = args.fn(args)
        return int(rc) if rc else 0
    except (KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) closed the pipe: exit quietly
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
