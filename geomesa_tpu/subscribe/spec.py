"""Standing-query specs (docs/STANDING.md).

A :class:`StandingSpec` is the registered half of the inverted query
model (PAPERS.md: 1411.3212 — index the standing queries, stream the
points through them): one viewport (bbox, optionally intersected with a
``region`` polygon) plus one aggregate over it. Specs are VALUE objects —
two subscribers registering equal specs fuse into one standing group
(serving/fuse.py's :func:`~geomesa_tpu.serving.fuse.subscription_key`
is the canonical identity), and the spec's dict codec is what rides the
sidecar wire (PROTOCOL §5 v1.6) and the fleet warm handoff.

Supported aggregates:

* ``count``      — exact feature count inside the viewport;
* ``density``    — unweighted (height, width) f32 grid over the viewport
                   bbox (integer-valued cells: delta adds are bit-exact
                   to 2^24);
* ``pyramid``    — quadtree rollup: an f64 leaf grid of side
                   2^levels downsample-added up to the 1x1 root in the
                   fixed SW/SE/NW/NE order (cache/hierarchy.downsample;
                   integer-valued cells exact to 2^53);
* ``stats``      — a sketch spec whose every leaf merges exactly
                   (cache/service.stats_exact_merge) — the same
                   eligibility gate cache decomposition and the fleet
                   scatter apply.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

AGGREGATES = ("count", "density", "pyramid", "stats")

WORLD = (-180.0, -90.0, 180.0, 90.0)


@dataclass(frozen=True)
class StandingSpec:
    """One registered viewport + aggregate. Immutable; hash/eq follow the
    canonical :meth:`key` so dict-of-group lookups fuse equal specs."""

    schema: str
    aggregate: str
    #: viewport bbox (xmin, ymin, xmax, ymax), f64. Always present —
    #: region-only registrations carry the polygon's bounds.
    bbox: Tuple[float, float, float, float]
    #: optional polygon viewport (WKT), intersected with the bbox
    region: Optional[str] = None
    #: density grid dims
    width: int = 256
    height: int = 256
    #: pyramid depth (leaf side = 2^levels)
    levels: int = 5
    #: stats sketch spec (aggregate == "stats")
    stat_spec: Optional[str] = None

    def __post_init__(self):
        if self.aggregate not in AGGREGATES:
            raise ValueError(
                f"[GM-ARG] unknown standing aggregate {self.aggregate!r} "
                f"(one of {AGGREGATES})"
            )
        if self.aggregate == "stats" and not self.stat_spec:
            raise ValueError("[GM-ARG] stats subscription needs stat_spec")
        xmin, ymin, xmax, ymax = self.bbox
        if not (xmax > xmin and ymax > ymin):
            raise ValueError(f"[GM-ARG] degenerate viewport bbox {self.bbox}")

    # -- identity ----------------------------------------------------------
    def key(self) -> tuple:
        """Canonical fuse identity (delegates to serving/fuse.py so the
        subscriber-fusion contract lives next to the query-fusion one)."""
        from geomesa_tpu.serving.fuse import subscription_key

        return subscription_key(self)

    def ecql(self, geom: str = "geom") -> str:
        """The membership predicate: the viewport as ECQL text — exactly
        the shape :meth:`GeoDataset._with_region` folds a region into, so
        the compiled mask (filter/compile.py) is the single membership
        oracle for BOTH the delta path and the from-scratch re-scan."""
        xmin, ymin, xmax, ymax = (repr(float(v)) for v in self.bbox)
        base = f"BBOX({geom}, {xmin}, {ymin}, {xmax}, {ymax})"
        if self.region:
            return f"({base}) AND INTERSECTS({geom}, {self.region})"
        return base

    def route_key(self, level: int) -> str:
        """The fleet ring key: the viewport center's SFC cell at the
        routing level — byte-identical to the router's ``_affinity_key``
        for a query over the same bbox, so a subscription lands on the
        replica whose cell cache its viewport keeps hot."""
        from geomesa_tpu.cache import cells as cellmod

        n = 1 << level
        cx = (self.bbox[0] + self.bbox[2]) / 2.0
        cy = (self.bbox[1] + self.bbox[3]) / 2.0
        ix = int(np.clip((cx + 180.0) / 360.0 * n, 0, n - 1))
        iy = int(np.clip((cy + 90.0) / 180.0 * n, 0, n - 1))
        prefix = cellmod.cell_prefix(level, (ix, iy))
        return f"{self.schema}:z{level}:{prefix}"

    def intersects(self, bounds) -> bool:
        """Viewport-vs-dirty-bounds test (bbox level): False means a
        non-additive mutation provably cannot have changed this group's
        result, so the dirty re-scan skips it. ``bounds`` None = unknown
        extent = always intersects."""
        if bounds is None:
            return True
        xmin, ymin, xmax, ymax = self.bbox
        bx0, by0, bx1, by1 = bounds
        return not (bx1 < xmin or bx0 > xmax or by1 < ymin or by0 > ymax)

    # -- wire codec (PROTOCOL §5 v1.6) -------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "schema": self.schema, "aggregate": self.aggregate,
            "bbox": [float(v) for v in self.bbox],
        }
        if self.region:
            d["region"] = self.region
        if self.aggregate == "density":
            d["width"], d["height"] = int(self.width), int(self.height)
        if self.aggregate == "pyramid":
            d["levels"] = int(self.levels)
        if self.aggregate == "stats":
            d["stat_spec"] = self.stat_spec
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "StandingSpec":
        return cls(
            schema=d["schema"], aggregate=d["aggregate"],
            bbox=tuple(float(v) for v in d["bbox"]),
            region=d.get("region"),
            width=int(d.get("width", 256)), height=int(d.get("height", 256)),
            levels=int(d.get("levels", 5)),
            stat_spec=d.get("stat_spec"),
        )


def make_spec(schema: str, aggregate: str, bbox=None, region=None,
              width: int = 256, height: int = 256,
              levels: Optional[int] = None,
              stat_spec: Optional[str] = None) -> StandingSpec:
    """Build + validate a spec from loose request arguments (the CLI /
    sidecar-action entry shape). A region-only registration derives its
    bbox from the polygon bounds; neither given covers the world."""
    from geomesa_tpu import config

    wkt = None
    if region is not None:
        from geomesa_tpu.utils import geometry as geo

        wkt = region if isinstance(region, str) else region.wkt()
        g = geo.parse_wkt(wkt)  # validate before it reaches a compile
        if bbox is None:
            bbox = g.bounds()
    if bbox is None:
        bbox = WORLD
    if levels is None:
        levels = config.SUBSCRIBE_PYRAMID_LEVELS.to_int() or 5
    return StandingSpec(
        schema=schema, aggregate=aggregate,
        bbox=tuple(float(v) for v in bbox), region=wkt,
        width=int(width), height=int(height), levels=int(levels),
        stat_spec=stat_spec,
    )
