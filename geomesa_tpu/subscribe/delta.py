"""The standing-query evaluator (docs/STANDING.md "Bit-identity").

ONE evaluation routine serves both halves of the incremental contract:

* the **delta path** runs it over just an applied ingest batch's rows and
  adds (or, for a moved feature's old position, subtracts) the result
  into the standing aggregate;
* the **re-scan path** runs the SAME routine over the full window from a
  zero aggregate.

Every supported aggregate is integer-valued exact algebra — counts are
ints, unweighted f32 density cells hold integers (exact to 2^24), f64
pyramid cells hold integers (exact to 2^53), and stats sketches are
gated to :func:`~geomesa_tpu.cache.service.stats_exact_merge` kinds — so
add/subtract/downsample compose associatively WITHOUT rounding, and a
delta-accumulated result is bit-identical to the from-scratch re-scan at
the same epoch. That identity is not hoped for: the engine hard-asserts
it under ``geomesa.subscribe.verify`` and the standing-smoke CI gate.

The membership oracle is the compiled viewport mask
(filter/compile.py — the same vectorized kernel the query path uses),
evaluated host-side over the batch's encoded columns: the megakernel
batch shape (docs/SERVING.md "Query-axis batching") on the numpy
backend, one pass over the rows however many fused groups watch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.cache import hierarchy
from geomesa_tpu.kernels import density as kdensity


def compile_viewport(spec, ft, dicts):
    """Compile the spec's membership predicate against a schema. The
    returned mask kernel is the ONLY membership decision in the
    subsystem — delta and re-scan can't disagree on who's inside."""
    from geomesa_tpu.filter import parse_ecql
    from geomesa_tpu.filter.compile import compile_filter

    geom = ft.geom_field
    if geom is None:
        raise ValueError(
            f"[GM-ARG] schema {spec.schema!r} has no geometry field"
        )
    return compile_filter(parse_ecql(spec.ecql(geom)), ft, dicts)


def member_mask(cf, ft, cols: Dict[str, np.ndarray], n: int) -> np.ndarray:
    """Exact viewport membership over ``n`` rows, with the live-window
    validity rule folded in (null/NaN geometry is invisible — the same
    mask ``StreamingDataset._masked`` applies)."""
    if n == 0:
        return np.zeros(0, dtype=bool)
    m = cf.exact_mask(cols, n)
    g = ft.geom_field
    gx = cols.get(g + "__x") if g is not None else None
    if gx is not None:
        m = m & np.isfinite(gx)
    return m


def zero_result(spec):
    if spec.aggregate == "count":
        return 0
    if spec.aggregate == "density":
        return np.zeros((spec.height, spec.width), np.float32)
    if spec.aggregate == "pyramid":
        side = 1 << spec.levels
        out = []
        while side >= 1:
            out.append(np.zeros((side, side), np.float64))
            side >>= 1
        return out
    if spec.aggregate == "stats":
        from geomesa_tpu.stats import parse_stat

        return parse_stat(spec.stat_spec)
    raise ValueError(spec.aggregate)


def _pyramid_leaf(spec, xs, ys, mask) -> np.ndarray:
    """Leaf-level f64 count grid over the viewport bbox (side 2^levels).
    Same clip-cast binning as the density kernel's numpy path, f64
    accumulation for 2^53 integer headroom."""
    side = 1 << spec.levels
    x0, y0, x1, y1 = spec.bbox
    dx, dy = x1 - x0, y1 - y0
    px = np.clip(((xs - x0) / dx * side).astype(np.int32), 0, side - 1)
    py = np.clip(((ys - y0) / dy * side).astype(np.int32), 0, side - 1)
    grid = np.zeros(side * side, np.float64)
    np.add.at(grid, py[mask] * side + px[mask], 1.0)
    return grid.reshape(side, side)


def eval_rows(spec, cf, ft, cols: Dict[str, np.ndarray], n: int,
              dicts=None):
    """Evaluate the spec's aggregate over ``n`` rows: returns
    ``(partial_result, rows_matched)``. THE shared routine — a delta is
    this over a batch, a re-scan is this over the window. ``dicts``
    decodes enumeration/topk sketch keys from dictionary codes to their
    string values (stats_scan.decode_enum_keys — the same mapping the
    query path applies), so a standing sketch reads like ``ds.stats``
    and merges consistently across batches."""
    mask = member_mask(cf, ft, cols, n)
    matched = int(mask.sum())
    g = ft.geom_field
    if spec.aggregate == "count":
        return matched, matched
    if spec.aggregate == "density":
        if n == 0:
            return np.zeros((spec.height, spec.width), np.float32), 0
        grid = kdensity.density_grid(
            cols[g + "__x"], cols[g + "__y"], mask, spec.bbox,
            spec.width, spec.height, None, np,
        )
        return np.asarray(grid), matched
    if spec.aggregate == "pyramid":
        if n == 0:
            return zero_result(spec), 0
        # leaf delta, then downsample-added up the ancestor chain in the
        # fixed SW/SE/NW/NE order (cache/hierarchy.downsample) — the
        # quadtree-rollup contract: a level-k cell is exactly the sum of
        # its four level-(k+1) children
        d = _pyramid_leaf(spec, cols[g + "__x"], cols[g + "__y"], mask)
        out = [d]
        while d.shape[0] > 1:
            d = hierarchy.downsample(d)
            out.append(d)
        return out, matched
    if spec.aggregate == "stats":
        from geomesa_tpu.kernels.stats_scan import decode_enum_keys

        stat = zero_result(spec)
        if matched:
            stat.observe(cols, mask)
            if dicts is not None:
                decode_enum_keys(stat, dicts)
        return stat, matched
    raise ValueError(spec.aggregate)


def apply_delta(spec, result, delta, sign: int = 1):
    """Fold a partial into the standing result, in place where the result
    is array-backed. ``sign=-1`` subtracts (a moved feature's old
    position) — additive aggregates only; stats callers re-scan
    instead (sketches cannot unobserve)."""
    if spec.aggregate == "count":
        return result + sign * delta
    if spec.aggregate == "density":
        if sign >= 0:
            result += delta
        else:
            result -= delta
        return result
    if spec.aggregate == "pyramid":
        for lvl, d in zip(result, delta):
            if sign >= 0:
                lvl += d
            else:
                lvl -= d
        return result
    if spec.aggregate == "stats":
        if sign < 0:
            raise ValueError("stats aggregates cannot subtract")
        result.merge(delta)
        return result
    raise ValueError(spec.aggregate)


def results_equal(spec, a, b) -> bool:
    """Bit-identity comparison between two results of one spec."""
    if spec.aggregate == "count":
        return int(a) == int(b)
    if spec.aggregate == "density":
        return (a.dtype == b.dtype and a.shape == b.shape
                and np.array_equal(a, b))
    if spec.aggregate == "pyramid":
        return (len(a) == len(b)
                and all(np.array_equal(x, y) for x, y in zip(a, b)))
    if spec.aggregate == "stats":
        return a.to_json() == b.to_json()
    raise ValueError(spec.aggregate)


# -- wire codec (PROTOCOL §5 v1.6; rides subscribe-poll + warm handoff) ----

def encode_result(spec, result):
    from geomesa_tpu.cache.store import encode_wire_value

    if spec.aggregate == "count":
        return encode_wire_value(int(result))
    if spec.aggregate == "density":
        return encode_wire_value(np.asarray(result, np.float32))
    if spec.aggregate == "pyramid":
        return encode_wire_value(tuple(np.asarray(g) for g in result))
    if spec.aggregate == "stats":
        return encode_wire_value(result.to_json())
    raise ValueError(spec.aggregate)


def decode_result(spec, d):
    from geomesa_tpu.cache.store import decode_wire_value

    v = decode_wire_value(d)
    if spec.aggregate == "count":
        return int(v)
    if spec.aggregate == "density":
        return np.asarray(v, np.float32)
    if spec.aggregate == "pyramid":
        return [np.asarray(g, np.float64) for g in v]
    if spec.aggregate == "stats":
        from geomesa_tpu.stats import sketches as sk

        return sk.Stat.from_json(v)
    raise ValueError(spec.aggregate)
