"""Standing-query engine (docs/STANDING.md).

One engine instance rides each dataset (GeoDataset and StreamingDataset
both attach one lazily). It keeps the registered viewports as **standing
groups** — same-spec subscribers fuse into one group
(serving/fuse.subscription_key), so a hot viewport with 10k watchers
costs ONE standing result and ONE update per ingest batch — and advances
every group incrementally as mutations apply:

* **additive batches** (inserts; a moved feature's -old/+new pair) run
  the shared evaluator (subscribe/delta.py) over just the batch rows in
  one host pass and fold the partial into the standing result — the
  ``subscribe.update.dispatches`` counter increments once per applied
  batch per schema, however many groups/subscribers watch (the CI-gated
  one-dispatch contract);
* **non-additive mutations** (deletes, age-off/expiry, clears) mark
  groups whose viewport intersects the mutation's bounds dirty and
  re-scan ONLY those from scratch; provably-disjoint groups are
  untouched.

Delta-applied results are bit-identical to a from-scratch re-scan at the
same epoch — hard-asserted after every settle under
``geomesa.subscribe.verify`` (tests + the standing-smoke CI gate keep it
on).

Fleet placement: a subscription id embeds its ring route key
(``schema:z<lvl>:<prefix>:<uuid>`` — the viewport center's SFC cell at
the routing level), so any router can re-derive the owner replica from
the id alone; :meth:`StandingQueryEngine.export_groups` /
:meth:`import_groups` migrate groups across membership changes exactly
like cache entries over cache-export/cache-import (PROTOCOL v1.6,
docs/RESILIENCE.md §7): a matching ``{count, spec}`` guard adopts the
exported results + update rings verbatim; a mismatch adopts the
subscribers but re-scans against the local window and emits a ``resync``
update so pollers keep a contiguous version sequence either way.
"""

from __future__ import annotations

import threading
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics
from geomesa_tpu.subscribe import delta as dl
from geomesa_tpu.subscribe.spec import StandingSpec


class UnknownSubscription(KeyError):
    """Typed miss: this replica holds no such subscription — the fleet
    router fails the poll over to the next ring owner on this marker."""

    MARKER = "[GM-SUB-UNKNOWN]"

    def __init__(self, sub_id: str):
        super().__init__(f"{self.MARKER} no subscription {sub_id!r}")


def route_key_of(sub_id: str) -> str:
    """The ring key embedded in a subscription id (strip the uuid tail)."""
    return sub_id.rsplit(":", 1)[0]


# -- window adapters -------------------------------------------------------

class StoreWindow:
    """GeoDataset-backed window: the schema's FeatureStore, whole."""

    def __init__(self, ds, name: str):
        self.ds = ds
        self.name = name

    @property
    def st(self):
        return self.ds._store(self.name)

    @property
    def ft(self):
        return self.st.ft

    @property
    def dicts(self):
        return self.st.dicts

    def columns(self) -> Tuple[Dict[str, np.ndarray], int]:
        st = self.st
        st.flush()
        if st._all is None:
            return {}, 0
        return st._all.columns, st._all.n

    def epoch(self) -> int:
        return int(self.st.version)

    def guard(self) -> Dict[str, Any]:
        st = self.st
        return {"count": int(st.count), "spec": st.ft.spec()}

    def validate(self, spec: StandingSpec) -> None:
        from geomesa_tpu.index.partitioned import PartitionedFeatureStore

        if isinstance(self.st, PartitionedFeatureStore):
            # partitioned windows spill rows out of host RAM — the full
            # re-scan contract doesn't hold yet (ROADMAP follow-up)
            raise ValueError(
                "[GM-SUB] standing queries do not support partitioned "
                f"schemas yet ({self.name!r})"
            )
        _validate_common(self.ft, spec)


class LiveWindow:
    """StreamingDataset-backed window: one schema's live feature cache."""

    def __init__(self, sds, name: str):
        self.sds = sds
        self.name = name

    @property
    def cache(self):
        return self.sds._caches[self.name]

    @property
    def ft(self):
        return self.cache.ft

    @property
    def dicts(self):
        return self.cache.dicts

    def columns(self) -> Tuple[Dict[str, np.ndarray], int]:
        b = self.cache.batch()
        return b.columns, b.n

    def epoch(self) -> int:
        return int(self.cache.epoch)

    def guard(self) -> Dict[str, Any]:
        return {"count": len(self.cache), "spec": self.ft.spec()}

    def validate(self, spec: StandingSpec) -> None:
        _validate_common(self.ft, spec)


def _validate_common(ft, spec: StandingSpec) -> None:
    g = ft.geom_field
    if g is None or not ft.attr(g).is_point:
        raise ValueError(
            "[GM-SUB] standing queries need a point-geometry schema "
            f"({spec.schema!r})"
        )
    if spec.aggregate == "stats":
        from geomesa_tpu.cache.service import stats_exact_merge
        from geomesa_tpu.stats import parse_stat

        if not stats_exact_merge(parse_stat(spec.stat_spec)):
            raise ValueError(
                "[GM-SUB] stats subscriptions need exact-merge sketches "
                f"(cache/service.EXACT_MERGE_KINDS); got {spec.stat_spec!r}"
            )


# -- groups ----------------------------------------------------------------

@dataclass
class StandingGroup:
    """One fused viewport: the standing result all same-spec subscribers
    share, plus the bounded ring of per-batch update records."""

    spec: StandingSpec
    cf: Any                      # compiled viewport mask
    result: Any
    version: int = 0
    epoch: int = 0
    subscribers: set = field(default_factory=set)
    updates: deque = field(default_factory=deque)

    def emit(self, kind: str, rows: int, epoch: int) -> None:
        self.version += 1
        self.epoch = epoch
        cap = config.SUBSCRIBE_UPDATES_RING.to_int() or 256
        self.updates.append(
            {"version": self.version, "kind": kind, "rows": int(rows),
             "epoch": int(epoch)}
        )
        while len(self.updates) > cap:
            self.updates.popleft()
        metrics.inc(metrics.SUBSCRIBE_UPDATES)


@dataclass
class _Pending:
    """Buffered live-cache events, settled once per applied poll batch."""

    adds: List[Tuple[str, Dict]] = field(default_factory=list)
    moves: List[Tuple[str, Dict, Dict]] = field(default_factory=list)
    removed: List[Dict] = field(default_factory=list)
    clear: bool = False

    def any(self) -> bool:
        return bool(self.adds or self.moves or self.removed or self.clear)


class StandingQueryEngine:
    """Registered viewports + incremental maintenance for one dataset."""

    def __init__(self, window_of: Callable[[str], Any]):
        self._window_of = window_of
        self._groups: Dict[str, Dict[tuple, StandingGroup]] = {}
        self._subs: Dict[str, Tuple[str, tuple]] = {}  # sub_id -> (schema, key)
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.RLock()

    # -- fast ingest-path gate --------------------------------------------
    def active(self, schema: str) -> bool:
        g = self._groups.get(schema)
        return bool(g)

    # -- registration ------------------------------------------------------
    def register(self, spec: StandingSpec,
                 sub_id: Optional[str] = None) -> str:
        if not config.SUBSCRIBE_ENABLED.to_bool():
            raise ValueError("[GM-SUB] standing queries are disabled "
                             "(geomesa.subscribe.enabled)")
        with self._lock:
            win = self._window_of(spec.schema)
            win.validate(spec)
            key = spec.key()
            groups = self._groups.setdefault(spec.schema, {})
            grp = groups.get(key)
            if grp is None:
                cap = config.SUBSCRIBE_MAX_GROUPS.to_int() or 256
                if len(groups) >= cap:
                    raise ValueError(
                        f"[GM-SUB-LIMIT] schema {spec.schema!r} already "
                        f"holds {cap} distinct standing groups"
                    )
                cf = dl.compile_viewport(spec, win.ft, win.dicts)
                cols, n = win.columns()
                result, rows = dl.eval_rows(spec, cf, win.ft, cols, n,
                                            win.dicts)
                grp = StandingGroup(spec=spec, cf=cf, result=result,
                                    epoch=win.epoch())
                grp.emit("snapshot", rows, win.epoch())
                groups[key] = grp
            else:
                # fused: the new subscriber rides the existing standing
                # result — no extra scan, no extra per-batch work
                metrics.inc(metrics.SUBSCRIBE_FUSED)
            if sub_id is None:
                sub_id = self.make_sub_id(spec)
            grp.subscribers.add(sub_id)
            self._subs[sub_id] = (spec.schema, key)
            self._set_gauges()
            return sub_id

    def make_sub_id(self, spec: StandingSpec) -> str:
        """Pre-generate a routable subscription id for ``spec``. The
        durability path journals (sub_id, spec) BEFORE registering so a
        crash between the WAL append and the register replays into the
        SAME id (docs/STANDING.md §7) — register() then accepts it
        verbatim."""
        lvl = self._routing_level()
        return f"{spec.route_key(lvl)}:{uuid.uuid4().hex[:12]}"

    def schema_of(self, sub_id: str) -> Optional[str]:
        """The schema a live subscription is registered on (None when
        unknown) — the unsubscribe journal record needs it to land in
        the right schema's WAL (docs/STANDING.md §7)."""
        with self._lock:
            got = self._subs.get(sub_id)
            return got[0] if got else None

    def subscriptions(self, schema: str) -> List[Dict[str, Any]]:
        """Live subscriptions on ``schema`` as durable records —
        ``[{"sub_id", "spec"}]`` sorted by id. This is what ``save()``
        persists in the manifest entry and ``_attach_schema_entry``
        replays through ``register(spec, sub_id=...)`` on load
        (docs/STANDING.md §7)."""
        with self._lock:
            out = []
            for sid, (sch, key) in self._subs.items():
                if sch != schema:
                    continue
                grp = self._groups.get(sch, {}).get(key)
                if grp is None:  # pragma: no cover — _subs implies group
                    continue
                out.append({"sub_id": sid, "spec": grp.spec.to_dict()})
            return sorted(out, key=lambda r: r["sub_id"])

    def unregister(self, sub_id: str) -> bool:
        with self._lock:
            got = self._subs.pop(sub_id, None)
            if got is None:
                return False
            schema, key = got
            grp = self._groups.get(schema, {}).get(key)
            if grp is not None:
                grp.subscribers.discard(sub_id)
                if not grp.subscribers:
                    del self._groups[schema][key]
                    if not self._groups[schema]:
                        del self._groups[schema]
            self._set_gauges()
            return True

    @staticmethod
    def _routing_level() -> int:
        lvl = config.FLEET_ROUTING_LEVEL.to_int()
        return 3 if lvl is None else max(1, min(int(lvl), 15))

    def _set_gauges(self) -> None:
        reg = metrics.registry()
        reg.gauge(metrics.SUBSCRIBE_GROUPS).set(
            sum(len(g) for g in self._groups.values())
        )
        reg.gauge(metrics.SUBSCRIBE_SUBSCRIBERS).set(len(self._subs))

    # -- reads -------------------------------------------------------------
    def poll(self, sub_id: str, cursor: int = 0) -> Dict[str, Any]:
        """Current result + every update record past ``cursor``. A poller
        that sees ``updates[0].version > cursor + 1`` lagged past the
        ring depth: re-anchor on the carried full result."""
        with self._lock:
            got = self._subs.get(sub_id)
            if got is None:
                raise UnknownSubscription(sub_id)
            schema, key = got
            self.settle(schema)
            grp = self._groups[schema][key]
            return {
                "sub_id": sub_id,
                "schema": schema,
                "aggregate": grp.spec.aggregate,
                "version": grp.version,
                "epoch": grp.epoch,
                "subscribers": len(grp.subscribers),
                "result": dl.encode_result(grp.spec, grp.result),
                "updates": [u for u in grp.updates
                            if u["version"] > int(cursor)],
            }

    def snapshot(self) -> Dict[str, Any]:
        """Operator view (/debug/queries, subscribe-stats)."""
        with self._lock:
            out = []
            for schema, groups in sorted(self._groups.items()):
                for grp in groups.values():
                    out.append({
                        "schema": schema,
                        "aggregate": grp.spec.aggregate,
                        "bbox": list(grp.spec.bbox),
                        "region": bool(grp.spec.region),
                        "subscribers": len(grp.subscribers),
                        "version": grp.version,
                        "epoch": grp.epoch,
                    })
            return {
                "groups": out,
                "subscribers": len(self._subs),
            }

    # -- mutation hooks (GeoDataset edges; fire on journal replay too) -----
    def on_batch(self, schema: str, cols: Dict[str, np.ndarray],
                 n: int) -> None:
        """An applied additive ingest batch: ONE delta evaluation pass
        over its rows updates every standing group of the schema."""
        with self._lock:
            groups = self._groups.get(schema)
            if not groups or n == 0:
                return
            win = self._window_of(schema)
            epoch = win.epoch()
            metrics.inc(metrics.SUBSCRIBE_DISPATCHES)
            for grp in groups.values():
                d, rows = dl.eval_rows(grp.spec, grp.cf, win.ft, cols, n,
                                       win.dicts)
                if rows:
                    grp.result = dl.apply_delta(grp.spec, grp.result, d)
                    grp.emit("delta", rows, epoch)
                else:
                    grp.epoch = epoch
            self._verify_all(schema)

    def on_dirty(self, schema: str, bounds=None) -> None:
        """A non-additive mutation (delete, age-off): re-scan ONLY the
        groups whose viewport intersects ``bounds`` (None = unknown =
        all); disjoint groups provably kept their exact results."""
        with self._lock:
            groups = self._groups.get(schema)
            if not groups:
                return
            win = self._window_of(schema)
            epoch = win.epoch()
            cols_n = None
            for grp in groups.values():
                if not grp.spec.intersects(bounds):
                    grp.epoch = epoch
                    continue
                if cols_n is None:
                    cols_n = win.columns()
                self._rescan(win, grp, cols_n, "rescan", epoch)
            self._verify_all(schema)

    def _rescan(self, win, grp: StandingGroup, cols_n, kind: str,
                epoch: int) -> None:
        cols, n = cols_n
        grp.result, rows = dl.eval_rows(grp.spec, grp.cf, win.ft, cols, n,
                                        win.dicts)
        grp.emit(kind, rows, epoch)
        metrics.inc(metrics.SUBSCRIBE_RESCANS)

    # -- live-cache events (StreamingDataset) ------------------------------
    def live_observer(self, schema: str) -> Callable:
        """The LiveFeatureCache observer: buffers events cheaply; the
        dataset settles once per applied poll batch."""

        def observe(event: str, fid: Optional[str], old, new) -> None:
            with self._lock:
                if not self.active(schema):
                    return
                p = self._pending.setdefault(schema, _Pending())
                if event == "put":
                    if old is None:
                        p.adds.append((fid, new))
                    else:
                        p.moves.append((fid, old, new))
                elif event == "remove":
                    if old is not None:
                        p.removed.append(old)
                elif event == "clear":
                    p.clear = True

        return observe

    def settle(self, schema: str) -> None:
        """Fold buffered live events into the standing results: adds and
        moves as ONE delta pass (+new, -old), removals/clears through the
        dirty-bounds re-scan path."""
        with self._lock:
            p = self._pending.get(schema)
            groups = self._groups.get(schema)
            if p is None or not p.any():
                return
            self._pending[schema] = _Pending()
            if not groups:
                return
            win = self._window_of(schema)
            epoch = win.epoch()
            add_rows = [a for _, a in p.adds] + [n for _, _, n in p.moves]
            sub_rows = [o for _, o, _ in p.moves]
            if add_rows or sub_rows:
                badd = _encode_rows(win.ft, win.dicts, add_rows)
                bsub = _encode_rows(win.ft, win.dicts, sub_rows)
                metrics.inc(metrics.SUBSCRIBE_DISPATCHES)
                for grp in groups.values():
                    if grp.spec.aggregate == "stats" and sub_rows:
                        # sketches cannot unobserve a move's old position
                        self._rescan(win, grp, win.columns(), "rescan",
                                     epoch)
                        continue
                    rows = 0
                    if badd is not None:
                        d, r = dl.eval_rows(grp.spec, grp.cf, win.ft,
                                            badd.columns, badd.n,
                                            win.dicts)
                        if r:
                            grp.result = dl.apply_delta(
                                grp.spec, grp.result, d)
                        rows += r
                    if bsub is not None:
                        d, r = dl.eval_rows(grp.spec, grp.cf, win.ft,
                                            bsub.columns, bsub.n,
                                            win.dicts)
                        if r:
                            grp.result = dl.apply_delta(
                                grp.spec, grp.result, d, sign=-1)
                        rows += r
                    if rows:
                        grp.emit("delta", rows, epoch)
                    else:
                        grp.epoch = epoch
            if p.removed or p.clear:
                bounds = None if p.clear else _bounds_of(
                    win.ft, p.removed)
                self.on_dirty(schema, bounds)
            else:
                self._verify_all(schema)

    # -- bit-identity hard assert (geomesa.subscribe.verify) ---------------
    def _verify_all(self, schema: str) -> None:
        if not config.SUBSCRIBE_VERIFY.to_bool():
            return
        groups = self._groups.get(schema)
        if not groups:
            return
        win = self._window_of(schema)
        cols, n = win.columns()
        for grp in groups.values():
            fresh, _ = dl.eval_rows(grp.spec, grp.cf, win.ft, cols, n,
                                    win.dicts)
            metrics.inc(metrics.SUBSCRIBE_VERIFY)
            if not dl.results_equal(grp.spec, grp.result, fresh):
                raise AssertionError(
                    f"[GM-SUB-VERIFY] standing {grp.spec.aggregate} over "
                    f"{schema!r} diverged from the epoch-{win.epoch()} "
                    f"re-scan (viewport {grp.spec.bbox})"
                )

    # -- warm handoff (fleet membership changes; PROTOCOL v1.6) ------------
    def export_groups(self, schema: Optional[str] = None,
                      keys: Optional[List[str]] = None,
                      remove: bool = False) -> Dict[str, Any]:
        """Wire-encode standing groups for migration: every group (or
        just those whose route key is in ``keys``), with the per-schema
        ``{count, spec}`` guard the importer verifies before adopting
        results verbatim. ``remove=True`` drops the exported groups here
        (the leaver's half of a migration)."""
        with self._lock:
            want = None if keys is None else set(keys)
            out: List[Dict[str, Any]] = []
            guards: Dict[str, Any] = {}
            drop: List[Tuple[str, tuple]] = []
            for nm, groups in self._groups.items():
                if schema is not None and nm != schema:
                    continue
                self.settle(nm)
                for key, grp in groups.items():
                    lvl = self._routing_level()
                    rk = grp.spec.route_key(lvl)
                    if want is not None and rk not in want:
                        continue
                    if nm not in guards:
                        guards[nm] = self._window_of(nm).guard()
                    out.append({
                        "spec": grp.spec.to_dict(),
                        "route_key": rk,
                        "subscribers": sorted(grp.subscribers),
                        "version": grp.version,
                        "epoch": grp.epoch,
                        "result": dl.encode_result(grp.spec, grp.result),
                        "updates": list(grp.updates),
                    })
                    metrics.inc(metrics.SUBSCRIBE_HANDOFF_EXPORTED)
                    if remove:
                        drop.append((nm, key))
            for nm, key in drop:
                for sid in self._groups[nm][key].subscribers:
                    self._subs.pop(sid, None)
                del self._groups[nm][key]
                if not self._groups[nm]:
                    del self._groups[nm]
            if drop:
                self._set_gauges()
            return {"groups": out, "guards": guards}

    def import_groups(self, payload: Dict[str, Any]) -> Dict[str, int]:
        """Adopt exported groups: a matching guard proves this replica's
        window holds the same logical rows the results were maintained
        over, so results + update rings transfer verbatim (zero missed,
        zero duplicated updates); a mismatch keeps the subscribers but
        re-scans against the LOCAL window and emits a ``resync`` update —
        the version sequence stays contiguous either way."""
        with self._lock:
            adopted = resynced = 0
            guards = payload.get("guards") or {}
            for g in payload.get("groups") or []:
                spec = StandingSpec.from_dict(g["spec"])
                win = self._window_of(spec.schema)
                win.validate(spec)
                key = spec.key()
                groups = self._groups.setdefault(spec.schema, {})
                grp = groups.get(key)
                if grp is None:
                    cf = dl.compile_viewport(spec, win.ft, win.dicts)
                    grp = StandingGroup(spec=spec, cf=cf,
                                        result=dl.zero_result(spec))
                    groups[key] = grp
                grp.version = max(grp.version, int(g.get("version", 0)))
                guard = guards.get(spec.schema) or {}
                local = win.guard()
                if (int(guard.get("count", -1)) == int(local["count"])
                        and guard.get("spec") == local["spec"]):
                    grp.result = dl.decode_result(spec, g["result"])
                    grp.epoch = win.epoch()
                    grp.updates = deque(g.get("updates") or [])
                    adopted += 1
                    metrics.inc(metrics.SUBSCRIBE_HANDOFF_IMPORTED)
                else:
                    self._rescan(win, grp, win.columns(), "resync",
                                 win.epoch())
                    resynced += 1
                    metrics.inc(metrics.SUBSCRIBE_HANDOFF_RESYNC)
                for sid in g.get("subscribers") or []:
                    grp.subscribers.add(sid)
                    self._subs[sid] = (spec.schema, key)
            self._set_gauges()
            return {"adopted": adopted, "resynced": resynced}

    # -- schema lifecycle --------------------------------------------------
    def drop_schema(self, schema: str) -> None:
        with self._lock:
            groups = self._groups.pop(schema, None)
            if groups:
                for grp in groups.values():
                    for sid in grp.subscribers:
                        self._subs.pop(sid, None)
            self._pending.pop(schema, None)
            self._set_gauges()

    def reattach(self, schema: str) -> None:
        """The schema's backing store object was replaced (fleet refresh,
        reload): recompile viewports against the fresh dicts and re-scan
        — results stay exact across the swap."""
        with self._lock:
            groups = self._groups.get(schema)
            if not groups:
                return
            win = self._window_of(schema)
            cols_n = win.columns()
            epoch = win.epoch()
            for grp in groups.values():
                grp.cf = dl.compile_viewport(grp.spec, win.ft, win.dicts)
                self._rescan(win, grp, cols_n, "rescan", epoch)


# -- helpers ---------------------------------------------------------------

def _encode_rows(ft, dicts, rows: List[Dict[str, Any]]):
    """Encode loose attr rows into a ColumnBatch — the exact packing
    LiveFeatureCache.batch() applies, so a delta batch's columns are
    byte-compatible with the window's."""
    if not rows:
        return None
    from geomesa_tpu.schema.columns import encode_batch

    data: Dict[str, Any] = {}
    for a in ft.attributes:
        if a.is_geom and a.is_point:
            xs, ys = [], []
            for r in rows:
                v = r.get(a.name)
                if v is None:
                    xs.append(np.nan)
                    ys.append(np.nan)
                else:
                    xs.append(float(v[0]))
                    ys.append(float(v[1]))
            data[a.name + "__x"] = np.array(xs)
            data[a.name + "__y"] = np.array(ys)
        else:
            data[a.name] = [r.get(a.name) for r in rows]
    return encode_batch(ft, data, dicts, None)


def _bounds_of(ft, rows: List[Dict[str, Any]]):
    """BBox of removed rows' point geometries — the dirty extent a
    non-additive mutation is scoped to. None when no finite geometry
    (conservative: dirties everything)."""
    g = ft.geom_field
    if g is None:
        return None
    xs, ys = [], []
    for r in rows:
        v = r.get(g)
        if v is None:
            continue
        try:
            xs.append(float(v[0]))
            ys.append(float(v[1]))
        except (TypeError, ValueError, IndexError):
            return None
    if not xs:
        return None
    return (min(xs), min(ys), max(xs), max(ys))
