"""Standing queries: fleet-wide continuous viewports over moving objects
(docs/STANDING.md; PAPERS.md 1411.3212 — index the standing queries,
stream the points through them)."""

from geomesa_tpu.subscribe.engine import (  # noqa: F401
    LiveWindow, StandingGroup, StandingQueryEngine, StoreWindow,
    UnknownSubscription, route_key_of,
)
from geomesa_tpu.subscribe.spec import (  # noqa: F401
    AGGREGATES, StandingSpec, make_spec,
)
