"""Notebook map display — geomesa-jupyter Leaflet parity
(reference geomesa-jupyter/.../Leaflet.scala: render query results /
density grids on a Leaflet map inside a notebook cell)."""

from __future__ import annotations

import json
from typing import Optional, Sequence

import numpy as np

_PAGE = """<!DOCTYPE html>
<html><head>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>#map{{height:{height}px;}}</style>
</head><body><div id="map"></div>
<script>
var map = L.map('map');
L.tileLayer('https://{{s}}.tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
  {{attribution: '&copy; OpenStreetMap contributors'}}).addTo(map);
{layers}
</script></body></html>"""


def _fc_layer(geojson_text: str) -> str:
    return (
        f"var gj = L.geoJSON({geojson_text});\n"
        "gj.addTo(map);\nmap.fitBounds(gj.getBounds());\n"
    )


def _density_layer(grid: np.ndarray, bbox) -> str:
    xmin, ymin, xmax, ymax = bbox
    h, w = grid.shape
    top = float(grid.max()) or 1.0
    rects = []
    ys, xs = np.nonzero(grid)
    for r, c in zip(ys.tolist(), xs.tolist()):
        a = float(grid[r, c]) / top
        x0 = xmin + c * (xmax - xmin) / w
        y0 = ymin + r * (ymax - ymin) / h
        x1 = xmin + (c + 1) * (xmax - xmin) / w
        y1 = ymin + (r + 1) * (ymax - ymin) / h
        rects.append(
            f"L.rectangle([[{y0:.6f},{x0:.6f}],[{y1:.6f},{x1:.6f}]],"
            f"{{stroke:false,fillOpacity:{min(0.85, 0.15 + 0.7 * a):.2f},"
            f"fillColor:'#d7301f'}}).addTo(map);"
        )
    fit = f"map.fitBounds([[{ymin},{xmin}],[{ymax},{xmax}]]);"
    return "\n".join(rects + [fit])


def render_features(dataset, name: str, query="INCLUDE",
                    height: int = 500) -> str:
    """Query -> standalone Leaflet HTML (display with IPython.display.HTML
    or write to a file)."""
    fc = dataset.query(name, query)
    st = dataset._store(name)
    from geomesa_tpu.io import geojson

    return _PAGE.format(
        height=height, layers=_fc_layer(geojson.dumps(st.ft, fc.batch, st.dicts))
    )


def render_density(dataset, name: str, query="INCLUDE", bbox=None,
                   width: int = 128, height_cells: int = 128,
                   height: int = 500) -> str:
    """Density heatmap -> standalone Leaflet HTML."""
    if bbox is None:
        bbox = dataset.bounds(name) or (-180, -90, 180, 90)
    grid = dataset.density(
        name, query, bbox=bbox, width=width, height=height_cells
    )
    return _PAGE.format(height=height, layers=_density_layer(grid, bbox))


def show(html: str):
    """Display in a notebook (no-op fallback outside IPython)."""
    try:
        from IPython.display import HTML, display  # type: ignore

        display(HTML(html))
    except Exception:
        return html
