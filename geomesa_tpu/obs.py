"""Live observability surface (docs/OBSERVABILITY.md).

A stdlib ``ThreadingHTTPServer`` exposing the process's operational state —
the Dropwizard-reporter role of the reference's geomesa-metrics module
(SURVEY.md §2.8), plus the ``_queries`` audit table as a debug endpoint:

    GET /metrics        prometheus text exposition (counters, gauges,
                        timers WITH latency histogram buckets, the
                        trace.<stage> span histograms, per-site
                        kernel.recompiles.* and the recompile alert gauge)
    GET /healthz        JSON health: circuit-breaker states
                        (resilience.py), quarantine counters (stream
                        poison messages, corrupt partitions), accelerator
                        reachability — 200 when healthy, 503 when any
                        breaker is open
    GET /debug/queries  JSON: recent query audit events, the degradation
                        trail, and slow-query span trees
                        (?n= bounds each list, default 50)

``web.py`` mounts the same three routes on the REST server, so a process
already serving the API needs no second port; :func:`serve` runs a
standalone endpoint (e.g. next to the Flight sidecar, which has no HTTP
listener of its own).

Payload builders are plain functions so both servers — and tests — share
one implementation.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from geomesa_tpu import metrics, resilience, tracing


def metrics_text() -> str:
    """The /metrics payload: prometheus text exposition."""
    return metrics.registry().prometheus()


# -- device reachability -----------------------------------------------------
# jax.devices() can BLOCK indefinitely on a wedged device claim (the bench
# probes it in a throwaway subprocess for the same reason), so the health
# probe runs it on a daemon thread with a short join and caches the answer.

_device_lock = threading.Lock()
_device_state: Dict[str, Any] = {"status": "unknown", "checked_at": 0.0}
_DEVICE_TTL_S = 60.0


def _probe_devices(timeout_s: float = 2.0) -> Dict[str, Any]:
    out: Dict[str, Any] = {}

    def probe():
        try:
            import jax

            out["devices"] = [str(d) for d in jax.devices()]
            out["status"] = "ok"
        except Exception as e:  # unreachable backend / import failure
            out["status"] = "unreachable"
            out["error"] = repr(e)[:200]

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return {"status": "unreachable",
                "error": f"device probe hung > {timeout_s}s (wedged claim?)"}
    return out


def device_health() -> Dict[str, Any]:
    """Cached accelerator reachability (TTL so /healthz polling never
    hammers — or re-hangs on — the PJRT client)."""
    with _device_lock:
        if time.monotonic() - _device_state.get("checked_at", 0.0) < _DEVICE_TTL_S \
                and _device_state.get("status") != "unknown":
            return {k: v for k, v in _device_state.items() if k != "checked_at"}
    probed = _probe_devices()
    with _device_lock:
        _device_state.clear()
        _device_state.update(probed)
        _device_state["checked_at"] = time.monotonic()
    return probed


def _fs_quarantine() -> Dict[str, Dict[str, str]]:
    """Per-instance FileSystemStorage quarantine maps (root -> file ->
    first failure), beyond the aggregate counters: the operator sees WHICH
    files are quarantined, not just how many. Imported lazily — the fs
    module needs pyarrow, and /healthz must work without it."""
    import sys

    mod = sys.modules.get("geomesa_tpu.fs.storage")
    if mod is None:
        return {}
    try:
        return mod.quarantine_snapshot()
    except Exception:  # pragma: no cover — defensive
        return {}


def health() -> Dict[str, Any]:
    """The /healthz payload. ``status`` is ``ok`` unless a circuit breaker
    is open (``degraded``); quarantine counters (plus the per-instance
    fs-storage quarantine maps) and device reachability ride along for the
    operator's first glance."""
    breakers = resilience.breaker_states()
    report = metrics.registry().report()
    quarantine = {
        name: v for name, v in report.items()
        if "quarantin" in name and isinstance(v, (int, float)) and v
    }
    open_breakers = [n for n, s in breakers.items() if s == "open"]
    return {
        "status": "degraded" if open_breakers else "ok",
        "breakers": breakers,
        "open_breakers": open_breakers,
        "quarantine": quarantine,
        "fs_quarantine": _fs_quarantine(),
        "device": device_health(),
        "tracing": tracing.enabled(),
    }


def debug_queries(dataset=None, n: int = 50) -> Dict[str, Any]:
    """The /debug/queries payload: recent audits + degradations + slow
    traces + per-user serving rollups. ``dataset`` optional — the
    degradation trail and slow traces are process-wide; audit events and
    the user rollup need the dataset (the rollup reads the serving
    scheduler's ledger, the SAME accounting fair-share runs on —
    docs/SERVING.md)."""
    from geomesa_tpu import audit as audit_mod

    events = []
    users: Dict[str, Any] = {}
    serving: Dict[str, Any] = {}
    if dataset is not None:
        events = [json.loads(e.to_json()) for e in dataset.audit.recent(n)]
        sched = getattr(dataset, "serving", None)
        if sched is not None:
            users = sched.user_rollups()
            serving = sched.snapshot()
    degraded = [
        json.loads(e.to_json()) for e in audit_mod.degradations.recent(n)
    ]
    return {
        "queries": events,
        "degradations": degraded,
        "slow_traces": tracing.slow_traces(n),
        "users": users,
        "serving": serving,
    }


def handle(path: str, dataset=None):
    """Route one GET path to (status, content_type, body-bytes), or None
    when the path is not an observability route (web.py falls through to
    its own API routing)."""
    parsed = urllib.parse.urlparse(path)
    q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
    route = parsed.path.rstrip("/") or "/"
    if route == "/metrics":
        return 200, "text/plain; version=0.0.4", metrics_text().encode()
    if route == "/healthz":
        h = health()
        code = 200 if h["status"] == "ok" else 503
        return code, "application/json", json.dumps(h).encode()
    if route == "/debug/queries":
        try:
            n = max(1, min(int(q.get("n", "50")), 1000))
        except ValueError:
            return (400, "application/json",
                    json.dumps({"error": "?n= must be an integer"}).encode())
        body = json.dumps(debug_queries(dataset, n), default=str).encode()
        return 200, "application/json", body
    return None


class _ObsHandler(BaseHTTPRequestHandler):
    dataset = None  # injected by serve()

    def log_message(self, fmt, *args):  # noqa: D102 — quiet stderr
        pass

    def do_GET(self):  # noqa: N802
        try:
            out = handle(self.path, self.dataset)
        except Exception as e:  # pragma: no cover - defensive
            out = (500, "application/json",
                   json.dumps({"error": f"{type(e).__name__}: {e}"}).encode())
        if out is None:
            out = (404, "application/json",
                   json.dumps({"error": f"unknown path {self.path!r}"}).encode())
        code, ctype, body = out
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def serve(dataset=None, host: str = "127.0.0.1", port: int = 9090,
          background: bool = False) -> ThreadingHTTPServer:
    """Serve /metrics + /healthz + /debug/queries. ``background=True``
    runs in a daemon thread and returns the server (tests / embedding
    next to a Flight sidecar)."""
    handler = type("ObsHandler", (_ObsHandler,), {"dataset": dataset})
    server = ThreadingHTTPServer((host, port), handler)
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return server
