"""Live observability surface (docs/OBSERVABILITY.md).

A stdlib ``ThreadingHTTPServer`` exposing the process's operational state —
the Dropwizard-reporter role of the reference's geomesa-metrics module
(SURVEY.md §2.8), plus the ``_queries`` audit table as a debug endpoint:

    GET /metrics        prometheus text exposition (counters, gauges,
                        timers WITH latency histogram buckets, the
                        trace.<stage> span histograms, per-site
                        kernel.recompiles.* and the recompile alert gauge)
    GET /healthz        JSON health: circuit-breaker states
                        (resilience.py), quarantine counters (stream
                        poison messages, corrupt partitions), accelerator
                        reachability — 200 when healthy, 503 when any
                        breaker is open
    GET /debug/queries  JSON: recent query audit events, the degradation
                        trail, and slow-query span trees
                        (?n= bounds each list, default 50; ?user= and
                        ?op= filter events/rollups/slow traces)
    GET /debug/devices  JSON: per-device busy fractions + totals, the
                        per-device HEALTH map (ok/cordoned/broken,
                        reassignment counts, last failure —
                        parallel/health.py, docs/RESILIENCE.md §6),
                        serving slot occupancy + the pool supervision
                        digest, the queue-wait vs device-time breakdown,
                        and the SLO burn summary (utilization.py, slo.py)
    GET /debug/fleet    JSON: every live fleet router's ring membership,
                        per-replica health + breaker states, fleet
                        epochs, routing counters, and the anomaly-
                        watchdog advice row (fleet/router.py,
                        docs/RESILIENCE.md §7)
    GET /metrics/fleet  fleet-level prometheus exposition merged from
                        every replica's metrics-export snapshot —
                        counters summed, histograms merged bucket-wise,
                        gauges labeled per replica (fleet/obs.py,
                        docs/OBSERVABILITY.md §9); 404 when this process
                        runs no fleet router
    GET /healthz/fleet  fleet-composed health: hard (503) only when no
                        usable replica remains or the fleet SLO burns;
                        survivable defects degrade soft (200)
    GET /debug/heat     JSON: per-(schema, SFC cell) access heat — this
                        process's table, plus the fleet-merged table
                        (with per-replica touch splits) per live router

``/debug/queries?trace=<id>`` is an exact-match lookup: the full span
tree behind one trace id — the fleet-STITCHED tree (router spans +
per-replica subtrees) when a live router stitched it, else the local
retained trace.

``web.py`` mounts the same routes on the REST server, so a process
already serving the API needs no second port; :func:`serve` runs a
standalone endpoint (e.g. next to the Flight sidecar, which has no HTTP
listener of its own).

Payload builders are plain functions so both servers — and tests — share
one implementation.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from geomesa_tpu import metrics, resilience, tracing


#: OpenMetrics content type served when the scraper negotiates it
OPENMETRICS_CTYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def metrics_text(openmetrics: bool = False) -> str:
    """The /metrics payload. Classic prometheus text by default;
    ``openmetrics`` renders the OpenMetrics exposition instead —
    exemplars on histogram buckets plus the required ``# EOF`` trailer.
    Exemplars are ONLY legal there: a classic-format scrape with a ``#``
    suffix would fail entirely, so the format is chosen by Accept-header
    negotiation in :func:`handle`."""
    text = metrics.registry().prometheus(exemplars=openmetrics)
    return text + "# EOF\n" if openmetrics else text


# -- device reachability -----------------------------------------------------
# jax.devices() can BLOCK indefinitely on a wedged device claim (the bench
# probes it in a throwaway subprocess for the same reason), so the health
# probe runs it on a daemon thread with a short join and caches the answer.

_device_lock = threading.Lock()
_device_state: Dict[str, Any] = {"status": "unknown", "checked_at": 0.0}
_DEVICE_TTL_S = 60.0


def _probe_devices(timeout_s: float = 2.0) -> Dict[str, Any]:
    out: Dict[str, Any] = {}

    def probe():
        try:
            import jax

            out["devices"] = [str(d) for d in jax.devices()]
            out["status"] = "ok"
        except Exception as e:  # unreachable backend / import failure
            out["status"] = "unreachable"
            out["error"] = repr(e)[:200]

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return {"status": "unreachable",
                "error": f"device probe hung > {timeout_s}s (wedged claim?)"}
    return out


def device_health() -> Dict[str, Any]:
    """Cached accelerator reachability (TTL so /healthz polling never
    hammers — or re-hangs on — the PJRT client)."""
    with _device_lock:
        if time.monotonic() - _device_state.get("checked_at", 0.0) < _DEVICE_TTL_S \
                and _device_state.get("status") != "unknown":
            return {k: v for k, v in _device_state.items() if k != "checked_at"}
    probed = _probe_devices()
    with _device_lock:
        _device_state.clear()
        _device_state.update(probed)
        _device_state["checked_at"] = time.monotonic()
    return probed


def _fs_quarantine() -> Dict[str, Dict[str, str]]:
    """Per-instance FileSystemStorage quarantine maps (root -> file ->
    first failure), beyond the aggregate counters: the operator sees WHICH
    files are quarantined, not just how many. Imported lazily — the fs
    module needs pyarrow, and /healthz must work without it."""
    import sys

    mod = sys.modules.get("geomesa_tpu.fs.storage")
    if mod is None:
        return {}
    try:
        return mod.quarantine_snapshot()
    except Exception:  # pragma: no cover — defensive
        return {}


def _journal_lag() -> Dict[str, int]:
    """Per-root pending (appended, not yet fsynced) journal frames —
    nonzero sustained means the group committer is behind its writers
    (docs/RESILIENCE.md §8). Imported lazily like the fs quarantine map:
    /healthz must work in a process that never touched a journal."""
    import sys

    mod = sys.modules.get("geomesa_tpu.fs.journal")
    if mod is None:
        return {}
    try:
        return mod.lag_snapshot()
    except Exception:  # pragma: no cover — defensive
        return {}


def health() -> Dict[str, Any]:
    """The /healthz payload. ``status`` is ``ok`` unless a circuit breaker
    is open, an SLO's fast window burns past geomesa.slo.burn.threshold,
    or a mesh device is cordoned/broken (``degraded``). Device-level
    degradation is SOFT while capacity remains — one cordoned device of
    eight means "look at me", not "stop sending traffic" — so the HTTP
    code stays 200 (``soft: true``); an open non-device breaker, a
    burning SLO, or a mesh with NO usable device is hard (503). Quarantine
    counters (plus the per-instance fs-storage quarantine maps) and
    device reachability ride along for the operator's first glance."""
    from geomesa_tpu import slo
    from geomesa_tpu.parallel import health as phealth

    # breaker-open transitions ride the SLO alert surface too: this call
    # keeps the slo.breaker.<name> gauges registered for every breaker the
    # process has ever named (docs/OBSERVABILITY.md, RESILIENCE follow-up)
    breakers = slo.sync_breaker_gauges()
    report = metrics.registry().report()
    quarantine = {
        name: v for name, v in report.items()
        if "quarantin" in name and isinstance(v, (int, float)) and v
    }
    open_breakers = [n for n, s in breakers.items() if s == "open"]
    # device:* breakers degrade softly (capacity permitting) — the mesh
    # summary below carries them; everything else fencing open is hard
    hard_breakers = [n for n in open_breakers
                     if not n.startswith("device:")]
    slo_status = slo.monitor().status()
    slo_hot = {op: s for op, s in slo_status.items() if s["hot"]}
    dev = device_health()
    total_devices = len(dev.get("devices") or ())
    mesh = phealth.registry().summary(total_devices)
    mesh_degraded = bool(mesh["cordoned"] or mesh["broken"])
    no_capacity = total_devices > 0 and mesh["usable"] <= 0
    hard = bool(hard_breakers or slo_hot or no_capacity)
    degraded = hard or mesh_degraded or bool(open_breakers)
    out = {
        "status": "degraded" if degraded else "ok",
        "soft": bool(degraded and not hard),
        "breakers": breakers,
        "open_breakers": open_breakers,
        "quarantine": quarantine,
        "fs_quarantine": _fs_quarantine(),
        "journal": _journal_lag(),
        "device": dev,
        "mesh": mesh,
        "tracing": tracing.enabled(),
    }
    if open_breakers:
        # soft-degrade note: any open breaker marks the payload even when
        # the HTTP code stays 200 (device breakers with capacity left) —
        # the same transition the slo.breaker.<name> gauges page on
        out["breaker_note"] = (
            "breaker open: " + ", ".join(sorted(open_breakers))
            + " — see slo.breaker.* gauges"
        )
    if slo_status:
        out["slo"] = slo_status
        if slo_hot:
            out["slo_burning"] = sorted(slo_hot)
    return out


def debug_queries(dataset=None, n: int = 50, user: Optional[str] = None,
                  op: Optional[str] = None) -> Dict[str, Any]:
    """The /debug/queries payload: recent audits + degradations + slow
    traces + per-user serving rollups. ``dataset`` optional — the
    degradation trail and slow traces are process-wide; audit events and
    the user rollup need the dataset (the rollup reads the serving
    scheduler's ledger, the SAME accounting fair-share runs on —
    docs/SERVING.md). ``user``/``op`` filter events, rollups, and slow
    traces (filters apply BEFORE the ``n`` cap, so "the last 5 of user
    X's density queries" means what it says)."""
    from geomesa_tpu import audit as audit_mod

    events = []
    users: Dict[str, Any] = {}
    serving: Dict[str, Any] = {}
    user_tids = None
    if dataset is not None:
        # pull a deeper window when filtering, so the filter selects from
        # history rather than from an already-capped tail
        raw = dataset.audit.recent(n if user is None and op is None
                                   else 10_000)
        events = [json.loads(e.to_json()) for e in raw]
        if user is not None:
            events = [e for e in events if e.get("user") == user]
            # slow traces carry no user — join through the trace_id the
            # audit event and the trace share, so a filtered view never
            # leaks another tenant's slow query trees
            user_tids = {
                e.get("hints", {}).get("trace_id") for e in events
            } - {None}
        if op is not None:
            events = [e for e in events
                      if e.get("hints", {}).get("op") == op]
        events = events[-n:]
        sched = getattr(dataset, "serving", None)
        if sched is not None:
            users = sched.user_rollups()
            if user is not None:
                users = {u: r for u, r in users.items() if u == user}
            serving = sched.snapshot()
    degraded = [
        json.loads(e.to_json()) for e in audit_mod.degradations.recent(n)
    ]
    slow = tracing.slow_traces(
        10_000 if (op is not None or user is not None) else n
    )
    if op is not None:
        # a slow trace's op is its root span's name
        slow = [s for s in slow if s.get("tree", {}).get("name") == op]
    if user is not None:
        slow = [s for s in slow if s.get("trace_id") in (user_tids or ())]
    subscriptions: Dict[str, Any] = {"groups": [], "subscribers": 0}
    eng = getattr(dataset, "standing", None) if dataset is not None else None
    if eng is not None:
        # standing-group residency + versions (docs/STANDING.md): with
        # the stream.epoch.<schema> gauges in /metrics, this is the
        # subscription-staleness view — a group whose epoch trails its
        # schema's gauge has updates it hasn't settled yet
        subscriptions = eng.snapshot()
    return {
        "queries": events,
        "degradations": degraded,
        "slow_traces": slow[-n:],
        "users": users,
        "serving": serving,
        "subscriptions": subscriptions,
    }


def _live_routers() -> list:
    """Live FleetRouter instances in this process (lazily — the fleet
    module needs pyarrow, and these routes must 404 cleanly without
    it)."""
    import sys

    mod = sys.modules.get("geomesa_tpu.fleet.router")
    if mod is None:
        return []
    try:
        return sorted(mod._ROUTERS, key=lambda r: r.name)
    except Exception:  # pragma: no cover — defensive
        return []


def trace_lookup(trace_id: str) -> Optional[Dict[str, Any]]:
    """The /debug/queries?trace=<id> payload: the STITCHED fleet tree
    when a live router assembled one for the id (replica subtrees
    grafted under the router spans that called them), else the local
    retained trace. None when the id is unknown everywhere here."""
    for r in _live_routers():
        try:
            rec = r.observability().stitched(trace_id)
        except Exception:  # pragma: no cover — defensive
            continue
        if rec is not None:
            return rec
    return tracing.finished_trace(trace_id)


def debug_heat(top: Optional[int] = None) -> Dict[str, Any]:
    """The /debug/heat payload (docs/OBSERVABILITY.md §9): this
    process's own heat table plus, per live router, the fleet-merged
    table with per-replica touch splits — the autoscaler's input."""
    from geomesa_tpu import heat

    out: Dict[str, Any] = {"local": heat.snapshot(top)}
    fleet: Dict[str, Any] = {}
    for r in _live_routers():
        try:
            fleet[r.name] = r.observability().fleet_heat(top=top)
        except Exception as e:  # pragma: no cover — defensive
            fleet[r.name] = {"error": repr(e)[:200]}
    if fleet:
        out["fleet"] = fleet
    return out


def debug_fleet() -> Dict[str, Any]:
    """The /debug/fleet payload (docs/RESILIENCE.md §7): every live
    router's ring membership, per-replica health (state, breaker,
    failure/failover counts), fleet epochs, routing counters, and the
    router's serving ledger rollups. Empty ``routers`` when this process
    runs no router. Imported lazily — the fleet module needs pyarrow."""
    import sys

    mod = sys.modules.get("geomesa_tpu.fleet.router")
    if mod is None:
        return {"routers": []}
    try:
        return mod.debug_fleet()
    except Exception:  # pragma: no cover — defensive
        return {"routers": []}


def debug_devices(dataset=None) -> Dict[str, Any]:
    """The /debug/devices payload: per-device utilization, pool slot
    occupancy, the queue-wait vs device-time breakdown, the SLO burn
    summary (docs/OBSERVABILITY.md), and — docs/RESILIENCE.md §6 — the
    per-device HEALTH map (ok/cordoned/broken, breaker state, failure +
    reassignment counts, last failure) plus the serving pool's
    supervision digest (width, respawns) when a dataset is mounted."""
    from geomesa_tpu import slo, utilization
    from geomesa_tpu.parallel import health as phealth

    out = utilization.snapshot()
    out["slo"] = slo.monitor().status()
    out["health"] = phealth.registry().snapshot()
    if dataset is not None:
        sched = getattr(dataset, "serving", None)
        if sched is not None:
            out["pool"] = sched.snapshot()
    return out


def handle(path: str, dataset=None, accept: Optional[str] = None):
    """Route one GET path to (status, content_type, body-bytes), or None
    when the path is not an observability route (web.py falls through to
    its own API routing). ``accept`` is the request's Accept header:
    a scraper negotiating ``application/openmetrics-text`` gets the
    OpenMetrics exposition (with exemplars) from /metrics; everyone else
    gets the classic exemplar-free text format."""
    parsed = urllib.parse.urlparse(path)
    q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
    route = parsed.path.rstrip("/") or "/"
    if route == "/metrics":
        if accept and "application/openmetrics-text" in accept:
            return (200, OPENMETRICS_CTYPE,
                    metrics_text(openmetrics=True).encode())
        return 200, "text/plain; version=0.0.4", metrics_text().encode()
    if route == "/metrics/fleet":
        routers = _live_routers()
        if not routers:
            return (404, "application/json", json.dumps(
                {"error": "no live fleet router in this process"}
            ).encode())
        om = bool(accept and "application/openmetrics-text" in accept)
        text = routers[0].observability().fleet_metrics_text(openmetrics=om)
        if om:
            return 200, OPENMETRICS_CTYPE, (text + "# EOF\n").encode()
        return 200, "text/plain; version=0.0.4", text.encode()
    if route == "/healthz/fleet":
        routers = _live_routers()
        if not routers:
            return (404, "application/json", json.dumps(
                {"error": "no live fleet router in this process"}
            ).encode())
        h = routers[0].observability().fleet_health()
        code = 200 if h["status"] == "ok" or h.get("soft") else 503
        return code, "application/json", json.dumps(h, default=str).encode()
    if route == "/debug/heat":
        try:
            top = max(1, min(int(q["top"]), 10_000)) if "top" in q else None
        except ValueError:
            return (400, "application/json",
                    json.dumps({"error": "?top= must be an integer"}
                               ).encode())
        return (200, "application/json",
                json.dumps(debug_heat(top), default=str).encode())
    if route == "/healthz":
        h = health()
        # soft (device-cordon with capacity standing) degrades the STATUS
        # but keeps 200: load balancers must not eject a node that is
        # merely running narrower (docs/RESILIENCE.md §6)
        code = 200 if h["status"] == "ok" or h.get("soft") else 503
        return code, "application/json", json.dumps(h).encode()
    if route == "/debug/queries":
        if "trace" in q:
            # exact-match span-tree lookup (stitched when fleet)
            rec = trace_lookup(q["trace"])
            if rec is None:
                return (404, "application/json", json.dumps(
                    {"error": f"trace {q['trace']!r} not retained here"}
                ).encode())
            return (200, "application/json",
                    json.dumps(rec, default=str).encode())
        try:
            n = max(1, min(int(q.get("n", "50")), 10_000))
        except ValueError:
            return (400, "application/json",
                    json.dumps({"error": "?n= must be an integer"}).encode())
        body = json.dumps(
            debug_queries(dataset, n, user=q.get("user"), op=q.get("op")),
            default=str,
        ).encode()
        return 200, "application/json", body
    if route == "/debug/devices":
        return (200, "application/json",
                json.dumps(debug_devices(dataset), default=str).encode())
    if route == "/debug/fleet":
        return (200, "application/json",
                json.dumps(debug_fleet(), default=str).encode())
    return None


class _ObsHandler(BaseHTTPRequestHandler):
    dataset = None  # injected by serve()

    def log_message(self, fmt, *args):  # noqa: D102 — quiet stderr
        pass

    def do_GET(self):  # noqa: N802
        try:
            out = handle(self.path, self.dataset,
                         accept=self.headers.get("Accept"))
        except Exception as e:  # pragma: no cover - defensive
            out = (500, "application/json",
                   json.dumps({"error": f"{type(e).__name__}: {e}"}).encode())
        if out is None:
            out = (404, "application/json",
                   json.dumps({"error": f"unknown path {self.path!r}"}).encode())
        code, ctype, body = out
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def serve(dataset=None, host: str = "127.0.0.1", port: int = 9090,
          background: bool = False) -> ThreadingHTTPServer:
    """Serve /metrics + /healthz + /debug/queries. ``background=True``
    runs in a daemon thread and returns the server (tests / embedding
    next to a Flight sidecar)."""
    handler = type("ObsHandler", (_ObsHandler,), {"dataset": dataset})
    server = ThreadingHTTPServer((host, port), handler)
    if background:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        return server
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return server
