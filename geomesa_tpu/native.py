"""ctypes loader for the host-side native runtime (native/geomesa_native.cpp).

The TPU compute path is JAX/XLA; this module accelerates the *host* runtime
around it — morton interleave at ingest, z-range cover at plan time, Java
string hashing for BIN export, and searchsorted window resolution. Every
function has a NumPy fallback (used when the library is absent or when
``GEOMESA_NATIVE=0``), so behavior is identical either way; parity is
enforced by tests/test_native.py.

The shared library is built lazily with ``g++ -O3 -shared`` the first time it
is needed (single attempt, guarded by a marker to avoid repeated failures).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(_HERE), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libgeomesa_native.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "geomesa_native.cpp")

_lock = threading.Lock()
_lib: "Optional[ctypes.CDLL]" = None
_tried = False

_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _build() -> bool:
    """Compile the shared library in-place. Returns success."""
    if not os.path.exists(_SRC_PATH):
        return False
    # build to a temp name and rename: concurrent first-callers (sidecar +
    # CLI, pytest workers) must never dlopen a half-written .so
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    base = ["g++", "-O3", "-fPIC", "-std=c++17", "-shared", "-o", tmp, _SRC_PATH]
    for cmd in (base[:1] + ["-fopenmp"] + base[1:], base):  # openmp optional
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _SO_PATH)
            return True
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c64, c32 = ctypes.c_int64, ctypes.c_int32
    cu64 = ctypes.c_uint64
    lib.gm_abi_version.restype = c32
    lib.gm_interleave2.argtypes = [_u64p, _u64p, _u64p, c64]
    lib.gm_deinterleave2.argtypes = [_u64p, _u64p, _u64p, c64]
    lib.gm_interleave3.argtypes = [_u64p, _u64p, _u64p, _u64p, c64]
    lib.gm_deinterleave3.argtypes = [_u64p, _u64p, _u64p, _u64p, c64]
    lib.gm_zcover.argtypes = [_u64p, _u64p, c32, c32, c64, _u64p, _u64p, c64]
    lib.gm_zcover.restype = c64
    lib.gm_java_hash_utf16.argtypes = [_u16p, _i64p, c64, _i32p]
    lib.gm_windows_u64.argtypes = [_u64p, c64, _u64p, _u64p, c64, _i64p, _i64p]
    lib.gm_bin_windows.argtypes = [
        _i32p, _u64p, c64, _i32p, c64, cu64, cu64, _i64p, _i64p,
    ]
    lib.gm_bin_windows.restype = c64
    lib.gm_z2_encode.argtypes = [_f64p, _f64p, c64, _u64p]
    lib.gm_z3_encode.argtypes = [_f64p, _f64p, _i64p, ctypes.c_double, c64, _u64p]
    lib.gm_fid_hash64.argtypes = [_u8p, c64, c64, _u64p]
    lib.gm_time_split.argtypes = [
        _i64p, c64, c64, c32,
        _i32p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.gm_pack_idx.argtypes = [
        _u64p, c64, c32, c32, c32, ctypes.c_void_p,
        ctypes.c_void_p, c32, c64, _u64p,
    ]
    lib.gm_unpack_idx.argtypes = [
        _u64p, c64, c32, c32, c32, c32, c64,
        ctypes.c_void_p, ctypes.c_void_p, _u64p, ctypes.c_void_p,
    ]
    lib.gm_off_from_bin.argtypes = [_i64p, _i32p, c64, c64, _i64p]
    lib.gm_sort_u64.argtypes = [_u64p, c64]
    _u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    lib.gm_u32_to_s.argtypes = [_u32p, _u8p, c64]
    lib.gm_u32_to_s.restype = c32
    lib.gm_s_to_u32.argtypes = [_u8p, _u32p, c64]
    lib.gm_s_to_u32.restype = c32
    lib.gm_num_threads.restype = c32
    return lib


def lib() -> "Optional[ctypes.CDLL]":
    """The loaded library, or None (disabled / unbuildable)."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried or os.environ.get("GEOMESA_NATIVE", "1") == "0":
        return _lib
    with _lock:
        if _tried or _lib is not None:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) or (
            os.path.exists(_SRC_PATH)
            and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_SO_PATH)
        ):
            if not _build():
                return None
        try:
            candidate = ctypes.CDLL(_SO_PATH)
            if candidate.gm_abi_version() != 4:
                # stale .so from an older source tree: rebuild once
                if _build():
                    candidate = ctypes.CDLL(_SO_PATH)
            if candidate.gm_abi_version() == 4:
                _lib = _bind(candidate)
        except (OSError, AttributeError):
            _lib = None
    return _lib


def available() -> bool:
    return lib() is not None


# ---------------------------------------------------------------------------
# Wrappers (native when available, identical NumPy fallback otherwise)
# ---------------------------------------------------------------------------

def interleave2(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    L = lib()
    x = np.ascontiguousarray(x, np.uint64)
    y = np.ascontiguousarray(y, np.uint64)
    if L is None:
        from geomesa_tpu.curves import zorder

        return zorder._interleave2_np(x, y)
    out = np.empty(len(x), np.uint64)
    L.gm_interleave2(x, y, out, len(x))
    return out


def deinterleave2(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    L = lib()
    z = np.ascontiguousarray(z, np.uint64)
    if L is None:
        from geomesa_tpu.curves import zorder

        return zorder._deinterleave2_np(z)
    x = np.empty(len(z), np.uint64)
    y = np.empty(len(z), np.uint64)
    L.gm_deinterleave2(z, x, y, len(z))
    return x, y


def interleave3(x: np.ndarray, y: np.ndarray, t: np.ndarray) -> np.ndarray:
    L = lib()
    x = np.ascontiguousarray(x, np.uint64)
    y = np.ascontiguousarray(y, np.uint64)
    t = np.ascontiguousarray(t, np.uint64)
    if L is None:
        from geomesa_tpu.curves import zorder

        return zorder._interleave3_np(x, y, t)
    out = np.empty(len(x), np.uint64)
    L.gm_interleave3(x, y, t, out, len(x))
    return out


def deinterleave3(z: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    L = lib()
    z = np.ascontiguousarray(z, np.uint64)
    if L is None:
        from geomesa_tpu.curves import zorder

        return zorder._deinterleave3_np(z)
    x = np.empty(len(z), np.uint64)
    y = np.empty(len(z), np.uint64)
    t = np.empty(len(z), np.uint64)
    L.gm_deinterleave3(z, x, y, t, len(z))
    return x, y, t


def zcover(
    lo: Sequence[int], hi: Sequence[int], bits: int, dims: int,
    max_ranges: int = 2000,
):
    """Native z-range cover; returns List[ZRange]. Falls back to Python."""
    from geomesa_tpu.curves.cover import ZRange, zcover as py_zcover

    L = lib()
    if L is None:
        return py_zcover(lo, hi, bits, dims, max_ranges)
    qlo = np.ascontiguousarray(list(lo), np.uint64)
    qhi = np.ascontiguousarray(list(hi), np.uint64)
    cap = max_ranges + 16
    out_lo = np.empty(cap, np.uint64)
    out_hi = np.empty(cap, np.uint64)
    n = L.gm_zcover(qlo, qhi, bits, dims, max_ranges, out_lo, out_hi, cap)
    if n < 0:
        # invalid args (-2: Python raises the descriptive error) or
        # capacity overflow (-1): resolve through the fallback either way
        return py_zcover(lo, hi, bits, dims, max_ranges)
    return [ZRange(int(out_lo[i]), int(out_hi[i])) for i in range(n)]


def java_hash(values: Sequence[str]) -> np.ndarray:
    """Java String.hashCode for a batch of strings (int32)."""
    L = lib()
    if L is None:
        from geomesa_tpu.io.bin_format import java_string_hash

        return np.array([java_string_hash(str(v)) for v in values], np.int32)
    units_parts: List[np.ndarray] = []
    offsets = np.zeros(len(values) + 1, np.int64)
    for i, v in enumerate(values):
        b = str(v).encode("utf-16-be", "surrogatepass")
        u = np.frombuffer(b, dtype=">u2").astype(np.uint16)
        units_parts.append(u)
        offsets[i + 1] = offsets[i] + len(u)
    units = (
        np.concatenate(units_parts) if units_parts else np.zeros(0, np.uint16)
    )
    units = np.ascontiguousarray(units)
    out = np.empty(len(values), np.int32)
    L.gm_java_hash_utf16(units, offsets, len(values), out)
    return out


def windows_u64(
    keys: np.ndarray, lo: np.ndarray, hi: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched [lo, hi] -> (start, end) windows over one sorted u64 column."""
    keys = np.ascontiguousarray(keys, np.uint64)
    lo = np.ascontiguousarray(lo, np.uint64)
    hi = np.ascontiguousarray(hi, np.uint64)
    L = lib()
    if L is None:
        return (
            np.searchsorted(keys, lo, side="left").astype(np.int64),
            np.searchsorted(keys, hi, side="right").astype(np.int64),
        )
    k = len(lo)
    starts = np.empty(k, np.int64)
    ends = np.empty(k, np.int64)
    L.gm_windows_u64(keys, len(keys), lo, hi, k, starts, ends)
    return starts, ends


def bin_windows(
    bins_col: np.ndarray, z_col: np.ndarray, bins: np.ndarray,
    zlo: int, zhi: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-time-bin [zlo, zhi] windows over (bin, z)-sorted columns.

    Returns (starts, ends) of only the non-empty windows. Falls back to the
    NumPy loop when the library is absent."""
    bins_col = np.ascontiguousarray(bins_col, np.int32)
    z_col = np.ascontiguousarray(z_col, np.uint64)
    bins = np.ascontiguousarray(bins, np.int32)
    L = lib()
    if L is None:
        starts, ends = [], []
        for b in bins.tolist():
            s = int(np.searchsorted(bins_col, b, side="left"))
            e = int(np.searchsorted(bins_col, b, side="right"))
            if e <= s:
                continue
            seg = z_col[s:e]
            s2 = s + int(np.searchsorted(seg, np.uint64(zlo), side="left"))
            e2 = s + int(np.searchsorted(seg, np.uint64(zhi), side="right"))
            if e2 > s2:
                starts.append(s2)
                ends.append(e2)
        return np.asarray(starts, np.int64), np.asarray(ends, np.int64)
    n = len(bins)
    starts = np.empty(n, np.int64)
    ends = np.empty(n, np.int64)
    m = L.gm_bin_windows(
        bins_col, z_col, len(bins_col), bins, n,
        np.uint64(zlo), np.uint64(zhi), starts, ends,
    )
    return starts[:m], ends[:m]


def z2_encode(x: np.ndarray, y: np.ndarray) -> Optional[np.ndarray]:
    """Fused normalize+interleave z2 encode; None -> numpy fallback path."""
    L = lib()
    if L is None:
        return None
    x = np.ascontiguousarray(x, np.float64)
    y = np.ascontiguousarray(y, np.float64)
    out = np.empty(len(x), np.uint64)
    L.gm_z2_encode(x, y, len(x), out)
    return out


def z3_encode(
    x: np.ndarray, y: np.ndarray, off_ms: np.ndarray, off_max: float
) -> Optional[np.ndarray]:
    """Fused normalize+interleave z3 encode; None -> numpy fallback path."""
    L = lib()
    if L is None:
        return None
    x = np.ascontiguousarray(x, np.float64)
    y = np.ascontiguousarray(y, np.float64)
    off_ms = np.ascontiguousarray(off_ms, np.int64)
    out = np.empty(len(x), np.uint64)
    L.gm_z3_encode(x, y, off_ms, float(off_max), len(x), out)
    return out


def fid_hash64(a: np.ndarray) -> Optional[np.ndarray]:
    """Single-pass feature-id hash over a U/S string column; None ->
    numpy fallback (packsort.fid_hash64 python path, bit-identical)."""
    L = lib()
    if L is None:
        return None
    a = np.ascontiguousarray(a)
    u8 = a.view(np.uint8)
    out = np.empty(len(a), np.uint64)
    L.gm_fid_hash64(u8, len(a), a.dtype.itemsize, out)
    return out


def off_from_bin(t: np.ndarray, bins: np.ndarray, period_ms: int):
    """offset_ms = t - bin*period fused; None -> numpy fallback path."""
    L = lib()
    if L is None:
        return None
    t = np.ascontiguousarray(t, np.int64)
    bins = np.ascontiguousarray(bins, np.int32)
    out = np.empty(len(t), np.int64)
    L.gm_off_from_bin(t, bins, int(period_ms), len(t), out)
    return out


def time_split(
    t: np.ndarray, period_ms: int, scale: int,
    want_off_ms: bool = True, want_scaled: bool = False,
):
    """epoch_ms -> (bin i32, off_ms i64 | None, off_scaled i32 | None) in one
    native pass; None -> numpy fallback path."""
    L = lib()
    if L is None:
        return None
    t = np.ascontiguousarray(t, np.int64)
    n = len(t)
    b = np.empty(n, np.int32)
    off = np.empty(n, np.int64) if want_off_ms else None
    sc = np.empty(n, np.int32) if want_scaled else None
    L.gm_time_split(
        t, n, int(period_ms), int(scale), b,
        off.ctypes.data if off is not None else None,
        sc.ctypes.data if sc is not None else None,
    )
    return b, off, sc


def u32_to_s(cp: np.ndarray) -> "Optional[np.ndarray]":
    """Fused UCS4->bytes narrowing with ASCII check. ``cp`` is the flat
    uint32 code-point view of a 'U' array; returns the uint8 buffer, or
    None when unavailable / non-ASCII (caller keeps the unicode layout)."""
    L = lib()
    if L is None:
        return None
    cp = np.ascontiguousarray(cp, np.uint32)
    out = np.empty(cp.size, np.uint8)
    if not L.gm_u32_to_s(cp.reshape(-1), out, cp.size):
        return None
    return out


def s_to_u32(by: np.ndarray) -> "Optional[np.ndarray]":
    """Fused bytes->UCS4 widening with ASCII check (export mirror)."""
    L = lib()
    if L is None:
        return None
    by = np.ascontiguousarray(by, np.uint8)
    out = np.empty(by.size, np.uint32)
    if not L.gm_s_to_u32(by.reshape(-1), out, by.size):
        return None
    return out
