"""Time-bin sequence parallelism — the long-context axis of the framework.

The reference scales huge spatio-temporal windows by decomposing intervals
into per-time-bin key ranges (Z3IndexKeySpace.getIndexValues:133-158) and
scanning them with a bounded client fan-out. Here that becomes a second mesh
axis: a 2D mesh ``(shard, bin)`` where the *data* is sharded over ``shard``
(horizontal partitioning) and the *bin-window space* — the query's temporal
extent, the analog of sequence length — is blocked over ``bin``. Each device
computes partial aggregates for its (data-shard x bin-block) tile; merges are
explicit XLA collectives (``psum``) over both axes, riding ICI.

For windows wider than device memory appetite, ``stream_chunks > 1`` streams
bin-blocks through a ``lax.scan`` (double-buffered by XLA), accumulating
partials — "ring over time bins, not tokens" (SURVEY.md §5).

Contract: the aggregate must be additive (count, density grids, histograms,
any sketch merged by ``+``) — both the cross-device psum and the scan
accumulation rely on it. Non-additive reductions (min/max) use the 1-D GSPMD
path in the executor.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np


def mesh_2d(n_shard: int, n_bin: int):
    """A (shard, bin) 2-D device mesh: data parallel x bin-space parallel."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    need = n_shard * n_bin
    if len(devs) < need:
        raise ValueError(f"mesh_2d({n_shard}, {n_bin}) needs {need} devices, have {len(devs)}")
    return Mesh(
        np.array(devs[:need]).reshape(n_shard, n_bin),
        axis_names=("shard", "bin"),
    )


def pad_windows(
    starts: np.ndarray, ends: np.ndarray, multiple: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad the window axis to a multiple (padded windows are empty (0, 0))."""
    K = starts.shape[1]
    Kp = ((K + multiple - 1) // multiple) * multiple
    if Kp == K:
        return starts, ends
    pad = ((0, 0), (0, Kp - K))
    return (
        np.pad(starts, pad),
        np.pad(ends, pad),
    )


def build_bin_parallel(
    mesh,
    col_names,
    L: int,
    predicate: Callable,
    agg_fn: Callable,
    stream_chunks: int = 1,
):
    """Build the jitted (shard, bin) shard_map kernel.

    Returned callable takes ``(dev_cols, starts, ends, counts)`` already
    placed with :func:`placements` shardings. Separate from
    :func:`bin_parallel_run` so callers (the executor) can cache the
    compiled kernel across queries.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.kernels import masks as kmasks

    col_spec = P("shard", None)
    win_spec = P("shard", "bin")

    def body(cols, starts, ends, counts):
        if stream_chunks == 1:
            m = kmasks.window_mask(starts, ends, counts, L)
            m = m & predicate(cols, jnp)
            part = agg_fn(cols, m, jnp)
        else:
            # sequence streaming: scan over bin-window chunks; each step's
            # windows are a slice of the local bin block
            k_loc = starts.shape[1]
            chunk = k_loc // stream_chunks

            def step(acc, i):
                s = jax.lax.dynamic_slice_in_dim(starts, i * chunk, chunk, 1)
                e = jax.lax.dynamic_slice_in_dim(ends, i * chunk, chunk, 1)
                m = kmasks.window_mask(s, e, counts, L)
                m = m & predicate(cols, jnp)
                p = agg_fn(cols, m, jnp)
                return jax.tree.map(jnp.add, acc, p), None

            shapes = jax.eval_shape(
                lambda c: agg_fn(c, jnp.zeros((c[next(iter(c))].shape[0], L), bool), jnp),
                cols,
            )
            # newer jax types shard_map carries as varying/manual; the
            # pcast marks the zeros accordingly. Older jax (no pcast) has
            # untyped manual values — plain zeros are already correct.
            pcast = getattr(jax.lax, "pcast", None)
            init = jax.tree.map(
                (lambda sd: pcast(jnp.zeros(sd.shape, sd.dtype),
                                  ("shard", "bin"), to="varying"))
                if pcast is not None
                else (lambda sd: jnp.zeros(sd.shape, sd.dtype)),
                shapes,
            )
            part, _ = jax.lax.scan(step, init, jnp.arange(stream_chunks))
        # explicit merge over both mesh axes (ICI collectives)
        return jax.tree.map(lambda p: jax.lax.psum(p, ("shard", "bin")), part)

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # older jax: experimental module
        from jax.experimental.shard_map import shard_map

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(
                {k: col_spec for k in col_names},
                win_spec,
                win_spec,
                P("shard"),
            ),
            out_specs=P(),  # prefix spec: every leaf fully replicated post-psum
        )
    )


def placements(mesh):
    """(column, window, count) NamedShardings for :func:`build_bin_parallel`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return (
        NamedSharding(mesh, P("shard", None)),
        NamedSharding(mesh, P("shard", "bin")),
        NamedSharding(mesh, P("shard")),
    )


def bin_parallel_run(
    mesh,
    cols: Dict[str, "np.ndarray"],
    starts: np.ndarray,
    ends: np.ndarray,
    counts: np.ndarray,
    L: int,
    predicate: Callable,
    agg_fn: Callable,
    stream_chunks: int = 1,
):
    """Place inputs and run mask+aggregate over a (shard, bin) mesh.

    ``cols``: [S, L] column arrays (S divisible by the shard axis size).
    ``starts``/``ends``: [S, K] per-bin scan windows (padded here to the bin
    axis x ``stream_chunks``). ``predicate(cols, jnp)``: fused fine filter;
    ``agg_fn(cols, mask, jnp)``: additive partial aggregate (pytree).

    Returns the merged aggregate (fully replicated). Convenience wrapper —
    hot paths use :func:`build_bin_parallel` + :func:`placements` and cache.
    """
    import jax

    n_bin = mesh.shape["bin"]
    starts, ends = pad_windows(starts, ends, n_bin * stream_chunks)
    fn = build_bin_parallel(
        mesh, tuple(cols), L, predicate, agg_fn, stream_chunks
    )
    col_sh, win_sh, cnt_sh = placements(mesh)
    dev_cols = {k: jax.device_put(v, col_sh) for k, v in cols.items()}
    return fn(
        dev_cols,
        jax.device_put(starts.astype(np.int32), win_sh),
        jax.device_put(ends.astype(np.int32), win_sh),
        jax.device_put(counts.astype(np.int32), cnt_sh),
    )
