from geomesa_tpu.parallel.mesh import shard_mesh, device_count  # noqa: F401
from geomesa_tpu.parallel.devices import (  # noqa: F401
    TreeReducer, device_sharding, merge_partials, scan_devices,
    slot_device, tree_merge,
)
