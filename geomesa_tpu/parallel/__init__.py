from geomesa_tpu.parallel.mesh import shard_mesh, device_count  # noqa: F401
from geomesa_tpu.parallel.devices import (  # noqa: F401
    TreeReducer, device_sharding, healthy_device_count, merge_partials,
    scan_devices, slot_device, tree_merge,
)
from geomesa_tpu.parallel import health  # noqa: F401
