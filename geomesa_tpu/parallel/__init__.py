from geomesa_tpu.parallel.mesh import shard_mesh, device_count  # noqa: F401
