"""Local device fan-out: the sharded partitioned scan + serving pool
substrate (docs/SCALE.md, docs/SERVING.md).

The reference scales range scans by fanning partitions out across tablet
servers and merging server-side partial aggregates (SURVEY.md §2.9). The
TPU-native analog here is *partition-level* device parallelism, distinct
from the GSPMD mesh (`parallel/mesh.py`, which shards one partition's
arrays ACROSS devices): each pruned time partition is pinned whole to one
local device, per-device partial aggregates dispatch asynchronously from
the single query thread (jax dispatch is async, so device d executes
partition i while the thread dispatches partition i+1 to device d+1), and
the partials merge in a fixed, documented order — see :func:`tree_merge`.

Two consumers share these helpers and must not overlap:

* the **sharded partitioned scan** (`planning/partitioned_exec.py`) —
  intra-query parallelism, devices resolved by :func:`scan_devices`;
* the **serving pool** (`serving/scheduler.py`) — inter-query
  parallelism, one dispatch thread per executor slot, slot i pinned to
  :func:`slot_device`. While a pool wider than one executor is running it
  owns the devices (one jit thread per device), so :func:`scan_devices`
  stands down — the scheduler flips :func:`set_pool_width` on
  start()/stop().
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

from geomesa_tpu import config

#: LIVE serving pools, owner (scheduler) -> executor width. Weak keys:
#: a scheduler that is garbage-collected without stop() must not pin the
#: sharded scan down forever. Per-owner (not one process global) because
#: every GeoDataset owns a scheduler: dataset B starting/stopping its
#: width-1 scheduler must not release devices that dataset A's width-4
#: pool still owns.
_pools: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_lock = threading.Lock()


def register_pool(owner, n: int) -> None:
    """Record ``owner``'s live executor-pool width (scheduler start()).
    A pool wider than 1 claims exclusive per-device dispatch threads, so
    the sharded partitioned scan stands down while it runs."""
    with _lock:
        _pools[owner] = max(1, int(n))


def unregister_pool(owner) -> None:
    """Forget ``owner``'s pool (scheduler stop())."""
    with _lock:
        _pools.pop(owner, None)


def pool_width() -> int:
    """Width of the WIDEST live pool (1 = no pool owns the devices)."""
    with _lock:
        return max(_pools.values(), default=1)


def scan_devices() -> Optional[List]:
    """Devices the sharded partitioned scan may fan out over, resolved
    from ``geomesa.mesh.devices`` (unset/"all" = every local device, an
    integer caps the count, 0/1/"off" disables) and filtered through the
    per-device health registry (cordoned/broken devices never receive
    partitions — docs/RESILIENCE.md §6). None = the sharded scan does not
    engage (single usable device, knob off, or a >1-executor serving pool
    owns the devices); the serial path then runs on the default placement
    regardless of health — cordoning every device caps capacity, it never
    zeroes it."""
    if pool_width() > 1:
        return None
    raw = (config.MESH_DEVICES.get() or "all").strip().lower()
    if raw in ("0", "1", "off", "false", "no", "none"):
        return None
    import jax

    devs = list(jax.devices())
    if raw not in ("all", "true", "on", "yes", ""):
        try:
            devs = devs[: max(int(raw), 0)]
        except ValueError:
            return None
    from geomesa_tpu.parallel import health as phealth

    hreg = phealth.registry()
    devs = [d for d in devs if hreg.usable(d.id)]
    if len(devs) < 2:
        return None
    return devs


def healthy_device_count() -> int:
    """Local devices the health registry allows scheduling on (>= 1 so a
    fully cordoned mesh still leaves the default serial placement — the
    capacity floor, never a zero)."""
    try:
        import jax

        devs = list(jax.devices())
    except Exception:
        return 1
    from geomesa_tpu.parallel import health as phealth

    hreg = phealth.registry()
    return max(1, sum(1 for d in devs if hreg.usable(d.id)))


def slot_device(slot: int):
    """The device pinned to serving-pool executor slot ``slot``
    (slot i -> device i % healthy_device_count; slot 0 keeps the default
    placement and is handled by the caller). Health-aware: cordoned and
    broken devices drop out of the rotation, so a respawned (or re-pinned)
    slot lands on a healthy device — GeoDataset's slot-keyed executors
    re-pin when this mapping moves (docs/RESILIENCE.md §6). With the pool
    width re-clamped to the healthy count by the supervisor, distinct
    slots keep distinct devices (the one-jit-thread-per-device rule).
    Falls back to the full device list when health fences everything."""
    import jax

    devs = list(jax.devices())
    from geomesa_tpu.parallel import health as phealth

    hreg = phealth.registry()
    healthy = [d for d in devs if hreg.usable(d.id)]
    devs = healthy or devs
    return devs[slot % len(devs)]


#: SingleDeviceSharding singletons per device id. Singletons matter:
#: IndexTable.device_columns keys its upload cache by id(sharding), so the
#: prefetch thread's device_put overlap and the query thread's executor
#: must present the SAME object to hit one cache entry.
_shardings: Dict[int, object] = {}


def device_sharding(device):
    """The process-wide SingleDeviceSharding for ``device`` (cached)."""
    sh = _shardings.get(device.id)
    if sh is None:
        from jax.sharding import SingleDeviceSharding

        with _lock:
            sh = _shardings.get(device.id)
            if sh is None:
                sh = _shardings[device.id] = SingleDeviceSharding(device)
    return sh


def tree_merge(parts, combine):
    """Fixed balanced pairwise reduction of ``parts`` (None = empty).

    THE documented merge order of the partitioned scan, serial and
    sharded alike: with partials ``[p0, p1, p2, p3, p4]`` in pruned-bin
    order, round 1 combines adjacent pairs left-to-right —
    ``(p0+p1), (p2+p3), p4`` — and rounds repeat until one remains:
    ``((p0+p1)+(p2+p3)) + p4``. The order depends ONLY on the input
    order (pruned-bin order), never on device assignment or completion
    timing, so the sharded scan is bit-identical to the single-device
    path by construction — the contract the aggregate cache and the
    fusion layer rely on (docs/CACHE.md, docs/SERVING.md)."""
    items = [p for p in parts if p is not None]
    if not items:
        return None
    while len(items) > 1:
        nxt = []
        for j in range(0, len(items) - 1, 2):
            nxt.append(combine(items[j], items[j + 1]))
        if len(items) % 2:
            nxt.append(items[-1])
        items = nxt
    return items[0]


class TreeReducer:
    """Streaming form of :func:`tree_merge`: push partials in pruned-bin
    order, :meth:`result` returns the SAME association (asserted against
    tree_merge for every size in tests) — so callers can merge
    incrementally, holding O(log n) partials instead of all n, without
    changing a single result bit. The classic binary-counter reduction:
    a pushed value combines with the stack top while both sit at the
    same level, and the leftover stack folds lowest-level-first at the
    end (exactly tree_merge's final odd-tail rounds)."""

    def __init__(self, combine):
        self.combine = combine
        self._stack: List = []  # (level, value), levels strictly decreasing

    def push(self, v) -> None:
        if v is None:
            return
        lvl = 0
        while self._stack and self._stack[-1][0] == lvl:
            _, u = self._stack.pop()
            v = self.combine(u, v)
            lvl += 1
        self._stack.append((lvl, v))

    def result(self):
        if not self._stack:
            return None
        vals = [v for _, v in self._stack]
        v = vals[-1]
        for u in reversed(vals[:-1]):
            v = self.combine(u, v)
        return v


def merge_partials(parts, device=None):
    """Additive merge of per-partition device/host partials via
    :func:`tree_merge`. With ``device`` set (the sharded scan), every
    partial is first transferred onto it — ``jax.devices()[0]``, the same
    device the serial path computes on — so the adds run on ONE device in
    the documented order and stay bit-identical to the serial merge."""
    items = [p for p in parts if p is not None]
    if not items:
        return None
    if device is not None:
        import jax

        sh = device_sharding(device)
        items = [jax.device_put(p, sh) for p in items]
    return tree_merge(items, lambda a, b: a + b)
