"""Per-device health: circuit breakers, cordon/drain, and the fault
bookkeeping behind mid-scan reassignment (docs/RESILIENCE.md §6).

Production heterogeneous meshes routinely see one lane run slow or fail
outright ("Large-Scale Geospatial Processing on Multi-Core and Many-Core
Processors", PAPERS.md); before this module a sick device took the whole
sharded scan — or its serving-pool slot — down with it. Now every local
device carries:

* a **circuit breaker** (``resilience.breaker("device:<id>")``) fed by
  sharded-scan dispatch failures and latency-outlier streaks:
  ``geomesa.device.breaker.threshold`` consecutive failures open it
  (state *broken*), the normal half-open trial after
  ``geomesa.device.breaker.reset.ms`` restores it;
* a **latency-outlier detector**: a per-partition device sync slower than
  ``geomesa.device.latency.outlier`` x the trailing mesh-wide median
  *for its kernel shape* (and over ``geomesa.device.latency.floor.ms``)
  counts one outlier; a threshold-long consecutive streak trips the
  breaker — the slow-but-not-failing straggler lane is fenced like a
  failing one. Baselines are kept PER KERNEL SHAPE (the op kind plus the
  partition's padded-length bucket — what actually determines the
  compiled kernel): one mesh-wide median would let a heterogeneous
  workload mask a straggler (a slow lane's density syncs hide behind
  everyone's cheap counts) or, worse, fence a healthy lane that merely
  drew the big partitions;
* an explicit **cordon** state — operator action via the CLI
  (``geomesa-tpu devices cordon``), the sidecar ``cordon-device``
  action, :func:`cordon` in process, or the ``geomesa.mesh.cordon``
  config knob — that removes the device from scheduling without a
  restart and without touching its breaker.

Consumers: ``parallel/devices.py`` filters :func:`usable` devices out of
the sharded fan-out and serving-pool slot pinning; the partitioned
executor records failures/successes/latencies per dispatch and requeues a
failed device's partitions onto survivors (``scan.reassigned``); obs.py
surfaces :func:`snapshot` at ``/debug/devices`` and degrades (not 503)
``/healthz`` while cordoned/broken devices leave capacity standing.

Everything is process-local state at partition/dispatch granularity —
never consulted inside per-row loops.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Set

from geomesa_tpu import config, metrics, resilience

#: health states surfaced to operators (gauge values in parens)
OK, CORDONED, BROKEN = "ok", "cordoned", "broken"
_GAUGE_VALUE = {OK: 1.0, CORDONED: 0.0, BROKEN: -1.0}


def _cordon_config_ids() -> Set[int]:
    """Device ids cordoned via the ``geomesa.mesh.cordon`` knob."""
    raw = (config.MESH_CORDON.get() or "").strip()
    if not raw:
        return set()
    out: Set[int] = set()
    for tok in raw.split(","):
        tok = tok.strip()
        if tok:
            try:
                out.add(int(tok))
            except ValueError:
                pass  # a malformed token never un-cordons the valid ones
    return out


class DeviceHealthRegistry:
    """Process-wide per-device health state. Thread-safe; device ids are
    the local jax device ids (bounded cardinality — one entry, one
    ``device.health.<id>`` gauge per local device)."""

    def __init__(self):
        self._lock = threading.Lock()
        #: explicit cordons: id -> reason (the config knob is consulted
        #: separately so scoped/env cordons never leak into this map)
        self._cordoned: Dict[int, str] = {}
        self._last_failure: Dict[int, str] = {}
        #: partitions requeued off this device (docs/RESILIENCE.md §6)
        self._reassigned: Dict[int, int] = {}
        self._failures: Dict[int, int] = {}
        #: trailing sync-latency samples PER KERNEL SHAPE (the outlier
        #: baselines); key None is the shape-less fallback. Insertion-
        #: ordered, oldest shape evicted past _MAX_SHAPES.
        self._lat_recent: Dict[Optional[tuple], "deque"] = {}
        self._outlier_streak: Dict[int, int] = {}
        self._gauged: Set[int] = set()

    #: distinct kernel-shape baselines retained (beyond it the least
    #: recently SEEN shape's samples drop — bounded state, like the 256-
    #: sample deques themselves)
    _MAX_SHAPES = 64

    # -- breaker plumbing --------------------------------------------------
    def _breaker(self, did: int) -> resilience.CircuitBreaker:
        """The device's circuit breaker, through the process-wide named
        registry (so it shows up in resilience.breaker_states() and the
        /healthz breaker map like every other breaker — obs.py treats
        ``device:*`` breakers as soft-degrading, not 503)."""
        return resilience.breaker(
            f"device:{did}",
            threshold=config.DEVICE_BREAKER_THRESHOLD.to_int() or 3,
            reset_ms=config.DEVICE_BREAKER_RESET_MS.to_float() or 30_000.0,
        )

    def _ensure_gauge(self, did: int) -> None:
        if did in self._gauged:
            return
        with self._lock:
            if did in self._gauged:
                return
            self._gauged.add(did)
        metrics.registry().gauge(
            f"{metrics.DEVICE_HEALTH_PREFIX}.{did}",
            lambda d=did: _GAUGE_VALUE[self.state(d)],
            replace=True,
        )

    # -- state -------------------------------------------------------------
    def cordon_reason(self, did: int) -> Optional[str]:
        with self._lock:
            reason = self._cordoned.get(did)
        if reason is not None:
            return reason
        if did in _cordon_config_ids():
            return "geomesa.mesh.cordon"
        return None

    def state(self, did: int) -> str:
        """``ok`` | ``cordoned`` (operator/config) | ``broken`` (breaker
        open or half-open awaiting its trial — the trial dispatch itself
        is admitted through :meth:`usable`)."""
        if self.cordon_reason(did) is not None:
            return CORDONED
        if self._breaker(did).state != resilience.CircuitBreaker.CLOSED:
            return BROKEN
        return OK

    def usable(self, did: int) -> bool:
        """May the scheduler place work on this device? Cordoned: no.
        Open breaker: no. Half-open: yes — the next dispatch IS the trial
        (its success/failure report closes or re-opens the circuit); a
        pure state read here, never ``allow()``, so an observability poll
        can never consume the trial slot without dispatching."""
        self._ensure_gauge(did)
        if self.cordon_reason(did) is not None:
            return False
        return self._breaker(did).state != resilience.CircuitBreaker.OPEN

    # -- operator surface --------------------------------------------------
    def cordon(self, did: int, reason: str = "operator") -> None:
        """Remove a device from scheduling (sticky until uncordon)."""
        self._ensure_gauge(did)
        with self._lock:
            self._cordoned[int(did)] = str(reason)

    def uncordon(self, did: int) -> bool:
        """Re-admit an explicitly cordoned device. Returns False when the
        device was not cordoned here (a ``geomesa.mesh.cordon`` config
        cordon is cleared by unsetting the knob, not through this API)."""
        with self._lock:
            return self._cordoned.pop(int(did), None) is not None

    def cordoned_ids(self) -> Set[int]:
        with self._lock:
            out = set(self._cordoned)
        return out | _cordon_config_ids()

    # -- fault bookkeeping (partition/dispatch granularity) ----------------
    def record_failure(self, did: int, error: BaseException) -> None:
        """One failed dispatch on ``did`` — feeds its breaker."""
        self._ensure_gauge(did)
        self._breaker(did).record_failure()
        with self._lock:
            self._failures[did] = self._failures.get(did, 0) + 1
            self._last_failure[did] = repr(error)[:300]

    def record_success(self, did: int) -> None:
        """One successful dispatch — closes a half-open trial, resets the
        consecutive-failure count."""
        self._breaker(did).record_success()

    def record_latency(self, did: int, seconds: float,
                       shape: Optional[tuple] = None) -> None:
        """One partition-sync latency sample for kernel ``shape`` (op kind
        + padded-length bucket; None = shape-less fallback). Consecutive
        outliers (vs the trailing median OF THE SAME SHAPE, over the
        floor) trip the device's breaker: the straggler lane the many-core
        evaluations in PAPERS.md blame for lost headroom gets fenced like
        a failing one, and a heterogeneous mix of cheap and expensive
        kernels can neither mask it nor fake one (RESILIENCE.md §6)."""
        try:
            factor = config.DEVICE_LATENCY_OUTLIER.to_float() or 0.0
        except (TypeError, ValueError):
            factor = 0.0
        if factor <= 0:
            return
        floor_s = (config.DEVICE_LATENCY_FLOOR_MS.to_float() or 250.0) / 1e3
        with self._lock:
            dq = self._lat_recent.pop(shape, None)
            if dq is None:
                dq = deque(maxlen=256)
            self._lat_recent[shape] = dq  # re-insert = most recently seen
            while len(self._lat_recent) > self._MAX_SHAPES:
                self._lat_recent.pop(next(iter(self._lat_recent)))
            samples = sorted(dq)
            dq.append(seconds)
            median = samples[len(samples) // 2] if len(samples) >= 8 else None
            if median is not None \
                    and seconds >= max(floor_s, factor * median):
                streak = self._outlier_streak.get(did, 0) + 1
                self._outlier_streak[did] = streak
                threshold = config.DEVICE_BREAKER_THRESHOLD.to_int() or 3
                if streak < threshold:
                    return
                self._outlier_streak[did] = 0
                self._last_failure[did] = (
                    f"latency outlier: {seconds * 1e3:.1f} ms >= "
                    f"{factor:g} x median {median * 1e3:.1f} ms for "
                    f"kernel shape {shape} ({streak} consecutive)"
                )
            else:
                self._outlier_streak[did] = 0
                return
        # trip outside the registry lock (breaker has its own)
        self._breaker(did).trip()

    def latency_baselines(self) -> Dict[str, int]:
        """Operator view: sample counts per kernel-shape baseline."""
        with self._lock:
            return {str(k): len(v) for k, v in self._lat_recent.items()}

    def note_reassigned(self, did: int) -> None:
        """One partition requeued OFF this device onto a survivor."""
        with self._lock:
            self._reassigned[did] = self._reassigned.get(did, 0) + 1

    # -- operator payloads -------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-device health payload (/debug/devices, the CLI ``devices``
        command): state, breaker state, cordon reason, failure counts,
        reassignments, and the last failure's repr."""
        with self._lock:
            ids = (set(self._gauged) | set(self._cordoned)
                   | set(self._last_failure) | set(self._reassigned))
            cordons = dict(self._cordoned)
            failures = dict(self._failures)
            reassigned = dict(self._reassigned)
            last = dict(self._last_failure)
        ids |= _cordon_config_ids()
        out: Dict[str, Dict[str, Any]] = {}
        for did in sorted(ids):
            entry: Dict[str, Any] = {
                "state": self.state(did),
                "breaker": self._breaker(did).state,
                "failures": failures.get(did, 0),
                "reassigned": reassigned.get(did, 0),
            }
            reason = cordons.get(did) or (
                "geomesa.mesh.cordon" if did in _cordon_config_ids()
                else None
            )
            if reason is not None:
                entry["cordon_reason"] = reason
            if did in last:
                entry["last_failure"] = last[did]
            out[str(did)] = entry
        return out

    def summary(self, total_devices: int) -> Dict[str, Any]:
        """The /healthz device-capacity digest: cordoned/broken id lists
        plus how many of ``total_devices`` remain schedulable."""
        cordoned: List[int] = []
        broken: List[int] = []
        for did in range(max(int(total_devices), 0)):
            st = self.state(did)
            if st == CORDONED:
                cordoned.append(did)
            elif st == BROKEN:
                broken.append(did)
        usable = max(int(total_devices), 0) - len(cordoned) - len(broken)
        return {
            "total": int(total_devices),
            "usable": usable,
            "cordoned": cordoned,
            "broken": broken,
        }


_registry = DeviceHealthRegistry()


def registry() -> DeviceHealthRegistry:
    return _registry


def reset() -> None:
    """Fresh registry (test isolation). Does NOT clear the underlying
    ``device:*`` breakers — pair with ``resilience.reset_breakers()``."""
    global _registry
    _registry = DeviceHealthRegistry()
