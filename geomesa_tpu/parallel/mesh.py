"""Device mesh helpers — the distributed substrate.

The reference's horizontal partitioning + scatter-gather fan-out
(SURVEY.md §2.9: rowkey splits across tablet servers, client batch scans,
server-side partial aggregates merged by reducers) maps to SPMD: shard axis
over devices, one jit'd scan, XLA-inserted collectives for the merge (psum
over ICI within a slice; DCN across slices is handled by jax's global mesh on
multi-host deployments).
"""

from __future__ import annotations

from typing import Optional


def device_count() -> int:
    import jax

    return jax.device_count()


def shard_mesh(n: Optional[int] = None):
    """A 1-D mesh over ``n`` (default: all) devices with axis name 'shard'."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devs = jax.devices()[: (n or len(jax.devices()))]
    return Mesh(np.array(devs), axis_names=("shard",))


def shard_spec():
    from jax.sharding import PartitionSpec

    return PartitionSpec("shard", None)
