"""Lambda store: hot streaming window + cold persisted tier, queried as one.

Reference parity (geomesa-lambda, SURVEY.md §2.5): writes land in the
transient (Kafka) tier immediately and migrate to the persistent delegate
store once older than an age threshold (DataStorePersistence.scala:45);
queries merge transient + persistent with the transient copy winning
(LambdaQueryRunner); stats merge across tiers (LambdaStats).

This is the architecture for 'live window in HBM + historical tier on
Parquet' — the persistent tier is a GeoDataset (device store) which can
itself be backed by FileSystemStorage.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stream.live import StreamingDataset


class LambdaDataset:
    """Hot/cold hybrid datastore (LambdaDataStore analog)."""

    def __init__(self, persistent: Optional[GeoDataset] = None,
                 transient: Optional[StreamingDataset] = None,
                 persist_age_ms: int = 60_000):
        self.persistent = persistent or GeoDataset()
        self.transient = transient or StreamingDataset()
        self.persist_age_ms = persist_age_ms

    # -- schema ------------------------------------------------------------
    def create_schema(self, name_or_ft, spec: Optional[str] = None) -> FeatureType:
        ft = self.transient.create_schema(name_or_ft, spec)
        self.persistent.create_schema(FeatureType.from_spec(ft.name, ft.spec()))
        return ft

    def list_schemas(self) -> List[str]:
        return self.transient.list_schemas()

    # -- writes (always to the transient tier first) ------------------------
    def write(self, name: str, data: Dict[str, Sequence], fids: Sequence[str],
              ts_ms: Optional[Sequence[int]] = None):
        self.transient.write(name, data, fids, ts_ms)

    # -- tier migration (DataStorePersistence analog) ------------------------
    def run_persistence(self, name: Optional[str] = None,
                        now_ms: Optional[int] = None) -> int:
        """Move transient features older than the age threshold into the
        persistent store. Returns the number migrated."""
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        cutoff = now_ms - self.persist_age_ms
        moved = 0
        for nm in [name] if name else self.transient.list_schemas():
            self.transient.poll(nm)
            cache = self.transient.cache(nm)
            with cache._lock:
                old = [
                    (fid, ts, attrs)
                    for fid, (ts, attrs) in cache._state.items()
                    if ts <= cutoff
                ]
            if not old:
                continue
            ft = self.transient.get_schema(nm)
            keys = [a.name for a in ft.attributes]
            data = {k: [attrs.get(k) for _, _, attrs in old] for k in keys}
            # point geometries arrive as [x, y] pairs; null geometry -> NaN
            g = ft.geom_field
            if g is not None and ft.attr(g).is_point:
                pairs = data.pop(g)
                data[g + "__x"] = np.array(
                    [np.nan if p is None else float(p[0]) for p in pairs], np.float64
                )
                data[g + "__y"] = np.array(
                    [np.nan if p is None else float(p[1]) for p in pairs], np.float64
                )
            fids = [fid for fid, _, _ in old]
            # an updated feature may age out again: replace, don't duplicate
            pst = self.persistent._store(nm)
            if pst.count:
                from geomesa_tpu.filter import ir as fir
                from geomesa_tpu.filter.compile import compile_filter

                cf = compile_filter(fir.IdIn(tuple(fids)), pst.ft, pst.dicts)
                pst.delete(lambda cols: np.asarray(cf(cols, np)))
            self.persistent.insert(nm, data, fids)
            self.persistent.flush(nm)
            # evict only if the entry is still the snapshot we persisted —
            # a concurrent newer update must survive in the hot tier
            with cache._lock:
                for fid, ts, _ in old:
                    cur = cache._state.get(fid)
                    if cur is not None and cur[0] == ts:
                        del cache._state[fid]
                        cache._invalidate()
            moved += len(old)
        return moved

    # -- merged reads (LambdaQueryRunner analog) ----------------------------
    def dicts(self, name: str):
        """The merged result's dictionary space = the transient tier's."""
        return self.transient.cache(name).dicts

    def _recode_cold(self, name: str, cold: ColumnBatch) -> ColumnBatch:
        """Re-encode the persistent tier's string codes into the transient
        dictionary space so merged columns share one vocabulary."""
        ft = self.transient.get_schema(name)
        cold_dicts = self.persistent._store(name).dicts
        hot_dicts = self.dicts(name)
        cols = dict(cold.columns)
        for a in ft.attributes:
            if a.type == "string" and a.name in cols:
                d_cold = cold_dicts.get(a.name)
                if d_cold is None:
                    continue
                decoded = d_cold.decode(cols[a.name])
                d_hot = hot_dicts.setdefault(a.name, type(d_cold)())
                cols[a.name] = d_hot.encode(decoded)
        return ColumnBatch(cols, cold.n)

    def query(self, name: str, ecql: str = "INCLUDE") -> ColumnBatch:
        """Transient + persistent results; transient wins on duplicate fid."""
        hot = self.transient.query(name, ecql)
        cold = self._recode_cold(name, self.persistent.query(name, ecql).batch)
        if hot.n == 0:
            return cold
        if cold.n == 0:
            return hot
        # normalize both tiers to str: the fid column layout ('S' vs 'U')
        # is content-dependent, and a bytes set never matches str elements
        from geomesa_tpu.schema.columns import fid_strs

        hot_fids = set(fid_strs(hot.columns["__fid__"]).tolist())
        keep = np.array(
            [f not in hot_fids for f in fid_strs(cold.columns["__fid__"])],
            dtype=bool,
        )
        cold = cold.select(keep)
        # align to the shared column set (key columns may differ per tier)
        common = [k for k in hot.columns if k in cold.columns]
        return ColumnBatch.concat([
            ColumnBatch({k: hot.columns[k] for k in common}, hot.n),
            ColumnBatch({k: cold.columns[k] for k in common}, cold.n),
        ])

    def count(self, name: str, ecql: str = "INCLUDE") -> int:
        return int(self.query(name, ecql).n)

    def density(self, name: str, ecql: str = "INCLUDE",
                bbox=(-180, -90, 180, 90), width: int = 256,
                height: int = 256) -> np.ndarray:
        """Merged density over both tiers with the same duplicate resolution
        as query(): hot wins. One grid kernel over the merged columns keeps
        feature results and map overlays consistent."""
        from geomesa_tpu.kernels import density as kdensity

        merged = self.query(name, ecql)
        if merged.n == 0:
            return np.zeros((height, width), np.float32)
        g = self.transient.get_schema(name).geom_field
        return np.asarray(kdensity.density_grid(
            merged.columns[g + "__x"], merged.columns[g + "__y"],
            np.ones(merged.n, dtype=bool), tuple(bbox), width, height,
            None, np,
        ))

    def stats(self, name: str, stat_spec: str, ecql: str = "INCLUDE"):
        """Merged stats: observe both tiers into one sketch (LambdaStats)."""
        from geomesa_tpu.kernels.stats_scan import decode_enum_keys
        from geomesa_tpu.stats import parse_stat

        stat = parse_stat(stat_spec)
        merged = self.query(name, ecql)
        if merged.n:
            stat.observe(merged.columns)
            decode_enum_keys(stat, self.dicts(name))
        return stat
