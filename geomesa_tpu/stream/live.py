"""Live feature cache + streaming dataset (Kafka datastore analog).

Reference parity (SURVEY.md §2.5 Kafka row, §3.5 call stack):

* ``LiveFeatureCache`` ~ KafkaFeatureCacheImpl over BucketIndexSupport: the
  current state of each feature id, with event-time ordering (stale updates
  dropped), optional event-time expiry, and a uniform grid bucket index for
  spatial candidate pruning.
* ``StreamingDataset`` ~ KafkaDataStore: schemas map to topics; writers
  produce GeoMessages; ``poll()`` is the micro-batch consumer populating the
  cache; queries run the local pipeline (compiled ECQL mask + aggregation
  kernels) over the live window — KafkaQueryRunner/LocalQueryRunner.
* feature listeners ~ GeoMesaFeatureListener events.

The live window is columnar: the cache rebuilds (and caches) a ColumnBatch
on demand, so density/stats over the window use the same kernels as the
batch store, and the window can be device_put as a whole (the double-buffer
ring of SURVEY.md §2.9.5).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.filter import ir, parse_ecql
from geomesa_tpu.filter.compile import compile_filter
from geomesa_tpu.kernels import density as kdensity
from geomesa_tpu.schema.columns import (
    ColumnBatch, DictionaryEncoder, encode_batch,
)
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stream.messages import (
    CHANGE, CLEAR, DELETE, GeoMessage, MessageBus, Topic,
)


def _cell_of(v: np.ndarray, off: float, span: float, n: int) -> np.ndarray:
    """Grid cell index along one axis (NaN-safe: NaN clamps to cell 0; null
    geometries are excluded by the caller's validity mask anyway)."""
    with np.errstate(invalid="ignore"):
        return np.clip(
            np.nan_to_num((np.asarray(v) + off) / span * n).astype(np.int64),
            0, n - 1,
        )


class LiveFeatureCache:
    """Current feature state keyed by fid (KafkaFeatureCache analog)."""

    def __init__(self, ft: FeatureType, expiry_ms: Optional[int] = None,
                 grid_bins: int = 64):
        self.ft = ft
        self.expiry_ms = expiry_ms
        self.grid_bins = grid_bins
        self.dicts: Dict[str, DictionaryEncoder] = {}
        self._state: Dict[str, Tuple[int, Dict[str, Any]]] = {}  # fid -> (ts, attrs)
        self._lock = threading.Lock()
        self._batch: Optional[ColumnBatch] = None  # columnar view cache
        self._grid: Optional[Dict[int, List[str]]] = None
        #: mutation epoch: bumped by every applied change/delete/clear/expiry
        #: — the invalidation key for anything caching aggregates over the
        #: live window (same contract as FeatureStore.version; docs/CACHE.md)
        self.epoch = 0
        #: standing-query event hook (docs/STANDING.md): called as
        #: ``observer(event, fid, old_attrs, new_attrs)`` for every APPLIED
        #: mutation (stale-dropped puts don't fire) — the subscribe
        #: engine's delta feed. None = no subscriptions, zero overhead.
        self.observer: Optional[Callable] = None

    def __len__(self):
        return len(self._state)

    # -- mutation ----------------------------------------------------------
    def validate(self, attrs: Dict[str, Any]) -> None:
        """Reject a payload the columnar encode could not absorb (poison
        protection: an unappliable feature must fail HERE, at the message,
        not later in ``batch()`` where it would poison every query of the
        window). Point geometries must be None or an (x, y) pair of
        numbers; extent geometries must be None or a WKT string."""
        for a in self.ft.attributes:
            if not a.is_geom:
                continue
            v = attrs.get(a.name)
            if v is None:
                continue
            if a.is_point:
                try:
                    float(v[0]), float(v[1])
                except (TypeError, ValueError, IndexError, KeyError) as e:
                    raise ValueError(
                        f"bad point payload for {a.name!r}: {v!r}"
                    ) from e
            elif not isinstance(v, str):
                raise ValueError(
                    f"bad geometry payload for {a.name!r}: {type(v).__name__}"
                )

    def put(self, fid: str, attrs: Dict[str, Any], ts_ms: int):
        with self._lock:
            cur = self._state.get(fid)
            if cur is not None and cur[0] > ts_ms:
                return  # event-time ordering: drop stale update
            self._state[fid] = (ts_ms, attrs)
            self._invalidate()
        if self.observer is not None:
            # old attrs distinguish a MOVE (delta -old/+new) from an add
            self.observer("put", fid, cur[1] if cur else None, attrs)

    def remove(self, fid: str):
        with self._lock:
            old = self._state.pop(fid, None)
            if old is not None:
                self._invalidate()
        if old is not None and self.observer is not None:
            self.observer("remove", fid, old[1], None)

    def clear(self):
        with self._lock:
            self._state.clear()
            self._invalidate()
        if self.observer is not None:
            self.observer("clear", None, None, None)

    def expire(self, now_ms: Optional[int] = None) -> int:
        """Drop features older than the event-time expiry. Returns #dropped."""
        if self.expiry_ms is None:
            return 0
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        cutoff = now_ms - self.expiry_ms
        with self._lock:
            stale = [(f, self._state[f][1]) for f, (ts, _)
                     in self._state.items() if ts < cutoff]
            for f, _ in stale:
                del self._state[f]
            if stale:
                self._invalidate()
        if stale and self.observer is not None:
            # expiry is the stream's age-off: non-additive, dirty-scoped
            for f, old in stale:
                self.observer("remove", f, old, None)
        return len(stale)

    def _invalidate(self):
        self._batch = None
        self._grid = None
        self.epoch += 1

    # -- columnar view ------------------------------------------------------
    def batch(self) -> ColumnBatch:
        """The live window as encoded columns (rebuilt lazily)."""
        with self._lock:
            if self._batch is not None:
                return self._batch
            if not self._state:
                self._batch = ColumnBatch({}, 0)
                return self._batch
            fids = list(self._state)
            rows = [self._state[f][1] for f in fids]
            data: Dict[str, list] = {}
            for a in self.ft.attributes:
                if a.is_geom and not a.is_point:
                    data[a.name] = [r.get(a.name) for r in rows]
                elif a.is_geom:
                    # points arrive as (x, y) / [x, y]; null/missing geometry
                    # rides as NaN and is excluded by the query validity mask
                    xs, ys = [], []
                    for r in rows:
                        v = r.get(a.name)
                        if v is None:
                            xs.append(np.nan)
                            ys.append(np.nan)
                        else:
                            xs.append(float(v[0]))
                            ys.append(float(v[1]))
                    data[a.name + "__x"] = np.array(xs)
                    data[a.name + "__y"] = np.array(ys)
                else:
                    data[a.name] = [r.get(a.name) for r in rows]
            self._batch = encode_batch(self.ft, data, self.dicts, fids)
            return self._batch

    def grid_index(self, b: Optional[ColumnBatch] = None) -> Dict[int, np.ndarray]:
        """Uniform grid bucket index over the window (BucketIndex analog):
        cell id -> row indices. The cached grid is tied to the batch snapshot
        it was built from, so row indices can never point into a different
        (concurrently rebuilt) batch."""
        if b is None:
            b = self.batch()
        with self._lock:
            if self._grid is not None and self._grid[0] is b:
                return self._grid[1]
        g = self.ft.geom_field
        out: Dict[int, np.ndarray] = {}
        if b.n and g is not None and g + "__x" in b.columns:
            n = self.grid_bins
            if g + "__xmin" in b.columns:
                # extent geometries: bucket every cell the row bbox overlaps
                # (a centroid-only bucket would hide rows from queries that
                # hit the geometry far from its centroid)
                x0 = _cell_of(b.columns[g + "__xmin"], 180.0, 360.0, n)
                x1 = _cell_of(b.columns[g + "__xmax"], 180.0, 360.0, n)
                y0 = _cell_of(b.columns[g + "__ymin"], 90.0, 180.0, n)
                y1 = _cell_of(b.columns[g + "__ymax"], 90.0, 180.0, n)
                ok = np.isfinite(b.columns[g + "__x"])
                cell_l: List[int] = []
                row_l: List[int] = []
                for i in np.nonzero(ok)[0]:
                    for cy in range(y0[i], y1[i] + 1):
                        base = cy * n
                        for cx in range(x0[i], x1[i] + 1):
                            cell_l.append(base + cx)
                            row_l.append(i)
                cell = np.asarray(cell_l, np.int64)
                order_rows = np.asarray(row_l, np.int64)
            else:
                cell = (
                    _cell_of(b.columns[g + "__y"], 90.0, 180.0, n) * n
                    + _cell_of(b.columns[g + "__x"], 180.0, 360.0, n)
                )
                order_rows = np.arange(b.n, dtype=np.int64)
            order = np.argsort(cell, kind="stable")
            cells, starts = np.unique(cell[order], return_index=True)
            bounds = np.append(starts, len(order))
            for i, c in enumerate(cells):
                out[int(c)] = order_rows[order[bounds[i]: bounds[i + 1]]]
        with self._lock:
            self._grid = (b, out)
        return out

    def candidate_rows(self, f: ir.Filter,
                       b: Optional[ColumnBatch] = None) -> Optional[np.ndarray]:
        """Row candidates from the grid index for the filter's bbox, or None
        for 'all rows'. Pass the batch snapshot the caller is masking so grid
        rows and batch rows stay coherent under concurrent writes."""
        g = self.ft.geom_field
        if g is None:
            return None
        fv = ir.extract_geometries(f, g)
        if fv.is_empty or fv.disjoint:
            return None
        n = self.grid_bins
        idx = self.grid_index(b)
        rows: List[np.ndarray] = []
        for geom in fv.values:
            xmin, ymin, xmax, ymax = geom.bounds()
            x0 = max(0, int((xmin + 180.0) / 360.0 * n))
            x1 = min(n - 1, int((xmax + 180.0) / 360.0 * n))
            y0 = max(0, int((ymin + 90.0) / 180.0 * n))
            y1 = min(n - 1, int((ymax + 90.0) / 180.0 * n))
            for cy in range(y0, y1 + 1):
                for cx in range(x0, x1 + 1):
                    got = idx.get(cy * n + cx)
                    if got is not None:
                        rows.append(got)
        if not rows:
            return np.zeros(0, np.int64)
        return np.unique(np.concatenate(rows))


class StreamingDataset:
    """Topic-backed streaming datastore (KafkaDataStore analog)."""

    def __init__(self, bus: Optional[MessageBus] = None,
                 expiry_ms: Optional[int] = None, partitions: int = 4,
                 prefer_device: bool = False):
        self.bus = bus or MessageBus()
        self.expiry_ms = expiry_ms
        self.partitions = partitions
        self.prefer_device = prefer_device
        self._schemas: Dict[str, FeatureType] = {}
        self._topics: Dict[str, Topic] = {}
        self._caches: Dict[str, LiveFeatureCache] = {}
        self._offsets: Dict[str, List[int]] = {}
        self._listeners: Dict[str, List[Callable[[GeoMessage], None]]] = {}
        #: poison-message quarantine counters per schema (docs/RESILIENCE.md):
        #: a message that fails to decode or apply is counted + recorded and
        #: skipped — it can never kill the consumer loop
        self.quarantined: Dict[str, int] = {}
        #: durable mutation journal (docs/RESILIENCE.md §8). When attached,
        #: every applied poll batch is journaled WITH its source offsets, so
        #: a restarted consumer resumes exactly where the crashed one acked.
        self._journal = None
        self._replaying = False
        #: standing-query engine over the live windows (docs/STANDING.md);
        #: created lazily on the first subscribe()
        self.standing = None

    # -- durability --------------------------------------------------------
    def attach_journal(self, root: str) -> None:
        """Journal applied batches under ``root`` (docs/RESILIENCE.md §8).

        The record goes down AFTER the batch applies — the live cache is
        idempotent under event-time ordering (re-putting a feature at the
        same ts is a no-op state-wise), so a crash in the journal-after-
        apply gap re-consumes at most one batch from the topic, never
        loses an acked one."""
        from geomesa_tpu import config
        from geomesa_tpu.fs.journal import MutationJournal

        if self._journal is not None or not config.JOURNAL_ENABLED.to_bool():
            return
        self._journal = MutationJournal(root)

    def recover(self) -> int:
        """Replay the attached journal: recreate journaled schemas, restore
        the live caches from applied batches, and restore consumer offsets
        so the next :meth:`poll` resumes past everything already applied.
        Returns the number of records replayed."""
        if self._journal is None:
            return 0
        applied = 0
        self._replaying = True
        try:
            applied = self._recover_records()
        finally:
            self._replaying = False
        return applied

    def _recover_records(self) -> int:
        from geomesa_tpu import metrics, resilience

        applied = 0
        for rec in self._journal.records():
            kind = rec.get("kind")
            nm = rec.get("schema", "")
            seq = int(rec.get("seq", 0))
            try:
                if kind == "stream-create":
                    if nm not in self._schemas:
                        self.create_schema(
                            FeatureType.from_spec(nm, rec["spec"]))
                elif kind == "stream-batch":
                    cache = self._caches.get(nm)
                    if cache is None:
                        continue  # schema dropped since: batch is moot
                    for mk, fid, payload, ts_ms in rec.get("msgs", []):
                        if mk == CHANGE:
                            cache.put(fid, payload or {}, int(ts_ms))
                        elif mk == DELETE:
                            cache.remove(fid)
                        elif mk == CLEAR:
                            cache.clear()
                    offs = rec.get("offsets")
                    if offs and nm in self._offsets:
                        self._offsets[nm] = [
                            max(a, int(b))
                            for a, b in zip(self._offsets[nm], offs)
                        ]
                else:
                    continue
                applied += 1
                metrics.inc(metrics.JOURNAL_REPLAYED)
            except Exception as e:
                # one bad record must not fail the whole recovery
                resilience.record_skip(
                    "journal.replay", f"{nm}@{seq}", e, phase="stream")
        return applied

    # -- schema CRUD -------------------------------------------------------
    def create_schema(self, name_or_ft, spec: Optional[str] = None) -> FeatureType:
        ft = (
            name_or_ft if isinstance(name_or_ft, FeatureType)
            else FeatureType.from_spec(name_or_ft, spec)
        )
        if ft.name in self._schemas:
            raise ValueError(f"schema {ft.name!r} already exists")
        self._schemas[ft.name] = ft
        self._topics[ft.name] = self.bus.create(f"geomesa-{ft.name}", self.partitions)
        self._caches[ft.name] = LiveFeatureCache(ft, self.expiry_ms)
        self._offsets[ft.name] = [0] * self.partitions
        self._listeners[ft.name] = []
        if self._journal is not None and not self._replaying:
            self._journal.append({
                "kind": "stream-create", "schema": ft.name,
                "spec": ft.spec(),
            })
        return ft

    def get_schema(self, name: str) -> FeatureType:
        return self._schemas[name]

    def list_schemas(self) -> List[str]:
        return sorted(self._schemas)

    def cache(self, name: str) -> LiveFeatureCache:
        return self._caches[name]

    def add_listener(self, name: str, fn: Callable[[GeoMessage], None]):
        self._listeners[name].append(fn)

    # -- standing queries (geomesa_tpu/subscribe/; docs/STANDING.md) -------
    def _standing_engine(self):
        if self.standing is None:
            from geomesa_tpu.subscribe import (
                LiveWindow, StandingQueryEngine,
            )

            self.standing = StandingQueryEngine(
                lambda nm: LiveWindow(self, nm)
            )
        return self.standing

    def subscribe(self, name: str, aggregate: str, bbox=None, region=None,
                  width: int = 256, height: int = 256,
                  levels: Optional[int] = None,
                  stat_spec: Optional[str] = None,
                  sub_id: Optional[str] = None) -> str:
        """Register a standing viewport over the live window: each applied
        poll batch updates the result incrementally — moves delta-apply
        (-old, +new), deletes/expiry re-scan only intersecting groups."""
        from geomesa_tpu.subscribe import spec as subspec

        sp = subspec.make_spec(
            name, aggregate, bbox=bbox, region=region, width=width,
            height=height, levels=levels, stat_spec=stat_spec,
        )
        cache = self._caches[name]  # raise on unknown schema
        eng = self._standing_engine()
        sid = eng.register(sp, sub_id=sub_id)
        if cache.observer is None:
            cache.observer = eng.live_observer(name)
        return sid

    def unsubscribe(self, sub_id: str) -> bool:
        return (self.standing is not None
                and self.standing.unregister(sub_id))

    def subscription_poll(self, sub_id: str, cursor: int = 0):
        """Drain pending stream messages, then return the standing result
        + update records past ``cursor``."""
        from geomesa_tpu.subscribe import UnknownSubscription

        if self.standing is None:
            raise UnknownSubscription(sub_id)
        self.poll()
        return self.standing.poll(sub_id, cursor)

    # -- producer ----------------------------------------------------------
    def write(self, name: str, data: Dict[str, Sequence], fids: Sequence[str],
              ts_ms: Optional[Sequence[int]] = None):
        """Produce Change messages for a batch of features."""
        ft = self._schemas[name]
        topic = self._topics[name]
        keys = list(data)
        n = len(fids)
        now = int(time.time() * 1000)
        dtg = ft.dtg_field
        for i in range(n):
            attrs: Dict[str, Any] = {}
            for k in keys:
                v = data[k][i]
                if isinstance(v, np.datetime64):
                    v = int(v.astype("datetime64[ms]").astype(np.int64))
                elif isinstance(v, np.generic):
                    v = v.item()
                elif isinstance(v, tuple):
                    v = list(v)
                attrs[k] = v
            if ts_ms is not None:
                ts = int(ts_ms[i])
            elif dtg is not None and dtg in attrs and attrs[dtg] is not None:
                ts = int(attrs[dtg])
            else:
                ts = now
            topic.send(GeoMessage.change(str(fids[i]), attrs, ts))

    def delete(self, name: str, fid: str):
        self._topics[name].send(GeoMessage.delete(fid, int(time.time() * 1000)))

    def clear(self, name: str):
        self._topics[name].send(GeoMessage.clear(int(time.time() * 1000)))

    # -- consumer (micro-batch) --------------------------------------------
    def _quarantine(self, name: str, part, error: BaseException,
                    phase: str) -> None:
        """Poison-message quarantine (docs/RESILIENCE.md): count, record
        through the audit degradation trail, and move on — a bad message
        must never kill the consumer. Counters ride the process metrics
        registry (ROADMAP open item) so operators see quarantine volume in
        the same exposition as the cache/query counters:
        ``stream.poll.quarantined`` total plus a per-schema breakdown."""
        from geomesa_tpu import metrics, resilience

        self.quarantined[name] = self.quarantined.get(name, 0) + 1
        metrics.inc("stream.poll.quarantined")
        metrics.inc(f"stream.poll.quarantined.{name}")
        resilience.record_skip(
            "stream.poll.decode", f"{name}/{part}", error, phase=phase
        )

    def poll(self, name: Optional[str] = None, max_messages: int = 100_000) -> int:
        """Consume pending messages into the live cache(s). Returns #consumed
        (quarantined poison messages are skipped, counted in
        :attr:`quarantined`, and NOT included in the returned count).

        Observability (docs/OBSERVABILITY.md): each schema's apply phase
        runs under a ``stream.apply`` span + timer, and the ``stream.lag``
        gauge (plus a per-schema breakdown) tracks poll→apply latency —
        apply wall-clock minus the last applied message's event time, the
        consumer-lag signal /metrics exposes."""
        from geomesa_tpu import metrics, tracing

        names = [name] if name else list(self._schemas)
        total = 0
        for nm in names:
            msgs, self._offsets[nm] = self._topics[nm].poll(
                self._offsets[nm], max_messages,
                on_error=lambda p, off, raw, e, nm=nm: self._quarantine(
                    nm, f"{p}@{off}", e, "decode"
                ),
            )
            cache = self._caches[nm]
            listeners = self._listeners[nm]
            if not msgs:
                # empty polls skip the span AND the timer: a tight idle
                # poll loop would otherwise flood stream.apply with ~0 s
                # samples and collapse its histogram quantiles exactly
                # when an operator investigates apply latency
                cache.expire()
                self._settle_standing(nm, cache)
                continue
            applied_ts: Optional[int] = None
            applied_msgs: List[Tuple[int, str, Any, int]] = []
            with tracing.span("stream.apply", schema=nm,
                              messages=len(msgs)) as sp, \
                    metrics.registry().timer(metrics.STREAM_APPLY).time():
                for m in msgs:
                    try:
                        if m.kind == CHANGE:
                            cache.validate(m.payload or {})
                            cache.put(m.fid, m.payload or {}, m.ts_ms)
                        elif m.kind == DELETE:
                            cache.remove(m.fid)
                        elif m.kind == CLEAR:
                            cache.clear()
                    except Exception as e:
                        # decoded but unappliable (bad payload types): same
                        # quarantine path as an undecodable message
                        self._quarantine(nm, m.fid or m.kind, e, "apply")
                        continue
                    applied_ts = m.ts_ms
                    if self._journal is not None:
                        applied_msgs.append(
                            (m.kind, m.fid, m.payload, m.ts_ms))
                    for fn in listeners:
                        try:
                            fn(m)
                        except Exception:
                            # a throwing listener is an observer bug, not a
                            # data fault: log it, keep the message (it
                            # applied) and the consumer alive
                            import logging

                            logging.getLogger(__name__).warning(
                                "feature listener failed on %s/%s",
                                nm, m.fid or m.kind, exc_info=True,
                            )
                    total += 1
                if applied_ts is not None:
                    lag_ms = max(int(time.time() * 1000) - applied_ts, 0)
                    sp.set(lag_ms=lag_ms)
                    metrics.registry().gauge(metrics.STREAM_LAG).set(lag_ms)
                    metrics.registry().gauge(
                        f"{metrics.STREAM_LAG}.{nm}"
                    ).set(lag_ms)
            if applied_msgs and self._journal is not None:
                # journaled WITH the post-batch source offsets: recovery
                # replays the batch into the cache, then resumes the topic
                # consumer past it — exactly-once for acked batches
                # (docs/RESILIENCE.md §8, docs/PROTOCOL.md stream resume)
                self._journal.append({
                    "kind": "stream-batch", "schema": nm,
                    "offsets": list(self._offsets[nm]),
                    "msgs": [list(t) for t in applied_msgs],
                })
            if applied_ts is not None:
                # per-poll applied-batch counter (docs/OBSERVABILITY.md):
                # with the epoch gauge below, the subscription-staleness
                # pair /metrics and /debug/queries expose
                metrics.inc(metrics.STREAM_POLL_BATCHES)
                metrics.inc(f"{metrics.STREAM_POLL_BATCHES}.{nm}")
            cache.expire()
            self._settle_standing(nm, cache)
        return total

    def _settle_standing(self, nm: str, cache: LiveFeatureCache) -> None:
        """Post-apply bookkeeping for one schema's poll round: export the
        window's mutation epoch as a gauge (``stream.epoch.<schema>`` —
        the staleness anchor standing results are versioned against) and
        fold any buffered cache events into the standing groups (ONE
        delta pass per applied batch, docs/STANDING.md)."""
        from geomesa_tpu import metrics

        metrics.registry().gauge(f"{metrics.STREAM_EPOCH}.{nm}").set(
            cache.epoch
        )
        if self.standing is not None:
            self.standing.settle(nm)

    # -- local query runner (KafkaQueryRunner analog) ----------------------
    def _masked(self, name: str, ecql: "str | ir.Filter"):
        ft = self._schemas[name]
        cache = self._caches[name]
        batch = cache.batch()
        if batch.n == 0:
            return ft, cache, batch, np.zeros(0, dtype=bool)
        f = parse_ecql(ecql) if isinstance(ecql, str) else ecql
        cf = compile_filter(f, ft, cache.dicts)
        # validity: features with null geometry are invisible to queries
        # (the reference's cache requires a geometry; we tolerate and mask)
        valid = np.ones(batch.n, dtype=bool)
        g = ft.geom_field
        if g is not None and g + "__x" in batch.columns:
            valid &= np.isfinite(batch.columns[g + "__x"])
        cand = cache.candidate_rows(f, batch)
        if cand is not None and len(cand) < batch.n:
            sub = ColumnBatch(
                {k: v[cand] for k, v in batch.columns.items()}, len(cand)
            )
            sub_mask = cf.exact_mask(sub.columns, len(cand))
            mask = np.zeros(batch.n, dtype=bool)
            mask[cand[sub_mask]] = True
        else:
            mask = cf.exact_mask(batch.columns, batch.n)
        return ft, cache, batch, mask & valid

    def query(self, name: str, ecql: "str | ir.Filter" = "INCLUDE") -> ColumnBatch:
        self.poll(name)
        _, _, batch, mask = self._masked(name, ecql)
        if batch.n == 0:
            return batch
        return batch.select(mask)

    def count(self, name: str, ecql: "str | ir.Filter" = "INCLUDE") -> int:
        self.poll(name)
        _, _, _, mask = self._masked(name, ecql)
        return int(mask.sum())

    def density(self, name: str, ecql: "str | ir.Filter" = "INCLUDE",
                bbox=(-180, -90, 180, 90), width: int = 256,
                height: int = 256) -> np.ndarray:
        """Density over the live window (DensityScan on the stream)."""
        self.poll(name)
        ft, _, batch, mask = self._masked(name, ecql)
        g = ft.geom_field
        if batch.n == 0:
            return np.zeros((height, width), np.float32)
        xs = batch.columns[g + "__x"]
        ys = batch.columns[g + "__y"]
        if self.prefer_device:
            import jax.numpy as jnp

            grid = kdensity.density_grid(
                jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask),
                tuple(bbox), width, height, None, jnp,
            )
            return np.asarray(grid)
        return np.asarray(kdensity.density_grid(
            xs, ys, mask, tuple(bbox), width, height, None, np
        ))

    def stats(self, name: str, stat_spec: str,
              ecql: "str | ir.Filter" = "INCLUDE"):
        from geomesa_tpu.kernels.stats_scan import decode_enum_keys
        from geomesa_tpu.stats import parse_stat

        self.poll(name)
        _, cache, batch, mask = self._masked(name, ecql)
        stat = parse_stat(stat_spec)
        if batch.n:
            sel = batch.select(mask)
            if sel.n:
                stat.observe(sel.columns)
                decode_enum_keys(stat, cache.dicts)
        return stat


def playback(ds: "StreamingDataset", name: str, data: Dict[str, Sequence],
             fids: Sequence[str], dtg_ms: Sequence[int], rate: float = 10.0,
             batch_ms: int = 1000, sleep: bool = False):
    """Replay a dtg-ordered dataset onto the stream (tools `playback`):
    batches of ``batch_ms`` event-time are produced at ``rate``x speed."""
    order = np.argsort(np.asarray(dtg_ms, np.int64), kind="stable")
    ts = np.asarray(dtg_ms, np.int64)[order]
    keys = list(data)
    start = 0
    while start < len(order):
        end = start
        t0 = ts[start]
        while end < len(order) and ts[end] - t0 < batch_ms:
            end += 1
        rows = order[start:end]
        ds.write(
            name,
            {k: [data[k][i] for i in rows] for k in keys},
            [fids[i] for i in rows],
            ts_ms=ts[start:end],
        )
        if sleep and rate > 0:
            time.sleep(batch_ms / 1000.0 / rate)
        start = end
