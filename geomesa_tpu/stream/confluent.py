"""Confluent-style schema-registry Avro streaming ingest.

Reference parity: geomesa-kafka-confluent (ConfluentKafkaDataStore +
ConfluentFeatureSerializer): feature messages on the wire are
**registry-framed Avro** — a magic byte, a 4-byte big-endian schema id,
then the Avro binary record — and consumers resolve the WRITER schema by
id against their own READER schema, so producers and consumers can evolve
schemas independently (the Confluent wire format and resolution rules).

This module provides the TPU-side equivalents over the in-process stream
layer (:mod:`geomesa_tpu.stream.messages` / ``StreamingDataset``):

- :class:`SchemaRegistry` — subject -> versioned schemas with global ids
  (the Confluent Schema Registry's data model, in process; swap in a
  remote registry by giving the same three methods an HTTP backing).
- :class:`ConfluentSerializer` — feature dict -> framed bytes.
- :class:`ConfluentDeserializer` — framed bytes -> (fid, attributes),
  applying Avro schema resolution: fields matched by name, writer-only
  fields skipped, reader-only fields filled from their defaults.

Deletes follow Kafka semantics: a tombstone (``None`` payload) keyed by
feature id.
"""

from __future__ import annotations

import io
import json
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from geomesa_tpu.io.avro_io import (
    _read_value, _write_row, avro_schema, read_bytes,
)
from geomesa_tpu.schema.feature_type import FeatureType

#: Confluent wire format magic byte
MAGIC_BYTE = 0


class SchemaRegistry:
    """In-process schema registry (Confluent data model: globally unique
    schema ids; per-subject version lists; structurally identical schemas
    deduplicate to one id)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_id: Dict[int, Dict[str, Any]] = {}
        self._ids_by_canon: Dict[str, int] = {}
        self._subjects: Dict[str, List[int]] = {}
        self._next = 1

    @staticmethod
    def _canon(schema: Dict[str, Any]) -> str:
        return json.dumps(schema, sort_keys=True, separators=(",", ":"))

    def register(self, subject: str, schema: Dict[str, Any]) -> int:
        """Register a schema under a subject; returns its global id
        (existing id when the schema is already registered)."""
        canon = self._canon(schema)
        with self._lock:
            sid = self._ids_by_canon.get(canon)
            if sid is None:
                sid = self._next
                self._next += 1
                self._ids_by_canon[canon] = sid
                self._by_id[sid] = json.loads(canon)
            versions = self._subjects.setdefault(subject, [])
            if sid not in versions:
                versions.append(sid)
            return sid

    def by_id(self, schema_id: int) -> Dict[str, Any]:
        schema = self._by_id.get(schema_id)
        if schema is None:
            raise KeyError(f"no schema with id {schema_id} in the registry")
        return schema

    def latest(self, subject: str) -> Tuple[int, Dict[str, Any]]:
        versions = self._subjects.get(subject)
        if not versions:
            raise KeyError(f"no subject {subject!r} in the registry")
        sid = versions[-1]
        return sid, self._by_id[sid]

    def versions(self, subject: str) -> List[int]:
        return list(self._subjects.get(subject, ()))


def _frame(schema_id: int, payload: bytes) -> bytes:
    return struct.pack(">bI", MAGIC_BYTE, schema_id) + payload


def _unframe(data: bytes) -> Tuple[int, bytes]:
    if len(data) < 5 or data[0] != MAGIC_BYTE:
        raise ValueError(
            "not a registry-framed Avro message (missing magic byte 0)"
        )
    (schema_id,) = struct.unpack(">I", data[1:5])
    return schema_id, data[5:]


class ConfluentSerializer:
    """Feature -> framed Avro bytes under a registered schema."""

    def __init__(self, registry: SchemaRegistry, subject: str,
                 ft: FeatureType):
        self.ft = ft
        self.schema = avro_schema(ft)
        self.schema_id = registry.register(subject, self.schema)
        self._names = [f["name"] for f in self.schema["fields"]]
        self._types = [f["type"] for f in self.schema["fields"]]

    def serialize(self, fid: str, attributes: Dict[str, Any]) -> bytes:
        buf = io.BytesIO()
        row = tuple(
            fid if n == "__fid__" else attributes.get(n)
            for n in self._names
        )
        _write_row(buf, row, self._types)
        return _frame(self.schema_id, buf.getvalue())


class ConfluentDeserializer:
    """Framed Avro bytes -> (fid, attributes) under the READER schema,
    resolving the writer schema from the registry by id (Avro schema
    resolution: name-matched fields, writer-only fields decoded and
    dropped, reader-only fields filled from their declared defaults)."""

    def __init__(self, registry: SchemaRegistry,
                 reader: "FeatureType | Dict[str, Any]"):
        self.registry = registry
        self.reader = (avro_schema(reader)
                       if isinstance(reader, FeatureType) else reader)
        self._reader_names = {f["name"] for f in self.reader["fields"]}
        self._defaults = {
            f["name"]: f.get("default")
            for f in self.reader["fields"] if f["name"] != "__fid__"
        }

    def deserialize(self, data: bytes) -> Tuple[str, Dict[str, Any]]:
        schema_id, payload = _unframe(data)
        writer = self.registry.by_id(schema_id)
        buf = io.BytesIO(payload)
        decoded: Dict[str, Any] = {}
        for f in writer["fields"]:
            v = _read_value(buf, f["type"])
            if f["name"] in self._reader_names:
                decoded[f["name"]] = v
            # writer-only field: decoded (the bytes must be consumed) and
            # dropped — Avro resolution's "ignored" rule
        fid = str(decoded.pop("__fid__", ""))
        attrs = dict(self._defaults)
        attrs.update(decoded)
        return fid, attrs


def attach_confluent(sds, name: str, registry: SchemaRegistry):
    """Wire a ``StreamingDataset`` schema for framed-Avro ingest: returns
    (serializer, ingest) where ``ingest(data: bytes | None, fid=None,
    ts_ms=None)`` routes one Kafka-style record into the live cache —
    framed Avro value = upsert, ``None`` value + fid = tombstone delete
    (ConfluentKafkaDataStore's consumer loop semantics).

    Observability (docs/OBSERVABILITY.md): each record applies under a
    ``stream.apply`` span + timer, and the ``stream.lag`` gauge tracks
    poll→apply latency (apply wall-clock minus the record's event time) —
    the same lag signal ``StreamingDataset.poll`` exposes, here measured
    at the broker-facing decode/apply edge.

    Resilience (docs/RESILIENCE.md, ``stream.confluent.ingest`` fault
    point): a poison record — unframeable bytes, an unresolvable schema
    id, a malformed geometry, a keyless tombstone — must never kill the
    consumer loop: it QUARANTINES (counted in
    ``stream.confluent.quarantined`` + the per-schema breakdown, recorded
    through the audit degradation trail) and ``ingest`` returns ``""``;
    the consumer's offset advances past it. Corruption quarantines —
    there is nothing to retry in a broken payload; transient broker
    errors live on the broker client's side of this edge and are its
    retry domain."""
    import time as _time

    from geomesa_tpu import metrics, resilience, tracing

    ft = sds.get_schema(name)
    ser = ConfluentSerializer(registry, name, ft)
    de = ConfluentDeserializer(registry, ft)
    # metric objects are invariant for the attachment's lifetime — resolve
    # them once here, not per record under the registry lock on the
    # broker-facing hot path
    apply_timer = metrics.registry().timer(metrics.STREAM_APPLY)
    lag_gauge = metrics.registry().gauge(metrics.STREAM_LAG)
    lag_gauge_schema = metrics.registry().gauge(f"{metrics.STREAM_LAG}.{name}")

    def ingest(data: Optional[bytes], fid: Optional[str] = None,
               ts_ms: Optional[int] = None,
               offset: Optional[int] = None) -> str:
        with tracing.span("stream.apply", schema=name, edge="confluent") \
                as sp, apply_timer.time():
            try:
                resilience.fault_point("stream.confluent.ingest",
                                       schema=name, fid=fid)
                out = _ingest(data, fid, ts_ms, sp)
            except resilience.QueryTimeoutError:
                raise
            except Exception as e:
                # poison-record quarantine (never kill the consumer)
                metrics.inc("stream.confluent.quarantined")
                metrics.inc(f"stream.confluent.quarantined.{name}")
                resilience.record_skip(
                    "stream.confluent.ingest", f"{name}/{fid or '?'}", e,
                    phase="decode",
                )
                sp.set(quarantined=True, error=type(e).__name__)
                return ""
        if offset is not None and getattr(sds, "_journal", None) is not None:
            # durable broker-offset high-water mark (docs/RESILIENCE.md §8,
            # docs/PROTOCOL.md stream resume): once this record is down, a
            # restarted consumer resumes PAST this broker offset via
            # confluent_resume_offset — the acked record can never be lost
            # (the feature data itself rides the stream-batch records
            # journaled by StreamingDataset.poll)
            sds._journal.append({
                "kind": "confluent-offset", "schema": name,
                "offset": int(offset), "fid": out,
            })
        return out

    def _ingest(data: Optional[bytes], fid: Optional[str],
                ts_ms: Optional[int], sp) -> str:
        now = int(_time.time() * 1000) if ts_ms is None else int(ts_ms)
        if ts_ms is not None:
            # lag is only meaningful against a real record timestamp — a
            # producer that sets none would pin the gauge at 0 and mask
            # genuine consumer lag (same guard as StreamingDataset.poll's
            # applied_ts check)
            lag_ms = max(int(_time.time() * 1000) - int(ts_ms), 0)
            sp.set(lag_ms=lag_ms)
            lag_gauge.set(lag_ms)
            lag_gauge_schema.set(lag_ms)
        if data is None:
            if not fid:
                raise ValueError("a tombstone needs a feature id")
            sds.delete(name, fid)
            return fid
        rid, attrs = de.deserialize(data)
        rid = fid or rid
        import math

        cols: Dict[str, Any] = {}
        for a in ft.attributes:
            v = attrs.get(a.name)
            if a.is_geom:
                if a.is_point and isinstance(v, str):
                    from geomesa_tpu.utils.geometry import parse_wkt

                    g = parse_wkt(v)
                    cols[a.name] = [(g.x, g.y)]
                else:
                    cols[a.name] = [v]
            elif a.type == "date":
                cols[a.name] = [now if v is None else int(v)]
            elif a.type == "string":
                cols[a.name] = ["" if v is None else str(v)]
            elif a.type in ("float32", "float64"):
                cols[a.name] = [math.nan if v is None else float(v)]
            elif a.type == "bool":
                cols[a.name] = [bool(v)]
            elif a.type == "json":
                cols[a.name] = [v if isinstance(v, str) else json.dumps(v)]
            else:
                cols[a.name] = [0 if v is None else int(v)]
        sds.write(name, cols, [rid], ts_ms=[now])
        return rid

    return ser, ingest


def confluent_resume_offset(sds, name: str) -> int:
    """Highest broker offset journaled for ``name``'s Confluent edge, or
    ``-1`` when none was recorded — seek the external consumer to
    ``resume + 1`` after a restart and no acked record replays twice
    (docs/PROTOCOL.md stream-offset resume)."""
    j = getattr(sds, "_journal", None)
    if j is None:
        return -1
    hi = -1
    for rec in j.records():
        if (rec.get("kind") == "confluent-offset"
                and rec.get("schema") == name):
            hi = max(hi, int(rec.get("offset", -1)))
    return hi
