"""Streaming layer: live feature caches over message topics (Kafka analog)
and hot/cold tiering (Lambda analog)."""

from geomesa_tpu.stream.messages import GeoMessage, MessageBus, Topic  # noqa: F401
from geomesa_tpu.stream.live import LiveFeatureCache, StreamingDataset  # noqa: F401
from geomesa_tpu.stream.lambda_store import LambdaDataset  # noqa: F401
