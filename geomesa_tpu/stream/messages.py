"""Message layer: GeoMessage wire format + in-process topic bus.

Reference parity (geomesa-kafka, SURVEY.md §2.5): features travel as
``GeoMessage``s (utils/GeoMessage.scala — Change/Delete/Clear) on
partitioned topics; consumers track offsets. The in-process ``MessageBus``
plays the broker's role for single-host deployments and tests (the
reference's EmbeddedKafka analog); the byte wire format mirrors
GeoMessageSerializer so a real broker can be swapped in without touching
producers/consumers.

Wire format (little-endian):
    [1: kind (0=change 1=delete 2=clear)][8: timestamp ms]
    [2: fid len][fid utf8][4: payload len][payload json utf8]
"""

from __future__ import annotations

import json
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CHANGE, DELETE, CLEAR = 0, 1, 2
_KINDS = {0: "change", 1: "delete", 2: "clear"}


@dataclass(frozen=True)
class GeoMessage:
    kind: int
    ts_ms: int
    fid: str = ""
    payload: Optional[Dict[str, Any]] = None

    @staticmethod
    def change(fid: str, attributes: Dict[str, Any], ts_ms: int) -> "GeoMessage":
        return GeoMessage(CHANGE, ts_ms, fid, attributes)

    @staticmethod
    def delete(fid: str, ts_ms: int) -> "GeoMessage":
        return GeoMessage(DELETE, ts_ms, fid)

    @staticmethod
    def clear(ts_ms: int) -> "GeoMessage":
        return GeoMessage(CLEAR, ts_ms)

    def serialize(self) -> bytes:
        fid_b = self.fid.encode()
        payload_b = b"" if self.payload is None else json.dumps(self.payload).encode()
        return (
            struct.pack("<BqH", self.kind, self.ts_ms, len(fid_b))
            + fid_b
            + struct.pack("<I", len(payload_b))
            + payload_b
        )

    @staticmethod
    def deserialize(data: bytes) -> "GeoMessage":
        kind, ts, fid_len = struct.unpack_from("<BqH", data, 0)
        off = 11
        fid = data[off : off + fid_len].decode()
        off += fid_len
        (plen,) = struct.unpack_from("<I", data, off)
        off += 4
        payload = json.loads(data[off : off + plen]) if plen else None
        return GeoMessage(kind, ts, fid, payload)


class Topic:
    """An append-only partitioned log with consumer offsets (broker analog).

    Messages are stored serialized — producers/consumers always cross the
    byte boundary, keeping the wire format honest."""

    def __init__(self, name: str, partitions: int = 4):
        self.name = name
        self.partitions = partitions
        self._logs: List[List[bytes]] = [[] for _ in range(partitions)]
        self._lock = threading.Lock()

    def send(self, msg: GeoMessage):
        # fid-hash partitioner (reference GeoMessageSerializer partitioner):
        # same feature id always lands on the same partition, preserving
        # per-feature ordering
        # fid-hash partitioner; control messages (CLEAR) go to partition 0
        # only — the consumer reads every partition, so one delivery suffices
        # and listeners fire exactly once
        p = (hash(msg.fid) & 0x7FFFFFFF) % self.partitions if msg.fid else 0
        data = msg.serialize()
        with self._lock:
            self._logs[p].append(data)

    def poll(self, offsets: List[int], max_messages: int = 10_000,
             on_error=None) -> Tuple[List[GeoMessage], List[int]]:
        """Read from per-partition ``offsets``; returns (messages, new offsets).

        ``on_error(partition, offset, raw_bytes, exc)`` — when given, an
        undecodable (poison) message is reported and SKIPPED, and the offset
        still advances past it; without it, decode errors raise (a consumer
        that doesn't opt into quarantine must not silently lose data)."""
        from geomesa_tpu import resilience

        out: List[GeoMessage] = []
        new = list(offsets)
        with self._lock:
            for p in range(self.partitions):
                log = self._logs[p]
                end = min(len(log), offsets[p] + max_messages)
                for i in range(offsets[p], end):
                    try:
                        resilience.fault_point(
                            "stream.poll.decode", topic=self.name,
                            partition=p, offset=i,
                        )
                        out.append(GeoMessage.deserialize(log[i]))
                    except Exception as e:
                        if on_error is None:
                            raise
                        on_error(p, i, log[i], e)
                new[p] = end
        out.sort(key=lambda m: m.ts_ms)
        return out, new

    def end_offsets(self) -> List[int]:
        with self._lock:
            return [len(log) for log in self._logs]


class MessageBus:
    """Topic registry (the in-proc 'broker')."""

    def __init__(self):
        self._topics: Dict[str, Topic] = {}
        self._lock = threading.Lock()

    def create(self, name: str, partitions: int = 4) -> Topic:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = Topic(name, partitions)
            return self._topics[name]

    def topic(self, name: str) -> Topic:
        t = self._topics.get(name)
        if t is None:
            raise KeyError(f"no topic {name!r}")
        return t

    def delete(self, name: str):
        with self._lock:
            self._topics.pop(name, None)
