"""Predicate IR -> fused columnar mask kernel.

The FastFilterFactory analog (reference
geomesa-filter/.../factory/FastFilterFactory.scala:40,410): instead of
rewriting a CQL tree into per-row fast evaluators, we compile it into ONE
vectorized boolean expression over column arrays. The compiled function is
backend-generic — pass ``numpy`` for the host path or ``jax.numpy`` inside a
jit'd scan kernel; XLA fuses the whole mask into the surrounding aggregation.

String predicates are resolved to dictionary codes at compile time (the device
never sees strings). Geometry literals become captured numpy edge buffers; the
point-in-polygon test is even-odd crossing parity, vectorized N points × E
edges per polygon.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.schema.columns import DictionaryEncoder
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.utils import geometry as geo


@dataclass
class CompiledFilter:
    """A compiled mask kernel. ``fn(cols, xp)`` -> bool mask array.

    When the filter contains spatial predicates over extent (line/polygon)
    columns, ``fn`` is a *coarse* mask — a guaranteed superset of the exact
    matches (polarity-corrected through NOT) — and ``refine`` holds the
    exact host evaluator: ``refine(cols) -> bool mask`` over candidate rows,
    needing ``refine_columns`` (the ``<geom>__wkt`` host columns) in
    addition to ``columns``. The executor applies refine to coarse-true
    rows only; it may clear bits, never set them. ``refine is None`` means
    ``fn`` is already exact (the reference evaluates exact JTS predicates
    everywhere — FastFilterFactory.scala:395; here the split keeps the
    device kernel dense while candidates are refined on host).
    """

    fn: Callable
    columns: List[str]
    ecql: Optional[str] = None
    refine: Optional[Callable] = None
    refine_columns: Optional[List[str]] = None
    #: device-evaluable mask of rows whose membership is UNCERTAIN at f32
    #: precision (f64 column values colliding with an f32-rounded query
    #: bound). None = the f32 evaluation is provably exact. The executor
    #: counts band rows once per (plan, store version): zero (the usual
    #: case) certifies the device result; nonzero reroutes to the
    #: device-coarse + exact-f64-host-refine path. This is how "f64 never
    #: reaches the device" coexists with reference-exact boundary
    #: semantics.
    band: Optional[Callable] = None
    #: True when ``refine`` exists ONLY as the band fallback: with a clean
    #: band certificate the device mask is already exact and refinement is
    #: skipped entirely
    refine_only_if_band: bool = False

    def __call__(self, cols, xp=np):
        return self.fn(cols, xp)

    def exact_mask(self, cols: Dict[str, np.ndarray], n: int) -> np.ndarray:
        """Full exact 1-D host mask over ``n`` rows: coarse mask, then the
        refinement tree on coarse-true candidates. ``cols`` must include
        ``refine_columns`` when refinement is present."""
        m = np.asarray(self.fn(cols, np))
        if m.ndim == 0:
            m = np.full(n, bool(m))
        else:
            m = m.astype(bool, copy=True)
        if self.refine is not None:
            idx = np.nonzero(m)[0]
            if len(idx):
                keep = self.refine_rows({k: v[idx] for k, v in cols.items()}, len(idx))
                m[idx[~keep]] = False
        return m

    def refine_rows(self, cols_rows: Dict[str, np.ndarray], n: int) -> np.ndarray:
        """Run the exact refinement tree over already-subset candidate rows.
        Returns the keep mask (bool, length ``n``)."""
        keep = np.asarray(self.refine(cols_rows, np))
        if keep.ndim == 0:
            return np.full(n, bool(keep))
        return keep.astype(bool)


def _geom_cols(ft: FeatureType, prop: str) -> Dict[str, str]:
    a = ft.attr(prop)
    if not a.is_geom:
        raise ValueError(f"attribute {prop!r} is not a geometry")
    if a.is_point:
        return {"x": prop + "__x", "y": prop + "__y", "point": "1"}
    return {
        "x": prop + "__x", "y": prop + "__y",
        "xmin": prop + "__xmin", "ymin": prop + "__ymin",
        "xmax": prop + "__xmax", "ymax": prop + "__ymax",
    }


def _pip_fn(g: geo.Geometry, xcol: str, ycol: str, need_band=None,
            neg: bool = False):
    """Point-in-(multi)polygon via even-odd crossing parity (holes included
    naturally by the even-odd rule). Returns fn(cols, xp) -> mask.

    ``need_band(col, *bounds)``: f32-uncertainty registration for the
    rectangle fast path (bbox boundary collisions), with NOT-polarity
    rounding via ``neg``. General polygon edges remain f32-evaluated on
    device (near-edge rows within ~1e-5 deg of an edge may classify
    differently than exact f64 — the rectangle case, which CQL BBOX
    compiles to, is band-exact)."""
    polys = g.polygons if isinstance(g, geo.MultiPolygon) else (g,)
    # Fast path: single axis-aligned rectangle -> bbox compare (the loose-bbox
    # trick; reference Z3IndexKeySpace.useFullFilter:235).
    if len(polys) == 1 and isinstance(polys[0], geo.Polygon) and polys[0].is_rectangle():
        xmin, ymin, xmax, ymax = polys[0].bounds()
        if need_band is not None:
            need_band(xcol, xmin, xmax)
            need_band(ycol, ymin, ymax)
            return _f32_box_fn(xcol, ycol, (xmin, ymin, xmax, ymax), neg)

        def rect(cols, xp):
            x, y = cols[xcol], cols[ycol]
            return (x >= xmin) & (x <= xmax) & (y >= ymin) & (y <= ymax)

        return rect

    from geomesa_tpu.kernels import pallas_kernels as pk

    tables = [pk.polygon_edge_tables(p) for p in polys]
    pallas_ok = all(pk.edges_fit(packed.shape[1]) for _, packed in tables)

    def pip(cols, xp):
        x = cols[xcol]
        y = cols[ycol]
        if xp is not np and pallas_ok:
            # TPU: edge table pinned in VMEM, point blocks streamed through
            # the VPU — the [block, E] intermediate never touches HBM.
            # Under a NamedSharding'd mesh the kernel runs per device via
            # an inner shard_map over the local block.
            mesh = pk.current_mesh()
            run = None
            if mesh is None and pk.use_pallas():
                pk.record_dispatch("pip", "pallas")
                run = lambda packed: pk.pip_mask(  # noqa: E731
                    x, y, packed, interpret=pk.interpret_mode()
                )
            elif (
                mesh is not None and x.ndim == 2
                and pk.use_pallas_sharded(mesh, x.shape[0], kernel="pip")
            ):
                pk.record_dispatch("pip", "pallas-sharded")
                run = lambda packed: pk.pip_mask_sharded(  # noqa: E731
                    x, y, packed, mesh, interpret=pk.interpret_mode()
                )
            if run is not None:
                out = None
                for _, packed in tables:
                    inside = run(packed)
                    out = inside if out is None else (out | inside)
                return out
            # record WHY the hand kernel was skipped (the uneven-mesh
            # case records inside use_pallas_sharded)
            if mesh is None:
                pk.record_dispatch("pip", "xla-fallback(no pallas backend)")
            elif x.ndim != 2:
                pk.record_dispatch("pip", "xla-fallback(1-D layout)")
        elif xp is not np:
            pk.record_dispatch(
                "pip", "xla-fallback(edge table exceeds the VMEM cap)")
        # backend-generic broadcast path: trailing-axis broadcast handles
        # 1-D host shards and [S, L] device layouts alike
        out = None
        for (x1, y1, x2, y2, slope), packed in tables:
            if xp is not np:  # device: reuse the f32 rows already packed
                x1, y1, y2, slope = (xp.asarray(packed[i]) for i in range(4))
            yb = y[..., None]
            cond = (y1 > yb) != (y2 > yb)
            xint = x1 + (yb - y1) * slope
            crossings = (cond & (x[..., None] < xint)).sum(axis=-1)
            inside = (crossings % 2) == 1
            out = inside if out is None else (out | inside)
        return out

    return pip


def _edges_of(g: geo.Geometry) -> np.ndarray:
    """[E, 4] boundary segments of a line/polygon literal."""
    from geomesa_tpu import geofn

    return geofn._edges(g).astype(np.float64)


def _boundary_endpoints(g: geo.Geometry) -> np.ndarray:
    """[K, 2] mod-2 boundary points of a (multi)linestring literal."""
    lines = g.lines if isinstance(g, geo.MultiLineString) else [g]
    counts: Dict[tuple, int] = {}
    for ls in lines:
        for pt in (tuple(ls.coords[0]), tuple(ls.coords[-1])):
            counts[pt] = counts.get(pt, 0) + 1
    pts = [p for p, c in counts.items() if c % 2 == 1]
    return np.asarray(pts, np.float64).reshape(-1, 2)


def _on_segments_fn(E: np.ndarray, xcol: str, ycol: str):
    """Coarse vectorized point-on-any-segment test (backend-generic).

    The collinearity threshold is relative to the f32 rounding error of the
    cross product (~1e-5 of the term magnitudes ≈ 80 f32 ulps), so on an
    f32 device path this is a guaranteed *superset* of the exact f64 test —
    near-misses are cleared by the host refinement pass. Broadcast is
    [..., 1] x [E] so the kernel stays dense on device."""
    x1, y1, x2, y2 = E[:, 0], E[:, 1], E[:, 2], E[:, 3]
    dx, dy = x2 - x1, y2 - y1
    pad = 1e-5 * np.maximum(np.abs(E).max(), 1.0)
    lox, hix = np.minimum(x1, x2) - pad, np.maximum(x1, x2) + pad
    loy, hiy = np.minimum(y1, y2) - pad, np.maximum(y1, y2) + pad

    def fn(cols, xp):
        x, y = cols[xcol][..., None], cols[ycol][..., None]
        cross = dx * (y - y1) - dy * (x - x1)
        err = 1e-5 * (
            xp.abs(dx) * (xp.abs(y) + np.abs(y1) + 1.0)
            + xp.abs(dy) * (xp.abs(x) + np.abs(x1) + 1.0)
        )
        inb = (x >= lox) & (x <= hix) & (y >= loy) & (y <= hiy)
        return ((xp.abs(cross) <= err) & inb).any(axis=-1)

    return fn


def _point_eq_fn(pts: np.ndarray, xcol: str, ycol: str):
    """Point-column equality against a set of literal coordinates."""

    def fn(cols, xp):
        x, y = cols[xcol], cols[ycol]
        out = None
        for px, py in pts:
            m = (x == px) & (y == py)
            out = m if out is None else (out | m)
        if out is None:
            return xp.asarray(False)
        return out

    return fn


_FALSE = lambda cols, xp: np.False_  # noqa: E731  broadcasts like a scalar
_TRUE = lambda cols, xp: np.True_  # noqa: E731


def during_device_bounds(ft: FeatureType, lo_ms: int,
                         hi_ms: int) -> Tuple[int, int, int, int]:
    """Quantize a [lo_ms, hi_ms] interval to the device time representation:
    ``(lo_bin, lo_off, hi_bin, hi_off)`` against the (bin, scaled-offset)
    int32 column pair. ONE implementation shared by the baked During
    compile below and the batched query-template kernels
    (filter/template.py) — the two must quantize identically or a batched
    member's time mask could drift a row off its serial execution."""
    from geomesa_tpu.curves.binned_time import BinnedTime

    bt = BinnedTime(ft.time_period)
    scale = bt.off_scale
    CLAMP = 2**45  # ~±1100 years; keeps bins in int32
    lo = max(min(lo_ms, CLAMP), -CLAMP)
    hi = max(min(hi_ms, CLAMP), -CLAMP)
    lo_b, lo_o = (int(v[0]) for v in bt.to_bin_and_offset(np.asarray([lo])))
    hi_b, hi_o = (int(v[0]) for v in bt.to_bin_and_offset(np.asarray([hi])))
    # floor-quantize both sides; quantization fuzz is < scale ms
    return lo_b, lo_o // scale, hi_b, hi_o // scale


def _f32_box_fn(xc: str, yc: str, box, neg: bool):
    """Backend-identical f32 box test (columns cast to f32 on the host too,
    so the coarse mask means the same thing on both paths): inclusive
    bounds when a superset is needed (even NOT-polarity), strict when a
    subset is (odd)."""
    x0, y0, x1, y1 = (float(np.float32(v)) for v in box)

    def fn(cols, xp):
        x = xp.asarray(cols[xc]).astype(xp.float32)
        y = xp.asarray(cols[yc]).astype(xp.float32)
        if neg:
            return (x > x0) & (x < x1) & (y > y0) & (y < y1)
        return (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)

    return fn


def _point_exact_fns(g: geo.Geometry, dim: int, xc: str, yc: str):
    """Exact host (f64) evaluators for a point column vs a literal, keyed by
    op — the refinement-side counterparts of the coarse kernels below."""
    from geomesa_tpu import geofn

    def pip(cols, xp=np):
        return g.contains_points(
            np.asarray(cols[xc], np.float64), np.asarray(cols[yc], np.float64)
        )

    if dim == 0:
        pts = (
            np.asarray([[g.x, g.y]])
            if isinstance(g, geo.Point)
            else np.asarray([[p.x, p.y] for p in g.points])
        )
        eq = _point_eq_fn(pts, xc, yc)
        return {
            "eq": eq,
            "disjoint": lambda cols, xp=np: ~eq(cols, np),
        }
    if dim == 1:
        ends = _boundary_endpoints(g)
        at_end = _point_eq_fn(ends, xc, yc) if len(ends) else _FALSE
        return {
            "intersects": pip,  # LineString.contains_points = exact on-segment
            "disjoint": lambda cols, xp=np: ~pip(cols, np),
            "within": lambda cols, xp=np: pip(cols, np) & ~np.asarray(at_end(cols, np)),
            "touches": at_end,
        }

    def on_bnd(cols, xp=np):
        return geofn._on_boundary_of(
            g, np.asarray(cols[xc], np.float64), np.asarray(cols[yc], np.float64)
        )

    return {
        "intersects": pip,  # boundary-inclusive ring containment
        "disjoint": lambda cols, xp=np: ~pip(cols, np),
        "within": lambda cols, xp=np: pip(cols, np) & ~on_bnd(cols, np),
        "touches": on_bnd,
    }


def _point_spatial_fn(node, xc: str, yc: str, exact: bool, neg: bool,
                      need_refine, need_band=None) -> Callable:
    """Spatial predicate for a POINT column vs a geometry literal.

    A point's interior is the point itself, so every DE-9IM predicate
    reduces to membership / boundary tests (SpatialRelationFunctions.scala
    semantics, evaluated columnar). Polygon-literal interior tests
    (intersects/disjoint) run fully in the scan kernel; boundary- and
    coincidence-sensitive ops (line/point literals, touches, within) are
    not robust at f32 device precision, so they emit a relaxed-epsilon
    coarse superset plus an exact f64 host refinement."""
    g, op = node.geom, node.op
    dim = (
        0 if isinstance(g, (geo.Point, geo.MultiPoint))
        else 1 if isinstance(g, (geo.LineString, geo.MultiLineString))
        else 2
    )
    if dim == 0:
        if op in ("touches", "crosses", "overlaps"):
            return _FALSE  # empty boundaries / dimension rules
        if op in ("contains", "equals") and not isinstance(g, geo.Point):
            # a single point can only contain/equal a single distinct point
            distinct = {(p.x, p.y) for p in g.points}
            if len(distinct) > 1:
                return _FALSE
        ex = _point_exact_fns(g, dim, xc, yc)
        if exact:
            return ex["disjoint"] if op == "disjoint" else ex["eq"]
        need_refine(None)  # f32 equality can collide distinct f64 values
        if neg:
            return _FALSE
        if op == "disjoint":
            return _TRUE
        pts = (
            np.asarray([[g.x, g.y]])
            if isinstance(g, geo.Point)
            else np.asarray([[p.x, p.y] for p in g.points])
        )
        return _point_eq_fn(pts, xc, yc)  # f32 eq is a superset of f64 eq
    if dim == 1:
        if op in ("contains", "crosses", "overlaps", "equals"):
            return _FALSE  # dimension rules for a single point
        ex = _point_exact_fns(g, dim, xc, yc)
        if exact:
            return ex[op]
        need_refine(None)
        if neg:
            return _FALSE
        if op == "disjoint":
            return _TRUE
        # intersects/within/touches: all lie on the (relaxed) segments
        return _on_segments_fn(_edges_of(g), xc, yc)
    # dim == 2: polygon / multipolygon literal
    if op in ("contains", "crosses", "overlaps", "equals"):
        return _FALSE
    if op == "intersects":
        return _pip_fn(g, xc, yc, None if exact else need_band, neg)
    if op == "disjoint":
        # internal complement flips the rounding polarity: disjoint's
        # superset is the complement of intersects' SUBSET
        pip_n = _pip_fn(g, xc, yc, None if exact else need_band, not neg)
        return lambda cols, xp: ~pip_n(cols, xp)
    pip = _pip_fn(g, xc, yc, None if exact else need_band, neg)
    # within/touches: boundary-sensitive -> coarse + refine
    ex = _point_exact_fns(g, dim, xc, yc)
    if exact:
        return ex[op]
    need_refine(None)
    if neg:
        return _FALSE
    if op == "within":
        return pip  # superset of the interior
    return _on_segments_fn(_edges_of(g), xc, yc)  # touches: relaxed boundary


#: parsed-geometry LRU for the refinement pass: candidate rows repeat
#: across refine calls (pagination, repeated queries) and re-parsing WKT
#: per row dominated the host refine cost (r3 verdict weak #3). Bounded
#: LRU, not clear-on-overflow: unique-geometry sweeps evict steadily
#: instead of wiping repeated candidates.
from collections import OrderedDict  # noqa: E402
from threading import Lock  # noqa: E402

_GEOM_CACHE: "OrderedDict[str, geo.Geometry]" = OrderedDict()
_GEOM_CACHE_MAX = 8192
_GEOM_CACHE_LOCK = Lock()  # the Flight sidecar refines on gRPC pool threads


_JSON_CACHE: "OrderedDict[str, object]" = OrderedDict()
_JSON_CACHE_MAX = 8192
_JSON_CACHE_LOCK = Lock()


def _parse_json_cached(s):
    import json as _json

    key = str(s)
    with _JSON_CACHE_LOCK:
        if key in _JSON_CACHE:
            _JSON_CACHE.move_to_end(key)
            return _JSON_CACHE[key]
    try:
        doc = _json.loads(key)
    except ValueError:
        doc = None
    with _JSON_CACHE_LOCK:
        while len(_JSON_CACHE) >= _JSON_CACHE_MAX:
            _JSON_CACHE.popitem(last=False)
        _JSON_CACHE[key] = doc
    return doc


def _json_path_pred(jp: "ir.JsonPath", test) -> Callable:
    """Host evaluator for a jsonPath() predicate: parse each row's stored
    document (cached) and test the extracted values (reference
    geomesa-feature-kryo json/ JSONPath pushdown — there inside the kryo
    lazy deserializer, here on the host object column)."""
    from geomesa_tpu.convert.converter import _json_path_get

    attr, path = jp.attr, jp.path

    def fn(cols, xp=np):
        col = cols[attr]
        out = np.zeros(len(col), bool)
        for i, s in enumerate(col):
            if s is None:
                continue
            doc = _parse_json_cached(s)
            if doc is None:
                continue
            vals = _json_path_get(doc, path)
            out[i] = any(v is not None and test(v) for v in vals)
        return out

    return fn


def _json_test(op: str, val) -> Callable:
    """Value test with JSON-side type coercion: numeric compare when the
    literal is numeric, else string compare."""
    import operator

    o = {
        "=": operator.eq, "<>": operator.ne, "<": operator.lt,
        "<=": operator.le, ">": operator.gt, ">=": operator.ge,
    }[op]
    numeric = isinstance(val, (int, float)) and not isinstance(val, bool)

    def test(v):
        try:
            if numeric:
                return bool(o(float(v), float(val)))
            return bool(o(str(v), str(val)))
        except (TypeError, ValueError):
            return False

    return test


def _require_json_attr(ft: FeatureType, jp: "ir.JsonPath"):
    a = ft.attr(jp.attr)
    if a.type != "json":
        raise ValueError(
            f"jsonPath() requires a Json attribute; {jp.attr!r} is {a.type}"
        )


def _parse_wkt_cached(w) -> geo.Geometry:
    if isinstance(w, geo.Geometry):
        return w
    s = str(w)
    with _GEOM_CACHE_LOCK:
        g = _GEOM_CACHE.get(s)
        if g is not None:
            _GEOM_CACHE.move_to_end(s)
            return g
    g = geo.parse_wkt(s)
    with _GEOM_CACHE_LOCK:
        while len(_GEOM_CACHE) >= _GEOM_CACHE_MAX:
            _GEOM_CACHE.popitem(last=False)
        _GEOM_CACHE[s] = g
    return g


def _exact_extent_fn(op: str, prop: str, literal: geo.Geometry):
    """Exact host evaluator for an extent column: parse each candidate
    row's WKT (cached) and run the scalar geofn predicate (the JTS-parity
    path)."""
    from geomesa_tpu import geofn

    wcol = prop + "__wkt"
    ops = {
        "intersects": geofn.st_intersects,
        "within": geofn.st_within,
        "contains": geofn.st_contains,
        "crosses": geofn.st_crosses,
        "overlaps": geofn.st_overlaps,
        "touches": geofn.st_touches,
        "equals": geofn.st_equals,
    }

    def fn(cols, xp=np):
        wkts = cols[wcol]
        out = np.zeros(len(wkts), bool)
        for i, w in enumerate(wkts):
            g = _parse_wkt_cached(w)
            if op == "disjoint":
                out[i] = not geofn.st_intersects(g, literal)
            else:
                out[i] = bool(ops[op](g, literal))
        return out

    return fn


def _exact_extent_dwithin_fn(prop: str, literal: geo.Geometry, dist_m: float):
    """Exact host DWITHIN for an extent column: geodesic distance from the
    literal to the row geometry's closest point."""
    from geomesa_tpu import geofn

    wcol = prop + "__wkt"

    def fn(cols, xp=np):
        wkts = cols[wcol]
        out = np.zeros(len(wkts), bool)
        for i, w in enumerate(wkts):
            g = _parse_wkt_cached(w)
            out[i] = float(geofn.st_distanceSphere(g, literal)) <= dist_m
        return out

    return fn


def _like_regex(pattern: str, ci: bool):
    """LIKE pattern (%/_ wildcards) -> anchored compiled regex."""
    rx = "".join(
        ".*" if ch == "%" else "." if ch == "_" else re.escape(ch) for ch in pattern
    )
    return re.compile("^" + rx + "$", re.IGNORECASE if ci else 0)


def _like_codes(d: DictionaryEncoder, pattern: str, ci: bool) -> np.ndarray:
    """Resolve a LIKE pattern against the dictionary vocab -> matching codes."""
    cre = _like_regex(pattern, ci)
    return np.array(
        [i for i, v in enumerate(d.values) if cre.match(v)], dtype=np.int32
    )


def _isin_fn(col: str, codes: np.ndarray):
    codes = np.asarray(codes)

    def fn(cols, xp):
        c = cols[col]
        if codes.size == 0:
            return xp.zeros(c.shape, dtype=bool)
        if codes.size <= 16:
            m = c == codes[0]
            for v in codes[1:]:
                m = m | (c == v)
            return m
        return xp.isin(c, codes)

    return fn


# -- expression comparisons (ExprCompare) --------------------------------

def _expr_mark_needs(node: "ir.ExprCompare", ft: FeatureType,
                     need, need_refine) -> bool:
    """Register column needs for an expression comparison; returns True
    when the expression can only be evaluated on the host (functions,
    strings, or geometry-valued properties)."""
    host_only = ir.expr_has_fn(node.left) or ir.expr_has_fn(node.right)
    for p in node.props():
        a = ft.attr(p)  # raises KeyError naming unknown attributes
        if a.is_geom:
            host_only = True
            if a.is_point:
                need(p + "__x", p + "__y")
            else:
                need_refine(p + "__wkt")
        elif a.type == "json":
            raise ValueError(
                f"json attribute {p!r} cannot appear in an expression; "
                "query it via jsonPath('$...', attr) instead"
            )
        elif a.type == "string":
            host_only = True
            need(p)
        else:
            need(p)
    return host_only


def _expr_resolve_fn(name: str):
    from geomesa_tpu import geofn

    fn = getattr(geofn, name, None)
    if fn is None and not name.startswith("st_"):
        fn = getattr(geofn, "st_" + name, None)
    if fn is None or not callable(fn):
        raise ValueError(
            f"unknown filter function {name!r} (available: geofn st_*)"
        )
    return fn


def _expr_eval_exact(e: "ir.Expr", ft: FeatureType,
                     dicts: Dict[str, DictionaryEncoder], cols, n: int):
    """Exact host evaluation -> f64 ndarray, object ndarray (strings /
    geometries), or a scalar for literal subtrees."""
    if isinstance(e, ir.Lit):
        return e.value
    if isinstance(e, ir.Prop):
        a = ft.attr(e.name)
        if a.is_geom:
            if a.is_point:
                x = np.asarray(cols[e.name + "__x"], np.float64)
                y = np.asarray(cols[e.name + "__y"], np.float64)
                out = np.empty(len(x), dtype=object)
                for i in range(len(x)):
                    out[i] = geo.Point(float(x[i]), float(y[i]))
                return out
            wkt = cols[e.name + "__wkt"]
            out = np.empty(len(wkt), dtype=object)
            for i, w in enumerate(wkt):
                out[i] = None if w is None else geo.parse_wkt(str(w))
            return out
        if a.type == "string":
            d = dicts.setdefault(e.name, DictionaryEncoder())
            codes = np.asarray(cols[e.name])
            vocab = np.array(list(d.values) + [None], dtype=object)
            return vocab[np.where(codes >= 0, codes, len(d.values))]
        col = np.asarray(cols[e.name])
        if col.dtype.kind in "iu":
            # int64 stays exact (a float64 cast corrupts > 2^53 — the
            # legacy Compare path reads the i64 master column exactly)
            return col.astype(np.int64, copy=False)
        return np.asarray(col, np.float64)
    if isinstance(e, ir.Arith):
        left = _expr_eval_exact(e.left, ft, dicts, cols, n)
        right = _expr_eval_exact(e.right, ft, dicts, cols, n)
        with np.errstate(divide="ignore", invalid="ignore"):
            if e.op == "+":
                return left + right
            if e.op == "-":
                return left - right
            if e.op == "*":
                return left * right
            # scalar/scalar division follows PYTHON semantics, so a zero
            # literal divisor raised an uncaught ZeroDivisionError at query
            # time; coerce literal operands to np.float64 (x/0 -> inf/nan,
            # matching the array path under errstate)
            if not isinstance(left, np.ndarray) \
                    and not isinstance(right, np.ndarray):
                try:
                    left, right = np.float64(left), np.float64(right)
                except (TypeError, ValueError) as exc:
                    raise ValueError(
                        f"non-numeric operands in division: {e!r}"
                    ) from exc
            return left / right
    if isinstance(e, ir.FnCall):
        fn = _expr_resolve_fn(e.name)
        # point-geometry property args keep their raw (x, y) column form
        # so vectorized geofn paths can run in one call instead of a
        # Python Point object per row
        xy_forms: Dict[int, tuple] = {}
        args: list = []
        for i, a in enumerate(e.args):
            if isinstance(a, ir.Prop) and ft.has(a.name) \
                    and ft.attr(a.name).is_point:
                xy_forms[i] = (
                    np.asarray(cols[a.name + "__x"], np.float64),
                    np.asarray(cols[a.name + "__y"], np.float64),
                )
                args.append(None)  # object array built lazily below
            else:
                args.append(_expr_eval_exact(a, ft, dicts, cols, n))
        if not xy_forms and not any(
                isinstance(a, np.ndarray) for a in args):
            # pure-literal subtree (e.g. st_geomFromWKT('...')): one call,
            # result may be a geometry, number, or string
            return fn(*args)
        # distance functions are symmetric, and geofn vectorizes their
        # SECOND argument as an (xs, ys) tuple: one haversine call for
        # the whole window instead of a per-row loop
        if e.name in ("st_distance", "st_distanceSphere",
                      "st_distanceSpheroid") \
                and len(e.args) == 2 and len(xy_forms) == 1:
            i = next(iter(xy_forms))
            other = args[1 - i]
            if not isinstance(other, np.ndarray):
                try:
                    out = fn(other, xy_forms[i])
                    out = np.asarray(out, np.float64)
                    if out.shape == (n,):
                        return out
                except Exception:
                    pass
        if xy_forms:
            # some geofn functions take (xs, ys) tuples directly
            try:
                out = fn(*[xy_forms.get(i, v) for i, v in enumerate(args)])
                if isinstance(out, np.ndarray) and out.shape[:1] == (n,):
                    return (out if out.dtype.kind == "O"
                            else np.asarray(out, np.float64))
            except Exception:
                pass
            for i, (x, y) in xy_forms.items():
                pts = np.empty(n, dtype=object)
                for j in range(n):
                    pts[j] = geo.Point(float(x[j]), float(y[j]))
                args[i] = pts
        try:
            out = fn(*args)
            if isinstance(out, np.ndarray) and out.shape[:1] == (n,):
                return (out if out.dtype.kind == "O"
                        else np.asarray(out, np.float64))
        except Exception:
            pass
        # scalar function: map row-wise over the array arguments
        vals = np.empty(n, dtype=object)
        for i in range(n):
            row = [a[i] if isinstance(a, np.ndarray) else a for a in args]
            if any(r is None for r in row):
                continue
            try:
                vals[i] = fn(*row)
            except Exception:
                pass  # per-row failure -> null -> row excluded
        try:
            return np.array(
                [np.nan if v is None else float(v) for v in vals],
                np.float64)
        except (TypeError, ValueError):
            return vals  # geometry/string-valued results stay objects
    raise ValueError(f"cannot evaluate expression node {e!r}")


def _expr_const_fold(node: "ir.ExprCompare", ft: FeatureType,
                     dicts: Dict[str, DictionaryEncoder]) -> bool:
    """Truth value of a property-free comparison (both sides are literal
    subtrees — literals, arithmetic over literals, function calls on
    literals). Evaluated once at compile time."""
    left = _expr_eval_exact(node.left, ft, dicts, {}, 1)
    right = _expr_eval_exact(node.right, ft, dicts, {}, 1)

    def scalar(v):
        if isinstance(v, np.ndarray):
            return v.reshape(-1)[0] if v.size else None
        return v

    left, right = scalar(left), scalar(right)
    op = node.op
    try:
        if op == "=":
            return bool(left == right)
        if op == "<>":
            return bool(left != right)
        if left is None or right is None:
            return False
        if op == "<":
            return bool(left < right)
        if op == "<=":
            return bool(left <= right)
        if op == ">":
            return bool(left > right)
        return bool(left >= right)
    except TypeError as e:
        raise ValueError(
            f"incomparable constant operands in {node!r}"
        ) from e


def _expr_exact_fn(node: "ir.ExprCompare", ft: FeatureType,
                   dicts: Dict[str, DictionaryEncoder]):
    op = node.op

    def fn(cols, xp=np):
        probe = None
        for p in node.props():
            a = ft.attr(p)
            key = p + "__x" if a.is_point else (
                p + "__wkt" if a.is_geom else p)
            if key in cols:
                probe = cols[key]
                break
        if probe is None:
            raise ValueError(
                f"expression references no resolvable column: {node!r}")
        n = len(probe)
        left = _expr_eval_exact(node.left, ft, dicts, cols, n)
        right = _expr_eval_exact(node.right, ft, dicts, cols, n)
        lobj = isinstance(left, np.ndarray) and left.dtype.kind == "O"
        robj = isinstance(right, np.ndarray) and right.dtype.kind == "O"
        if lobj or robj or isinstance(left, str) or isinstance(right, str):
            if op not in ("=", "<>"):
                raise ValueError(
                    f"ordering comparison {op!r} is not defined for "
                    "string/geometry expressions"
                )
            la = left if isinstance(left, np.ndarray) else np.full(
                n, left, dtype=object)
            ra = right if isinstance(right, np.ndarray) else np.full(
                n, right, dtype=object)
            valid = np.array([a is not None and b is not None
                              for a, b in zip(la, ra)])
            eqm = np.array([a == b for a, b in zip(la, ra)], dtype=bool)
            return (eqm if op == "=" else ~eqm) & valid
        lint = (np.asarray(left).dtype.kind in "iub"
                if isinstance(left, np.ndarray)
                else isinstance(left, (int, np.integer)))
        rint = (np.asarray(right).dtype.kind in "iub"
                if isinstance(right, np.ndarray)
                else isinstance(right, (int, np.integer)))
        if lint and rint:
            # pure-integer comparison stays in int64 (exact beyond 2^53)
            left = np.asarray(left, np.int64)
            right = np.asarray(right, np.int64)
            valid = np.asarray(True)
        else:
            left = np.asarray(left, np.float64)
            right = np.asarray(right, np.float64)
            valid = ~(np.isnan(left) | np.isnan(right))
        if op == "=":
            m = left == right
        elif op == "<>":
            m = left != right
        elif op == "<":
            m = left < right
        elif op == "<=":
            m = left <= right
        elif op == ">":
            m = left > right
        else:
            m = left >= right
        return m & valid

    return fn


#: relative f32 ulp with a 4x safety factor absorbing the error
#: arithmetic's own rounding
_EXPR_EPS = 4.0 * 2.0 ** -23


def _expr_eval_coarse(e: "ir.Expr", cols, xp):
    """f32 interval evaluation -> (value, absolute error bound)."""
    if isinstance(e, ir.Lit):
        v = float(e.value)
        return v, abs(v) * _EXPR_EPS
    if isinstance(e, ir.Prop):
        v = xp.asarray(cols[e.name]) * 1.0  # promote int/bool to float
        return v, xp.abs(v) * _EXPR_EPS
    if isinstance(e, ir.Arith):
        lv, le = _expr_eval_coarse(e.left, cols, xp)
        rv, re_ = _expr_eval_coarse(e.right, cols, xp)
        if e.op == "+":
            v = lv + rv
            return v, le + re_ + xp.abs(v) * _EXPR_EPS
        if e.op == "-":
            v = lv - rv
            return v, le + re_ + xp.abs(v) * _EXPR_EPS
        if e.op == "*":
            v = lv * rv
            return v, (xp.abs(lv) * re_ + xp.abs(rv) * le + le * re_
                       + xp.abs(v) * _EXPR_EPS)
        # division: denominator interval must exclude zero, else the
        # bound is infinite (row stays a candidate). Literal/literal
        # operands are Python floats whose division RAISES on zero —
        # coerce to np.float64 so x/0 follows IEEE (inf/nan) like the
        # column path
        if not hasattr(lv, "shape") and not hasattr(rv, "shape"):
            lv, rv = np.float64(lv), np.float64(rv)
        with np.errstate(divide="ignore", invalid="ignore"):
            v = lv / rv
        den = xp.maximum(xp.abs(rv) - re_, 0.0)
        err = xp.where(
            den > 0,
            (le + xp.abs(v) * re_) / xp.maximum(den, 1e-30)
            + xp.abs(v) * _EXPR_EPS,
            xp.asarray(xp.inf),
        )
        return v, err
    raise ValueError(f"cannot device-evaluate expression node {e!r}")


def _expr_coarse_fn(node: "ir.ExprCompare", neg: bool):
    """Device prefilter: superset of exact matches under even NOT-polarity
    (include every possibly-true row), subset under odd (only certainly-
    true rows). NaN rows compare False either way — matching the exact
    tree's validity mask."""
    op = node.op

    def fn(cols, xp):
        lv, le = _expr_eval_coarse(node.left, cols, xp)
        rv, re_ = _expr_eval_coarse(node.right, cols, xp)
        slack = le + re_
        if not neg:  # possibly true
            if op == "=":
                return xp.abs(lv - rv) <= slack
            if op == "<>":
                return ~((xp.abs(lv - rv) == 0) & (slack == 0))
            if op in ("<", "<="):
                return lv - slack <= rv
            return lv + slack >= rv
        # certainly true (mask will be inverted by the NOT above)
        if op == "=":
            return (xp.abs(lv - rv) == 0) & (slack == 0)
        if op == "<>":
            return xp.abs(lv - rv) > slack
        if op == "<":
            return lv + slack < rv
        if op == "<=":
            return lv + slack <= rv
        if op == ">":
            return lv - slack > rv
        return lv - slack >= rv

    return fn


def compile_filter(
    f: ir.Filter,
    ft: FeatureType,
    dicts: Dict[str, DictionaryEncoder],
) -> CompiledFilter:
    """Compile a predicate IR tree into a columnar mask kernel.

    Spatial predicates over extent columns compile twice: a *coarse* bbox
    mask for the dense scan (``neg`` tracks NOT-polarity so the coarse mask
    stays a superset of the exact matches — under odd negations the node
    emits its certain-match subset instead), and an *exact* host tree
    (``exact=True``) over the ``__wkt`` columns used as the refinement
    pass on coarse-true candidates."""
    needed: List[str] = []
    refine_needed: List[str] = []

    def need(*cols):
        for c in cols:
            if c not in needed:
                needed.append(c)

    has_refine = [False]

    def need_refine(c):
        has_refine[0] = True
        if c is not None and c not in refine_needed:
            refine_needed.append(c)

    # f32-uncertainty bands: each entry masks rows whose f64 value rounds
    # to the f32 image of a query bound — the only rows where the device's
    # f32 compare can disagree with the exact f64 semantics
    bands: List[Callable] = []

    def band_eq(col: str, *bounds: float):
        b32s = sorted({float(np.float32(b)) for b in bounds})

        def bfn(cols, xp):
            # f32-cast on BOTH backends: the band is defined by f32
            # collision, and the host evaluates it on f64 master columns
            c = xp.asarray(cols[col]).astype(xp.float32)
            m = c == b32s[0]
            for b in b32s[1:]:
                m = m | (c == b)
            return m

        bands.append(bfn)

    def compile_node(node: ir.Filter, neg: bool = False, exact: bool = False) -> Callable:
        if isinstance(node, ir.Include):
            # scalar True broadcasts against the window/validity mask
            return lambda cols, xp: xp.asarray(True)
        if isinstance(node, ir.Exclude):
            return lambda cols, xp: xp.asarray(False)
        if isinstance(node, ir.And):
            fns = [compile_node(c, neg, exact) for c in node.children]

            def f_and(cols, xp):
                m = fns[0](cols, xp)
                for fn in fns[1:]:
                    m = m & fn(cols, xp)
                return m

            return f_and
        if isinstance(node, ir.Or):
            fns = [compile_node(c, neg, exact) for c in node.children]

            def f_or(cols, xp):
                m = fns[0](cols, xp)
                for fn in fns[1:]:
                    m = m | fn(cols, xp)
                return m

            return f_or
        if isinstance(node, ir.Not):
            fn = compile_node(node.child, not neg, exact)
            return lambda cols, xp: ~fn(cols, xp)

        if isinstance(node, ir.BBox):
            gc = _geom_cols(ft, node.prop)
            xmin, ymin, xmax, ymax = node.xmin, node.ymin, node.xmax, node.ymax
            if "point" in gc:
                need(gc["x"], gc["y"])
                xc, yc = gc["x"], gc["y"]
                if exact:

                    def bbox_exact(cols, xp):
                        x, y = cols[xc], cols[yc]
                        return (
                            (x >= xmin) & (x <= xmax)
                            & (y >= ymin) & (y <= ymax)
                        )

                    return bbox_exact
                # f32 evaluation with polarity-correct rounding semantics:
                # inclusive compares are a SUPERSET of the exact f64 box
                # (monotone rounding), strict compares a SUBSET — so under
                # even NOT-polarity emit inclusive, under odd emit strict.
                # Rows colliding with an f32 bound (the band) are the only
                # ones where the two differ; a clean band certificate makes
                # either form bit-exact.
                band_eq(xc, xmin, xmax)
                band_eq(yc, ymin, ymax)
                return _f32_box_fn(xc, yc, (xmin, ymin, xmax, ymax), neg)
            from geomesa_tpu import config

            if config.LOOSE_BBOX.to_bool():
                # loose-bbox: envelope overlap only, no refinement (exact
                # either way when the stored geometry IS its envelope)
                need(gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
                ks = (gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])

                def bbox_ext(cols, xp):
                    return (
                        (cols[ks[0]] <= xmax) & (cols[ks[2]] >= xmin)
                        & (cols[ks[1]] <= ymax) & (cols[ks[3]] >= ymin)
                    )

                return bbox_ext
            # exact semantics: BBOX == intersects with the box polygon, so
            # delegate to the Spatial machinery (polarity + refinement)
            return compile_node(
                ir.Spatial(
                    "intersects", node.prop,
                    geo.bbox_polygon(xmin, ymin, xmax, ymax),
                ),
                neg, exact,
            )

        if isinstance(node, ir.Spatial):
            gc = _geom_cols(ft, node.prop)
            b = node.geom.bounds()
            if "point" in gc:
                need(gc["x"], gc["y"])
                return _point_spatial_fn(
                    node, gc["x"], gc["y"], exact, neg, need_refine,
                    need_band=band_eq,
                )
            # extent (line/polygon) column
            if exact:
                need_refine(node.prop + "__wkt")
                return _exact_extent_fn(node.op, node.prop, node.geom)
            need(gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
            ks = (gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
            need_refine(node.prop + "__wkt")

            def overlap(cols, xp):
                return (
                    (cols[ks[0]] <= b[2]) & (cols[ks[2]] >= b[0])
                    & (cols[ks[1]] <= b[3]) & (cols[ks[3]] >= b[1])
                )

            op = node.op
            if not neg:
                # superset-of-exact ("maybe") masks
                if op == "disjoint":
                    return _TRUE  # bbox overlap can't prove intersection
                if op == "within":
                    # row within literal => row bbox inside literal bbox
                    return lambda cols, xp: (
                        (cols[ks[0]] >= b[0]) & (cols[ks[2]] <= b[2])
                        & (cols[ks[1]] >= b[1]) & (cols[ks[3]] <= b[3])
                    )
                if op == "contains":
                    return lambda cols, xp: (
                        (cols[ks[0]] <= b[0]) & (cols[ks[2]] >= b[2])
                        & (cols[ks[1]] <= b[1]) & (cols[ks[3]] >= b[3])
                    )
                if op == "equals":
                    return lambda cols, xp: (
                        (xp.abs(cols[ks[0]] - b[0]) <= 1e-9)
                        & (xp.abs(cols[ks[1]] - b[1]) <= 1e-9)
                        & (xp.abs(cols[ks[2]] - b[2]) <= 1e-9)
                        & (xp.abs(cols[ks[3]] - b[3]) <= 1e-9)
                    )
                return overlap  # intersects/crosses/overlaps/touches
            # negated context: emit the certain-match subset so the
            # enclosing NOT yields a superset
            if op == "disjoint":
                return lambda cols, xp: ~overlap(cols, xp)
            return _FALSE

        if isinstance(node, ir.DWithin):
            gc = _geom_cols(ft, node.prop)
            # expanded literal bbox, used by every coarse path below
            d_deg = node.distance_m / geo.METERS_PER_DEGREE
            bb = node.geom.bounds()
            maxlat = min(89.0, max(abs(bb[1]), abs(bb[3])))
            dxp = d_deg / max(np.cos(np.radians(maxlat)), 1e-3)
            exp = (bb[0] - dxp, bb[1] - d_deg, bb[2] + dxp, bb[3] + d_deg)
            if "point" in gc:
                need(gc["x"], gc["y"])
                xc, yc = gc["x"], gc["y"]
                if isinstance(node.geom, geo.Point):
                    # exact great-circle test, fused into the kernel
                    px, py, dist = node.geom.x, node.geom.y, node.distance_m

                    def dwithin(cols, xp):
                        x, y = cols[xc], cols[yc]
                        rx1, ry1 = xp.radians(x), xp.radians(y)
                        rx2, ry2 = np.radians(px), np.radians(py)
                        a = (
                            xp.sin((ry2 - ry1) / 2) ** 2
                            + xp.cos(ry1) * np.cos(ry2) * xp.sin((rx2 - rx1) / 2) ** 2
                        )
                        d = 2 * geo.EARTH_RADIUS_M * xp.arcsin(xp.sqrt(xp.clip(a, 0, 1)))
                        return d <= dist

                    return dwithin
                # non-point literal: coarse expanded bbox + exact geodesic
                # distance-to-geometry refinement on host candidates
                if exact:
                    from geomesa_tpu import geofn

                    lit, dist = node.geom, node.distance_m

                    def dw_exact(cols, xp=np):
                        d = geofn.st_distanceSphere(
                            lit, (np.asarray(cols[xc], np.float64),
                                  np.asarray(cols[yc], np.float64))
                        )
                        return np.asarray(d) <= dist

                    return dw_exact
                need_refine(None)  # mark refinement required (no extra cols)
                if neg:
                    return _FALSE

                def dwithin_box(cols, xp):
                    x, y = cols[xc], cols[yc]
                    return (x >= exp[0]) & (x <= exp[2]) & (y >= exp[1]) & (y <= exp[3])

                return dwithin_box
            # extent column: coarse expanded-bbox overlap on the row bbox +
            # exact geodesic refinement over the __wkt host column
            if exact:
                need_refine(node.prop + "__wkt")
                return _exact_extent_dwithin_fn(node.prop, node.geom, node.distance_m)
            need(gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
            ks = (gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
            need_refine(node.prop + "__wkt")
            if neg:
                return _FALSE

            def dwithin_ext(cols, xp):
                return (
                    (cols[ks[0]] <= exp[2]) & (cols[ks[2]] >= exp[0])
                    & (cols[ks[1]] <= exp[3]) & (cols[ks[3]] >= exp[1])
                )

            return dwithin_ext

        if isinstance(node, ir.Compare):
            if isinstance(node.prop, ir.JsonPath):
                _require_json_attr(ft, node.prop)
                need(node.prop.attr)
                return _json_path_pred(
                    node.prop, _json_test(node.op, node.value)
                )
            a = ft.attr(node.prop)
            col = node.prop
            if (
                a.type in ("int32", "int64")
                and isinstance(node.value, (float, np.floating))
                and not float(node.value).is_integer()
                and node.op in ("=", "<>")
            ):
                # constant result: no int equals a non-integral literal —
                # resolved BEFORE need(col) so the scan never ships a
                # column the predicate cannot read
                const = node.op == "<>"
                return lambda cols, xp, c=const: xp.asarray(c)
            need(col)
            if a.type == "string":
                d = dicts.setdefault(node.prop, DictionaryEncoder())
                if node.op == "=":
                    code = d.code_of(str(node.value))
                    return lambda cols, xp: cols[col] == code
                if node.op == "<>":
                    code = d.code_of(str(node.value))
                    return lambda cols, xp: (cols[col] != code) & (cols[col] >= 0)
                # ordering on strings: resolve against vocab on host
                sval = str(node.value)
                ops = {
                    "<": lambda v: v < sval, "<=": lambda v: v <= sval,
                    ">": lambda v: v > sval, ">=": lambda v: v >= sval,
                }[node.op]
                codes = np.array(
                    [i for i, v in enumerate(d.values) if ops(v)], dtype=np.int32
                )
                return _isin_fn(col, codes)
            if a.type == "bool":
                bv = (
                    node.value
                    if isinstance(node.value, bool)
                    else str(node.value).lower() == "true"
                )
                if node.op == "=":
                    return lambda cols, xp: cols[col] == bv
                if node.op == "<>":
                    return lambda cols, xp: cols[col] != bv
                raise ValueError(f"unsupported boolean comparison {node.op!r}")
            val = node.value
            if a.type == "date":
                if not isinstance(val, (int, np.integer)):
                    from geomesa_tpu.filter.ecql import parse_iso_ms

                    val = parse_iso_ms(str(val))
                v = int(val)
                # rewrite to interval form -> (bin, off) pair compare
                if node.op == "=":
                    return compile_node(ir.During(node.prop, v, v))
                if node.op == "<>":
                    return compile_node(ir.Not(ir.During(node.prop, v, v)))
                if node.op == "<":
                    return compile_node(ir.During(node.prop, ir.MIN_MS, v - 1))
                if node.op == "<=":
                    return compile_node(ir.During(node.prop, ir.MIN_MS, v))
                if node.op == ">":
                    return compile_node(ir.During(node.prop, v + 1, ir.MAX_MS))
                if node.op == ">=":
                    return compile_node(ir.During(node.prop, v, ir.MAX_MS))
            if a.type in ("float32", "float64"):
                val = float(val)
            elif isinstance(val, (float, np.floating)) \
                    and not float(val).is_integer():
                # non-integral literal vs an INT column: int(val) truncates
                # toward zero and corrupts ordering bounds (fuzz-found r5;
                # = and <> resolved to constants before need(col) above).
                # Resolve with exact integer semantics.
                import math

                fv = float(val)
                if node.op in ("<", "<="):
                    val, op = math.floor(fv), "<="
                    node = ir.Compare(node.prop, op, val)
                else:  # > or >=
                    val, op = math.ceil(fv), ">="
                    node = ir.Compare(node.prop, op, val)
            else:
                val = int(val)
            op = node.op
            if a.type == "float64" and not exact:
                # f64 column rides the device as f32: rows colliding with
                # the f32 image of the bound are uncertain (the band), and
                # the f32 compare must round with the right polarity —
                # superset under even NOT-nesting, subset under odd (same
                # monotone-rounding argument as the int64 case below)
                band_eq(col, val)
                v32 = float(np.float32(val))

                def as32f(cols, xp):
                    return xp.asarray(cols[col]).astype(xp.float32)

                if op == "=":
                    return (
                        _FALSE if neg
                        else (lambda cols, xp: as32f(cols, xp) == v32)
                    )
                if op == "<>":
                    return (
                        (lambda cols, xp: as32f(cols, xp) != v32)
                        if neg else _TRUE
                    )
                if op in ("<", "<="):
                    if neg:
                        return lambda cols, xp: as32f(cols, xp) < v32
                    return lambda cols, xp: as32f(cols, xp) <= v32
                if op in (">", ">="):
                    if neg:
                        return lambda cols, xp: as32f(cols, xp) > v32
                    return lambda cols, xp: as32f(cols, xp) >= v32
            if (
                a.type == "int64" and not exact and abs(val) >= (1 << 24)
            ):
                # The device carries int64 as float32; beyond 2^24 that
                # representation is lossy, so emit a COARSE f32 compare +
                # exact host refinement on the int64 master column.
                # float32 rounding is monotone, hence for exact x ? v:
                #   superset of {x < v}  is  f32(x) <= f32(v)
                #   subset   of {x < v}  is  f32(x) <  f32(v)
                # (and symmetrically for >); f32 equality has no false
                # negatives (x == v -> f32(x) == f32(v)), only collisions.
                need_refine(None)  # refine re-reads `col` exactly (i64 host)
                v32 = float(np.float32(val))

                def as32(cols, xp):
                    # the host fallback reads the exact i64 master column;
                    # cast to f32 there too so coarse semantics are
                    # backend-identical (else i64 == f32(val) false-negates)
                    return xp.asarray(cols[col]).astype(xp.float32)

                if op == "=":
                    return (
                        _FALSE if neg
                        else (lambda cols, xp: as32(cols, xp) == v32)
                    )
                if op == "<>":
                    return (
                        (lambda cols, xp: as32(cols, xp) != v32)
                        if neg else _TRUE
                    )
                if op in ("<", "<="):
                    if neg:
                        return lambda cols, xp: as32(cols, xp) < v32
                    return lambda cols, xp: as32(cols, xp) <= v32
                if op in (">", ">="):
                    if neg:
                        return lambda cols, xp: as32(cols, xp) > v32
                    return lambda cols, xp: as32(cols, xp) >= v32
            if op == "=":
                return lambda cols, xp: cols[col] == val
            if op == "<>":
                return lambda cols, xp: cols[col] != val
            if op == "<":
                return lambda cols, xp: cols[col] < val
            if op == "<=":
                return lambda cols, xp: cols[col] <= val
            if op == ">":
                return lambda cols, xp: cols[col] > val
            if op == ">=":
                return lambda cols, xp: cols[col] >= val

        if isinstance(node, ir.Between):
            inner = ir.And(
                (ir.Compare(node.prop, ">=", node.lo), ir.Compare(node.prop, "<=", node.hi))
            )
            return compile_node(inner, neg, exact)

        if isinstance(node, ir.In):
            if isinstance(node.prop, ir.JsonPath):
                _require_json_attr(ft, node.prop)
                need(node.prop.attr)
                tests = [_json_test("=", v) for v in node.values]
                return _json_path_pred(
                    node.prop, lambda v: any(t(v) for t in tests)
                )
            a = ft.attr(node.prop)
            need(node.prop)
            if a.type == "string":
                d = dicts.setdefault(node.prop, DictionaryEncoder())
                codes = np.array(
                    [d.code_of(str(v)) for v in node.values], dtype=np.int32
                )
                codes = codes[codes >= 0]
                return _isin_fn(node.prop, codes)
            if a.type.startswith("float"):
                vals = np.array([float(v) for v in node.values])
            else:
                # int columns: a non-integral literal can never match —
                # drop it instead of truncating it onto a wrong integer
                vals = np.array([
                    int(v) for v in node.values
                    if not (isinstance(v, (float, np.floating))
                            and not float(v).is_integer())
                    and -(2 ** 63) <= int(v) < 2 ** 63  # outside the
                    # column dtype can never match: drop, don't overflow
                ], dtype=np.int64)
            if a.type == "float64" and not exact and len(vals):
                band_eq(node.prop, *vals.tolist())
                if neg:
                    return _FALSE  # cannot CERTIFY membership at f32
                vals32f = np.unique(vals.astype(np.float32))
                propf = node.prop

                def in32f(cols, xp):
                    c = xp.asarray(cols[propf]).astype(xp.float32)
                    m = c == float(vals32f[0])
                    for v in vals32f[1:]:
                        m = m | (c == float(v))
                    return m

                return in32f
            if (
                a.type == "int64" and not exact
                and np.abs(vals).max(initial=0) >= (1 << 24)
            ):
                # f32 IN is a superset (no equality false negatives) but
                # can collide distinct values — refine on the exact column
                need_refine(None)
                if neg:
                    return _FALSE  # cannot CERTIFY membership at f32
                vals32 = np.unique(vals.astype(np.float32))
                prop = node.prop

                def in32(cols, xp):
                    c = xp.asarray(cols[prop]).astype(xp.float32)
                    m = c == float(vals32[0])
                    for v in vals32[1:]:
                        m = m | (c == float(v))
                    return m

                return in32
            return _isin_fn(node.prop, vals)

        if isinstance(node, ir.Like):
            if isinstance(node.prop, ir.JsonPath):
                _require_json_attr(ft, node.prop)
                need(node.prop.attr)
                cre = _like_regex(node.pattern, node.case_insensitive)
                return _json_path_pred(
                    node.prop, lambda v: bool(cre.match(str(v)))
                )
            a = ft.attr(node.prop)
            if a.type != "string":
                raise ValueError(f"LIKE requires a string attribute, got {a.type}")
            need(node.prop)
            d = dicts.setdefault(node.prop, DictionaryEncoder())
            return _isin_fn(node.prop, _like_codes(d, node.pattern, node.case_insensitive))

        if isinstance(node, ir.IsNull):
            if isinstance(node.prop, ir.JsonPath):
                _require_json_attr(ft, node.prop)
                need(node.prop.attr)
                exists = _json_path_pred(node.prop, lambda v: True)
                if node.negate:  # IS NOT NULL
                    return exists
                return lambda cols, xp: ~np.asarray(exists(cols, xp))
            a = ft.attr(node.prop)
            need(node.prop)
            col = node.prop
            if a.type == "string":
                fn = lambda cols, xp: cols[col] < 0  # noqa: E731
            elif a.type.startswith("float"):
                fn = lambda cols, xp: xp.isnan(cols[col])  # noqa: E731
            else:
                fn = lambda cols, xp: xp.zeros(cols[col].shape, dtype=bool)  # noqa: E731
            if node.negate:
                return lambda cols, xp: ~fn(cols, xp)
            return fn

        if isinstance(node, ir.During):
            if isinstance(node.prop, ir.JsonPath):
                raise ValueError(
                    "temporal predicates (DURING/BEFORE/AFTER/TEQUALS) are "
                    "not supported on jsonPath() accessors; compare the "
                    "extracted value numerically instead"
                )
            # Temporal predicates run on the (bin, scaled-offset) int32 pair —
            # the device time representation. Lexicographic pair compare.
            lo_b, lo_o, hi_b, hi_o = during_device_bounds(
                ft, node.lo_ms, node.hi_ms
            )
            cb, co = node.prop + "__bin", node.prop + "__off"
            need(cb, co)

            def during(cols, xp):
                b, o = cols[cb], cols[co]
                ge = (b > lo_b) | ((b == lo_b) & (o >= lo_o))
                le = (b < hi_b) | ((b == hi_b) & (o <= hi_o))
                return ge & le

            return during

        if isinstance(node, ir.IdIn):
            need("__fid__")
            ids = [str(i) for i in node.ids]

            def fid_mask(cols, xp):
                fids = np.asarray(cols["__fid__"])
                # host-only column; match in the column's own layout ('S'
                # bytes normally, 'U'/object fallback) — vectorized isin
                if fids.dtype.kind == "S":
                    # natural-width 'S' array: isin compares values, so a
                    # query id longer than the column width just never hits
                    q = np.asarray(
                        [i.encode("utf-8", "surrogateescape") for i in ids]
                    )
                elif fids.dtype.kind == "U":
                    q = np.asarray(ids)
                else:
                    idset = set(ids)
                    return np.array([f in idset for f in fids], dtype=bool)
                return np.isin(fids, q)

            return fid_mask

        if isinstance(node, ir.ExprCompare):
            # property-free comparisons (both sides fold to constants, e.g.
            # st_area(st_geomFromWKT('...')) > 0.5) reference no column at
            # all — the generic path would fail with "no resolvable
            # column"; fold them to a constant Include/Exclude instead
            if not node.props():
                const = _expr_const_fold(node, ft, dicts)
                return compile_node(
                    ir.Include() if const else ir.Exclude(), neg, exact
                )
            # property-vs-property / arithmetic / st_* function comparisons
            # (FastFilterFactory.scala:395 parity). Exact semantics live on
            # the host refine pass; function-free numeric expressions also
            # get an ERROR-BOUNDED f32 device prefilter (interval
            # arithmetic: every emitted coarse mask is a provable superset
            # of the exact matches under even NOT-polarity, a subset under
            # odd — same contract as the f32 box compares above).
            host_only = _expr_mark_needs(node, ft, need, need_refine)
            if exact:
                return _expr_exact_fn(node, ft, dicts)
            need_refine(None)
            if host_only:
                # device cannot evaluate (functions / strings / extent
                # geometries): pass every candidate to the host refine
                return _FALSE if neg else (lambda cols, xp: xp.asarray(True))
            return _expr_coarse_fn(node, neg)

        raise ValueError(f"cannot compile filter node: {node!r}")

    fn = compile_node(f)
    refine = None
    if has_refine[0]:
        # exact host tree over candidate rows (same scalar columns + the
        # __wkt host columns); applied by the executor to coarse-true rows
        refine = compile_node(f, exact=True)
    band = None
    band_only = False
    if bands and refine is None:
        # refine-bearing plans are already host-exact on candidates; only
        # the pure-device path needs the f32-uncertainty certificate. The
        # exact tree doubles as the fallback refiner when the certificate
        # fails (the f32 mask is a superset by monotone rounding, so
        # coarse + exact-f64 refine is correct).
        bfns = list(bands)

        def band(cols, xp):  # noqa: F811
            m = bfns[0](cols, xp)
            for b in bfns[1:]:
                m = m | b(cols, xp)
            return m

        refine = compile_node(f, exact=True)
        band_only = True

    return CompiledFilter(
        fn, needed, refine=refine, refine_columns=refine_needed, band=band,
        refine_only_if_band=band_only,
    )
