"""Predicate IR -> fused columnar mask kernel.

The FastFilterFactory analog (reference
geomesa-filter/.../factory/FastFilterFactory.scala:40,410): instead of
rewriting a CQL tree into per-row fast evaluators, we compile it into ONE
vectorized boolean expression over column arrays. The compiled function is
backend-generic — pass ``numpy`` for the host path or ``jax.numpy`` inside a
jit'd scan kernel; XLA fuses the whole mask into the surrounding aggregation.

String predicates are resolved to dictionary codes at compile time (the device
never sees strings). Geometry literals become captured numpy edge buffers; the
point-in-polygon test is even-odd crossing parity, vectorized N points × E
edges per polygon.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.schema.columns import DictionaryEncoder
from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.utils import geometry as geo


@dataclass
class CompiledFilter:
    """A compiled mask kernel. ``fn(cols, xp)`` -> bool mask array."""

    fn: Callable
    columns: List[str]
    ecql: Optional[str] = None

    def __call__(self, cols, xp=np):
        return self.fn(cols, xp)


def _geom_cols(ft: FeatureType, prop: str) -> Dict[str, str]:
    a = ft.attr(prop)
    if not a.is_geom:
        raise ValueError(f"attribute {prop!r} is not a geometry")
    if a.is_point:
        return {"x": prop + "__x", "y": prop + "__y", "point": "1"}
    return {
        "x": prop + "__x", "y": prop + "__y",
        "xmin": prop + "__xmin", "ymin": prop + "__ymin",
        "xmax": prop + "__xmax", "ymax": prop + "__ymax",
    }


def _pip_fn(g: geo.Geometry, xcol: str, ycol: str):
    """Point-in-(multi)polygon via even-odd crossing parity (holes included
    naturally by the even-odd rule). Returns fn(cols, xp) -> mask."""
    polys = g.polygons if isinstance(g, geo.MultiPolygon) else (g,)
    # Fast path: single axis-aligned rectangle -> bbox compare (the loose-bbox
    # trick; reference Z3IndexKeySpace.useFullFilter:235).
    if len(polys) == 1 and isinstance(polys[0], geo.Polygon) and polys[0].is_rectangle():
        xmin, ymin, xmax, ymax = polys[0].bounds()

        def rect(cols, xp):
            x, y = cols[xcol], cols[ycol]
            return (x >= xmin) & (x <= xmax) & (y >= ymin) & (y <= ymax)

        return rect

    from geomesa_tpu.kernels import pallas_kernels as pk

    tables = [pk.polygon_edge_tables(p) for p in polys]
    pallas_ok = all(pk.edges_fit(packed.shape[1]) for _, packed in tables)

    def pip(cols, xp):
        x = cols[xcol]
        y = cols[ycol]
        if xp is not np and pallas_ok and pk.use_pallas():
            # TPU: edge table pinned in VMEM, point blocks streamed through
            # the VPU — the [block, E] intermediate never touches HBM
            out = None
            for _, packed in tables:
                inside = pk.pip_mask(x, y, packed)
                out = inside if out is None else (out | inside)
            return out
        # backend-generic broadcast path: trailing-axis broadcast handles
        # 1-D host shards and [S, L] device layouts alike
        out = None
        for (x1, y1, x2, y2, slope), packed in tables:
            if xp is not np:  # device: reuse the f32 rows already packed
                x1, y1, y2, slope = (xp.asarray(packed[i]) for i in range(4))
            yb = y[..., None]
            cond = (y1 > yb) != (y2 > yb)
            xint = x1 + (yb - y1) * slope
            crossings = (cond & (x[..., None] < xint)).sum(axis=-1)
            inside = (crossings % 2) == 1
            out = inside if out is None else (out | inside)
        return out

    return pip


def _like_codes(d: DictionaryEncoder, pattern: str, ci: bool) -> np.ndarray:
    """Resolve a LIKE pattern against the dictionary vocab -> matching codes."""
    rx = "".join(
        ".*" if ch == "%" else "." if ch == "_" else re.escape(ch) for ch in pattern
    )
    flags = re.IGNORECASE if ci else 0
    cre = re.compile("^" + rx + "$", flags)
    return np.array(
        [i for i, v in enumerate(d.values) if cre.match(v)], dtype=np.int32
    )


def _isin_fn(col: str, codes: np.ndarray):
    codes = np.asarray(codes)

    def fn(cols, xp):
        c = cols[col]
        if codes.size == 0:
            return xp.zeros(c.shape, dtype=bool)
        if codes.size <= 16:
            m = c == codes[0]
            for v in codes[1:]:
                m = m | (c == v)
            return m
        return xp.isin(c, codes)

    return fn


def compile_filter(
    f: ir.Filter,
    ft: FeatureType,
    dicts: Dict[str, DictionaryEncoder],
) -> CompiledFilter:
    """Compile a predicate IR tree into a columnar mask kernel."""
    needed: List[str] = []

    def need(*cols):
        for c in cols:
            if c not in needed:
                needed.append(c)

    def compile_node(node: ir.Filter) -> Callable:
        if isinstance(node, ir.Include):
            # scalar True broadcasts against the window/validity mask
            return lambda cols, xp: xp.asarray(True)
        if isinstance(node, ir.Exclude):
            return lambda cols, xp: xp.asarray(False)
        if isinstance(node, ir.And):
            fns = [compile_node(c) for c in node.children]

            def f_and(cols, xp):
                m = fns[0](cols, xp)
                for fn in fns[1:]:
                    m = m & fn(cols, xp)
                return m

            return f_and
        if isinstance(node, ir.Or):
            fns = [compile_node(c) for c in node.children]

            def f_or(cols, xp):
                m = fns[0](cols, xp)
                for fn in fns[1:]:
                    m = m | fn(cols, xp)
                return m

            return f_or
        if isinstance(node, ir.Not):
            fn = compile_node(node.child)
            return lambda cols, xp: ~fn(cols, xp)

        if isinstance(node, ir.BBox):
            gc = _geom_cols(ft, node.prop)
            xmin, ymin, xmax, ymax = node.xmin, node.ymin, node.xmax, node.ymax
            if "point" in gc:
                need(gc["x"], gc["y"])
                xc, yc = gc["x"], gc["y"]

                def bbox_pt(cols, xp):
                    x, y = cols[xc], cols[yc]
                    return (x >= xmin) & (x <= xmax) & (y >= ymin) & (y <= ymax)

                return bbox_pt
            need(gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
            ks = (gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])

            def bbox_ext(cols, xp):
                return (
                    (cols[ks[0]] <= xmax) & (cols[ks[2]] >= xmin)
                    & (cols[ks[1]] <= ymax) & (cols[ks[3]] >= ymin)
                )

            return bbox_ext

        if isinstance(node, ir.Spatial):
            gc = _geom_cols(ft, node.prop)
            b = node.geom.bounds()
            if "point" in gc:
                need(gc["x"], gc["y"])
                if node.op in ("intersects", "within", "contains"):
                    if isinstance(node.geom, (geo.Polygon, geo.MultiPolygon)):
                        return _pip_fn(node.geom, gc["x"], gc["y"])
                    # point/line literal: intersects ~= tiny-bbox test
                    xc, yc = gc["x"], gc["y"]

                    def near(cols, xp):
                        x, y = cols[xc], cols[yc]
                        return (x >= b[0]) & (x <= b[2]) & (y >= b[1]) & (y <= b[3])

                    return near
                if node.op == "disjoint":
                    inner = compile_node(ir.Spatial("intersects", node.prop, node.geom))
                    return lambda cols, xp: ~inner(cols, xp)
            else:
                # extent attribute: bbox-overlap approximation at key level;
                # exact geometry refinement is a host post-pass (SURVEY §7
                # hard part (a)).
                need(gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])
                ks = (gc["xmin"], gc["ymin"], gc["xmax"], gc["ymax"])

                def overlap(cols, xp):
                    m = (
                        (cols[ks[0]] <= b[2]) & (cols[ks[2]] >= b[0])
                        & (cols[ks[1]] <= b[3]) & (cols[ks[3]] >= b[1])
                    )
                    return ~m if node.op == "disjoint" else m

                return overlap

        if isinstance(node, ir.DWithin):
            gc = _geom_cols(ft, node.prop)
            need(gc["x"], gc["y"])
            xc, yc = gc["x"], gc["y"]
            if isinstance(node.geom, geo.Point):
                px, py, dist = node.geom.x, node.geom.y, node.distance_m

                def dwithin(cols, xp):
                    x, y = cols[xc], cols[yc]
                    rx1, ry1 = xp.radians(x), xp.radians(y)
                    rx2, ry2 = np.radians(px), np.radians(py)
                    a = (
                        xp.sin((ry2 - ry1) / 2) ** 2
                        + xp.cos(ry1) * np.cos(ry2) * xp.sin((rx2 - rx1) / 2) ** 2
                    )
                    d = 2 * geo.EARTH_RADIUS_M * xp.arcsin(xp.sqrt(xp.clip(a, 0, 1)))
                    return d <= dist

                return dwithin
            # non-point literal: expanded-bbox approximation
            d_deg = node.distance_m / geo.METERS_PER_DEGREE
            bb = node.geom.bounds()
            maxlat = min(89.0, max(abs(bb[1]), abs(bb[3])))
            dx = d_deg / max(np.cos(np.radians(maxlat)), 1e-3)
            exp = (bb[0] - dx, bb[1] - d_deg, bb[2] + dx, bb[3] + d_deg)

            def dwithin_box(cols, xp):
                x, y = cols[xc], cols[yc]
                return (x >= exp[0]) & (x <= exp[2]) & (y >= exp[1]) & (y <= exp[3])

            return dwithin_box

        if isinstance(node, ir.Compare):
            a = ft.attr(node.prop)
            col = node.prop
            need(col)
            if a.type == "string":
                d = dicts.setdefault(node.prop, DictionaryEncoder())
                if node.op == "=":
                    code = d.code_of(str(node.value))
                    return lambda cols, xp: cols[col] == code
                if node.op == "<>":
                    code = d.code_of(str(node.value))
                    return lambda cols, xp: (cols[col] != code) & (cols[col] >= 0)
                # ordering on strings: resolve against vocab on host
                sval = str(node.value)
                ops = {
                    "<": lambda v: v < sval, "<=": lambda v: v <= sval,
                    ">": lambda v: v > sval, ">=": lambda v: v >= sval,
                }[node.op]
                codes = np.array(
                    [i for i, v in enumerate(d.values) if ops(v)], dtype=np.int32
                )
                return _isin_fn(col, codes)
            if a.type == "bool":
                bv = (
                    node.value
                    if isinstance(node.value, bool)
                    else str(node.value).lower() == "true"
                )
                if node.op == "=":
                    return lambda cols, xp: cols[col] == bv
                if node.op == "<>":
                    return lambda cols, xp: cols[col] != bv
                raise ValueError(f"unsupported boolean comparison {node.op!r}")
            val = node.value
            if a.type == "date":
                if not isinstance(val, (int, np.integer)):
                    from geomesa_tpu.filter.ecql import parse_iso_ms

                    val = parse_iso_ms(str(val))
                v = int(val)
                # rewrite to interval form -> (bin, off) pair compare
                if node.op == "=":
                    return compile_node(ir.During(node.prop, v, v))
                if node.op == "<>":
                    return compile_node(ir.Not(ir.During(node.prop, v, v)))
                if node.op == "<":
                    return compile_node(ir.During(node.prop, ir.MIN_MS, v - 1))
                if node.op == "<=":
                    return compile_node(ir.During(node.prop, ir.MIN_MS, v))
                if node.op == ">":
                    return compile_node(ir.During(node.prop, v + 1, ir.MAX_MS))
                if node.op == ">=":
                    return compile_node(ir.During(node.prop, v, ir.MAX_MS))
            val = float(val) if a.type in ("float32", "float64") else int(val)
            op = node.op
            if op == "=":
                return lambda cols, xp: cols[col] == val
            if op == "<>":
                return lambda cols, xp: cols[col] != val
            if op == "<":
                return lambda cols, xp: cols[col] < val
            if op == "<=":
                return lambda cols, xp: cols[col] <= val
            if op == ">":
                return lambda cols, xp: cols[col] > val
            if op == ">=":
                return lambda cols, xp: cols[col] >= val

        if isinstance(node, ir.Between):
            inner = ir.And(
                (ir.Compare(node.prop, ">=", node.lo), ir.Compare(node.prop, "<=", node.hi))
            )
            return compile_node(inner)

        if isinstance(node, ir.In):
            a = ft.attr(node.prop)
            need(node.prop)
            if a.type == "string":
                d = dicts.setdefault(node.prop, DictionaryEncoder())
                codes = np.array(
                    [d.code_of(str(v)) for v in node.values], dtype=np.int32
                )
                codes = codes[codes >= 0]
                return _isin_fn(node.prop, codes)
            vals = np.array(
                [float(v) if a.type.startswith("float") else int(v) for v in node.values]
            )
            return _isin_fn(node.prop, vals)

        if isinstance(node, ir.Like):
            a = ft.attr(node.prop)
            if a.type != "string":
                raise ValueError(f"LIKE requires a string attribute, got {a.type}")
            need(node.prop)
            d = dicts.setdefault(node.prop, DictionaryEncoder())
            return _isin_fn(node.prop, _like_codes(d, node.pattern, node.case_insensitive))

        if isinstance(node, ir.IsNull):
            a = ft.attr(node.prop)
            need(node.prop)
            col = node.prop
            if a.type == "string":
                fn = lambda cols, xp: cols[col] < 0  # noqa: E731
            elif a.type.startswith("float"):
                fn = lambda cols, xp: xp.isnan(cols[col])  # noqa: E731
            else:
                fn = lambda cols, xp: xp.zeros(cols[col].shape, dtype=bool)  # noqa: E731
            if node.negate:
                return lambda cols, xp: ~fn(cols, xp)
            return fn

        if isinstance(node, ir.During):
            # Temporal predicates run on the (bin, scaled-offset) int32 pair —
            # the device time representation. Lexicographic pair compare.
            from geomesa_tpu.curves.binned_time import BinnedTime

            bt = BinnedTime(ft.time_period)
            scale = bt.off_scale
            CLAMP = 2**45  # ~±1100 years; keeps bins in int32
            lo = max(min(node.lo_ms, CLAMP), -CLAMP)
            hi = max(min(node.hi_ms, CLAMP), -CLAMP)
            lo_b, lo_o = (int(v[0]) for v in bt.to_bin_and_offset(np.asarray([lo])))
            hi_b, hi_o = (int(v[0]) for v in bt.to_bin_and_offset(np.asarray([hi])))
            # floor-quantize both sides; quantization fuzz is < scale ms
            lo_o //= scale
            hi_o //= scale
            cb, co = node.prop + "__bin", node.prop + "__off"
            need(cb, co)

            def during(cols, xp):
                b, o = cols[cb], cols[co]
                ge = (b > lo_b) | ((b == lo_b) & (o >= lo_o))
                le = (b < hi_b) | ((b == hi_b) & (o <= hi_o))
                return ge & le

            return during

        if isinstance(node, ir.IdIn):
            need("__fid__")
            ids = set(node.ids)

            def fid_mask(cols, xp):
                fids = cols["__fid__"]
                # host-only column (object dtype)
                return np.array([f in ids for f in fids], dtype=bool)

            return fid_mask

        raise ValueError(f"cannot compile filter node: {node!r}")

    fn = compile_node(f)
    return CompiledFilter(fn, needed)
