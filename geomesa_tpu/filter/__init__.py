"""Filter engine (L2).

Capability parity with the reference's geomesa-filter module (SURVEY.md §2.3):
ECQL text -> predicate IR -> (a) plan-time analysis (extract spatial/temporal
bounds, the FilterHelper.extractGeometries/extractIntervals analog) and
(b) a fused boolean-mask kernel over columnar arrays (the FastFilterFactory
analog — but instead of per-row evaluators, one vectorized expression that XLA
fuses into the scan).
"""

from geomesa_tpu.filter import ir  # noqa: F401
from geomesa_tpu.filter.ecql import parse_ecql  # noqa: F401
from geomesa_tpu.filter.compile import compile_filter  # noqa: F401
from geomesa_tpu.filter.ir import extract_geometries, extract_intervals  # noqa: F401
