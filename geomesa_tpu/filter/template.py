"""Query templates: split viewport literals out of a predicate tree.

The query-axis megakernel (docs/SERVING.md "Query-axis batching") serves M
*distinct* viewports in one device dispatch by promoting bbox / time-window
literals from trace-baked constants to kernel **data**. This module is the
filter-layer half of that contract:

* :func:`split_literals` — partition a parsed filter tree into literal
  SLOTS (BBOX over a point-geometry column, DURING over a date column —
  the two predicates real map traffic varies per client) and a RESIDUAL
  tree (everything else, kept verbatim). Two queries share a *structural*
  template — and therefore a compiled kernel — iff their slot layout and
  residual repr match; only the slot literal VALUES differ.
* :func:`compile_batched` — compile one template into a literal-
  parameterized mask kernel ``fn(cols, xp, lits_f, lits_i)`` whose f32 /
  int32 comparisons are op-for-op the ones :func:`compile_filter` bakes,
  so each member's batched mask selects EXACTLY the rows its serial
  compiled predicate would (the bit-identity contract the fusion layer
  CI-gates).

Slots are recognized only in *positive conjunctive* position (top-level
AND, arbitrarily nested, no NOT/OR above the slot): that is the shape
panning/zooming viewport traffic has, and it keeps the f32 rounding
polarity of the batched compare identical to the serial compile (which
flips inclusive/strict under odd NOT-nesting).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.filter.compile import CompiledFilter, during_device_bounds
from geomesa_tpu.schema.feature_type import FeatureType


@dataclass(frozen=True)
class Slot:
    """One literal slot: ``kind`` ("bbox" | "during"), the property it
    constrains, and its offset into the float / int literal vectors."""

    kind: str
    prop: str
    f_off: int
    i_off: int


@dataclass
class QueryTemplate:
    """One query's structural template + its literal values.

    ``key`` is the structural identity: equal keys mean the queries
    compile to the same batched kernel and may ride one device dispatch
    (the fusion layer folds it into the fuse-compatibility key in place
    of the raw ECQL text). ``lits_f`` / ``lits_i`` are THIS query's slot
    literal values, laid out per ``slots``.
    """

    key: tuple
    slots: Tuple[Slot, ...]
    residual: ir.Filter
    lits_f: np.ndarray  # [nf] float32
    lits_i: np.ndarray  # [ni] int32


def _flatten_and(f: ir.Filter) -> List[ir.Filter]:
    if isinstance(f, ir.And):
        out: List[ir.Filter] = []
        for c in f.children:
            out.extend(_flatten_and(c))
        return out
    return [f]


def _is_point_geom(ft: FeatureType, prop: str) -> bool:
    try:
        a = ft.attr(prop)
    except Exception:
        return False
    return bool(getattr(a, "is_geom", False) and getattr(a, "is_point", False))


def _is_date(ft: FeatureType, prop) -> bool:
    if not isinstance(prop, str):
        return False
    try:
        a = ft.attr(prop)
    except Exception:
        return False
    return a.type == "date"


def split_literals(f: ir.Filter, ft: FeatureType) -> Optional[QueryTemplate]:
    """Extract the viewport-literal template of ``f``, or None when the
    tree has no batchable slot (nothing to promote to kernel data).

    Only top-level conjuncts slot: a BBOX under OR/NOT keeps its baked
    compile (the residual carries it verbatim, so such queries still fuse
    as identical-text repeats)."""
    conjuncts = _flatten_and(f)
    slots: List[Slot] = []
    slot_descr: List[tuple] = []
    residual: List[ir.Filter] = []
    lits_f: List[float] = []
    lits_i: List[int] = []
    for node in conjuncts:
        if isinstance(node, ir.BBox) and _is_point_geom(ft, node.prop):
            slots.append(Slot("bbox", node.prop, len(lits_f), len(lits_i)))
            slot_descr.append(("bbox", node.prop))
            # f32 images of the bounds — exactly the values
            # compile._f32_box_fn bakes (x0/y0/x1/y1 order)
            lits_f.extend(
                float(np.float32(v))
                for v in (node.xmin, node.ymin, node.xmax, node.ymax)
            )
        elif isinstance(node, ir.During) and _is_date(ft, node.prop):
            slots.append(Slot("during", node.prop, len(lits_f), len(lits_i)))
            slot_descr.append(("during", node.prop))
            # quantized (bin, offset) bounds — the same host quantization
            # the serial compile bakes (compile.during_device_bounds)
            lits_i.extend(during_device_bounds(ft, node.lo_ms, node.hi_ms))
        else:
            residual.append(node)
    if not slots:
        return None
    res: ir.Filter = (
        ir.Include() if not residual
        else residual[0] if len(residual) == 1
        else ir.And(tuple(residual))
    )
    key = ("qtpl.v1", tuple(slot_descr), repr(res))
    return QueryTemplate(
        key=key, slots=tuple(slots), residual=res,
        lits_f=np.asarray(lits_f, np.float32),
        lits_i=np.asarray(lits_i, np.int32),
    )


@dataclass
class BatchedFilter:
    """A literal-parameterized compiled mask kernel for one template.

    ``fn(cols, xp, lf, li)`` — the member mask with that member's literal
    vectors traced in; ``band(cols, xp, lf, li)`` — the member's f32-
    uncertainty band (None when no compare can collide at f32);
    ``columns`` — every column the mask reads. The residual sub-filter is
    compiled by the ordinary :func:`compile_filter` (literals baked —
    they are structural, identical across members by construction).
    """

    fn: Callable
    band: Optional[Callable]
    columns: List[str]
    #: True when the residual is device-exact (no host refinement beyond
    #: the band fallback) — the executor's batch-eligibility gate
    device_exact: bool


def _bbox_slot_fn(ft: FeatureType, slot: Slot):
    a = ft.attr(slot.prop)  # noqa: F841 — validated by split_literals
    xc, yc = slot.prop + "__x", slot.prop + "__y"
    o = slot.f_off

    def fn(cols, xp, lf, li):
        # op-for-op the serial _f32_box_fn (inclusive, even polarity)
        x = xp.asarray(cols[xc]).astype(xp.float32)
        y = xp.asarray(cols[yc]).astype(xp.float32)
        return (x >= lf[o]) & (x <= lf[o + 2]) \
            & (y >= lf[o + 1]) & (y <= lf[o + 3])

    def band(cols, xp, lf, li):
        # f32-collision band: union of the four bound collisions — the
        # same row set compile.band_eq registers (dedup is immaterial
        # for a boolean union)
        x = xp.asarray(cols[xc]).astype(xp.float32)
        y = xp.asarray(cols[yc]).astype(xp.float32)
        return (x == lf[o]) | (x == lf[o + 2]) \
            | (y == lf[o + 1]) | (y == lf[o + 3])

    return fn, band, [xc, yc]


def _during_slot_fn(slot: Slot):
    cb, co = slot.prop + "__bin", slot.prop + "__off"
    o = slot.i_off

    def fn(cols, xp, lf, li):
        # lexicographic (bin, offset) pair compare — the serial During
        # kernel with the quantized bounds traced instead of baked
        b, off = cols[cb], cols[co]
        ge = (b > li[o]) | ((b == li[o]) & (off >= li[o + 1]))
        le = (b < li[o + 2]) | ((b == li[o + 2]) & (off <= li[o + 3]))
        return ge & le

    return fn, None, [cb, co]


def compile_batched(tpl: QueryTemplate, ft: FeatureType,
                    residual_compiled: CompiledFilter) -> BatchedFilter:
    """Assemble the batched mask kernel for one template.

    ``residual_compiled`` is the compiled residual filter — built by the
    caller via the ordinary :func:`compile_filter` (and visibility-wrapped
    there when auths apply), so string-code resolution, f32 band
    registration and dictionary fingerprints keep their one
    implementation. Conjunct order differs from the serial compile
    (residual first, then slots) — boolean AND over exact masks is
    order-independent, so the member row set is unchanged."""
    slot_fns: List[Callable] = []
    slot_bands: List[Callable] = []
    columns = list(residual_compiled.columns)
    for slot in tpl.slots:
        if slot.kind == "bbox":
            fn, band, cols = _bbox_slot_fn(ft, slot)
        else:
            fn, band, cols = _during_slot_fn(slot)
        slot_fns.append(fn)
        if band is not None:
            slot_bands.append(band)
        for c in cols:
            if c not in columns:
                columns.append(c)
    res_fn = residual_compiled.fn
    res_band = residual_compiled.band

    def fn(cols, xp, lf, li):
        m = res_fn(cols, xp)
        for sfn in slot_fns:
            m = m & sfn(cols, xp, lf, li)
        return m

    band = None
    if slot_bands or res_band is not None:

        def band(cols, xp, lf, li):  # noqa: F811
            m = None
            if res_band is not None:
                m = res_band(cols, xp)
            for sb in slot_bands:
                b = sb(cols, xp, lf, li)
                m = b if m is None else (m | b)
            return m

    device_exact = (
        residual_compiled.refine is None
        or residual_compiled.refine_only_if_band
    )
    return BatchedFilter(
        fn=fn, band=band, columns=columns, device_exact=device_exact,
    )
