"""(E)CQL text -> predicate IR.

A hand-rolled recursive-descent parser for the ECQL subset GeoMesa queries
actually use (reference surface: GeoTools ECQL via FastFilterFactory.toFilter,
geomesa-filter/.../factory/FastFilterFactory.scala):

    INCLUDE | EXCLUDE
    BBOX(geom, xmin, ymin, xmax, ymax)
    INTERSECTS/CONTAINS/WITHIN/DISJOINT(geom, WKT)
    DWITHIN(geom, WKT, distance, units)
    a = | <> | != | < | <= | > | >= literal
    a BETWEEN x AND y | a IN (v1, v2) | a LIKE 'pat%' | a ILIKE
    a IS [NOT] NULL
    dtg DURING t1/t2 | dtg BEFORE t | dtg AFTER t | dtg TEQUALS t
    IN ('id1', 'id2')              -- feature-id filter
    AND / OR / NOT, parentheses
    expr CMP expr                  -- property-vs-property / arithmetic /
                                   -- function comparisons
                                   -- (FastFilterFactory.scala:395 parity):
        speed > heading
        weight * 2 < limit
        (a + b) * 2 >= c - 1
        st_area(geom) > 0.5
        st_distanceSphere(geom, st_geomFromWKT('POINT (0 0)')) < 1e5
    jsonPath('$.a.b', attr) CMP literal

Functions resolve against :mod:`geomesa_tpu.geofn`'s st_* library.
Dates are ISO-8601 (bare or quoted); bare date tokens are recognized lexically.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.filter import ir
from geomesa_tpu.utils import geometry as geo

_ISO = r"\d{4}-\d{2}-\d{2}(?:[T ]\d{2}:\d{2}(?::\d{2}(?:\.\d+)?)?(?:Z|[-+]\d{2}:?\d{2})?)?"

_TOKEN_RE = re.compile(
    "|".join(
        [
            r"(?P<date>" + _ISO + r")",
            r"(?P<num>[-+]?\d+\.?\d*(?:[eE][-+]?\d+)?)",
            r"(?P<str>'(?:[^']|'')*')",
            r"(?P<op><=|>=|<>|!=|=|<|>)",
            r"(?P<sym>[(),/*+\-])",
            r"(?P<id>[A-Za-z_][A-Za-z0-9_.:]*)",
            r"(?P<ws>\s+)",
        ]
    )
)

_KEYWORDS = {
    "AND", "OR", "NOT", "INCLUDE", "EXCLUDE", "BBOX", "INTERSECTS", "CONTAINS",
    "WITHIN", "DISJOINT", "CROSSES", "OVERLAPS", "TOUCHES", "EQUALS", "DWITHIN",
    "BEYOND", "DURING", "BEFORE", "AFTER", "TEQUALS", "BETWEEN", "IN", "LIKE",
    "ILIKE", "IS", "NULL",
}


class _Tok:
    __slots__ = ("kind", "text")

    def __init__(self, kind, text):
        self.kind = kind
        self.text = text

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def _lex(s: str) -> List[_Tok]:
    out = []
    pos = 0
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if not m:
            raise ValueError(f"ECQL lex error at: {s[pos:pos+30]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "id" and text.upper() in _KEYWORDS:
            out.append(_Tok("kw", text.upper()))
        else:
            out.append(_Tok(kind, text))
    return out


def parse_iso_ms(s: str) -> int:
    """ISO-8601 -> epoch ms (UTC assumed when no offset given)."""
    s = s.strip().strip("'")
    s = s.replace(" ", "T")
    if s.endswith("Z"):
        s = s[:-1]
    return int(np.datetime64(s, "ms").astype(np.int64))


class _Parser:
    def __init__(self, toks: List[_Tok], text: str):
        self.toks = toks
        self.pos = 0
        self.text = text

    def peek(self) -> Optional[_Tok]:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def next(self) -> _Tok:
        t = self.peek()
        if t is None:
            raise ValueError(f"unexpected end of ECQL: {self.text!r}")
        self.pos += 1
        return t

    def accept(self, kind, text=None) -> Optional[_Tok]:
        t = self.peek()
        if t and t.kind == kind and (text is None or t.text == text):
            self.pos += 1
            return t
        return None

    def expect(self, kind, text=None) -> _Tok:
        t = self.accept(kind, text)
        if t is None:
            raise ValueError(
                f"ECQL parse error: expected {text or kind} at token "
                f"{self.peek()!r} in {self.text!r}"
            )
        return t

    # expr := term (OR term)*
    def expr(self) -> ir.Filter:
        left = self.term()
        terms = [left]
        while self.accept("kw", "OR"):
            terms.append(self.term())
        return terms[0] if len(terms) == 1 else ir.Or(tuple(terms))

    # term := factor (AND factor)*
    def term(self) -> ir.Filter:
        left = self.factor()
        factors = [left]
        while self.accept("kw", "AND"):
            factors.append(self.factor())
        return factors[0] if len(factors) == 1 else ir.And(tuple(factors))

    def factor(self) -> ir.Filter:
        if self.accept("kw", "NOT"):
            return ir.Not(self.factor())
        t = self.peek()
        if t and t.kind == "sym" and t.text == "(":
            # '(' opens either a boolean group or an arithmetic group
            # ('(a + b) * 2 >= c'): try boolean, backtrack to the
            # expression-led predicate parse on failure
            mark = self.pos
            try:
                self.next()
                e = self.expr()
                self.expect("sym", ")")
                return e
            except ValueError:
                self.pos = mark
        return self.predicate()

    # -- literals ---------------------------------------------------------
    def literal(self):
        t = self.next()
        if t.kind == "num":
            v = float(t.text)
            return int(v) if v.is_integer() and "." not in t.text and "e" not in t.text.lower() else v
        if t.kind == "str":
            inner = t.text[1:-1].replace("''", "'")
            if re.fullmatch(_ISO, inner):
                return np.int64(parse_iso_ms(inner))
            return inner
        if t.kind == "date":
            return np.int64(parse_iso_ms(t.text))
        if t.kind == "id" and t.text.lower() in ("true", "false"):
            return t.text.lower() == "true"
        raise ValueError(f"ECQL: expected literal, got {t!r}")

    def wkt_literal(self) -> geo.Geometry:
        t = self.next()
        if t.kind == "str":
            return geo.parse_wkt(t.text[1:-1])
        # bare WKT: TYPE ( ... ) — re-lex from source text by paren matching
        if t.kind == "id" or (t.kind == "kw"):
            tag = t.text
            self.expect("sym", "(")
            depth = 1
            parts = ["("]
            while depth > 0:
                nt = self.next()
                if nt.kind == "sym" and nt.text == "(":
                    depth += 1
                elif nt.kind == "sym" and nt.text == ")":
                    depth -= 1
                parts.append(nt.text)
            return geo.parse_wkt(tag + " " + " ".join(parts))
        raise ValueError(f"ECQL: expected WKT geometry, got {t!r}")

    # -- scalar expressions (FastFilterFactory.scala:395 parity) ----------
    @staticmethod
    def _mk_arith(op: str, left, right):
        """Build an Arith node; jsonPath() refs cannot ride arithmetic,
        and literal-only subtrees fold to a literal (so 'speed < 1 + 1'
        and unary minus keep the legacy Compare IR + its pushdown)."""
        for side in (left, right):
            if isinstance(side, ir.JsonPath):
                raise ValueError(
                    "jsonPath() cannot appear inside arithmetic "
                    "expressions; compare it directly against a literal"
                )
        if isinstance(left, ir.Lit) and isinstance(right, ir.Lit) \
                and isinstance(left.value, (int, float, np.integer)) \
                and isinstance(right.value, (int, float, np.integer)):
            lv, rv = left.value, right.value
            if op == "+":
                return ir.Lit(lv + rv)
            if op == "-":
                return ir.Lit(lv - rv)
            if op == "*":
                return ir.Lit(lv * rv)
            if rv != 0:
                v = lv / rv
                return ir.Lit(int(v) if isinstance(lv, (int, np.integer))
                              and isinstance(rv, (int, np.integer))
                              and v == int(v) else v)
        return ir.Arith(op, left, right)

    # additive := multiplicative (('+'|'-') multiplicative)*
    def expr_operand(self):
        left = self.expr_mul()
        while True:
            t = self.peek()
            if t and t.kind == "sym" and t.text in "+-":
                self.next()
                left = self._mk_arith(t.text, left, self.expr_mul())
            elif t and t.kind == "num" and t.text[0] in "+-":
                # 'a -5' lexes the sign into the number: it is really a
                # binary minus (a + (-5))
                self.next()
                v = float(t.text)
                v = int(v) if v.is_integer() and "." not in t.text else v
                left = self._mk_arith("+", left, ir.Lit(v))
            else:
                return left

    def expr_mul(self):
        left = self.expr_unary()
        while True:
            t = self.peek()
            if t and t.kind == "sym" and t.text in "*/":
                self.next()
                left = self._mk_arith(t.text, left, self.expr_unary())
            else:
                return left

    def expr_unary(self):
        t = self.peek()
        if t is None:
            raise ValueError("ECQL: expected expression operand")
        if t.kind == "sym" and t.text == "(":
            self.next()
            e = self.expr_operand()
            self.expect("sym", ")")
            return e
        if t.kind == "sym" and t.text == "-":
            self.next()
            return self._mk_arith("-", ir.Lit(0), self.expr_unary())
        if t.kind in ("num", "str", "date"):
            return ir.Lit(self.literal())
        if t.kind == "id":
            name = self.next().text
            if name.lower() in ("true", "false"):
                return ir.Lit(name.lower() == "true")
            nt = self.peek()
            if nt and nt.kind == "sym" and nt.text == "(":
                if name.lower() == "jsonpath":
                    self.next()
                    path = str(self.literal())
                    self.expect("sym", ",")
                    attr = self.expect("id").text
                    self.expect("sym", ")")
                    return ir.JsonPath(attr, path)
                self.next()
                args = []
                if not self.accept("sym", ")"):
                    while True:
                        a = self.expr_operand()
                        if isinstance(a, ir.JsonPath):
                            raise ValueError(
                                "jsonPath() cannot be a function argument;"
                                " compare it directly against a literal"
                            )
                        args.append(a)
                        if not self.accept("sym", ","):
                            break
                    self.expect("sym", ")")
                return ir.FnCall(name, tuple(args))
            return ir.Prop(name)
        raise ValueError(f"ECQL: expected expression operand, got {t!r}")

    # -- predicates -------------------------------------------------------
    def predicate(self) -> ir.Filter:
        t = self.peek()
        if t is None:
            raise ValueError("empty predicate")
        if t.kind == "kw":
            kw = t.text
            if kw == "INCLUDE":
                self.next()
                return ir.Include()
            if kw == "EXCLUDE":
                self.next()
                return ir.Exclude()
            if kw == "BBOX":
                self.next()
                self.expect("sym", "(")
                prop = self.expect("id").text
                self.expect("sym", ",")
                nums = []
                for i in range(4):
                    nums.append(float(self.expect("num").text))
                    if i < 3:
                        self.expect("sym", ",")
                # optional CRS arg
                if self.accept("sym", ","):
                    self.next()  # ignore crs string
                self.expect("sym", ")")
                return ir.BBox(prop, nums[0], nums[1], nums[2], nums[3])
            if kw in ("INTERSECTS", "CONTAINS", "WITHIN", "DISJOINT", "CROSSES",
                      "OVERLAPS", "TOUCHES", "EQUALS"):
                self.next()
                self.expect("sym", "(")
                prop = self.expect("id").text
                self.expect("sym", ",")
                g = self.wkt_literal()
                self.expect("sym", ")")
                return ir.Spatial(kw.lower(), prop, g)
            if kw in ("DWITHIN", "BEYOND"):
                self.next()
                self.expect("sym", "(")
                prop = self.expect("id").text
                self.expect("sym", ",")
                g = self.wkt_literal()
                self.expect("sym", ",")
                dist = float(self.expect("num").text)
                self.expect("sym", ",")
                units = self.expect("id").text.lower()
                self.expect("sym", ")")
                factor = {
                    "meters": 1.0, "metres": 1.0, "m": 1.0,
                    "kilometers": 1000.0, "km": 1000.0,
                    "feet": 0.3048, "statute miles": 1609.344, "miles": 1609.344,
                    "nautical miles": 1852.0,
                }.get(units, 1.0)
                node = ir.DWithin(prop, g, dist * factor)
                return ir.Not(node) if kw == "BEYOND" else node
            if kw == "IN":  # feature-id filter
                self.next()
                self.expect("sym", "(")
                ids = []
                while True:
                    lit = self.literal()
                    ids.append(str(lit))
                    if not self.accept("sym", ","):
                        break
                self.expect("sym", ")")
                return ir.IdIn(tuple(ids))
        # property-led predicates: the LHS is a full scalar expression
        # (property, jsonPath(), arithmetic, st_* function call); plain
        # property-vs-literal forms keep the legacy Compare IR (and all
        # its device pushdown), anything richer becomes ExprCompare
        lhs = self.expr_operand()
        if isinstance(lhs, ir.JsonPath):
            prop = lhs
        elif isinstance(lhs, ir.Prop):
            prop = lhs.name
        else:
            prop = None  # expression: comparison operators only
        t = self.peek()
        if t and t.kind == "op":
            op = self.next().text
            if op == "!=":
                op = "<>"
            rhs = self.expr_operand()
            if prop is not None and isinstance(rhs, ir.Lit):
                return ir.Compare(prop, op, rhs.value)
            if isinstance(lhs, ir.Lit) and isinstance(rhs, ir.Prop):
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                return ir.Compare(rhs.name, flip.get(op, op), lhs.value)
            if isinstance(lhs, ir.JsonPath) or isinstance(rhs, ir.JsonPath):
                raise ValueError(
                    "jsonPath() comparisons support literal operands only"
                )
            if isinstance(lhs, ir.Lit) and isinstance(rhs, ir.Lit):
                # constant comparison folds at parse time ('1 + 1 = 2').
                # Dispatch on the op — eagerly building a table of all six
                # evaluated '1 < "a"' even for '1 = "a"', leaking TypeError
                # past parser backtracking
                a, b = lhs.value, rhs.value
                try:
                    if op == "=":
                        res = a == b
                    elif op == "<>":
                        res = a != b
                    elif op == "<":
                        res = a < b
                    elif op == "<=":
                        res = a <= b
                    elif op == ">":
                        res = a > b
                    else:
                        res = a >= b
                except TypeError as e:
                    raise ValueError(
                        f"incomparable literal types in {self.text!r}: "
                        f"{a!r} {op} {b!r}"
                    ) from e
                return ir.Include() if res else ir.Exclude()
            return ir.ExprCompare(op, lhs, rhs)
        if prop is None:
            raise ValueError(
                f"ECQL: expression must be followed by a comparison "
                f"operator in {self.text!r}"
            )
        if t and t.kind == "kw":
            kw = self.next().text
            if kw == "BETWEEN":
                lo = self.literal()
                self.expect("kw", "AND")
                hi = self.literal()
                return ir.Between(prop, lo, hi)
            if kw == "IN":
                self.expect("sym", "(")
                vals = []
                while True:
                    vals.append(self.literal())
                    if not self.accept("sym", ","):
                        break
                self.expect("sym", ")")
                return ir.In(prop, tuple(vals))
            if kw in ("LIKE", "ILIKE"):
                pat = self.literal()
                return ir.Like(prop, str(pat), case_insensitive=(kw == "ILIKE"))
            if kw == "IS":
                neg = bool(self.accept("kw", "NOT"))
                self.expect("kw", "NULL")
                return ir.IsNull(prop, negate=neg)
            if kw == "DURING":
                lo = self.literal()
                self.expect("sym", "/")
                hi = self.literal()
                return ir.During(prop, int(lo), int(hi))
            if kw == "BEFORE":
                return ir.During(prop, ir.MIN_MS, int(self.literal()) - 1)
            if kw == "AFTER":
                return ir.During(prop, int(self.literal()) + 1, ir.MAX_MS)
            if kw == "TEQUALS":
                v = int(self.literal())
                return ir.During(prop, v, v)
        raise ValueError(f"ECQL parse error near {prop!r} in {self.text!r}")


def parse_ecql(text: str) -> ir.Filter:
    """Parse ECQL text into the predicate IR."""
    toks = _lex(text)
    if not toks:
        return ir.Include()
    p = _Parser(toks, text)
    f = p.expr()
    if p.peek() is not None:
        raise ValueError(f"trailing tokens in ECQL: {p.peek()!r} in {text!r}")
    return f
