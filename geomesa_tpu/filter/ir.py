"""Predicate intermediate representation + plan-time analysis.

The IR is the common currency between the ECQL parser, the query planner
(index selection from extracted bounds — FilterHelper.extractGeometries /
extractIntervals analogs, reference filter/FilterHelper.scala), and the mask
compiler.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.utils import geometry as geo

MIN_MS = -(2**62)
MAX_MS = 2**62


class Filter:
    def __and__(self, other):
        return And([self, other])

    def __or__(self, other):
        return Or([self, other])

    def __invert__(self):
        return Not(self)


@dataclass(frozen=True)
class Include(Filter):
    """Match everything (ECQL INCLUDE)."""


@dataclass(frozen=True)
class Exclude(Filter):
    """Match nothing (ECQL EXCLUDE)."""


@dataclass(frozen=True)
class And(Filter):
    children: Sequence[Filter]


@dataclass(frozen=True)
class Or(Filter):
    children: Sequence[Filter]


@dataclass(frozen=True)
class Not(Filter):
    child: Filter


@dataclass(frozen=True)
class BBox(Filter):
    prop: str
    xmin: float
    ymin: float
    xmax: float
    ymax: float


@dataclass(frozen=True)
class Spatial(Filter):
    """INTERSECTS / CONTAINS / WITHIN / DISJOINT / CROSSES / OVERLAPS /
    TOUCHES / EQUALS — exact semantics (FastFilterFactory.scala:395):
    point columns evaluate exactly in the scan kernel; extent columns get a
    bbox coarse mask plus an exact host refinement pass."""

    op: str  # intersects|contains|within|disjoint|crosses|overlaps|touches|equals
    prop: str
    geom: geo.Geometry


@dataclass(frozen=True)
class DWithin(Filter):
    prop: str
    geom: geo.Geometry
    distance_m: float


@dataclass(frozen=True)
class JsonPath:
    """Property reference into a stored-JSON attribute: the ECQL
    ``jsonPath('$.a.b', attr)`` accessor (reference geomesa-feature-kryo
    json/ JSONPath pushdown). Usable wherever a property name is — the
    filter compiler emits a host-side document evaluator for it."""

    attr: str
    path: str


@dataclass(frozen=True)
class Compare(Filter):
    """=, <>, <, <=, >, >= on a scalar attribute."""

    prop: "str | JsonPath"
    op: str
    value: object  # float | int | str | np.int64 epoch-ms for dates


# -- expression trees (FastFilterFactory.scala:395 parity: arbitrary
# GeoTools expressions — property-vs-property, arithmetic, functions) ----

@dataclass(frozen=True)
class Expr:
    """Scalar expression node (the GeoTools Expression analog)."""


@dataclass(frozen=True)
class Prop(Expr):
    name: str


@dataclass(frozen=True)
class Lit(Expr):
    value: object


@dataclass(frozen=True)
class Arith(Expr):
    """Binary arithmetic: + - * /"""

    op: str
    left: Expr
    right: Expr


@dataclass(frozen=True)
class FnCall(Expr):
    """Filter-function call, e.g. ``st_area(geom)`` (the GeoTools
    FilterFunction surface; resolved against geofn's st_* library)."""

    name: str
    args: Tuple[Expr, ...]


def expr_props(e: Expr) -> List[str]:
    """Attribute names referenced by an expression tree."""
    if isinstance(e, Prop):
        return [e.name]
    if isinstance(e, Arith):
        return expr_props(e.left) + expr_props(e.right)
    if isinstance(e, FnCall):
        out: List[str] = []
        for a in e.args:
            out.extend(expr_props(a))
        return out
    return []


def expr_has_fn(e: Expr) -> bool:
    if isinstance(e, FnCall):
        return True
    if isinstance(e, Arith):
        return expr_has_fn(e.left) or expr_has_fn(e.right)
    return False


@dataclass(frozen=True)
class ExprCompare(Filter):
    """Comparison where either side is a non-trivial expression:
    ``speed > heading``, ``weight * 2 < limit``, ``st_area(geom) > 0.5``.
    Compiles to an exact host mask (+ an error-bounded f32 device
    prefilter when function-free)."""

    op: str  # = <> < <= > >=
    left: Expr
    right: Expr

    def props(self) -> List[str]:
        return expr_props(self.left) + expr_props(self.right)


@dataclass(frozen=True)
class Between(Filter):
    prop: str
    lo: object
    hi: object


@dataclass(frozen=True)
class In(Filter):
    prop: str
    values: Tuple[object, ...]


@dataclass(frozen=True)
class Like(Filter):
    prop: str
    pattern: str
    case_insensitive: bool = False


@dataclass(frozen=True)
class IsNull(Filter):
    prop: str
    negate: bool = False


@dataclass(frozen=True)
class During(Filter):
    """Temporal interval (also covers BEFORE/AFTER/TEQUALS via open bounds)."""

    prop: str
    lo_ms: int  # inclusive
    hi_ms: int  # inclusive


@dataclass(frozen=True)
class IdIn(Filter):
    """Feature-id filter (ECQL ``IN ('id1', 'id2')`` with no property)."""

    ids: Tuple[str, ...]


# ---------------------------------------------------------------------------
# Analysis: pull spatial / temporal / attribute bounds out of a filter tree
# (reference FilterHelper.extractGeometries:/.extractIntervals)
# ---------------------------------------------------------------------------

@dataclass
class FilterValues:
    """Extracted values plus a 'disjoint' flag (provably-empty query)."""

    values: list
    disjoint: bool = False

    @property
    def is_empty(self):
        return not self.values and not self.disjoint


def extract_geometries(f: Filter, geom_prop: str) -> FilterValues:
    """Extract the spatial query geometries constraining ``geom_prop``.

    Returns geometries whose union bounds the query window (over-approximate
    for Or, intersection-of-bboxes for And). Conservative: anything not
    understood widens to unbounded (empty list).
    """

    def walk(node: Filter) -> Optional[List[geo.Geometry]]:
        # None = unbounded
        if isinstance(node, BBox) and node.prop == geom_prop:
            return [geo.bbox_polygon(node.xmin, node.ymin, node.xmax, node.ymax)]
        if isinstance(node, Spatial) and node.prop == geom_prop:
            if node.op != "disjoint":
                # every non-disjoint relation implies bbox interaction with
                # the literal, so its bounds constrain the scan window
                return [node.geom]
            return None  # disjoint: unbounded
        if isinstance(node, DWithin) and node.prop == geom_prop:
            d = node.distance_m / geo.METERS_PER_DEGREE
            b = node.geom.bounds()
            # widen longitude by latitude-dependent factor (conservative)
            maxlat = min(89.0, max(abs(b[1]), abs(b[3])))
            dx = d / max(np.cos(np.radians(maxlat)), 1e-3)
            return [geo.bbox_polygon(b[0] - dx, b[1] - d, b[2] + dx, b[3] + d)]
        if isinstance(node, And):
            bounds = None
            geoms = None
            for c in node.children:
                g = walk(c)
                if g is None:
                    continue
                if not g:
                    # a provably-empty arm (EXCLUDE, folded constants)
                    # empties the whole conjunction — and must not reach
                    # _union_bounds, which needs >= 1 geometry
                    return []
                if geoms is None:
                    geoms, bounds = g, _union_bounds(g)
                else:
                    nb = _union_bounds(g)
                    inter = _intersect_bounds(bounds, nb)
                    if inter is None:
                        return []  # provably disjoint
                    # keep the more selective (smaller-area) geometry list
                    if _area(nb) < _area(bounds):
                        geoms = g
                    bounds = inter
            return geoms
        if isinstance(node, Or):
            out = []
            for c in node.children:
                g = walk(c)
                if g is None:
                    return None  # one unbounded arm -> unbounded
                out.extend(g)
            return out
        if isinstance(node, Exclude):
            return []
        return None

    g = walk(f)
    if g is None:
        return FilterValues([])
    if g == []:
        return FilterValues([], disjoint=True)
    return FilterValues(g)


def extract_intervals(f: Filter, dtg_prop: str) -> FilterValues:
    """Extract temporal [lo_ms, hi_ms] intervals constraining ``dtg_prop``."""

    def walk(node: Filter) -> Optional[List[Tuple[int, int]]]:
        if isinstance(node, During) and node.prop == dtg_prop:
            return [(node.lo_ms, node.hi_ms)]
        if isinstance(node, Compare) and node.prop == dtg_prop:
            v = int(node.value)
            if node.op == "=":
                return [(v, v)]
            if node.op in ("<", "<="):
                return [(MIN_MS, v)]
            if node.op in (">", ">="):
                return [(v, MAX_MS)]
            return None
        if isinstance(node, Between) and node.prop == dtg_prop:
            return [(int(node.lo), int(node.hi))]
        if isinstance(node, And):
            acc = None
            for c in node.children:
                iv = walk(c)
                if iv is None:
                    continue
                if acc is None:
                    acc = iv
                else:
                    merged = []
                    for (a0, a1) in acc:
                        for (b0, b1) in iv:
                            lo, hi = max(a0, b0), min(a1, b1)
                            if lo <= hi:
                                merged.append((lo, hi))
                    if not merged:
                        return []
                    acc = merged
            return acc
        if isinstance(node, Or):
            out = []
            for c in node.children:
                iv = walk(c)
                if iv is None:
                    return None
                out.extend(iv)
            return out
        if isinstance(node, Exclude):
            return []
        return None

    iv = walk(f)
    if iv is None:
        return FilterValues([])
    if iv == []:
        return FilterValues([], disjoint=True)
    return FilterValues(_merge_intervals(iv))


def extract_ids(f: Filter) -> Optional[Tuple[str, ...]]:
    if isinstance(f, IdIn):
        return f.ids
    if isinstance(f, And):
        for c in f.children:
            ids = extract_ids(c)
            if ids is not None:
                return ids
    return None


def props_referenced(f: Filter) -> List[str]:
    out: List[str] = []

    def walk(node):
        if isinstance(node, (And, Or)):
            for c in node.children:
                walk(c)
        elif isinstance(node, Not):
            walk(node.child)
        elif isinstance(node, ExprCompare):
            for p in node.props():
                if p not in out:
                    out.append(p)
        elif hasattr(node, "prop"):
            p = node.prop
            if isinstance(p, JsonPath):
                p = p.attr
            if p not in out:
                out.append(p)

    walk(f)
    return out


def _merge_intervals(iv: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    iv = sorted(iv)
    out = [iv[0]]
    for lo, hi in iv[1:]:
        if lo <= out[-1][1] + 1:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _union_bounds(geoms: List[geo.Geometry]):
    bs = np.asarray([g.bounds() for g in geoms])
    return (bs[:, 0].min(), bs[:, 1].min(), bs[:, 2].max(), bs[:, 3].max())


def _intersect_bounds(a, b):
    lo = (max(a[0], b[0]), max(a[1], b[1]))
    hi = (min(a[2], b[2]), min(a[3], b[3]))
    if lo[0] > hi[0] or lo[1] > hi[1]:
        return None
    return (lo[0], lo[1], hi[0], hi[1])


def _area(b) -> float:
    return max(b[2] - b[0], 0.0) * max(b[3] - b[1], 0.0)


def extract_attr_bounds(f: Filter, prop: str) -> FilterValues:
    """Extract value bounds [(lo, hi)] constraining a scalar attribute — drives
    the attribute index's range windows (reference: FilterHelper bounds algebra
    over attribute predicates). Bounds are closed; None = open end."""

    def walk(node: Filter):
        if isinstance(node, Compare) and node.prop == prop:
            v = node.value
            if node.op == "=":
                return [(v, v)]
            if node.op in ("<", "<="):
                return [(None, v)]
            if node.op in (">", ">="):
                return [(v, None)]
            return None
        if isinstance(node, Between) and node.prop == prop:
            return [(node.lo, node.hi)]
        if isinstance(node, In) and node.prop == prop:
            return [(v, v) for v in node.values]
        if isinstance(node, During) and node.prop == prop:
            return [(node.lo_ms, node.hi_ms)]
        if isinstance(node, And):
            acc = None
            for c in node.children:
                b = walk(c)
                if b is None:
                    continue
                if acc is None:
                    acc = b
                else:
                    merged = []
                    for (a0, a1) in acc:
                        for (b0, b1) in b:
                            lo = b0 if a0 is None else a0 if b0 is None else max(a0, b0)
                            hi = b1 if a1 is None else a1 if b1 is None else min(a1, b1)
                            if lo is None or hi is None or lo <= hi:
                                merged.append((lo, hi))
                    if not merged:
                        return []
                    acc = merged
            return acc
        if isinstance(node, Or):
            out = []
            for c in node.children:
                b = walk(c)
                if b is None:
                    return None
                out.extend(b)
            return out
        if isinstance(node, Exclude):
            return []
        return None

    b = walk(f)
    if b is None:
        return FilterValues([])
    if b == []:
        return FilterValues([], disjoint=True)
    return FilterValues(b)
