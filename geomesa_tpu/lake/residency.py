"""Cross-chunk row-group residency cache (docs/JOIN.md §11).

Window-pushdown join side scans chunk the LEFT side's cells and re-scan
the RIGHT side once per chunk; adjacent chunks' inflated windows overlap
by the join reach, so the row groups straddling a chunk boundary survive
pruning in BOTH chunks and decode twice. A :class:`GroupResidencyCache`
rides the whole chunk loop (one per join, threaded plan → window →
``scan_child`` → ``PartitionSnapshot.read_column``): a decoded column
chunk keyed ``(snapshot dir, prefixed column, row group)`` is served from
memory on its second touch instead of re-reading + re-decoding the blob.

The cache is strictly an accelerator — a hit returns the SAME bytes a
fresh decode would (the lake file is immutable per snapshot dir and the
join holds its plans for the loop's duration), so join counts stay
bit-identical with the cache on, off, or thrashing. Cached arrays are
marked read-only; a consumer that tried to mutate a shared chunk fails
loudly instead of corrupting later chunks.

Budget is ``geomesa.join.pushdown.residency.mb`` (decoded bytes, LRU
evict; "0" disables). Hit/saved-bytes totals surface in
``stats.pushdown`` (``residency_hits`` / ``bytes_saved_residency``) and
the ``join.pushdown.residency.*`` counters.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from geomesa_tpu import config

_Key = Tuple[str, str, int]


class GroupResidencyCache:
    """LRU over decoded per-group arrays, bounded by decoded bytes.

    One instance spans one join's chunk loop. Thread-safe: the pushdown
    executor may fan a chunk's partitions over worker threads.
    """

    def __init__(self, budget_bytes: int):
        self.budget = int(budget_bytes)
        self._rows: "OrderedDict[_Key, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()
        self.held_bytes = 0
        #: times a group chunk was served from memory
        self.hits = 0
        self.misses = 0
        #: encoded blob bytes NOT re-read thanks to hits — the honest
        #: "saved" figure (decode cost scales with the encoded payload)
        self.bytes_saved = 0
        self.evictions = 0

    @classmethod
    def from_config(cls) -> Optional["GroupResidencyCache"]:
        mb = config.JOIN_PUSHDOWN_RESIDENCY_MB.to_int()
        mb = 64 if mb is None else int(mb)
        if mb <= 0:
            return None
        return cls(mb << 20)

    def fetch(self, dir_: str, name: str, gi: int, ref,
              file) -> np.ndarray:
        """The decoded array for blob ``ref`` of group ``gi``, from cache
        or via ``file.read_array`` (then cached, read-only)."""
        key = (dir_, name, int(gi))
        with self._lock:
            arr = self._rows.get(key)
            if arr is not None:
                self._rows.move_to_end(key)
                self.hits += 1
                self.bytes_saved += int(file.blob_nbytes(ref))
                return arr
        arr = file.read_array(ref)
        arr.setflags(write=False)
        with self._lock:
            self.misses += 1
            if key not in self._rows:
                self._rows[key] = arr
                self.held_bytes += int(arr.nbytes)
                while self.held_bytes > self.budget and len(self._rows) > 1:
                    _, old = self._rows.popitem(last=False)
                    self.held_bytes -= int(old.nbytes)
                    self.evictions += 1
        return arr

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "bytes_saved": self.bytes_saved,
                "held_bytes": self.held_bytes,
                "entries": len(self._rows),
                "evictions": self.evictions,
            }
