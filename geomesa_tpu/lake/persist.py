"""Aggregate-cache persistence through the lake tier (docs/CACHE.md).

The warm flat-cell / hierarchy / curve-chunk entries of a dataset's
:class:`~geomesa_tpu.cache.store.CacheStore` die with the process today;
this module writes them through the same footer-indexed container the
partition snapshots use, so a restarted process re-serves warm aggregates
without a rescan — a fully-warm zoom-out answers with ZERO device
dispatches right after restore (the bench/CI ``cache_persist_restore``
gate).

Contract: a persisted entry is only valid against the same logical data
snapshot it was computed from. Each schema's section carries a **guard**
(row count + schema spec); restore imports a section only when the live
store matches its guard, and imports under the live store's CURRENT
epoch, so the normal epoch invalidation keeps protecting every later
mutation. Persisting is snapshot-in-time: entries whose stored epoch no
longer matches the store's version are skipped at save.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Tuple

import numpy as np

from geomesa_tpu import metrics, resilience
from geomesa_tpu.lake.format import LakeCorruptError, LakeFile, LakeWriter


def _enc_value(w: LakeWriter, v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"t": "bool", "v": bool(v)}
    if isinstance(v, (int, np.integer)):
        return {"t": "int", "v": int(v)}
    if isinstance(v, (float, np.floating)):
        return {"t": "float", "v": float(v)}
    if isinstance(v, str):
        return {"t": "str",
                "r": w.add_array(np.frombuffer(v.encode(), np.uint8))}
    if isinstance(v, bytes):
        return {"t": "bytes", "r": w.add_array(np.frombuffer(v, np.uint8))}
    if isinstance(v, np.ndarray):
        # ravel through the delta encoder (integer-valued grids pack to a
        # few bits/cell); the shape restores on decode
        return {"t": "arr", "r": w.add_array(np.ascontiguousarray(v).ravel()),
                "shape": list(v.shape), "dtype": str(v.dtype)}
    if isinstance(v, tuple):
        return {"t": "tuple", "items": [_enc_value(w, i) for i in v]}
    raise TypeError(f"unpersistable cache value type {type(v).__name__}")


def _dec_value(f: LakeFile, d: Dict[str, Any]) -> Any:
    t = d["t"]
    if t in ("bool", "int", "float"):
        return d["v"]
    if t == "str":
        return f.read_array(d["r"]).tobytes().decode()
    if t == "bytes":
        return f.read_array(d["r"]).tobytes()
    if t == "arr":
        a = f.read_array(d["r"]).astype(np.dtype(d["dtype"]), copy=False)
        return a.reshape(d["shape"])
    if t == "tuple":
        return tuple(_dec_value(f, i) for i in d["items"])
    raise ValueError(f"unknown persisted value type {t!r}")


def save_cache(ds, path: str) -> Dict[str, Any]:
    """Write every schema's current-epoch cache entries to ``path``
    (atomic tmp-then-rename). Returns a per-schema entry-count summary."""
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    w = LakeWriter(tmp)
    summary: Dict[str, Any] = {}
    try:
        datasets: Dict[str, Any] = {}
        for name, st in ds._stores.items():
            epoch, items = ds.cache.store.export_uid(st.uid)
            if epoch is None or epoch != st.version:
                # the cache predates (or outlived) this store's state:
                # nothing here is provably valid to persist
                summary[name] = 0
                continue
            entries = []
            for key, value in items:
                kr = repr(key)
                try:
                    # a key must survive the repr -> literal_eval round
                    # trip (a leaked numpy scalar reprs as np.int64(5) on
                    # numpy>=2 and would poison the whole restore file)
                    if _literal_key(kr) != key:
                        continue
                except (ValueError, SyntaxError):
                    continue  # non-literal key: skip this entry, not all
                try:
                    entries.append([kr, _enc_value(w, value)])
                except TypeError:
                    continue  # unpersistable value kind: skip, not fail
            datasets[name] = {
                "epoch": int(epoch),
                "guard": {"count": int(st.count), "spec": st.ft.spec()},
                "entries": entries,
            }
            summary[name] = len(entries)
        w.finish({"kind": "cache", "datasets": datasets})
    except BaseException:
        w.abort()
        raise
    # the lake writer fsyncs the FILE; the rename is only durable once the
    # parent directory is synced too (docs/RESILIENCE.md §8)
    resilience.durable_replace(tmp, path)
    return summary


def restore_cache(ds, path: str) -> Dict[str, Any]:
    """Import persisted cache sections whose guard matches the live
    store, under the live store's current epoch. Returns per-schema
    ``{"restored": n}`` / ``{"skipped": reason}``."""
    f = LakeFile(path)
    if f.footer.get("kind") != "cache":
        raise LakeCorruptError(f"{path}: not a cache persistence file")
    out: Dict[str, Any] = {}
    for name, section in f.footer.get("datasets", {}).items():
        st = ds._stores.get(name)
        if st is None:
            out[name] = {"skipped": "no such schema"}
            continue
        guard = section.get("guard", {})
        if int(guard.get("count", -1)) != int(st.count):
            out[name] = {"skipped": "row count changed"}
            continue
        if guard.get("spec") != st.ft.spec():
            out[name] = {"skipped": "schema changed"}
            continue
        items = []
        skipped = 0
        for key_repr, vd in section.get("entries", []):
            try:
                items.append((_literal_key(key_repr), _dec_value(f, vd)))
            except LakeCorruptError:
                raise  # on-disk corruption is never a benign skip
            except (ValueError, SyntaxError):
                skipped += 1  # one bad entry must not fail the restore
        n = ds.cache.store.import_entries(st.uid, st.version, items)
        out[name] = ({"restored": n, "skipped_entries": skipped}
                     if skipped else {"restored": n})
    return out


def _literal_key(key_repr: str) -> Tuple:
    """Keys are tuples of str/int/float/None/tuples — exactly the
    ``ast.literal_eval``-safe subset — built by the cache layer itself."""
    return ast.literal_eval(key_repr)
