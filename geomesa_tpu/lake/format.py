"""Footer-indexed blob container + lossless lightweight column encoding.

The on-disk grammar every lake file speaks::

    [8B magic "GMLAKE01"]
    [blob 0][blob 1]...[blob B-1]          # raw encoded bytes, contiguous
    [footer: JSON, utf-8]
    [8B footer length, little-endian][8B magic]

The footer carries a blob table (offset, length, crc32 per blob) plus
whatever structure the layer above wants (row groups, statistics, cache
sections). A reader seeks the 16-byte tail, range-reads the footer, then
range-reads exactly the blobs it decides to load — the object-store-
friendly shape: one tail read + one footer read + one read per surviving
blob, never the whole file (docs/LAKE.md).

Column encoding (:func:`encode_array` / :func:`decode_array`) is LOSSLESS
and self-describing — the Spatial-Parquet "lightweight coordinate
encoding" shape without the lossy option:

* integer/datetime columns: zigzag(delta) bit-packed at the minimal width
  (sorted SFC keys and epoch timestamps pack to a few bits/row);
* float columns: the raw IEEE bits delta-encode the same way (bit-exact
  by construction — spatially sorted coordinate columns share exponent/
  mantissa prefixes, so deltas of the bit patterns stay narrow);
* bool: packbits; strings (U/S) ride the npy fallback.

Fault posture (docs/RESILIENCE.md): every payload read passes the
``lake.read`` fault point and verifies its crc32 (a flipped byte raises
``LakeCorruptError`` — the caller's quarantine contract distinguishes a
corrupt blob from a transient ``OSError``, which is retried and never
quarantined). Writes pass ``lake.write`` and go through the caller's
tmp-then-rename dance. ``lake.bytes.{read,skipped}`` and
``lake.rowgroups.{loaded,pruned}`` metrics are maintained here and by the
snapshot layer.
"""

from __future__ import annotations

import io
import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import metrics, resilience

MAGIC = b"GMLAKE01"
_TAIL = len(MAGIC) + 8


class LakeCorruptError(ValueError):
    """A structural failure (bad magic, torn footer, crc mismatch) — the
    quarantine-eligible kind, never raised for transient OS errors."""


# ---------------------------------------------------------------------------
# bit packing
# ---------------------------------------------------------------------------

def _pack_u64(values: np.ndarray, width: int) -> bytes:
    """Little-endian bit-pack ``values`` (uint64) to ``width`` bits each."""
    if width == 0 or not len(values):
        return b""
    bits = np.unpackbits(
        values.astype("<u8").view(np.uint8).reshape(-1, 8),
        axis=1, bitorder="little",
    )[:, :width]
    return np.packbits(bits.ravel(), bitorder="little").tobytes()


def _unpack_u64(buf: bytes, width: int, n: int) -> np.ndarray:
    """Inverse of :func:`_pack_u64` — uint64 [n]."""
    if width == 0 or n == 0:
        return np.zeros(n, np.uint64)
    bits = np.unpackbits(
        np.frombuffer(buf, np.uint8), bitorder="little"
    )[: n * width].reshape(n, width)
    full = np.zeros((n, 64), np.uint8)
    full[:, :width] = bits
    return np.packbits(full, axis=1, bitorder="little").view("<u8").ravel()


def _zigzag(d: np.ndarray) -> np.ndarray:
    """int64 -> uint64 zigzag (small magnitudes -> small codes)."""
    return ((d.astype(np.int64) << np.int64(1))
            ^ (d.astype(np.int64) >> np.int64(63))).view(np.uint64)


def _unzigzag(z: np.ndarray) -> np.ndarray:
    z = z.view(np.int64)
    return (z >> np.int64(1)) ^ -(z & np.int64(1))


# ---------------------------------------------------------------------------
# array encoding
# ---------------------------------------------------------------------------

def encode_array(a: np.ndarray) -> Tuple[Dict[str, Any], bytes]:
    """Encode one column chunk losslessly: ``(meta, payload)``. ``meta``
    is JSON-able and sufficient for :func:`decode_array`."""
    a = np.ascontiguousarray(a)
    kind = a.dtype.kind
    if a.ndim == 1 and kind in "iufM" and a.dtype.itemsize in (1, 2, 4, 8):
        # view as int64 bit patterns (wrapping delta arithmetic is exact
        # and self-inverse regardless of signedness or float layout)
        if kind == "f":
            bits = a.view(f"u{a.dtype.itemsize}").astype(np.uint64)
        elif kind == "M":
            bits = a.view(np.int64).view(np.uint64)
        else:
            bits = a.astype(np.int64, copy=False).view(np.uint64) \
                if kind == "i" else a.astype(np.uint64, copy=False)
        d = np.empty_like(bits, dtype=np.uint64)
        if len(bits):
            d[0] = bits[0]
            np.subtract(bits[1:], bits[:-1], out=d[1:])  # wrapping
        zz = _zigzag(d.view(np.int64))
        width = int(zz.max()).bit_length() if len(zz) and int(zz.max()) \
            else (1 if len(zz) else 0)
        payload = _pack_u64(zz, width)
        # the npy fallback is smaller for incompressible data — take it
        raw = a.tobytes()
        if len(payload) < len(raw):
            return (
                {"enc": "delta", "dtype": str(a.dtype), "n": len(a),
                 "width": width},
                payload,
            )
        return ({"enc": "raw", "dtype": str(a.dtype), "n": len(a)}, raw)
    if a.ndim == 1 and kind == "b":
        return (
            {"enc": "bits", "dtype": "bool", "n": len(a)},
            np.packbits(a.view(np.uint8), bitorder="little").tobytes(),
        )
    # strings / structured / multi-dim: npy container (no pickle)
    if kind == "O":
        a = a.astype("U")
    buf = io.BytesIO()
    np.save(buf, a, allow_pickle=False)
    return ({"enc": "npy"}, buf.getvalue())


def decode_array(meta: Dict[str, Any], payload: bytes) -> np.ndarray:
    enc = meta["enc"]
    if enc == "delta":
        n, width = int(meta["n"]), int(meta["width"])
        d = _unzigzag(_unpack_u64(payload, width, n)).view(np.uint64)
        bits = np.cumsum(d, dtype=np.uint64)  # wrapping inverse of diff
        dt = np.dtype(meta["dtype"])
        if dt.kind == "f":
            return bits.astype(f"u{dt.itemsize}").view(dt) \
                if dt.itemsize != 8 else bits.view(dt)
        if dt.kind == "M":
            return bits.view(np.int64).astype(np.int64).view(dt)
        if dt.kind == "i":
            return bits.view(np.int64).astype(dt)
        return bits.astype(dt)
    if enc == "raw":
        return np.frombuffer(payload, np.dtype(meta["dtype"])).copy()
    if enc == "bits":
        n = int(meta["n"])
        return np.unpackbits(
            np.frombuffer(payload, np.uint8), bitorder="little"
        )[:n].astype(bool)
    if enc == "npy":
        return np.load(io.BytesIO(payload), allow_pickle=False)
    raise LakeCorruptError(f"unknown lake encoding {enc!r}")


# ---------------------------------------------------------------------------
# container
# ---------------------------------------------------------------------------

class LakeWriter:
    """Streaming writer: blobs append in call order; :meth:`finish` seals
    footer + tail. The caller owns tmp-path/rename atomicity."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "wb")
        self._fh.write(MAGIC)
        self._off = len(MAGIC)
        #: blob table rows: [offset, length, crc32]
        self.blobs: List[List[int]] = []

    def add_blob(self, payload: bytes) -> int:
        """Append one blob; returns its blob-table index (the ``ref``
        footer structures point at)."""
        resilience.fault_point("lake.write", path=self.path,
                              blob=len(self.blobs))
        self._fh.write(payload)
        self.blobs.append([self._off, len(payload),
                           zlib.crc32(payload) & 0xFFFFFFFF])
        self._off += len(payload)
        return len(self.blobs) - 1

    def add_array(self, a: np.ndarray) -> Dict[str, Any]:
        """Encode + append one column chunk; returns the JSON-able ref
        (``{"b": blob_index, ...encoding meta}``)."""
        meta, payload = encode_array(a)
        meta["b"] = self.add_blob(payload)
        meta["nbytes"] = len(payload)
        return meta

    def finish(self, footer: Dict[str, Any]) -> None:
        footer = dict(footer)
        footer["blobs"] = self.blobs
        raw = json.dumps(footer, separators=(",", ":")).encode()
        self._fh.write(raw)
        self._fh.write(len(raw).to_bytes(8, "little"))
        self._fh.write(MAGIC)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()

    def abort(self) -> None:
        try:
            self._fh.close()
        finally:
            try:
                os.remove(self.path)
            except OSError:
                pass


class LakeFile:
    """Range reader over one lake file. Opening parses ONLY the tail +
    footer; payload bytes load per-blob on demand (with crc verification
    and the ``lake.read`` fault point), so statistics-pruned readers pay
    for exactly the blobs that survive.

    The handle opened here is HELD for the reader's lifetime and every
    blob read goes through it: lazy decodes (an ephemeral pruned child's
    ``_LakeLazyCols``) can land long after open, racing a concurrent
    re-spill's ``os.replace`` of the same path — reopening by path would
    read the NEW file against the OLD footer's offsets, a crc mismatch
    that falsely quarantines a healthy partition. An unlinked-but-open
    fd keeps serving the footer's own bytes."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        fh = self._fh = open(path, "rb")
        try:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            if size < len(MAGIC) + _TAIL:
                raise LakeCorruptError(f"{path}: truncated lake file")
            fh.seek(size - _TAIL)
            tail = fh.read(_TAIL)
            if tail[8:] != MAGIC:
                raise LakeCorruptError(f"{path}: bad tail magic")
            flen = int.from_bytes(tail[:8], "little")
            foot_at = size - _TAIL - flen
            if flen <= 0 or foot_at < len(MAGIC):
                raise LakeCorruptError(f"{path}: bad footer length {flen}")
            fh.seek(0)
            if fh.read(len(MAGIC)) != MAGIC:
                raise LakeCorruptError(f"{path}: bad head magic")
            fh.seek(foot_at)
            try:
                self.footer: Dict[str, Any] = json.loads(fh.read(flen))
            except ValueError as e:
                raise LakeCorruptError(f"{path}: torn footer: {e}") from e
        except BaseException:
            fh.close()
            raise
        self.blobs: List[List[int]] = self.footer.get("blobs", [])
        metrics.inc(metrics.LAKE_BYTES_READ, flen + _TAIL)

    def close(self) -> None:
        self._fh.close()

    # -- payload -----------------------------------------------------------
    def read_blob(self, ref: int) -> bytes:
        off, length, crc = self.blobs[ref]
        resilience.fault_point("lake.read", path=self.path, blob=ref)
        with self._lock:
            self._fh.seek(off)
            payload = self._fh.read(length)
        if len(payload) != length:
            raise LakeCorruptError(
                f"{self.path}: blob {ref} truncated "
                f"({len(payload)}/{length} bytes)"
            )
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            raise LakeCorruptError(
                f"{self.path}: blob {ref} crc mismatch"
            )
        metrics.inc(metrics.LAKE_BYTES_READ, length)
        return payload

    def read_array(self, ref_meta: Dict[str, Any]) -> np.ndarray:
        return decode_array(ref_meta, self.read_blob(int(ref_meta["b"])))

    def blob_nbytes(self, ref_meta: Optional[Dict[str, Any]]) -> int:
        if ref_meta is None:
            return 0
        return int(self.blobs[int(ref_meta["b"])][1])
