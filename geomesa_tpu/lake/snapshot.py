"""Partition spill snapshots on the lake container (docs/LAKE.md).

The np.savez replacement for :mod:`geomesa_tpu.index.partitioned`: one
``part.lake`` file per spilled partition, holding

* the master/attribute columns (``c/`` prefix) and cached index-key
  columns (``k/`` prefix) — **re-ordered to the primary SFC index's sort
  order** and chunked into row groups, so each group covers a contiguous
  slice of the space-filling curve;
* per-row-group statistics: point-geometry bbox, time range, and the
  primary sort key's SFC range — the footer a reader consults to prune
  groups BEFORE any payload bytes load;
* every index table's sort permutation + sorted key columns (the primary
  table's permutation is the identity after the re-order, so its key
  columns chunk 1:1 with the row groups and a pruned subset of groups is
  STILL sorted — a statistics-pruned partial load rebuilds nothing).

The re-order is observationally invisible: each table's ``order`` array
is remapped through the inverse permutation, so every sorted gather
produces byte-identical columns — the npz-vs-lake bit-identity contract
the bench and CI gate. ``meta.json`` (row count, key shifts, sketch
stats) is still written alongside for the readers that never touch
column data (merged stats, ``attach_snapshots``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu import config, metrics
from geomesa_tpu.lake.format import LakeFile, LakeWriter

SNAPSHOT_FILE = "part.lake"

#: preferred canonical row orders. z2 first: a pure-spatial sort gives
#: every row group a TIGHT bbox statistic (the pruning axis that matters
#: inside a time-partition bin — the bin already is a time range), where
#: z3's time-major interleave spreads each group across the whole extent.
_PRIMARY_PREFERENCE = ("z2", "z3")


def _primary_table(st) -> Optional[str]:
    """The snapshot's canonical row order: the spatial SFC index when one
    exists (its sorted runs make row-group statistics tight)."""
    for name in _PRIMARY_PREFERENCE:
        t = st.tables.get(name)
        if t is not None and t.n:
            return name
    return None


def _rowgroup_rows() -> int:
    r = config.LAKE_ROWGROUP_ROWS.to_int()
    return max(int(r) if r else 16384, 256)


def _group_stats(ft, cols: Dict[str, np.ndarray], lo: int, hi: int,
                 primary_key: Optional[np.ndarray]) -> Dict[str, Any]:
    """Footer statistics for rows [lo, hi) of the re-ordered master."""
    out: Dict[str, Any] = {"rows": hi - lo}
    g = ft.geom_field
    if g is not None:
        gx, gy = cols.get(g + "__x"), cols.get(g + "__y")
        if gx is not None and gy is not None:
            sx, sy = gx[lo:hi], gy[lo:hi]
            if len(sx):
                out["bbox"] = [float(np.min(sx)), float(np.min(sy)),
                               float(np.max(sx)), float(np.max(sy))]
    d = ft.dtg_field
    if d is not None:
        dc = cols.get(d)
        if dc is not None and dc.dtype.kind in "iuM" and hi > lo:
            dv = dc[lo:hi].astype(np.int64, copy=False) \
                if dc.dtype.kind != "M" else dc[lo:hi].view(np.int64)
            out["time"] = [int(dv.min()), int(dv.max())]
    if primary_key is not None and hi > lo:
        # the primary key column is sorted, so the group's SFC range is
        # its first/last entry
        out["sfc"] = [int(primary_key[lo]), int(primary_key[hi - 1])]
    return out


def write_snapshot(st, ft, d: str) -> None:
    """Write partition store ``st``'s lake snapshot into directory ``d``
    (the caller owns the tmp-dir/atomic-rename dance, exactly as the npz
    writer did). Produces ``d/part.lake`` + ``d/meta.json``."""
    os.makedirs(d, exist_ok=True)
    n = st._all.n if st._all is not None else 0
    master: Dict[str, np.ndarray] = {}
    if st._all is not None:
        for k, v in st._all.columns.items():
            master["c/" + k] = v.astype("U") if v.dtype.kind == "O" else v
    for k, v in st._key_cols.items():
        master["k/" + k] = v

    primary = _primary_table(st)
    inv = None
    if primary is not None and n:
        if st.tables[primary].n != n:
            primary = None  # inconsistent table: no canonical re-order
        else:
            perm = np.asarray(st.tables[primary].order, np.int64)
            inv = np.empty(n, np.int64)
            inv[perm] = np.arange(n, dtype=np.int64)
            master = {k: np.asarray(v)[perm] for k, v in master.items()}

    pt = st.tables.get(primary) if primary is not None else None
    primary_key = None
    if pt is not None and pt.key_columns:
        # the FIRST key column is the table's major sort key (the SFC key)
        primary_key = next(iter(pt.key_columns.values()))

    rows = _rowgroup_rows()
    if n:
        bounds = list(range(0, n, rows)) + [n]
        cut_pairs = list(zip(bounds[:-1], bounds[1:]))
    else:
        # one empty group preserves every column's dtype across reload
        cut_pairs = [(0, 0)] if master else []
    path = os.path.join(d, SNAPSHOT_FILE)
    w = LakeWriter(path)
    try:
        groups: List[Dict[str, Any]] = []
        plain = {k[2:]: v for k, v in master.items() if k.startswith("c/")}
        for lo, hi in cut_pairs:
            cols = {k: w.add_array(v[lo:hi]) for k, v in master.items()}
            groups.append({
                "cols": cols,
                "stats": _group_stats(ft, plain, lo, hi, primary_key),
            })
        shifts: Dict[str, Dict[str, int]] = {}
        tables: Dict[str, Dict[str, Any]] = {}
        for name, t in st.tables.items():
            if not t.n and n:
                continue  # snapshot predates this index: rebuilt on load
            order = np.asarray(t.order, np.int64)
            if inv is not None:
                order = inv[order]
            ent: Dict[str, Any] = {"n": int(t.n)}
            if name == primary:
                ent["order"] = None  # identity by construction
                # the primary's sorted key columns chunk 1:1 with the row
                # groups, so a pruned load slices them with the groups
                ent["keys"] = {
                    k: [w.add_array(v[lo:hi]) for lo, hi in cut_pairs]
                    for k, v in t.key_columns.items()
                }
            else:
                ent["order"] = w.add_array(order)
                ent["keys"] = {k: w.add_array(v)
                               for k, v in t.key_columns.items()}
            if t._rank_vocab is not None:
                ent["vocab"] = w.add_array(t._rank_vocab.astype("U"))
            if t.key_shifts is not None:
                shifts[name] = dict(t.key_shifts)
            tables[name] = ent
        meta = {
            "n": n,
            "shifts": shifts,
            "stats": {k: v.to_json() for k, v in st.stats.items()},
        }
        w.finish({
            "kind": "partition",
            "n": n,
            "primary": primary,
            "columns": sorted(master),
            "groups": groups,
            "tables": tables,
            "meta": meta,
        })
    except BaseException:
        w.abort()
        raise
    with open(os.path.join(d, "meta.json"), "w") as fh:
        json.dump(meta, fh)


class PartitionSnapshot:
    """Reader over one partition's ``part.lake``: footer-only on open;
    column payloads decode per row group on demand, with a pruning query
    over the footer statistics."""

    def __init__(self, d: str):
        self.dir = d
        self.file = LakeFile(os.path.join(d, SNAPSHOT_FILE))
        f = self.file.footer
        if f.get("kind") != "partition":
            from geomesa_tpu.lake.format import LakeCorruptError

            raise LakeCorruptError(f"{d}: not a partition snapshot")
        self.n: int = int(f["n"])
        self.primary: Optional[str] = f.get("primary")
        self.columns: List[str] = list(f.get("columns", []))
        self.groups: List[Dict[str, Any]] = f.get("groups", [])
        self.tables: Dict[str, Dict[str, Any]] = f.get("tables", {})
        self.meta: Dict[str, Any] = f["meta"]

    # -- statistics pruning ------------------------------------------------
    def group_rows(self, groups: Optional[Sequence[int]] = None) -> int:
        idx = range(len(self.groups)) if groups is None else groups
        return int(sum(self.groups[i]["stats"]["rows"] for i in idx))

    def payload_bytes(self, groups: Optional[Sequence[int]] = None) -> int:
        """Encoded payload bytes of the listed groups (all when None)."""
        idx = range(len(self.groups)) if groups is None else groups
        total = 0
        for i in idx:
            for ref in self.groups[i]["cols"].values():
                total += self.file.blob_nbytes(ref)
        return total

    def prune(self, boxes: Optional[List[Tuple[float, float, float, float]]],
              times: Optional[List[Tuple[float, float]]],
              margin: Optional[float] = None) -> List[int]:
        """Row groups that may hold matching rows. ``boxes``/``times`` are
        the query's extracted spatial/temporal bounds (None = that axis is
        unconstrained; an empty list = provably disjoint). Spatial checks
        inflate the group bbox by ``margin`` degrees so the scan kernel's
        f32 edge arithmetic can never match a row in a pruned group."""
        if margin is None:
            m = config.LAKE_PRUNE_MARGIN.to_float()
            margin = 1e-3 if m is None else float(m)
        out: List[int] = []
        for i, g in enumerate(self.groups):
            s = g["stats"]
            keep = True
            if boxes is not None:
                bb = s.get("bbox")
                if bb is None:
                    keep = bool(boxes)  # no stats: only disjoint prunes
                    if not boxes:
                        keep = False
                else:
                    x0, y0, x1, y1 = (bb[0] - margin, bb[1] - margin,
                                      bb[2] + margin, bb[3] + margin)
                    keep = any(
                        q[0] <= x1 and q[2] >= x0
                        and q[1] <= y1 and q[3] >= y0
                        for q in boxes
                    )
            if keep and times is not None:
                tt = s.get("time")
                if tt is None:
                    keep = bool(times)
                    if not times:
                        keep = False
                else:
                    keep = any(q[0] <= tt[1] and q[1] >= tt[0]
                               for q in times)
            if keep:
                out.append(i)
        return out

    def account(self, loaded: Sequence[int]) -> Dict[str, int]:
        """Metrics + audit numbers for a pruned load, and increments the
        process counters (docs/OBSERVABILITY.md ``lake.*``)."""
        total = len(self.groups)
        read_b = self.payload_bytes(loaded)
        all_b = self.payload_bytes(None)
        acct = {
            "groups_total": total,
            "groups_loaded": len(loaded),
            "groups_pruned": total - len(loaded),
            "bytes_payload": all_b,
            "bytes_loaded": read_b,
            "bytes_skipped": all_b - read_b,
        }
        metrics.inc(metrics.LAKE_ROWGROUPS_LOADED, len(loaded))
        metrics.inc(metrics.LAKE_ROWGROUPS_PRUNED, total - len(loaded))
        metrics.inc(metrics.LAKE_BYTES_SKIPPED, all_b - read_b)
        return acct

    # -- column decode -----------------------------------------------------
    def read_column(self, name: str,
                    groups: Optional[Sequence[int]] = None,
                    cache=None) -> np.ndarray:
        """Decode one prefixed column (``c/attr`` / ``k/__z3``) over the
        listed row groups (all when None), concatenated in group order.
        ``cache`` is an optional :class:`~geomesa_tpu.lake.residency.
        GroupResidencyCache`: per-group chunks then come from / land in
        the cross-chunk residency cache (docs/JOIN.md §11)."""
        idx = list(range(len(self.groups))) if groups is None else list(groups)
        parts = []
        for i in idx:
            ref = self.groups[i]["cols"].get(name)
            if ref is None:
                raise KeyError(name)
            if cache is not None:
                parts.append(cache.fetch(self.dir, name, i, ref, self.file))
            else:
                parts.append(self.file.read_array(ref))
        if not parts:
            # zero groups (empty partition / everything pruned): derive an
            # empty array of the right dtype from the encoding of nothing
            return np.zeros(0, np.float64 if name.startswith("c/")
                            else np.int64)
        return parts[0] if len(parts) == 1 else np.concatenate(parts)

    def table_order(self, name: str) -> Optional[np.ndarray]:
        ent = self.tables[name]
        if ent.get("order") is None:
            return None  # identity (the primary)
        return self.file.read_array(ent["order"])

    def table_keys(self, name: str,
                   groups: Optional[Sequence[int]] = None,
                   cache=None) -> Dict[str, np.ndarray]:
        ent = self.tables[name]
        out: Dict[str, np.ndarray] = {}
        for k, refs in ent.get("keys", {}).items():
            if isinstance(refs, list):  # primary: per-group chunks
                idx = (list(range(len(self.groups)))
                       if groups is None else list(groups))
                parts = [
                    cache.fetch(self.dir, f"tk/{name}/{k}", i, refs[i],
                                self.file)
                    if cache is not None else self.file.read_array(refs[i])
                    for i in idx
                ]
                out[k] = (parts[0] if len(parts) == 1
                          else np.concatenate(parts)) if parts \
                    else np.zeros(0, np.int64)
            else:
                out[k] = self.file.read_array(refs)
        return out

    def table_vocab(self, name: str) -> Optional[np.ndarray]:
        ent = self.tables[name]
        v = ent.get("vocab")
        return None if v is None else self.file.read_array(v)
