"""Columnar geo-lake tier (docs/LAKE.md).

The Spatial-Parquet-shaped storage boundary (PAPERS.md: "Spatial Parquet:
A Column File Format for Geospatial Data Lakes"): footer-indexed files of
row groups with lightweight (delta/bit-packed) lossless column encoding
and per-row-group spatial/temporal/SFC statistics, so pruning happens at
file/row-group granularity BEFORE any payload bytes load. Three layers:

* :mod:`~geomesa_tpu.lake.format` — the container: blobs + JSON footer +
  crc, range-read-friendly (a reader touches the footer plus exactly the
  blobs it wants), ``lake.read``/``lake.write`` fault points, ``lake.*``
  byte/row-group metrics;
* :mod:`~geomesa_tpu.lake.snapshot` — partition spill snapshots on the
  container (the np.savez replacement in ``index/partitioned.py``):
  master rows re-ordered to the primary SFC sort so row groups are
  SFC-contiguous, statistics-pruned partial loads that decode straight
  into the scan pipeline;
* :mod:`~geomesa_tpu.lake.persist` — aggregate-cache persistence
  (docs/CACHE.md): hot flat cells / hierarchy nodes / curve chunks
  written through the same tier, so a restarted process re-serves warm
  aggregates without a rescan.
"""

from geomesa_tpu.lake.format import (  # noqa: F401
    LakeFile, LakeWriter, decode_array, encode_array,
)
from geomesa_tpu.lake.snapshot import (  # noqa: F401
    PartitionSnapshot, SNAPSHOT_FILE, write_snapshot,
)
