"""Mergeable statistics sketches (L0).

Capability parity with the reference's stats package
(geomesa-utils/.../stats/Stat.scala:31-86 and siblings; SURVEY.md §2.1):
Count, MinMax, Enumeration, TopK, Histogram (binned), Frequency (count-min),
DescriptiveStats, GroupBy, Z3Histogram — each a mergeable sketch.

TPU-first design: every sketch's state is a small set of fixed-shape numpy
arrays, so the same state can be produced by a jit'd device reduction
(kernels/stats_scan.py), merged across shards with ``psum``/tree-map, and
persisted for the cost-based query planner (the reference's
StatsBasedEstimator role).
"""

from geomesa_tpu.stats.sketches import (  # noqa: F401
    Stat,
    SeqStat,
    CountStat,
    MinMax,
    EnumerationStat,
    TopK,
    Histogram,
    Frequency,
    DescriptiveStats,
    GroupBy,
    Z3HistogramStat,
)
from geomesa_tpu.stats.parser import parse_stat  # noqa: F401
