"""Stat DSL parser.

Parses the reference's stat string syntax (StatParser analog, SURVEY.md §2.1):

    Count();MinMax(attr);Histogram(attr,20,0,100);Enumeration(name);
    TopK(name);Frequency(attr);DescriptiveStats(a,b);GroupBy(cat,MinMax(v));
    Z3Histogram(geom,dtg,week,1024)

Semicolon-separated stats become a SeqStat. Arguments are attribute names,
numbers, or quoted strings.
"""

from __future__ import annotations

import re
from typing import Any, List

from geomesa_tpu.stats import sketches as sk

_TOKEN = re.compile(r"\s*(?:(?P<id>[A-Za-z_][A-Za-z0-9_.]*)|(?P<num>-?\d+(?:\.\d+)?)"
                    r"|'(?P<str>[^']*)'|\"(?P<dstr>[^\"]*)\"|(?P<sym>[(),]))")


class _Tokens:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0

    def peek(self):
        if self.pos >= len(self.text):
            return None, None
        m = _TOKEN.match(self.text, self.pos)
        if not m:
            raise ValueError(f"bad stat string at {self.text[self.pos:]!r}")
        for kind in ("id", "num", "str", "dstr", "sym"):
            v = m.group(kind)
            if v is not None:
                return ("str" if kind == "dstr" else kind), (m, v)
        raise ValueError("unreachable")

    def next(self):
        kind, mv = self.peek()
        if kind is None:
            raise ValueError("unexpected end of stat string")
        m, v = mv
        self.pos = m.end()
        return kind, v

    def expect(self, sym: str):
        kind, v = self.next()
        if kind != "sym" or v != sym:
            raise ValueError(f"expected {sym!r}, got {v!r}")


def _parse_args(toks: _Tokens) -> List[Any]:
    """Parse '(' arg, ... ')' where an arg is an id/number/string or a nested
    stat call (for GroupBy)."""
    toks.expect("(")
    args: List[Any] = []
    kind, mv = toks.peek()
    if kind == "sym" and mv[1] == ")":
        toks.next()
        return args
    while True:
        kind, v = toks.next()
        if kind == "id":
            # Nested stat call? e.g. GroupBy(cat,MinMax(v))
            k2, mv2 = toks.peek()
            if k2 == "sym" and mv2[1] == "(":
                start = toks.pos - len(v)
                _build(v, _parse_args(toks))  # validate
                args.append(("stat", toks.text[start:toks.pos].strip()))
            else:
                args.append(("id", v))
        elif kind == "num":
            args.append(("num", float(v) if "." in v else int(v)))
        elif kind == "str":
            args.append(("str", v))
        else:
            raise ValueError(f"unexpected token {v!r} in stat args")
        kind, v = toks.next()
        if kind == "sym" and v == ")":
            return args
        if not (kind == "sym" and v == ","):
            raise ValueError(f"expected ',' or ')', got {v!r}")


def _val(arg):
    return arg[1]


def _build(name: str, args: List[Any]) -> sk.Stat:
    n = name.lower()
    if n == "count":
        return sk.CountStat()
    if n == "minmax":
        return sk.MinMax(_val(args[0]))
    if n == "enumeration":
        return sk.EnumerationStat(_val(args[0]))
    if n == "topk":
        k = int(_val(args[1])) if len(args) > 1 else 10
        return sk.TopK(_val(args[0]), k)
    if n == "histogram":
        a, bins, lo, hi = (_val(x) for x in args[:4])
        return sk.Histogram(a, int(bins), float(lo), float(hi))
    if n == "frequency":
        width = int(_val(args[1])) if len(args) > 1 else 1024
        return sk.Frequency(_val(args[0]), width)
    if n == "descriptivestats":
        return sk.DescriptiveStats([_val(a) for a in args])
    if n == "groupby":
        return sk.GroupBy(_val(args[0]), _val(args[1]))
    if n == "z3histogram":
        geom, dtg = _val(args[0]), _val(args[1])
        period = _val(args[2]) if len(args) > 2 else "week"
        length = int(_val(args[3])) if len(args) > 3 else 1024
        return sk.Z3HistogramStat(geom, dtg, period, length)
    if n == "z3frequency":
        geom, dtg = _val(args[0]), _val(args[1])
        period = _val(args[2]) if len(args) > 2 else "week"
        precision = int(_val(args[3])) if len(args) > 3 else 10
        return sk.Z3FrequencyStat(geom, dtg, period, precision)
    raise ValueError(f"unknown stat function: {name!r}")


def parse_stat(spec: str) -> sk.Stat:
    """Parse a stat DSL string into a (possibly Seq) sketch."""
    parts = [p.strip() for p in spec.split(";") if p.strip()]
    stats = []
    for part in parts:
        toks = _Tokens(part)
        kind, v = toks.next()
        if kind != "id":
            raise ValueError(f"expected stat name, got {v!r}")
        stats.append(_build(v, _parse_args(toks)))
        if toks.peek()[0] is not None:
            raise ValueError(f"trailing content in stat spec: {part!r}")
    if not stats:
        raise ValueError("empty stat spec")
    return stats[0] if len(stats) == 1 else sk.SeqStat(stats)
