"""Sketch implementations.

Contract (mirrors the reference's ``Stat`` trait, Stat.scala:31-86):

* ``observe(columns)`` — ingest a batch (dict of column arrays + optional
  boolean mask). Vectorized; no per-row Python.
* ``merge(other)`` — combine two sketches (the ``+=`` of the reference); this
  is the cross-shard reduction.
* ``to_json()/from_json()`` — persistence format for the metadata catalog
  (reference: StatSerializer; we use JSON since sketches are small).
* ``is_empty`` — whether anything was observed.

Observe operates on the *encoded* columnar representation used device-side:
strings arrive as dictionary codes (int32), dates as epoch-ms int64, geometries
as x/y float64 pairs. The ``attribute`` name addresses one column; geometry
attributes expose ``<name>__x`` / ``<name>__y`` columns.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curves.zorder import Z3SFC


Columns = Dict[str, np.ndarray]


def _masked(values: np.ndarray, mask: Optional[np.ndarray]) -> np.ndarray:
    if mask is None:
        return values
    return values[mask]


class Stat:
    """Base sketch."""

    kind: str = "stat"

    def observe(self, columns: Columns, mask: Optional[np.ndarray] = None) -> None:
        raise NotImplementedError

    def unobserve(self, columns: Columns, mask: Optional[np.ndarray] = None) -> None:
        """Remove a batch (supported by count-like sketches; reference
        Stat.unobserve). Sketches that cannot unobserve raise."""
        raise NotImplementedError(f"{self.kind} cannot unobserve")

    def merge(self, other: "Stat") -> None:
        raise NotImplementedError

    @property
    def is_empty(self) -> bool:
        raise NotImplementedError

    def value(self) -> Any:
        """Human-consumable result (the reference's ``toJson`` payload)."""
        raise NotImplementedError

    # -- serialization ----------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({"kind": self.kind, **self._state()})

    def _state(self) -> Dict[str, Any]:
        raise NotImplementedError

    @staticmethod
    def from_json(s: str) -> "Stat":
        d = json.loads(s)
        cls = _KINDS[d.pop("kind")]
        return cls._from_state(d)


def _arr_to_b64(a: np.ndarray) -> Dict[str, Any]:
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(np.ascontiguousarray(a).tobytes()).decode(),
    }


def _arr_from_b64(d: Dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["data"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"]).copy()


class CountStat(Stat):
    """Total observed count (reference CountStat)."""

    kind = "count"

    def __init__(self, count: int = 0):
        self.count = int(count)

    def observe(self, columns, mask=None):
        n = len(next(iter(columns.values())))
        self.count += int(mask.sum()) if mask is not None else n

    def unobserve(self, columns, mask=None):
        n = len(next(iter(columns.values())))
        self.count -= int(mask.sum()) if mask is not None else n

    def merge(self, other):
        self.count += other.count

    @property
    def is_empty(self):
        return self.count == 0

    def value(self):
        return self.count

    def _state(self):
        return {"count": self.count}

    @classmethod
    def _from_state(cls, d):
        return cls(d["count"])


class MinMax(Stat):
    """Min/max of a numeric/date column; for geometries, the bounding box
    (min/max of x and y). Reference: MinMax.scala."""

    kind = "minmax"

    def __init__(self, attribute: str, lo=None, hi=None, count: int = 0):
        self.attribute = attribute
        self.lo = lo
        self.hi = hi
        self.count = int(count)

    def _columns_for(self, columns: Columns) -> List[np.ndarray]:
        if self.attribute + "__x" in columns:  # geometry: track bbox
            return [columns[self.attribute + "__x"], columns[self.attribute + "__y"]]
        return [columns[self.attribute]]

    def observe(self, columns, mask=None):
        cols = [_masked(np.asarray(c), mask) for c in self._columns_for(columns)]
        if cols[0].size == 0:
            return
        self.count += int(cols[0].size)
        los = [float(np.min(c)) for c in cols]
        his = [float(np.max(c)) for c in cols]
        if len(cols) == 1:
            los, his = los[0], his[0]
        if self.lo is None:
            self.lo, self.hi = los, his
        else:
            if len(cols) == 1:
                self.lo, self.hi = min(self.lo, los), max(self.hi, his)
            else:
                self.lo = [min(a, b) for a, b in zip(self.lo, los)]
                self.hi = [max(a, b) for a, b in zip(self.hi, his)]

    def merge(self, other: "MinMax"):
        if other.is_empty:
            return
        if self.is_empty:
            self.lo, self.hi, self.count = other.lo, other.hi, other.count
            return
        self.count += other.count
        if isinstance(self.lo, list):
            self.lo = [min(a, b) for a, b in zip(self.lo, other.lo)]
            self.hi = [max(a, b) for a, b in zip(self.hi, other.hi)]
        else:
            self.lo, self.hi = min(self.lo, other.lo), max(self.hi, other.hi)

    @property
    def is_empty(self):
        return self.count == 0

    def value(self):
        return {"min": self.lo, "max": self.hi, "cardinality": self.count}

    def _state(self):
        return {"attribute": self.attribute, "lo": self.lo, "hi": self.hi, "count": self.count}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], d["lo"], d["hi"], d["count"])


class EnumerationStat(Stat):
    """Exact value->count (reference EnumerationStat). Operates on dictionary
    codes for strings; raw values for small-cardinality ints."""

    kind = "enumeration"

    def __init__(self, attribute: str, counts: Optional[Dict[Any, int]] = None):
        self.attribute = attribute
        self.counts: Dict[Any, int] = dict(counts or {})

    def observe(self, columns, mask=None):
        vals = _masked(np.asarray(columns[self.attribute]), mask)
        uniq, cnt = np.unique(vals, return_counts=True)
        for u, c in zip(uniq.tolist(), cnt.tolist()):
            self.counts[u] = self.counts.get(u, 0) + int(c)

    def unobserve(self, columns, mask=None):
        vals = _masked(np.asarray(columns[self.attribute]), mask)
        uniq, cnt = np.unique(vals, return_counts=True)
        for u, c in zip(uniq.tolist(), cnt.tolist()):
            left = self.counts.get(u, 0) - int(c)
            if left > 0:
                self.counts[u] = left
            else:
                self.counts.pop(u, None)

    def merge(self, other: "EnumerationStat"):
        for k, v in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + v

    @property
    def is_empty(self):
        return not self.counts

    def value(self):
        return dict(self.counts)

    def _state(self):
        return {"attribute": self.attribute,
                "counts": [[k, v] for k, v in self.counts.items()]}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], {k: v for k, v in d["counts"]})


class TopK(Stat):
    """Top-k most frequent values (reference TopK via StreamSummary; here exact
    via enumeration — dictionary-coded columns keep this bounded)."""

    kind = "topk"

    def __init__(self, attribute: str, k: int = 10, counts: Optional[Dict[Any, int]] = None):
        self.attribute = attribute
        self.k = k
        self._enum = EnumerationStat(attribute, counts)

    def observe(self, columns, mask=None):
        self._enum.observe(columns, mask)

    def merge(self, other: "TopK"):
        self._enum.merge(other._enum)

    @property
    def is_empty(self):
        return self._enum.is_empty

    def value(self):
        items = sorted(self._enum.counts.items(), key=lambda kv: (-kv[1], str(kv[0])))
        return items[: self.k]

    def _state(self):
        return {"attribute": self.attribute, "k": self.k,
                "counts": [[k, v] for k, v in self._enum.counts.items()]}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], d["k"], {k: v for k, v in d["counts"]})


class Histogram(Stat):
    """Fixed-bin histogram over [lo, hi] (reference Histogram.scala: binned,
    with endpoints). Out-of-range values clamp to the edge bins, matching the
    reference's behavior of widening only on explicit re-bin."""

    kind = "histogram"

    def __init__(self, attribute: str, bins: int, lo: float, hi: float,
                 counts: Optional[np.ndarray] = None):
        self.attribute = attribute
        self.bins = int(bins)
        self.lo = float(lo)
        self.hi = float(hi)
        self.counts = (
            np.zeros(self.bins, dtype=np.int64) if counts is None
            else np.asarray(counts, dtype=np.int64)
        )

    def bin_of(self, vals: np.ndarray) -> np.ndarray:
        scaled = (np.asarray(vals, np.float64) - self.lo) / (self.hi - self.lo) * self.bins
        return np.clip(np.floor(scaled), 0, self.bins - 1).astype(np.int64)

    def observe(self, columns, mask=None):
        vals = _masked(np.asarray(columns[self.attribute]), mask)
        if vals.size == 0:
            return
        self.counts += np.bincount(self.bin_of(vals), minlength=self.bins).astype(np.int64)

    def merge(self, other: "Histogram"):
        self.counts += other.counts

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    def value(self):
        return {"lo": self.lo, "hi": self.hi, "counts": self.counts.tolist()}

    def count_between(self, lo: float, hi: float) -> float:
        """Estimated count in [lo, hi] — the selectivity hook for the planner."""
        if hi < self.lo or lo > self.hi:
            return 0.0
        width = (self.hi - self.lo) / self.bins
        edges = self.lo + width * np.arange(self.bins + 1)
        overlap = np.clip(
            np.minimum(hi, edges[1:]) - np.maximum(lo, edges[:-1]), 0.0, width
        )
        frac = np.divide(overlap, width, out=np.zeros_like(overlap), where=width > 0)
        return float((self.counts * frac).sum())

    def _state(self):
        return {"attribute": self.attribute, "bins": self.bins, "lo": self.lo,
                "hi": self.hi, "counts": _arr_to_b64(self.counts)}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], d["bins"], d["lo"], d["hi"], _arr_from_b64(d["counts"]))


class Frequency(Stat):
    """Count-min sketch (reference Frequency.scala, 308 LoC). State is a
    (depth, width) int64 grid — a pure scatter-add on device."""

    kind = "frequency"
    DEPTH = 4
    # multiplicative hashing constants (odd, 64-bit): h_i(x) = (a_i*x) >> s mod width
    _AS = np.array(
        [0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9, 0x27D4EB2F165667C5],
        dtype=np.uint64,
    )

    def __init__(self, attribute: str, width: int = 1024,
                 counts: Optional[np.ndarray] = None):
        self.attribute = attribute
        self.width = int(width)
        self.counts = (
            np.zeros((self.DEPTH, self.width), dtype=np.int64) if counts is None
            else np.asarray(counts, dtype=np.int64)
        )

    def _hash(self, vals: np.ndarray) -> np.ndarray:
        """(depth, n) bucket ids."""
        x = np.asarray(vals)
        if x.dtype.kind == "f":
            x = x.view(np.uint64) if x.dtype == np.float64 else x.astype(np.float64).view(np.uint64)
        else:
            x = x.astype(np.int64).view(np.uint64)
        h = (self._AS[:, None] * x[None, :])  # wraps mod 2^64
        return ((h >> np.uint64(33)) % np.uint64(self.width)).astype(np.int64)

    def observe(self, columns, mask=None):
        vals = _masked(np.asarray(columns[self.attribute]), mask)
        if vals.size == 0:
            return
        buckets = self._hash(vals)
        for d in range(self.DEPTH):
            self.counts[d] += np.bincount(buckets[d], minlength=self.width).astype(np.int64)

    def count(self, value) -> int:
        b = self._hash(np.asarray([value]))
        return int(min(self.counts[d, b[d, 0]] for d in range(self.DEPTH)))

    def merge(self, other: "Frequency"):
        self.counts += other.counts

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    def value(self):
        return {"width": self.width, "total": int(self.counts[0].sum())}

    def _state(self):
        return {"attribute": self.attribute, "width": self.width,
                "counts": _arr_to_b64(self.counts)}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], d["width"], _arr_from_b64(d["counts"]))


class DescriptiveStats(Stat):
    """Running count/sum/sum-of-outer-products for mean/variance/covariance
    (reference DescriptiveStats). Merge is exact (sums are associative)."""

    kind = "descriptive"

    def __init__(self, attributes: List[str], count: int = 0,
                 s1: Optional[np.ndarray] = None, s2: Optional[np.ndarray] = None):
        self.attributes = list(attributes)
        d = len(self.attributes)
        self.count = int(count)
        self.s1 = np.zeros(d) if s1 is None else np.asarray(s1, np.float64)
        self.s2 = np.zeros((d, d)) if s2 is None else np.asarray(s2, np.float64)

    def observe(self, columns, mask=None):
        mat = np.stack(
            [_masked(np.asarray(columns[a], np.float64), mask) for a in self.attributes],
            axis=1,
        )
        if mat.shape[0] == 0:
            return
        self.count += mat.shape[0]
        self.s1 += mat.sum(axis=0)
        self.s2 += mat.T @ mat

    def merge(self, other: "DescriptiveStats"):
        self.count += other.count
        self.s1 += other.s1
        self.s2 += other.s2

    @property
    def is_empty(self):
        return self.count == 0

    def value(self):
        if self.count == 0:
            return {"count": 0}
        mean = self.s1 / self.count
        cov = self.s2 / self.count - np.outer(mean, mean)
        return {
            "count": self.count,
            "mean": mean.tolist(),
            "variance": np.diag(cov).tolist(),
            "stddev": np.sqrt(np.maximum(np.diag(cov), 0)).tolist(),
            "covariance": cov.tolist(),
        }

    def _state(self):
        return {"attributes": self.attributes, "count": self.count,
                "s1": _arr_to_b64(self.s1), "s2": _arr_to_b64(self.s2)}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attributes"], d["count"], _arr_from_b64(d["s1"]), _arr_from_b64(d["s2"]))


class GroupBy(Stat):
    """Per-group sub-sketches keyed by an attribute's values (reference GroupBy)."""

    kind = "groupby"

    def __init__(self, attribute: str, substat_spec: str,
                 groups: Optional[Dict[Any, Stat]] = None):
        from geomesa_tpu.stats.parser import parse_stat

        self.attribute = attribute
        self.substat_spec = substat_spec
        self._parse = parse_stat
        self.groups: Dict[Any, Stat] = dict(groups or {})

    def observe(self, columns, mask=None):
        keys = np.asarray(columns[self.attribute])
        if mask is not None:
            base = mask
        else:
            base = np.ones(len(keys), dtype=bool)
        for k in np.unique(keys[base]).tolist():
            gmask = base & (keys == k)
            if k not in self.groups:
                self.groups[k] = self._parse(self.substat_spec)
            self.groups[k].observe(columns, gmask)

    def merge(self, other: "GroupBy"):
        for k, v in other.groups.items():
            if k in self.groups:
                self.groups[k].merge(v)
            else:
                self.groups[k] = v

    @property
    def is_empty(self):
        return not self.groups

    def value(self):
        return {k: v.value() for k, v in self.groups.items()}

    def _state(self):
        return {"attribute": self.attribute, "substat_spec": self.substat_spec,
                "groups": [[k, v.to_json()] for k, v in self.groups.items()]}

    @classmethod
    def _from_state(cls, d):
        return cls(d["attribute"], d["substat_spec"],
                   {k: Stat.from_json(v) for k, v in d["groups"]})


class Z3HistogramStat(Stat):
    """Spatio-temporal histogram keyed by (time bin, coarse z cell) — the
    planner's selectivity backbone (reference Z3Histogram.scala, 186 LoC).

    State per bin: counts over ``length`` buckets, where bucket = top bits of
    the Z3 value. Device-side this is a scatter-add; host keeps bins sparse.
    """

    kind = "z3histogram"

    def __init__(self, geom: str, dtg: str, period: "str | TimePeriod" = TimePeriod.WEEK,
                 length: int = 1024, bins: Optional[Dict[int, np.ndarray]] = None):
        self.geom = geom
        self.dtg = dtg
        self.period = TimePeriod.parse(period)
        self.length = int(length)
        self.sfc = Z3SFC(self.period)
        self.binned = BinnedTime(self.period)
        # z >> shift yields a bucket in [0, length)
        self.shift = 63 - int(np.log2(self.length))
        self.bins: Dict[int, np.ndarray] = {
            int(k): np.asarray(v, np.int64) for k, v in (bins or {}).items()
        }

    def observe(self, columns, mask=None):
        # reuse ingest-computed (bin, z3) keys — but only when the ingest
        # marker confirms they were built with THIS sketch's time period
        # (a DSL-requested sketch may use a different period than the schema)
        if (
            "__z3" in columns
            and columns.get("__z3_period") == self.period.value
        ):
            b = _masked(np.asarray(columns["__z3_bin"]), mask)
            z = _masked(np.asarray(columns["__z3"], np.uint64), mask)
        else:
            xs = _masked(np.asarray(columns[self.geom + "__x"]), mask)
            ys = _masked(np.asarray(columns[self.geom + "__y"]), mask)
            ts = _masked(np.asarray(columns[self.dtg]), mask)  # epoch ms
            if xs.size == 0:
                return
            b, off = self.binned.to_bin_and_offset(ts)
            z = self.sfc.index(xs, ys, off)
        if z.size == 0:
            return
        bucket = (z >> np.uint64(self.shift)).astype(np.int32)
        # one composite bincount over (bin, bucket) — per-bin masked
        # bincounts re-scan the whole batch once per distinct bin. Bin ids
        # are dense small ints, so min/max beats a full np.unique sort.
        bmin, bmax = int(b.min()), int(b.max())
        if bmin == bmax:
            if bmin not in self.bins:
                self.bins[bmin] = np.zeros(self.length, dtype=np.int64)
            self.bins[bmin] += np.bincount(bucket, minlength=self.length)
            return
        span = bmax - bmin + 1
        # dense layout allocates span*length counters: bound the PRODUCT
        # (a DSL-requested big length with a wide bin span would otherwise
        # demand GBs where the sparse loop needs length*distinct_bins)
        if span * self.length > (1 << 22):
            for bb in np.unique(b).tolist():
                sel = np.asarray(b) == bb
                if bb not in self.bins:
                    self.bins[bb] = np.zeros(self.length, dtype=np.int64)
                self.bins[bb] += np.bincount(bucket[sel], minlength=self.length)
            return
        # int64 rel: span*length stays < 2^22 but the MULTIPLY inputs are
        # per-row values — int64 keeps the composite index overflow-free
        rel = (np.asarray(b, np.int64) - bmin) * np.int64(self.length) + bucket
        counts = np.bincount(rel, minlength=span * self.length).reshape(
            span, self.length
        )
        nonzero = counts.any(axis=1)
        for i in np.nonzero(nonzero)[0].tolist():
            bb = bmin + i
            if bb not in self.bins:
                self.bins[bb] = counts[i].astype(np.int64)
            else:
                self.bins[bb] += counts[i]

    def merge(self, other: "Z3HistogramStat"):
        for k, v in other.bins.items():
            if k in self.bins:
                self.bins[k] += v
            else:
                self.bins[k] = v.copy()

    @property
    def is_empty(self):
        return not self.bins

    def value(self):
        return {int(k): int(v.sum()) for k, v in self.bins.items()}

    def estimate_count(self, time_bins: np.ndarray, zranges) -> float:
        """Estimated matches for z-ranges within the given time bins — drives
        the cost-based strategy decider (StatsBasedEstimator analog)."""
        total = 0.0
        for bb in np.asarray(time_bins).tolist():
            counts = self.bins.get(int(bb))
            if counts is None:
                continue
            bucket_span = 1 << self.shift
            for r in zranges:
                b0, b1 = r.lo >> self.shift, r.hi >> self.shift
                if b0 == b1:
                    total += float(counts[b0]) * ((r.hi - r.lo + 1) / bucket_span)
                else:
                    # fractional edge buckets + whole middle buckets
                    total += float(counts[b0]) * (((b0 + 1) * bucket_span - r.lo) / bucket_span)
                    total += float(counts[b1]) * ((r.hi - b1 * bucket_span + 1) / bucket_span)
                    if b1 > b0 + 1:
                        total += float(counts[b0 + 1 : b1].sum())
        return total

    def _state(self):
        return {"geom": self.geom, "dtg": self.dtg, "period": self.period.value,
                "length": self.length,
                "bins": [[k, _arr_to_b64(v)] for k, v in self.bins.items()]}

    @classmethod
    def _from_state(cls, d):
        return cls(d["geom"], d["dtg"], d["period"], d["length"],
                   {k: _arr_from_b64(v) for k, v in d["bins"]})


class Z2HistogramStat(Stat):
    """Spatial histogram over coarse z2 cells — the z2 index's selectivity
    estimator (pairs with Z3HistogramStat so the cost decider compares both
    spatial indexes on the same data distribution)."""

    kind = "z2histogram"

    def __init__(self, geom: str, length: int = 1024, counts: Optional[np.ndarray] = None):
        from geomesa_tpu.curves.zorder import Z2SFC

        self.geom = geom
        self.length = int(length)
        self.sfc = Z2SFC()
        self.shift = 62 - int(np.log2(self.length))
        self.counts = (
            np.zeros(self.length, dtype=np.int64) if counts is None
            else np.asarray(counts, np.int64)
        )

    def observe(self, columns, mask=None):
        if "__z2" in columns:  # ingest already computed the key column
            z = _masked(np.asarray(columns["__z2"], np.uint64), mask)
        else:
            xs = _masked(np.asarray(columns[self.geom + "__x"]), mask)
            ys = _masked(np.asarray(columns[self.geom + "__y"]), mask)
            if xs.size == 0:
                return
            z = self.sfc.index(xs, ys)
        if z.size == 0:
            return
        bucket = (z >> np.uint64(self.shift)).astype(np.int32)
        self.counts += np.bincount(bucket, minlength=self.length)

    def merge(self, other: "Z2HistogramStat"):
        self.counts += other.counts

    @property
    def is_empty(self):
        return int(self.counts.sum()) == 0

    def value(self):
        return {"total": int(self.counts.sum()), "length": self.length}

    def estimate_count(self, zranges) -> float:
        total = 0.0
        bucket_span = 1 << self.shift
        for r in zranges:
            b0, b1 = r.lo >> self.shift, r.hi >> self.shift
            if b0 == b1:
                total += float(self.counts[b0]) * ((r.hi - r.lo + 1) / bucket_span)
            else:
                total += float(self.counts[b0]) * (((b0 + 1) * bucket_span - r.lo) / bucket_span)
                total += float(self.counts[b1]) * ((r.hi - b1 * bucket_span + 1) / bucket_span)
                if b1 > b0 + 1:
                    total += float(self.counts[b0 + 1 : b1].sum())
        return total

    def _state(self):
        return {"geom": self.geom, "length": self.length,
                "counts": _arr_to_b64(self.counts)}

    @classmethod
    def _from_state(cls, d):
        return cls(d["geom"], d["length"], _arr_from_b64(d["counts"]))


class SeqStat(Stat):
    """Multiple sketches observed together ('Stat1;Stat2' in the DSL)."""

    kind = "seq"

    def __init__(self, stats: List[Stat]):
        self.stats = stats

    def observe(self, columns, mask=None):
        for s in self.stats:
            s.observe(columns, mask)

    def unobserve(self, columns, mask=None):
        for s in self.stats:
            s.unobserve(columns, mask)

    def merge(self, other: "SeqStat"):
        for a, b in zip(self.stats, other.stats):
            a.merge(b)

    @property
    def is_empty(self):
        return all(s.is_empty for s in self.stats)

    def value(self):
        return [s.value() for s in self.stats]

    def _state(self):
        return {"stats": [s.to_json() for s in self.stats]}

    @classmethod
    def _from_state(cls, d):
        return cls([Stat.from_json(s) for s in d["stats"]])


class Z3FrequencyStat(Stat):
    """Count-min sketch keyed by (time bin, coarse z3 cell) — approximate
    per-cell frequencies for spatio-temporal values (reference Z3Frequency,
    geomesa-utils/.../stats/Z3Frequency.scala): per time bin, a Frequency
    sketch over the truncated z value."""

    kind = "z3frequency"

    def __init__(self, geom: str, dtg: str, period: "str | TimePeriod" = TimePeriod.WEEK,
                 precision: int = 10, width: int = 1024,
                 bins: "Optional[Dict[int, Frequency]]" = None):
        self.geom = geom
        self.dtg = dtg
        self.period = TimePeriod.parse(period)
        self.precision = int(precision)  # bits of z kept (top 3*precision)
        if not 1 <= self.precision <= 21:
            raise ValueError(
                f"Z3Frequency precision must be in [1, 21], got {self.precision}"
            )
        self.width = int(width)
        self.sfc = Z3SFC(self.period)
        self.binned = BinnedTime(self.period)
        self.shift = 63 - 3 * self.precision
        self.bins: Dict[int, Frequency] = dict(bins or {})

    def _key(self, xs, ys, off) -> np.ndarray:
        z = self.sfc.index(xs, ys, off)
        return (z >> np.uint64(self.shift)).astype(np.int64)

    def observe(self, columns, mask=None):
        xs = _masked(np.asarray(columns[self.geom + "__x"]), mask)
        ys = _masked(np.asarray(columns[self.geom + "__y"]), mask)
        ts = _masked(np.asarray(columns[self.dtg]), mask)
        if xs.size == 0:
            return
        b, off = self.binned.to_bin_and_offset(ts)
        keys = self._key(xs, ys, off)
        for bb in np.unique(b).tolist():
            sel = b == bb
            fq = self.bins.get(int(bb))
            if fq is None:
                fq = self.bins[int(bb)] = Frequency("__z3__", width=self.width)
            fq.observe({"__z3__": keys[sel]})

    def merge(self, other: "Z3FrequencyStat"):
        if (
            self.period != other.period
            or self.precision != other.precision
            or self.width != other.width
        ):
            raise ValueError(
                "cannot merge Z3Frequency sketches with different "
                f"period/precision/width: {self.period.value}/{self.precision}"
                f"/{self.width} vs {other.period.value}/{other.precision}"
                f"/{other.width}"
            )
        for k, v in other.bins.items():
            if k in self.bins:
                self.bins[k].merge(v)
            else:
                self.bins[k] = Frequency(
                    "__z3__", width=v.width, counts=v.counts.copy()
                )

    @property
    def is_empty(self):
        return not self.bins

    def count(self, time_bin: int, x: float, y: float, offset_ms: float) -> int:
        """Approximate (over-)count of points in the cell containing
        (x, y, offset) within the given time bin."""
        fq = self.bins.get(int(time_bin))
        if fq is None:
            return 0
        key = self._key(
            np.asarray([x]), np.asarray([y]), np.asarray([offset_ms])
        )
        return fq.count(int(key[0]))

    def value(self):
        return {int(k): int(v.counts[0].sum()) for k, v in self.bins.items()}

    def _state(self):
        return {
            "geom": self.geom, "dtg": self.dtg, "period": self.period.value,
            "precision": self.precision, "width": self.width,
            "bins": {str(k): _arr_to_b64(v.counts) for k, v in self.bins.items()},
        }

    @classmethod
    def _from_state(cls, d):
        out = cls(d["geom"], d["dtg"], d["period"], d["precision"], d["width"])
        for k, v in d["bins"].items():
            fq = Frequency("__z3__", width=out.width)
            fq.counts = _arr_from_b64(v).reshape(fq.counts.shape)
            out.bins[int(k)] = fq
        return out


_KINDS = {
    c.kind: c
    for c in (
        CountStat, MinMax, EnumerationStat, TopK, Histogram, Frequency,
        DescriptiveStats, GroupBy, Z3HistogramStat, Z2HistogramStat,
        Z3FrequencyStat, SeqStat,
    )
}
