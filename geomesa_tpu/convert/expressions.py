"""Converter transform expressions.

Reference parity: geomesa-convert-common transforms/Expression.scala and the
function factories (transforms/*FunctionFactory.scala — date, geometry,
string, math, cast, id functions). The expression grammar is kept compatible
with the reference's converter configs:

    $0, $1 ... $N        raw input columns ($0 = whole record)
    $name                a previously-defined field by name
    'literal'  1  2.5    literals
    fn(a, b, ...)        function application, nestable

Evaluation is batch-vectorized: every expression maps a context of equal-
length columns to an output array (numpy where possible, object arrays
elsewhere).
"""

from __future__ import annotations

import hashlib
import re
import uuid as _uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class EvalError(Exception):
    pass


@dataclass
class Context:
    """Per-batch evaluation context."""

    #: raw input columns: index 0 = whole record, 1..N = split columns
    raw: List[np.ndarray]
    #: named fields already evaluated (in config order)
    fields: Dict[str, np.ndarray]
    #: batch length
    n: int
    #: global line-number offset of this batch
    line_offset: int = 0
    #: enrichment caches by name (EnrichmentCache.scala:19 analog):
    #: name -> {key -> {field -> value}}
    caches: "Dict[str, Dict[str, Dict[str, object]]]" = None


class Expr:
    def eval(self, ctx: Context) -> np.ndarray:
        raise NotImplementedError


@dataclass
class Lit(Expr):
    value: object

    def eval(self, ctx):
        if isinstance(self.value, str):
            return np.full(ctx.n, self.value, dtype=object)
        return np.full(ctx.n, self.value)


@dataclass
class Col(Expr):
    index: int

    def eval(self, ctx):
        try:
            return ctx.raw[self.index]
        except IndexError:
            raise EvalError(
                f"column ${self.index} out of range ({len(ctx.raw) - 1} columns)"
            )


@dataclass
class FieldRef(Expr):
    name: str

    def eval(self, ctx):
        try:
            return ctx.fields[self.name]
        except KeyError:
            raise EvalError(
                f"field ${self.name} not defined yet "
                f"(have: {', '.join(ctx.fields) or 'none'})"
            )


@dataclass
class Call(Expr):
    name: str
    args: List[Expr]

    def eval(self, ctx):
        fn = FUNCTIONS.get(self.name)
        if fn is None:
            raise EvalError(f"unknown converter function {self.name!r}")
        return fn(ctx, *[a.eval(ctx) for a in self.args]) if not getattr(
            fn, "_lazy", False
        ) else fn(ctx, *self.args)


# -- function registry -------------------------------------------------------

FUNCTIONS: Dict[str, Callable] = {}


def register(name):
    def deco(fn):
        FUNCTIONS[name] = fn
        return fn

    return deco


def lazy_register(name):
    """Register a function receiving unevaluated Expr args (try/withDefault)."""

    def deco(fn):
        fn._lazy = True
        FUNCTIONS[name] = fn
        return fn

    return deco


def _as_obj(a) -> np.ndarray:
    return a if isinstance(a, np.ndarray) and a.dtype == object else np.asarray(a, dtype=object)


def _elementwise(fn, *arrays):
    out = np.empty(len(arrays[0]), dtype=object)
    for i in range(len(arrays[0])):
        out[i] = fn(*[a[i] for a in arrays])
    return out


# strings (StringFunctionFactory parity)
@register("trim")
def _trim(ctx, a):
    return _elementwise(lambda v: None if v is None else str(v).strip(), _as_obj(a))


@register("lowercase")
def _lower(ctx, a):
    return _elementwise(lambda v: None if v is None else str(v).lower(), _as_obj(a))


@register("uppercase")
def _upper(ctx, a):
    return _elementwise(lambda v: None if v is None else str(v).upper(), _as_obj(a))


@register("capitalize")
def _cap(ctx, a):
    return _elementwise(lambda v: None if v is None else str(v).capitalize(), _as_obj(a))


@register("concat")
@register("concatenate")
def _concat(ctx, *args):
    return _elementwise(lambda *vs: "".join("" if v is None else str(v) for v in vs),
                        *[_as_obj(a) for a in args])


@register("substr")
@register("substring")
def _substr(ctx, a, lo, hi):
    return _elementwise(
        lambda v, l, h: None if v is None else str(v)[int(l): int(h)],
        _as_obj(a), _as_obj(lo), _as_obj(hi),
    )


@register("length")
def _length(ctx, a):
    return np.array([0 if v is None else len(str(v)) for v in _as_obj(a)], np.int64)


@register("regexReplace")
def _regex_replace(ctx, pattern, replacement, a):
    pat = re.compile(str(pattern[0]))
    rep = str(replacement[0])
    return _elementwise(lambda v: None if v is None else pat.sub(rep, str(v)), _as_obj(a))


@register("toString")
def _to_string(ctx, a):
    return _elementwise(lambda v: None if v is None else str(v), _as_obj(a))


@register("emptyToNull")
def _empty_to_null(ctx, a):
    return _elementwise(
        lambda v: None if v is None or str(v).strip() == "" else v, _as_obj(a)
    )


# casts (CastFunctionFactory parity)
def _cast_num(a, pytype):
    def one(v):
        if v is None or (isinstance(v, str) and not v.strip()):
            raise EvalError("cannot cast null/empty")
        return pytype(float(v)) if pytype in (int,) else pytype(v)

    return _elementwise(one, _as_obj(a))


@register("toInt")
@register("toInteger")
def _to_int(ctx, a):
    return _cast_num(a, int)


@register("toLong")
def _to_long(ctx, a):
    return _cast_num(a, int)


@register("toFloat")
@register("toDouble")
def _to_double(ctx, a):
    return _cast_num(a, float)


@register("toBoolean")
def _to_bool(ctx, a):
    return _elementwise(
        lambda v: str(v).strip().lower() in ("true", "1", "t", "yes"), _as_obj(a)
    )


# math (MathFunctionFactory parity)
def _binary_math(op):
    def fn(ctx, *args):
        out = np.asarray(args[0], np.float64)
        for a in args[1:]:
            out = op(out, np.asarray(a, np.float64))
        return out

    return fn


FUNCTIONS["add"] = _binary_math(np.add)
FUNCTIONS["subtract"] = _binary_math(np.subtract)
FUNCTIONS["multiply"] = _binary_math(np.multiply)
FUNCTIONS["divide"] = _binary_math(np.divide)
FUNCTIONS["min"] = _binary_math(np.minimum)
FUNCTIONS["max"] = _binary_math(np.maximum)


@register("abs")
def _abs(ctx, a):
    return np.abs(np.asarray(a, np.float64))


# dates (DateFunctionFactory parity). Patterns use Java letters; translate the
# common subset to strptime.
_JAVA2PY = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
    ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"), ("'T'", "T"), ("'Z'", "Z"),
]


def _java_pattern(p: str) -> str:
    for j, py in _JAVA2PY:
        p = p.replace(j, py)
    return p


def _parse_dates(vals, fmt: Optional[str]) -> np.ndarray:
    from datetime import datetime, timezone

    out = np.empty(len(vals), "datetime64[ms]")
    for i, v in enumerate(vals):
        if v is None or (isinstance(v, str) and not v.strip()):
            raise EvalError(f"cannot parse date from {v!r}")
        if fmt is None:
            out[i] = np.datetime64(str(v).rstrip("Z"), "ms")
        else:
            dt = datetime.strptime(str(v), fmt)
            if dt.tzinfo is not None:
                dt = dt.astimezone(timezone.utc).replace(tzinfo=None)
            out[i] = np.datetime64(dt, "ms")
    return out


@register("date")
@register("dateParse")
def _date_parse(ctx, pattern, a):
    fmt = _java_pattern(str(pattern[0]))
    # %f expects microseconds; Java SSS is millis — normalize by padding
    return _parse_dates(_as_obj(a), fmt)


@register("isoDate")
@register("isoDateTime")
@register("basicDateTimeNoMillis")
def _iso_date(ctx, a):
    return _parse_dates(_as_obj(a), None)


@register("millisToDate")
def _millis_to_date(ctx, a):
    return np.asarray(a, np.int64).astype("datetime64[ms]")


@register("secsToDate")
def _secs_to_date(ctx, a):
    return (np.asarray(a, np.int64) * 1000).astype("datetime64[ms]")


@register("now")
def _now(ctx):
    return np.full(ctx.n, np.datetime64("now", "ms"))


@register("dateToString")
def _date_to_string(ctx, pattern, a):
    fmt = _java_pattern(str(pattern[0]))
    import pandas as pd

    return np.array(
        pd.DatetimeIndex(np.asarray(a, "datetime64[ms]")).strftime(fmt).tolist(),
        dtype=object,
    )


# geometry (GeometryFunctionFactory parity)
@register("point")
def _point(ctx, x, y=None):
    if y is None:
        # WKT strings
        return _as_obj(x)
    xs = np.asarray(x, np.float64)
    ys = np.asarray(y, np.float64)
    out = np.empty(len(xs), dtype=object)
    for i in range(len(xs)):
        out[i] = (xs[i], ys[i])
    return out


@register("geometry")
@register("polygon")
@register("linestring")
@register("multipolygon")
def _geometry(ctx, a):
    return _as_obj(a)  # WKT strings pass through; parsed by encode_batch


# ids (IdFunctionFactory parity)
@register("md5")
def _md5(ctx, a):
    return _elementwise(
        lambda v: hashlib.md5(
            v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        ).hexdigest(),
        _as_obj(a),
    )


@register("murmur3_32")
@register("murmurHash3")
def _murmur(ctx, a):
    # 128-bit murmur is overkill here; stable hex digest parity is what
    # matters for ids. Use blake2 tagged to distinguish from md5.
    return _elementwise(
        lambda v: hashlib.blake2s(str(v).encode(), digest_size=16).hexdigest(),
        _as_obj(a),
    )


@register("uuid")
def _uuid_fn(ctx):
    return np.array([_uuid.uuid4().hex for _ in range(ctx.n)], dtype=object)


@register("string2bytes")
@register("stringToBytes")
def _string_to_bytes(ctx, a):
    return _elementwise(lambda v: str(v).encode(), _as_obj(a))


@register("lineNo")
@register("lineNumber")
def _line_no(ctx):
    return np.arange(ctx.line_offset, ctx.line_offset + ctx.n, dtype=np.int64)


@register("cacheLookup")
def _cache_lookup(ctx, name, key, field):
    """Enrichment-cache lookup (EnrichmentCacheFunctionFactory.scala:24:
    cacheLookup(cache, entity-key, field)) — vectorized over the batch:
    missing entities/fields yield None (the reference returns null)."""
    caches = ctx.caches or {}
    cname = name[0] if isinstance(name, np.ndarray) else name
    cache = caches.get(str(cname))
    if cache is None:
        raise EvalError(f"no enrichment cache named {cname!r}")
    keys = _as_obj(key)
    fields = _as_obj(field)
    out = np.empty(ctx.n, dtype=object)
    for i in range(ctx.n):
        row = cache.get(str(keys[i]))
        out[i] = None if row is None else row.get(str(fields[i]))
    return out


# lazy control flow
@lazy_register("try")
@lazy_register("tryEval")
def _try(ctx, expr, fallback):
    try:
        return expr.eval(ctx)
    except Exception:
        return fallback.eval(ctx)


@lazy_register("withDefault")
def _with_default(ctx, expr, default):
    try:
        vals = _as_obj(expr.eval(ctx))
    except Exception:
        return default.eval(ctx)
    dv = default.eval(ctx)
    return _elementwise(lambda v, d: d if v is None else v, vals, _as_obj(dv))


# -- parser ------------------------------------------------------------------

_TOKEN = re.compile(
    r"\s*(?:(?P<num>-?\d+\.\d+|-?\d+)|(?P<str>'(?:[^'\\]|\\.)*')"
    r"|(?P<col>\$\d+)|(?P<ref>\$[A-Za-z_][A-Za-z0-9_]*)"
    r"|(?P<name>[A-Za-z_][A-Za-z0-9_.]*)|(?P<punct>[(),]))"
)


def parse(text: str) -> Expr:
    """Parse a transform expression string into an Expr tree."""
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"bad expression at ...{text[pos:pos+20]!r}")
        tokens.append(m)
        pos = m.end()

    idx = 0

    def peek():
        return tokens[idx] if idx < len(tokens) else None

    def take():
        nonlocal idx
        t = tokens[idx]
        idx += 1
        return t

    def parse_one() -> Expr:
        t = take()
        if t.group("num") is not None:
            s = t.group("num")
            return Lit(float(s) if "." in s else int(s))
        if t.group("str") is not None:
            raw = t.group("str")[1:-1]
            return Lit(raw.replace("\\'", "'").replace("\\\\", "\\"))
        if t.group("col") is not None:
            return Col(int(t.group("col")[1:]))
        if t.group("ref") is not None:
            return FieldRef(t.group("ref")[1:])
        if t.group("name") is not None:
            name = t.group("name")
            nxt = peek()
            if nxt is not None and nxt.group("punct") == "(":
                take()  # (
                args: List[Expr] = []
                while True:
                    nxt = peek()
                    if nxt is None:
                        raise ValueError(f"unterminated call {name}(... in {text!r}")
                    if nxt.group("punct") == ")":
                        take()
                        break
                    if nxt.group("punct") == ",":
                        take()
                        continue
                    args.append(parse_one())
                return Call(name, args)
            # bare word: treat as string literal (HOCON-ish leniency)
            return Lit(name)
        raise ValueError(f"unexpected token in {text!r}")

    expr = parse_one()
    if idx != len(tokens):
        raise ValueError(f"trailing tokens in expression {text!r}")
    return expr
