"""Declarative ingest converters (geomesa-convert analog)."""

from geomesa_tpu.convert.converter import (  # noqa: F401
    ConverterConfig, DelimitedTextConverter, EvaluationContext, JsonConverter,
    converter_for, infer_schema,
)
