"""Converters: declarative ingest from raw records to feature batches.

Reference parity (geomesa-convert, SURVEY.md §2.7): a converter config names
an input format, an id expression, per-field transform expressions, and
validation options; an ``EvaluationContext`` counts successes/failures;
``ErrorMode`` chooses skip vs raise; ``TypeInference`` builds a schema +
converter from schema-less delimited input.

Config shape (HOCON or JSON or dict — same keys as the reference's):

    {
      "type": "delimited-text",          # or "json"
      "format": "CSV",                   # CSV | TSV | or {"delimiter": "|"}
      "id-field": "md5($0)",
      "options": {
        "skip-lines": 1,
        "error-mode": "skip-bad-records",  # or "raise-errors"
        "validators": ["index"]
      },
      "fields": [
        {"name": "dtg",  "transform": "date('yyyy-MM-dd', $2)"},
        {"name": "lon",  "transform": "toDouble($3)"},
        {"name": "geom", "transform": "point($lon, toDouble($4))"}
      ]
    }

JSON converters add ``feature-path`` (a JsonPath subset) and per-field
``path`` ($.a.b) instead of/alongside ``transform``.
"""

from __future__ import annotations

import csv
import io
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.convert import expressions as ex
from geomesa_tpu.convert import hocon
from geomesa_tpu.schema.feature_type import FeatureType


@dataclass
class EvaluationContext:
    """Ingest counters (reference EvaluationContext with metrics)."""

    success: int = 0
    failure: int = 0
    errors: List[str] = field(default_factory=list)

    def record_failure(self, msg: str, keep: int = 20):
        self.failure += 1
        if len(self.errors) < keep:
            self.errors.append(msg)


@dataclass
class ConverterConfig:
    type: str
    fields: List[Dict[str, str]]
    id_field: Optional[str] = None
    format: Any = "CSV"
    options: Dict[str, Any] = field(default_factory=dict)
    feature_path: Optional[str] = None
    #: enrichment-cache configs by name (EnrichmentCache.scala:19):
    #: {type: simple, data: {...}} or {type: csv, path, id-field, columns}
    caches: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    @staticmethod
    def parse(source: "str | Dict") -> "ConverterConfig":
        cfg = hocon.loads(source) if isinstance(source, str) else dict(source)
        # allow the reference's wrapping key `geomesa.converters.<name> = {...}`
        gm = cfg.get("geomesa", {}).get("converters") if "geomesa" in cfg else None
        if gm:
            cfg = next(iter(gm.values()))
        options = dict(cfg.get("options", {}))
        if "connection" in cfg:  # jdbc: top-level key, reference layout
            options.setdefault("connection", cfg["connection"])
        return ConverterConfig(
            type=cfg.get("type", "delimited-text"),
            fields=list(cfg.get("fields", [])),
            id_field=cfg.get("id-field") or cfg.get("id_field"),
            format=cfg.get("format", "CSV"),
            options=options,
            feature_path=cfg.get("feature-path") or cfg.get("feature_path"),
            caches=dict(cfg.get("caches", {})),
        )


def load_enrichment_caches(
    configs: Dict[str, Dict[str, Any]],
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Materialize enrichment caches: name -> {key -> {field -> value}}.

    ``simple`` holds inline data (SimpleEnrichmentCache); ``csv`` loads a
    delimited file keyed by ``id-field`` (ResourceLoadingCache, but from a
    filesystem path — there is no classpath here)."""
    import csv as _csv

    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for name, conf in (configs or {}).items():
        ctype = conf.get("type", "simple")
        if ctype == "simple":
            out[name] = {
                str(k): dict(v) for k, v in (conf.get("data") or {}).items()
            }
        elif ctype == "csv":
            path = conf["path"]
            id_field = conf.get("id-field") or conf.get("id_field")
            columns = conf.get("columns")
            table: Dict[str, Dict[str, Any]] = {}
            with open(path, newline="") as fh:
                reader = (
                    _csv.DictReader(fh, fieldnames=list(columns))
                    if columns
                    else _csv.DictReader(fh)
                )
                for rec in reader:
                    table[str(rec[id_field])] = dict(rec)
            out[name] = table
        else:
            raise ValueError(f"unknown enrichment cache type {ctype!r}")
    return out


class _LineTee:
    """Iterator wrapper capturing the raw lines csv.reader consumes, so $0
    can be the verbatim input record (multi-line quoted rows included)."""

    def __init__(self, it):
        self._it = iter(it)
        self.consumed: List[str] = []

    def __iter__(self):
        return self

    def __next__(self):
        line = next(self._it)
        self.consumed.append(line)
        return line


class BaseConverter:
    """Shared transform-evaluation pipeline."""

    def __init__(self, ft: FeatureType, config: ConverterConfig):
        self.ft = ft
        self.config = config
        self.error_mode = config.options.get("error-mode", "skip-bad-records")
        self.validators = config.options.get("validators", ["index"])
        self._field_exprs: List[Tuple[str, ex.Expr]] = [
            (f["name"], ex.parse(f["transform"]))
            for f in config.fields
            if "transform" in f
        ]
        self._plain_fields = [
            f["name"] for f in config.fields if "transform" not in f and "path" not in f
        ]
        self._id_expr = ex.parse(config.id_field) if config.id_field else None

    # -- per-batch transform + validation ---------------------------------
    def _transform(self, raw: List[np.ndarray], n: int, line_offset: int,
                   ctx: EvaluationContext,
                   preset: Optional[Dict[str, np.ndarray]] = None):
        """raw columns -> (data dict, fids, kept-mask)."""
        caches = self.__dict__.get("_caches")
        if caches is None:
            caches = self._caches = load_enrichment_caches(self.config.caches)
        ectx = ex.Context(raw=raw, fields=dict(preset or {}), n=n,
                          line_offset=line_offset, caches=caches)
        keep = np.ones(n, dtype=bool)
        for name, expr in self._field_exprs:
            try:
                ectx.fields[name] = expr.eval(ectx)
            except Exception as e:
                # batch-level failure: fall back to row-at-a-time so one bad
                # row doesn't poison the batch
                vals, row_ok = self._row_fallback(
                    expr, ectx, ctx, name, e, keep)
                ectx.fields[name] = vals
                keep &= row_ok
        fids = None
        if self._id_expr is not None:
            fids = ex._as_obj(self._id_expr.eval(ectx))
        # validation (IndexValidatorFactory analog: geom/dtg must be present
        # and in-bounds for the indexed fields). 'index' covers both; the
        # narrower validators check only their own field. Runs once, only on
        # rows not already failed, so each bad row is counted exactly once.
        check_geom = "index" in self.validators or "has-geo" in self.validators
        check_dtg = "index" in self.validators or "has-dtg" in self.validators
        if check_geom or check_dtg:
            keep &= self._index_validate(ectx, ctx, keep, check_geom, check_dtg)
        data = {}
        for a in self.ft.attributes:
            if a.name in ectx.fields:
                data[a.name] = ectx.fields[a.name]
        return data, fids, keep

    def _row_fallback(self, expr, ectx, ctx, name, batch_err,
                      still_ok=None):
        if self.error_mode == "raise-errors":
            raise ValueError(f"field {name!r}: {batch_err}") from batch_err
        n = ectx.n
        vals = np.empty(n, dtype=object)
        ok = np.ones(n, dtype=bool)
        for i in range(n):
            if still_ok is not None and not still_ok[i]:
                # an earlier field already failed this row: it is dead —
                # don't evaluate further fields and, critically, don't
                # record a SECOND failure for the same record (fuzz-found
                # r5: a row bad in two fields counted as two failures;
                # the reference's ErrorMode counts per record)
                ok[i] = False
                continue
            row_ctx = ex.Context(
                raw=[a[i: i + 1] for a in ectx.raw],
                fields={k: v[i: i + 1] for k, v in ectx.fields.items()},
                n=1, line_offset=ectx.line_offset + i,
                caches=ectx.caches,
            )
            try:
                vals[i] = expr.eval(row_ctx)[0]
            except Exception as e:
                ok[i] = False
                ctx.record_failure(f"line {ectx.line_offset + i}: {name}: {e}")
        return vals, ok

    def _index_validate(self, ectx, ctx: EvaluationContext,
                        already_kept: np.ndarray, check_geom: bool,
                        check_dtg: bool) -> np.ndarray:
        keep = np.ones(ectx.n, dtype=bool)
        g = self.ft.geom_field
        if check_geom and g is not None and g in ectx.fields:
            vals = ex._as_obj(ectx.fields[g])
            for i, v in enumerate(vals):
                if not already_kept[i]:
                    continue  # already failed upstream; don't double-count
                bad = v is None
                if not bad and isinstance(v, tuple):
                    bad = not (
                        -180 <= v[0] <= 180 and -90 <= v[1] <= 90
                        and v[0] == v[0] and v[1] == v[1]
                    )
                if bad:
                    keep[i] = False
                    ctx.record_failure(f"line {ectx.line_offset + i}: invalid geometry {v!r}")
        d = self.ft.dtg_field
        if check_dtg and d is not None and d in ectx.fields:
            vals = ectx.fields[d]
            if isinstance(vals, np.ndarray) and vals.dtype.kind == "M":
                nat = np.isnat(vals) & already_kept & keep
                keep &= ~nat
                for i in np.nonzero(nat)[0][:5]:
                    ctx.record_failure(f"line {ectx.line_offset + i}: missing dtg")
        return keep

    def _finish(self, data, fids, keep, ctx: EvaluationContext):
        n = len(keep)
        kept = int(keep.sum())
        ctx.success += kept
        if kept == n:
            return data, fids
        if self.error_mode == "raise-errors":
            raise ValueError(
                f"{n - kept} invalid records: {ctx.errors[:3]}"
            )
        data = {
            k: (v[keep] if isinstance(v, np.ndarray) else
                [x for x, m in zip(v, keep) if m])
            for k, v in data.items()
        }
        fids = fids[keep] if fids is not None else None
        return data, fids


class DelimitedTextConverter(BaseConverter):
    """CSV/TSV/custom-delimiter converter (geomesa-convert-text analog)."""

    def convert(self, source: "str | io.TextIOBase | Iterable[str]",
                ctx: Optional[EvaluationContext] = None,
                batch_size: int = 100_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        """Yield (data, fids) batches ready for GeoDataset.insert."""
        ctx = ctx if ctx is not None else EvaluationContext()
        fmt = self.config.format
        if isinstance(fmt, dict):
            delim = fmt.get("delimiter", ",")
        else:
            delim = {"CSV": ",", "TSV": "\t"}.get(str(fmt).upper(), str(fmt))
        if isinstance(source, str):
            lines: Iterable[str] = io.StringIO(source)
        else:
            lines = source
        skip = int(self.config.options.get("skip-lines", 0))
        tee = _LineTee(lines)
        reader = csv.reader(tee, delimiter=delim)
        rows: List[List[str]] = []
        raws: List[str] = []  # raw input per record ($0 must be verbatim)
        batch_start = None  # physical 1-based line of the batch's first row
        i = 0
        while True:
            mark = len(tee.consumed)
            try:
                row = next(reader)
            except StopIteration:
                break
            raw = "".join(tee.consumed[mark:]).rstrip("\r\n")
            tee.consumed[mark:] = []  # bound memory
            if i < skip:
                i += 1
                continue
            if batch_start is None:
                batch_start = i + 1
            rows.append(row)
            raws.append(raw)
            i += 1
            if len(rows) >= batch_size:
                yield self._convert_rows(rows, raws, batch_start, ctx)
                batch_start = None
                rows, raws = [], []
        if rows:
            yield self._convert_rows(rows, raws, batch_start, ctx)

    def _convert_rows(self, rows: List[List[str]], raws: List[str],
                      line_offset: int, ctx: EvaluationContext):
        n = len(rows)
        width = max(len(r) for r in rows)
        raw: List[np.ndarray] = [np.empty(n, dtype=object) for _ in range(width + 1)]
        for i, r in enumerate(rows):
            raw[0][i] = raws[i]
            for j in range(width):
                raw[j + 1][i] = r[j] if j < len(r) else None
        data, fids, keep = self._transform(raw, n, line_offset, ctx)
        return self._finish(data, fids, keep, ctx)


def _json_path_get(obj, path: str):
    """Tiny JsonPath subset: $.a.b, a.b, $['a'], array indices [0], [*]."""
    import re as _re

    parts = _re.findall(r"\[\*\]|\[(?:'([^']*)'|(\d+))\]|([A-Za-z0-9_\-]+)", path)
    cur = [obj]
    for quoted, idx, name in parts:
        nxt = []
        for c in cur:
            if c is None:
                continue
            if quoted or name:
                key = quoted or name
                if key == "$":
                    nxt.append(c)
                elif isinstance(c, dict):
                    nxt.append(c.get(key))
            elif idx:
                if isinstance(c, list) and int(idx) < len(c):
                    nxt.append(c[int(idx)])
            else:  # [*]
                if isinstance(c, list):
                    nxt.extend(c)
        cur = nxt
    return cur


class JsonConverter(BaseConverter):
    """JSON converter with feature-path + per-field path extraction
    (geomesa-convert-json analog)."""

    def convert(self, source: "str | bytes | dict | list",
                ctx: Optional[EvaluationContext] = None,
                batch_size: int = 100_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        ctx = ctx if ctx is not None else EvaluationContext()
        if isinstance(source, (str, bytes)):
            doc = json.loads(source)
        else:
            doc = source
        if self.config.feature_path:
            features = _json_path_get(doc, self.config.feature_path)
        elif isinstance(doc, list):
            features = doc
        else:
            features = [doc]
        features = [f for f in features if f is not None]
        for start in range(0, len(features), batch_size):
            chunk = features[start:start + batch_size]
            yield self._convert_objs(chunk, start, ctx)

    def _convert_objs(self, objs: List[dict], line_offset: int,
                      ctx: EvaluationContext):
        n = len(objs)
        raw = [np.empty(n, dtype=object)]
        for i, o in enumerate(objs):
            raw[0][i] = json.dumps(o)
        preset: Dict[str, np.ndarray] = {}
        for f in self.config.fields:
            if "path" in f:
                vals = np.empty(n, dtype=object)
                for i, o in enumerate(objs):
                    got = _json_path_get(o, f["path"])
                    vals[i] = got[0] if got else None
                preset[f["name"]] = vals
        data, fids, keep = self._transform(raw, n, line_offset, ctx, preset)
        # path-only fields (no transform) flow straight through
        for f in self.config.fields:
            name = f["name"]
            if "path" in f and "transform" not in f and self.ft.has(name):
                data.setdefault(name, preset[name])
        return self._finish(data, fids, keep, ctx)


class XmlConverter(BaseConverter):
    """XML converter: feature-path selects elements, per-field ``path`` is a
    relative child path (``a/b``, ``@attr``, or ``a/b/@attr``) — the
    XPath-subset model of geomesa-convert-xml."""

    def convert(self, source: "str | bytes",
                ctx: Optional[EvaluationContext] = None,
                batch_size: int = 100_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        import xml.etree.ElementTree as ET

        ctx = ctx if ctx is not None else EvaluationContext()
        if hasattr(source, "read"):
            source = source.read()
        root = ET.fromstring(
            source.decode() if isinstance(source, bytes) else source
        )
        fp = (self.config.feature_path or ".").strip("/")
        elems = root.findall(f".//{fp}") if fp not in (".", "") else [root]
        for start in range(0, len(elems), batch_size):
            chunk = elems[start:start + batch_size]
            yield self._convert_elems(chunk, start, ctx)

    @staticmethod
    def _xml_get(elem, path: str):
        if path.startswith("@"):
            return elem.get(path[1:])
        if "/@" in path:
            epath, attr = path.rsplit("/@", 1)
            child = elem.find(epath)
            return None if child is None else child.get(attr)
        child = elem.find(path)
        if child is None:
            return None
        return (child.text or "").strip() or None

    def _convert_elems(self, elems, line_offset: int, ctx: EvaluationContext):
        import xml.etree.ElementTree as ET

        n = len(elems)
        raw = [np.empty(n, dtype=object)]
        for i, e in enumerate(elems):
            raw[0][i] = ET.tostring(e, encoding="unicode")
        preset: Dict[str, np.ndarray] = {}
        for f in self.config.fields:
            if "path" in f:
                vals = np.empty(n, dtype=object)
                for i, e in enumerate(elems):
                    vals[i] = self._xml_get(e, f["path"])
                preset[f["name"]] = vals
        data, fids, keep = self._transform(raw, n, line_offset, ctx, preset)
        for f in self.config.fields:
            name = f["name"]
            if "path" in f and "transform" not in f and self.ft.has(name):
                data.setdefault(name, preset[name])
        return self._finish(data, fids, keep, ctx)


class FixedWidthConverter(BaseConverter):
    """Fixed-width text: per-field ``start``/``width`` character offsets
    (geomesa-convert-fixedwidth analog); transforms see the slice as $name."""

    def convert(self, source: "str | io.TextIOBase | Iterable[str]",
                ctx: Optional[EvaluationContext] = None,
                batch_size: int = 100_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        ctx = ctx if ctx is not None else EvaluationContext()
        lines = io.StringIO(source) if isinstance(source, str) else source
        skip = int(self.config.options.get("skip-lines", 0))
        buf: List[str] = []
        offset = 0
        for i, line in enumerate(lines):
            if i < skip:
                continue
            line = line.rstrip("\r\n")
            if line:
                buf.append(line)
            if len(buf) >= batch_size:
                yield self._convert_lines(buf, offset, ctx)
                offset += len(buf)
                buf = []
        if buf:
            yield self._convert_lines(buf, offset, ctx)

    def _convert_lines(self, lines: List[str], line_offset: int,
                       ctx: EvaluationContext):
        n = len(lines)
        raw = [np.array(lines, dtype=object)]
        preset: Dict[str, np.ndarray] = {}
        for f in self.config.fields:
            if "start" in f:
                s = int(f["start"])
                e = s + int(f["width"])
                vals = np.empty(n, dtype=object)
                for i, line in enumerate(lines):
                    piece = line[s:e].strip()
                    vals[i] = piece or None
                preset[f["name"]] = vals
        data, fids, keep = self._transform(raw, n, line_offset, ctx, preset)
        for f in self.config.fields:
            name = f["name"]
            if "start" in f and "transform" not in f and self.ft.has(name):
                data.setdefault(name, preset[name])
        return self._finish(data, fids, keep, ctx)


class _ColumnarConverter(BaseConverter):
    """Shared path for columnar inputs (Parquet/Avro): every input column is
    preset as $name; fields without transforms pass straight through."""

    def _convert_table(self, columns: Dict[str, np.ndarray], n: int,
                       ctx: EvaluationContext, line_offset: int = 0):
        raw = [np.empty(n, dtype=object)]  # $0 unused for columnar input
        raw[0][:] = ""
        preset = {k: v for k, v in columns.items()}
        data, fids, keep = self._transform(raw, n, line_offset, ctx, preset)
        declared = {f["name"] for f in self.config.fields}
        for a in self.ft.attributes:
            if a.name in data:
                continue
            src = a.name
            if src in preset and (src not in declared):
                data[src] = preset[src]
        for f in self.config.fields:
            name = f["name"]
            if "transform" not in f and self.ft.has(name) and name in preset:
                data.setdefault(name, preset[name])
        return self._finish(data, fids, keep, ctx)


class ParquetConverter(_ColumnarConverter):
    """Parquet ingest (geomesa-convert-parquet analog) via pyarrow."""

    def convert(self, source, ctx: Optional[EvaluationContext] = None,
                batch_size: int = 1_000_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        import pyarrow.parquet as pq

        ctx = ctx if ctx is not None else EvaluationContext()
        table = pq.read_table(source)
        for start in range(0, max(table.num_rows, 1), batch_size):
            chunk = table.slice(start, batch_size)
            if chunk.num_rows == 0:
                continue
            cols = {
                name: np.asarray(chunk.column(name).to_pylist(), dtype=object)
                for name in chunk.schema.names
            }
            yield self._convert_table(cols, chunk.num_rows, ctx, start)


class AvroConverter(_ColumnarConverter):
    """Avro container ingest (geomesa-convert-avro analog) via the built-in
    codec (io/avro_io.py)."""

    def convert(self, source, ctx: Optional[EvaluationContext] = None,
                batch_size: int = 1_000_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        from geomesa_tpu.io import avro_io

        ctx = ctx if ctx is not None else EvaluationContext()
        _, records = avro_io.read_avro(source)
        for start in range(0, len(records), batch_size):
            chunk = records[start:start + batch_size]
            if not chunk:
                continue
            names = list(chunk[0].keys())
            cols = {
                name: np.array([r.get(name) for r in chunk], dtype=object)
                for name in names
            }
            yield self._convert_table(cols, len(chunk), ctx, start)


class JdbcConverter(BaseConverter):
    """SQL-statement input (reference geomesa-convert-jdbc,
    JdbcConverter.scala:29): the SOURCE is SQL text — one SELECT per
    line — executed against the configured connection; each result row's
    columns become $1..$N ($0 is the row rendered as delimited text).
    The connection string accepts ``sqlite:///path/to.db``, a bare
    filesystem path, or ``:memory:`` (sqlite is the embedded engine here;
    the reference uses whatever JDBC driver is on the classpath)."""

    def convert(self, source: "str | Iterable[str]",
                ctx: Optional[EvaluationContext] = None,
                batch_size: int = 100_000) -> Iterator[Tuple[Dict, Optional[np.ndarray]]]:
        import sqlite3

        ctx = ctx if ctx is not None else EvaluationContext()
        conn_str = (
            self.config.options.get("connection")
            or self.config.options.get("jdbc-connection")
        )
        if not conn_str:
            raise ValueError("jdbc converter needs options.connection")
        path = conn_str
        for prefix in ("jdbc:sqlite:", "sqlite:///", "sqlite://", "sqlite:"):
            if path.startswith(prefix):
                path = path[len(prefix):] or ":memory:"
                break
        else:
            import re as _re

            # a URL scheme we don't speak (jdbc:postgresql://...): fail
            # clearly instead of treating it as a sqlite filename. The
            # scheme test requires >= 2 leading letters so Windows drive
            # paths (C:\data.db) still count as bare file paths.
            if path != ":memory:" and _re.match(
                r"[A-Za-z][A-Za-z0-9+.-]+:", path
            ):
                raise ValueError(
                    f"unsupported connection {conn_str!r}: only sqlite "
                    "connections (sqlite:///path, jdbc:sqlite:path, or a "
                    "bare file path) are supported"
                )
        conn = sqlite3.connect(path)
        try:
            stmts = (
                [s for s in source.splitlines() if s.strip()]
                if isinstance(source, str)
                else [s for s in source if str(s).strip()]
            )
            line_offset = 0
            for stmt in stmts:
                cur = conn.execute(str(stmt))
                while True:
                    rows = cur.fetchmany(batch_size)
                    if not rows:
                        break
                    n = len(rows)
                    ncols = len(rows[0])
                    raw = [
                        np.array(
                            [",".join("" if v is None else str(v) for v in r)
                             for r in rows],
                            dtype=object,
                        )
                    ] + [
                        np.array([r[c] for r in rows], dtype=object)
                        for c in range(ncols)
                    ]
                    data, fids, keep = self._transform(
                        raw, n, line_offset, ctx
                    )
                    line_offset += n
                    yield self._finish(data, fids, keep, ctx)
        finally:
            conn.close()


def converter_for(ft: FeatureType, config: "str | Dict | ConverterConfig"):
    cfg = config if isinstance(config, ConverterConfig) else ConverterConfig.parse(config)
    if cfg.type in ("delimited-text", "csv", "tsv"):
        return DelimitedTextConverter(ft, cfg)
    if cfg.type == "json":
        return JsonConverter(ft, cfg)
    if cfg.type == "xml":
        return XmlConverter(ft, cfg)
    if cfg.type in ("fixed-width", "fixedwidth"):
        return FixedWidthConverter(ft, cfg)
    if cfg.type == "parquet":
        return ParquetConverter(ft, cfg)
    if cfg.type == "avro":
        return AvroConverter(ft, cfg)
    if cfg.type == "jdbc":
        return JdbcConverter(ft, cfg)
    raise ValueError(f"unknown converter type {cfg.type!r}")


# -- type inference (TypeInference analog) -----------------------------------

def infer_schema(
    sample: str, name: str = "inferred", delimiter: str = ",",
    has_header: Optional[bool] = None,
) -> Tuple[FeatureType, ConverterConfig]:
    """Infer a schema + converter config from delimited text
    (reference TypeInference for schema-less ingest)."""
    rows = list(csv.reader(io.StringIO(sample), delimiter=delimiter))
    if not rows:
        raise ValueError("empty sample")
    header = rows[0]
    if has_header is None:
        has_header = all(not _looks_numeric(h) for h in header) and len(set(header)) == len(header)
    names = (
        [_safe_name(h) for h in header]
        if has_header
        else [f"col{i+1}" for i in range(len(header))]
    )
    body = rows[1:] if has_header else rows
    if not body:
        raise ValueError("no data rows to infer from")
    cols = list(zip(*[r + [""] * (len(names) - len(r)) for r in body]))
    types = [_infer_type(c) for c in cols]

    # lat/lon detection -> synthesize a point geometry
    lon_i = lat_i = None
    for i, nm in enumerate(names):
        low = nm.lower()
        if low in ("lon", "longitude", "long", "x") and types[i] in ("float64", "int64"):
            lon_i = i
        if low in ("lat", "latitude", "y") and types[i] in ("float64", "int64"):
            lat_i = i
    if lon_i is None or lat_i is None:
        # fall back to value-range detection on float columns
        floats = [i for i, t in enumerate(types) if t == "float64"]
        for i in floats:
            vals = [float(v) for v in cols[i] if _looks_numeric(v)]
            if not vals:
                continue
            if lon_i is None and all(-180 <= v <= 180 for v in vals) and any(abs(v) > 90 for v in vals):
                lon_i = i
            elif lat_i is None and all(-90 <= v <= 90 for v in vals):
                lat_i = i

    attr_specs = []
    fields = []
    type_names = {"int64": "Long", "float64": "Double", "string": "String", "date": "Date"}
    for i, (nm, t) in enumerate(zip(names, types)):
        if i in (lon_i, lat_i):
            continue
        attr_specs.append(f"{nm}:{type_names[t]}")
        tf = {
            "int64": f"toLong($({i}))", "float64": f"toDouble($({i}))",
            "date": f"isoDate($({i}))", "string": f"$({i})",
        }[t].replace(f"$({i})", f"${i+1}")
        fields.append({"name": nm, "transform": tf})
    if lon_i is not None and lat_i is not None:
        attr_specs.append("*geom:Point")
        fields.append({
            "name": "geom",
            "transform": f"point(toDouble(${lon_i+1}), toDouble(${lat_i+1}))",
        })
    ft = FeatureType.from_spec(name, ",".join(attr_specs))
    cfg = ConverterConfig(
        type="delimited-text",
        fields=fields,
        id_field="md5($0)",
        format={"delimiter": delimiter},
        options={"skip-lines": 1 if has_header else 0},
    )
    return ft, cfg


def _safe_name(s: str) -> str:
    import re as _re

    s = _re.sub(r"[^A-Za-z0-9_]", "_", s.strip()) or "col"
    return s if s[0].isalpha() or s[0] == "_" else "_" + s


def _looks_numeric(s: str) -> bool:
    try:
        float(s)
        return True
    except (ValueError, TypeError):
        return False


def _infer_type(vals: Sequence[str]) -> str:
    non_empty = [v for v in vals if v and v.strip()]
    if not non_empty:
        return "string"
    if all(_looks_int(v) for v in non_empty):
        return "int64"
    if all(_looks_numeric(v) for v in non_empty):
        return "float64"
    if all(_looks_date(v) for v in non_empty):
        return "date"
    return "string"


def _looks_int(s: str) -> bool:
    try:
        int(s)
        return True
    except (ValueError, TypeError):
        return False


def _looks_date(s: str) -> bool:
    try:
        np.datetime64(s.strip().rstrip("Z"))
        return True
    except (ValueError, TypeError):
        return False
