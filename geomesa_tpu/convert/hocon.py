"""Minimal HOCON-subset parser for converter configs.

The reference's converter definitions are HOCON (typesafe-config). This
parses the subset those configs actually use — nested objects, arrays,
``key = value`` / ``key { ... }``, quoted and unquoted scalars, ``//`` and
``#`` comments — into plain dicts. Full HOCON substitution/include is out of
scope; JSON is accepted as-is (HOCON is a superset of JSON).
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Tuple


def loads(text: str) -> Dict[str, Any]:
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    p = _Parser(text)
    return p.parse_root()


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.i = 0
        self.n = len(text)

    # -- lexing helpers ----------------------------------------------------
    def _skip_ws(self):
        while self.i < self.n:
            c = self.text[self.i]
            if c in " \t\r\n,":
                self.i += 1
            elif c == "#" or self.text.startswith("//", self.i):
                while self.i < self.n and self.text[self.i] != "\n":
                    self.i += 1
            else:
                return

    def _error(self, msg: str):
        line = self.text.count("\n", 0, self.i) + 1
        raise ValueError(f"HOCON parse error line {line}: {msg}")

    def _key(self) -> Tuple[str, bool]:
        """Returns (key, quoted) — quoted keys are literal, never path-split."""
        self._skip_ws()
        if self.i < self.n and self.text[self.i] in "\"'":
            return self._quoted(), True
        m = re.match(r"[A-Za-z0-9_.\-$]+", self.text[self.i:])
        if not m:
            self._error(f"expected key at {self.text[self.i:self.i+20]!r}")
        self.i += m.end()
        return m.group(0), False

    def _quoted(self) -> str:
        q = self.text[self.i]
        self.i += 1
        # triple-quoted
        if self.text.startswith(q * 2, self.i):
            self.i += 2
            end = self.text.find(q * 3, self.i)
            if end < 0:
                self._error("unterminated triple-quoted string")
            s = self.text[self.i:end]
            self.i = end + 3
            return s
        out = []
        while self.i < self.n:
            c = self.text[self.i]
            if c == "\\" and self.i + 1 < self.n:
                nxt = self.text[self.i + 1]
                out.append({"n": "\n", "t": "\t", '"': '"', "'": "'", "\\": "\\"}.get(nxt, nxt))
                self.i += 2
            elif c == q:
                self.i += 1
                return "".join(out)
            else:
                out.append(c)
                self.i += 1
        self._error("unterminated string")

    def _scalar(self) -> Any:
        # unquoted value up to newline/},] or an end-of-line comment
        start = self.i
        while self.i < self.n and self.text[self.i] not in "\n,}]":
            if self.text[self.i] == "#" or self.text.startswith("//", self.i):
                break
            self.i += 1
        raw = self.text[start:self.i].strip()
        if raw == "true":
            return True
        if raw == "false":
            return False
        if raw == "null":
            return None
        try:
            return int(raw)
        except ValueError:
            pass
        try:
            return float(raw)
        except ValueError:
            pass
        return raw

    # -- grammar -----------------------------------------------------------
    def parse_root(self) -> Dict[str, Any]:
        self._skip_ws()
        if self.i < self.n and self.text[self.i] == "{":
            return self._object()
        # braceless root object
        obj: Dict[str, Any] = {}
        while True:
            self._skip_ws()
            if self.i >= self.n:
                return obj
            self._entry(obj)

    def _entry(self, obj: Dict[str, Any]):
        key, quoted = self._key()
        # dotted unquoted keys create nested objects (HOCON path expressions)
        parts = [key] if quoted else key.split(".")
        for p in parts[:-1]:
            nxt = obj.get(p)
            if not isinstance(nxt, dict):
                nxt = obj[p] = {}
            obj = nxt
        key = parts[-1]
        self._skip_ws()
        if self.i < self.n and self.text[self.i] == "{":
            val = self._object()
            # key { } merges into existing object at key (HOCON semantics)
            if isinstance(obj.get(key), dict):
                obj[key].update(val)
            else:
                obj[key] = val
            return
        if self.i < self.n and self.text[self.i] in "=:":
            self.i += 1
            self._skip_ws()
            val = self._value()
            if isinstance(obj.get(key), dict) and isinstance(val, dict):
                obj[key].update(val)
            else:
                obj[key] = val
            return
        self._error(f"expected '=' or '{{' after key {key!r}")

    def _value(self) -> Any:
        self._skip_ws()
        c = self.text[self.i] if self.i < self.n else ""
        if c == "{":
            return self._object()
        if c == "[":
            return self._array()
        if c in "\"'":
            return self._quoted()
        return self._scalar()

    def _object(self) -> Dict[str, Any]:
        assert self.text[self.i] == "{"
        self.i += 1
        obj: Dict[str, Any] = {}
        while True:
            self._skip_ws()
            if self.i >= self.n:
                self._error("unterminated object")
            if self.text[self.i] == "}":
                self.i += 1
                return obj
            self._entry(obj)

    def _array(self) -> List[Any]:
        assert self.text[self.i] == "["
        self.i += 1
        out: List[Any] = []
        while True:
            self._skip_ws()
            if self.i >= self.n:
                self._error("unterminated array")
            if self.text[self.i] == "]":
                self.i += 1
                return out
            out.append(self._value())
