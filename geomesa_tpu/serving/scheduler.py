"""Multi-query serving scheduler (docs/SERVING.md).

GeoMesa's tablet/region servers serve many concurrent client scans against
one index, amortizing I/O across sessions (SURVEY §2.9). The TPU port
funnels every dataset operation through ONE dedicated query thread (the
jit-deadlock discipline from sidecar/service.py) — so concurrency is not a
thread-pool problem but a *scheduling* one, and the single-thread constraint
becomes a batching opportunity: while one query executes, everything else
queues, and whatever is queued can be reordered, shed, or fused.

This module is that scheduler:

* **bounded admission queue** — requests beyond ``geomesa.serving.queue.
  depth`` are rejected at submission with a typed
  :class:`~geomesa_tpu.resilience.AdmissionRejectedError`
  (``[GM-OVERLOADED]`` on the wire) before any planning or device work;
* **deadline-aware ordering + shedding** — each ticket carries a deadline
  budget; a ticket whose budget expires while queued (or whose budget is
  smaller than the estimated queue wait at admission) is SHED with a typed
  :class:`~geomesa_tpu.resilience.DeadlineShedError` (``[GM-SHED]``),
  never dispatched. Within a user, earliest-deadline-first;
* **per-user fair share** — the dispatcher serves the pending user with the
  least *attained service time* (accumulated execution seconds) instead of
  global FIFO, so one user's burst of heavy scans cannot starve another
  user's interactive queries ("Manycore processing of repeated range
  queries", PAPERS.md, motivates exactly this serving shape);
* **cross-query fusion** — tickets carrying a :class:`FuseSpec` with equal
  fusion keys (same dataset, predicate text, auths, op shape — hence the
  same version-stable kernel token, docs/PERF.md) coalesce into one
  micro-batch executed by the spec's ``batch`` callable as a single device
  pass (serving/fuse.py builds those). Only already-queued work fuses —
  fusion never delays dispatch to grow a batch — and a failing batch falls
  back to per-member serial execution, so fusion can change latency but
  never results;
* **one ledger** — per-user accounting (submitted/completed/shed/service/
  wait) backs BOTH the fair-share policy and the ``/debug/queries``
  per-user rollups (obs.py), so the operator's view and the scheduler's
  decisions cannot drift apart.

Two modes share the implementation:

* **inline** (the default; every :class:`~geomesa_tpu.api.dataset.
  GeoDataset` owns one): no thread — :meth:`admit` wraps each public op on
  the caller's thread, performing admission-time shed checks and ledger
  accounting;
* **dispatch-thread** (:meth:`start`; the Flight sidecar): tickets queue
  and a POOL of worker threads — ``geomesa.serving.executors`` wide,
  default 1, one executor slot per thread, slot i pinned to jax device
  i % device_count through the dataset's slot-keyed executors — drains
  them under the policy above. Each slot keeps the PR-1
  one-jit-thread-per-device discipline (slot 0 keeps the default
  placement, so the width-1 pool IS the original single dispatch
  thread); admission, shedding, fair share, and fusion stay GLOBAL, and
  a fusion group is assembled and executed entirely by ONE slot's
  thread, so batch results stay bit-identical to serial execution.
  Streamed exports enqueue *continuation* tickets (one per chunk) that
  bypass admission bounds and run ahead of new queries — pinned to the
  slot that opened the stream (its executor's device arrays belong to
  that slot's thread): an accepted stream must stay live under load.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from geomesa_tpu import config, metrics, resilience as resilience_mod, \
    tracing, utilization
from geomesa_tpu.resilience import (
    AdmissionRejectedError, Deadline, DeadlineShedError, DeviceDrainError,
    current_deadline, deadline_scope,
)

log = logging.getLogger(__name__)


@dataclass
class FuseSpec:
    """Fusion eligibility + group executor for one ticket.

    ``key`` — the compatibility key: tickets with equal keys may coalesce.
    serving/fuse.py derives it from (op, schema, predicate text, auths,
    op-shape params), i.e. the inputs that determine the version-stable
    kernel token — members of a group share compiled code and differ only
    in query DATA. ``payload`` — the member's per-query parameters (e.g.
    a tile bbox). ``batch`` — called with the whole group's tickets,
    returns one result per ticket in order; None = this op can mark
    compatibility but has no batch executor (members run serially)."""

    key: tuple
    payload: Any = None
    batch: Optional[Callable[[List["Ticket"]], List[Any]]] = None
    #: schema the group scans — the pool-aware placement policy keys its
    #: column-heat table on it (docs/SERVING.md §5c)
    schema: Optional[str] = None
    #: the placement decision the dispatcher made for this group (set at
    #: defer/execute time; serving/fuse.py surfaces it on the group span)
    placement: Optional[Dict[str, Any]] = None


class FusedMemberError:
    """A per-member failure inside an otherwise-successful fused batch:
    a batch executor returns this IN PLACE of that member's result and the
    scheduler delivers the wrapped exception to that member alone. This
    exists so post-execution failures (e.g. wire-frame serialization for
    one member) never trigger the whole-batch serial fallback — the batch
    already ran, and re-running would duplicate device work and audit
    events."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


@dataclass
class Ticket:
    """One admitted request."""

    seq: int
    user: str
    op: str
    fn: Callable[[], Any]
    future: Future
    deadline: Deadline
    submitted_at: float
    fuse: Optional[FuseSpec] = None
    trace_id: Optional[str] = None
    continuation: bool = False
    wait_s: float = 0.0
    #: executor-slot affinity (continuations only): a stream's chunks must
    #: all run on the slot that opened it — its executor's device arrays
    #: belong to that slot's dispatch thread (one jit thread per device)
    slot: Optional[int] = None
    #: the submitter's thread-local config overrides — adopted on the
    #: dispatch thread so a scoped knob resolves identically in queue and
    #: inline modes (the partition prefetcher crosses threads the same way)
    overrides: Dict[str, str] = field(default_factory=dict)
    #: speculative fallback (docs/SERVING.md): a cheap HOST-ONLY callable
    #: producing the typed coarse answer — when set, a deadline shed
    #: returns this instead of failing [GM-SHED] (the client opted in)
    speculative: Optional[Callable[[], Any]] = None
    #: pool-aware placement (docs/SERVING.md §5c): slot this fuse-bearing
    #: ticket was deferred toward (its schema's column-hot device), and
    #: when — other slots skip it for the placement grace window only
    defer_slot: Optional[int] = None
    defer_at: float = 0.0

    def _order_key(self):
        # deadline-aware ordering within a user: earliest deadline first,
        # FIFO among equal/absent deadlines
        exp = self.deadline.expires_at
        return (exp if exp is not None else float("inf"), self.seq)


class _UserLedger:
    """Per-user accounting (one entry per user). Backs the fair-share
    policy AND the /debug/queries rollup — a single source of truth."""

    __slots__ = ("submitted", "completed", "shed", "rejected", "errors",
                 "fused", "service_s", "wait_s", "last_ts", "weight",
                 "cost")

    def __init__(self):
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.rejected = 0
        self.errors = 0
        self.fused = 0
        self.service_s = 0.0
        self.wait_s = 0.0
        self.last_ts = 0.0
        #: fair-share weight (geomesa.serving.user.weight.<user>) captured
        #: on the SUBMITTING thread at each submit/admit — the dispatcher
        #: picks under its own ambient config, so resolving there would
        #: make caller-scoped overrides silently dead
        self.weight = 1.0
        #: accumulated per-query cost ledger (docs/OBSERVABILITY.md):
        #: device_ms.<id>, partitions_scanned/pruned, bytes_staged,
        #: cache_hits, recompiles — summed from each completed op's trace
        #: cost, so "what did this user's queries cost in device time?"
        #: reads straight off the /debug/queries rollup
        self.cost: Dict[str, float] = {}

    def add_cost(self, cost: Optional[Dict[str, float]]) -> None:
        if not cost:
            return
        for k, v in cost.items():
            self.cost[k] = self.cost.get(k, 0.0) + v

    def to_dict(self) -> Dict[str, Any]:
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "shed": self.shed,
            "rejected": self.rejected,
            "errors": self.errors,
            "fused": self.fused,
            "service_ms": round(self.service_s * 1e3, 3),
            "queue_wait_ms": round(self.wait_s * 1e3, 3),
            "mean_service_ms": round(
                self.service_s / self.completed * 1e3, 3
            ) if self.completed else 0.0,
            "last_ts": self.last_ts,
            "weight": self.weight,
            "cost": {k: round(v, 4) for k, v in sorted(self.cost.items())},
        }


def _default_user() -> str:
    return config.USER.get() or "anonymous"


#: weakref to the most recently STARTED scheduler — the one actually
#: dispatching for this process. The serving.queue.depth gauge reads it
#: through this indirection so (a) scratch inline schedulers (every
#: GeoDataset owns one) can never hijack the metric away from the live
#: sidecar scheduler, and (b) the gauge never strong-pins a scheduler.
_live_sched: Optional["weakref.ref[QueryScheduler]"] = None


def _depth_gauge_value() -> float:
    s = _live_sched() if _live_sched is not None else None
    return float(s._pending) if s is not None else 0.0


class QueryScheduler:
    """See the module docstring. Thread-safe; one per dataset (the sidecar
    reuses its dataset's scheduler so Flight and local ops share a ledger
    and one fair-share domain)."""

    def __init__(self, name: str = "geomesa-serving"):
        self.name = name
        self._cv = threading.Condition()
        self._queues: Dict[str, List[Ticket]] = {}
        self._continuations: "deque[Ticket]" = deque()
        self._pending = 0
        self._ledger: Dict[str, _UserLedger] = {}
        self._seq = 0
        #: dispatch-thread pool, slot -> thread (docs/SERVING.md): slot 0
        #: keeps the default device placement (the single-thread scheduler,
        #: byte-for-byte); slots 1..N-1 pin device slot % device_count via
        #: the dataset's slot-keyed executors. Admission, shedding, fair
        #: share, and fusion stay GLOBAL — the pool parallelizes dispatch,
        #: never policy.
        self._threads: Dict[int, threading.Thread] = {}
        self._stopped = False
        #: EWMA of recent execution times (seconds): the admission-time
        #: queue-wait estimate
        self._ewma_all: Optional[float] = None
        #: users whose tickets each dispatch slot is executing right now
        #: (guarded by _cv) — shielded from ledger eviction, which would
        #: otherwise reset their fair-share debt mid-query
        self._active_users: Dict[int, set] = {}
        #: users inside an inline admit() right now, refcounted (multiple
        #: caller threads may admit concurrently) — same eviction shield
        self._inline_users: Dict[str, int] = {}
        #: groups executed per slot (the pool-actually-parallel gate)
        self._slot_dispatch: Dict[int, int] = {}
        #: slot supervision (docs/RESILIENCE.md §6): the width start()
        #: was asked for (0 = never started / stopped — supervision off),
        #: slots flagged to DRAIN (exit typed at their next wake-up), and
        #: the lifetime respawn count (snapshot()/debug surface)
        self._width0 = 0
        self._draining: set = set()
        self._respawns = 0
        #: per-slot spawn GENERATION (bumped every time a slot's thread
        #: is (re)spawned): a stream captures its slot's generation at
        #: open, and a continuation from an older generation fails typed
        #: [GM-DRAINING] — a slot that died and respawned must never
        #: silently RESUME a stream whose in-flight work it cannot vouch
        #: for (docs/RESILIENCE.md §6: streams re-open, not resume)
        self._slot_gen: Dict[int, int] = {}
        self._last_supervise = 0.0
        #: pool-aware fusion placement (docs/SERVING.md §5c, guarded by
        #: _cv): schema -> {slot -> last dispatch time} — every slot that
        #: ever scanned the schema, ranked at defer time by ACTUAL column
        #: residency (the probe below) with recency as the tiebreak — and
        #: the set of slots currently blocked in the dispatch wait (only
        #: an IDLE preferred slot is worth deferring a group toward — a
        #: busy one would serialize the pool for a transfer it saves)
        self._schema_heat: Dict[str, Dict[int, float]] = {}
        #: residency probe (GeoDataset wires one): (schema, slot) ->
        #: device-resident column bytes for that schema on that slot's
        #: device RIGHT NOW. None falls back to pure recency ranking.
        self._residency_probe: Optional[Callable[[str, int], int]] = None
        self._idle: set = set()
        self._tls = threading.local()

    @staticmethod
    def _pool_size() -> int:
        """Effective geomesa.serving.executors ("all" = one per device).
        Integers clamp to the local HEALTHY device count (cordoned/broken
        devices hold no slot — docs/RESILIENCE.md §6): slot i pins device
        i % D, so a width beyond the usable count would put two dispatch
        threads on one device — the exact violation of the
        one-jit-thread-per-device rule the pool exists to preserve."""
        raw = (config.SERVING_EXECUTORS.get() or "1").strip().lower()
        try:
            from geomesa_tpu.parallel.devices import healthy_device_count

            n_dev = healthy_device_count()
        except Exception:
            n_dev = 1
        if raw in ("all", "devices"):
            return n_dev
        try:
            return max(1, min(int(raw), n_dev))
        except ValueError:
            return 1

    # -- introspection -----------------------------------------------------
    @property
    def depth(self) -> int:
        return self._pending

    def user_rollups(self) -> Dict[str, Dict[str, Any]]:
        """Per-user serving rollup (the /debug/queries ``users`` payload).
        Carries the user's effective fair-share ``weight`` (geomesa.
        serving.user.weight.<user>, as last captured at submission — the
        value the weighted policy actually divided by) next to the
        attained-service numbers."""
        with self._cv:
            return {u: led.to_dict() for u, led in self._ledger.items()}

    def snapshot(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "depth": self._pending,
                "users": len(self._ledger),
                "running": bool(self._threads) and not self._stopped,
                "executors": len(self._threads),
                "configured_width": self._width0,
                "respawns": self._respawns,
                "draining": sorted(self._draining),
                "slot_dispatches": dict(self._slot_dispatch),
                "ewma_service_ms": round((self._ewma_all or 0.0) * 1e3, 3),
            }

    def current_slot(self) -> Optional[int]:
        """Executor slot of the calling dispatch thread (None off the
        pool) — GeoDataset routes slot-keyed executors (and their device
        pins) through this."""
        return getattr(self._tls, "slot", None)

    def current_wait_ms(self) -> float:
        """Queue wait of the ticket executing on THIS thread (0 outside a
        dispatch) — the sidecar stamps it onto the root span."""
        return getattr(self._tls, "wait_ms", 0.0)

    def current_user(self) -> Optional[str]:
        """The user whose admitted op is running on THIS thread (ticket
        dispatch or inline admit) — audit events attribute to it."""
        return getattr(self._tls, "user", None)

    # -- ledger helpers (call under self._cv) ------------------------------
    def _led(self, user: str) -> _UserLedger:
        led = self._ledger.get(user)
        if led is None:
            if len(self._ledger) >= 4096:
                # bound the per-user map: evict the longest-idle entries
                # (a fuzzing client must not grow server memory forever) —
                # but never a user with queued work: dropping their ledger
                # would reset their fair-share debt mid-burst
                busy = {t.user for t in self._continuations}
                for users in self._active_users.values():
                    busy |= users
                busy |= self._inline_users.keys()
                idle = [
                    u for u in self._ledger
                    if not self._queues.get(u) and u not in busy
                ]
                for u in sorted(
                    idle, key=lambda u: self._ledger[u].last_ts
                )[:256]:
                    del self._ledger[u]
            led = self._ledger[user] = _UserLedger()
            led.last_ts = time.time()  # creation counts as activity
        return led

    def _note_service(self, user: str, op: str, seconds: float,
                      ewma: bool = True,
                      cost: Optional[Dict[str, float]] = None) -> None:
        with self._cv:
            led = self._led(user)
            led.completed += 1
            led.service_s += seconds
            led.last_ts = time.time()
            led.add_cost(cost)
            if ewma:
                self._ewma_update_locked(seconds)
        metrics.inc(metrics.SERVING_COMPLETED)

    @staticmethod
    def _take_cost() -> Optional[Dict[str, float]]:
        """The just-finished op's trace cost, read from THIS thread's
        completed-trace slot (the op's root trace closed inside the
        dispatched fn). None when the op didn't trace."""
        tr = tracing.pop_thread_trace()
        if tr is None:
            return None
        with tr.lock:
            return dict(tr.cost) or None

    def _ewma_update_locked(self, seconds: float) -> None:
        """One admission-estimate sample (call under self._cv).
        Continuation chunks and failures never feed it — thousands of ~ms
        samples would drag the wait estimate to zero exactly when the
        server is busiest — and a fused batch feeds ONE sample for the
        whole batch, not a per-member share (16 share samples would
        collapse the estimate to elapsed/16 after a single batch)."""
        a = 0.2  # EWMA horizon ~ last 5 queries
        self._ewma_all = (
            seconds if self._ewma_all is None
            else (1 - a) * self._ewma_all + a * seconds
        )

    # -- admission ---------------------------------------------------------
    def submit(self, fn: Callable[[], Any], user: Optional[str] = None,
               op: str = "op", fuse: Optional[FuseSpec] = None,
               budget_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               continuation: bool = False,
               slot: Optional[int] = None,
               slot_gen: Optional[int] = None,
               speculative: Optional[Callable[[], Any]] = None) -> Future:
        """Admit one request to the dispatch queue (requires :meth:`start`).
        Raises :class:`AdmissionRejectedError` when the bounded queue is
        full and :class:`DeadlineShedError` when the budget provably cannot
        be met — both BEFORE any planning or device work. ``budget_s``
        None inherits the submitter's ambient resilience deadline.
        ``slot`` pins a continuation to one executor slot (streams);
        ``slot_gen`` is the slot GENERATION the stream opened under — a
        mismatch (the slot died/drained and was respawned since) fails
        typed [GM-DRAINING], because the respawned dispatcher cannot
        vouch for the dead one's in-flight device work.

        ``speculative``: host-only fallback producing the TYPED coarse
        answer (docs/SERVING.md speculative counts) — a request that
        would be deadline-shed (here at admission, or at dispatch after
        queueing) resolves to ``speculative()`` instead of [GM-SHED].
        Still accounted as shed (the exact answer WAS refused); the
        fallback runs outside the scheduler lock and burns no device
        time — exactly what shedding protects."""
        user = user or _default_user()
        # supervision rides the submit path (docs/RESILIENCE.md §6): a
        # dead slot respawns — and a cordoned-out width re-clamps —
        # within one scheduling round, no supervisor thread needed
        # (throttled: the nothing-is-wrong case skips the health sweep)
        self.supervise(throttle=True)
        if budget_s is not None:
            deadline = Deadline.after(budget_s)
        else:
            deadline = current_deadline()
        fut: Future = Future()
        with self._cv:
            if self._stopped or not self._threads:
                raise RuntimeError("serving scheduler is not running")
            if continuation and slot is not None and (
                slot not in self._threads
                or (slot_gen is not None
                    and self._slot_gen.get(slot) != slot_gen)
            ):
                # the stream's slot thread died or drained (gone, or
                # respawned into a NEWER generation than the stream
                # opened under): its device arrays belong to the dead
                # dispatcher, so no surviving slot may drive the stream
                # — fail fast, typed, instead of enqueueing a ticket
                # nothing may safely pick up ([GM-DRAINING] on the wire;
                # the supervisor respawns the SLOT, but the stream must
                # re-open, not resume)
                raise DeviceDrainError(
                    f"serving executor slot {slot} died or was respawned "
                    "since this stream opened; re-open the stream"
                )
            led = self._led(user)
            # submitted counts EVERY attempt — shed and rejected included —
            # so shed/submitted means the same thing on the queue path as
            # on the inline admit() path
            led.submitted += 1
            led.last_ts = time.time()
            led.weight = config.user_weight(user)
            shed_speculative = False
            if not continuation:
                cap = config.SERVING_QUEUE_DEPTH.to_int()
                cap = 256 if cap is None else cap
                if self._pending >= cap:
                    led.rejected += 1
                    metrics.inc(metrics.SERVING_SHED_QUEUE_FULL)
                    raise AdmissionRejectedError(self._pending)
                shed_msg = self._admission_shed_locked(deadline)
                if shed_msg is not None:
                    led.shed += 1
                    metrics.inc(metrics.SERVING_SHED_DEADLINE)
                    if speculative is None:
                        raise DeadlineShedError(shed_msg)
                    # client opted into the typed coarse answer: resolve
                    # OUTSIDE the lock (below) instead of raising
                    shed_speculative = True
            if not shed_speculative:
                self._seq += 1
                t = Ticket(
                    seq=self._seq, user=user, op=op, fn=fn, future=fut,
                    deadline=deadline, submitted_at=time.perf_counter(),
                    fuse=fuse if config.SERVING_FUSION.to_bool() else None,
                    trace_id=trace_id, continuation=continuation,
                    overrides=config.snapshot_overrides(),
                    slot=slot if continuation else None,
                    speculative=speculative,
                )
                if continuation:
                    self._continuations.append(t)
                else:
                    self._queues.setdefault(user, []).append(t)
                self._pending += 1
                metrics.inc(metrics.SERVING_ADMITTED)
                # notify_all: with a pool, a slot-pinned continuation must
                # wake ITS slot's thread, whichever of the waiters that is
                self._cv.notify_all()
        if shed_speculative:
            self._resolve_speculative(fut, speculative)
        return fut

    @staticmethod
    def _resolve_speculative(fut: Future, speculative: Callable) -> None:
        """Resolve a shed request with its typed coarse answer
        (docs/SERVING.md speculative counts). Host-only by contract —
        never called under the scheduler lock; a fallback failure
        surfaces as the shed it replaced."""
        try:
            # the SERVING_SPECULATIVE metric and the distinct audit
            # marker are written by the fallback itself
            # (GeoDataset._speculative_count) — one owner, no double count
            out = speculative()
        except Exception as e:
            fut.set_exception(DeadlineShedError(
                f"query shed (speculative fallback failed: {e!r})"
            ))
            return
        fut.set_result(out)

    def _admission_shed_locked(self, deadline: Deadline) -> Optional[str]:
        """Reject-before-work check: a deadline that is already expired, or
        smaller than the estimated queue wait, cannot be met."""
        rem = deadline.remaining_s()
        if rem is None:
            return None
        if rem <= 0:
            return (
                "query shed at admission: deadline already expired before "
                "any work was scheduled"
            )
        if not config.SERVING_SHED_ESTIMATE.to_bool():
            return None
        # count queued QUERIES only — continuation (stream-chunk) tickets
        # are excluded from the EWMA, so they must not multiply it either
        n_queries = sum(len(q) for q in self._queues.values())
        if self._ewma_all is not None and n_queries > 0:
            est = self._ewma_all * (n_queries + 1)
            if est > rem:
                return (
                    f"query shed at admission: estimated queue wait "
                    f"{est * 1e3:.0f} ms exceeds the {rem * 1e3:.0f} ms "
                    "deadline budget"
                )
        return None

    def run(self, fn: Callable[[], Any], user: Optional[str] = None,
            op: str = "op", fuse: Optional[FuseSpec] = None,
            budget_s: Optional[float] = None,
            trace_id: Optional[str] = None,
            continuation: bool = False,
            slot: Optional[int] = None,
            slot_gen: Optional[int] = None):
        """Submit and wait (the ``_QueryThread.run`` shape). Without a
        dispatch thread, executes inline under admission accounting."""
        if not self._threads:
            if continuation:
                # a continuation belongs to a stream the dispatch thread
                # was driving: running it inline on the caller's (gRPC)
                # thread would break the jit discipline — fail like the
                # stopped query thread always did
                raise RuntimeError("serving scheduler stopped")
            # an explicit budget must bind inline too (admit() reads the
            # ambient deadline) — the two modes share one shed contract
            ctx = (deadline_scope(budget_s) if budget_s is not None
                   else contextlib.nullcontext())
            with ctx, self.admit(op, user=user):
                return fn()
        fut = self.submit(
            fn, user=user, op=op, fuse=fuse, budget_s=budget_s,
            trace_id=trace_id, continuation=continuation, slot=slot,
            slot_gen=slot_gen,
        )
        return fut.result()

    def iterate(self, it, user: Optional[str] = None, op: str = "stream"):
        """Drive iterator ``it`` with every ``next`` on the dispatch thread
        (streamed exports compute their chunks there). Every chunk rides a
        continuation ticket — head-of-line, never bounded or shed: the
        stream's opening request already passed admission, and an accepted
        stream must stay live under queue pressure.

        With an executor POOL, every chunk pins to ONE slot — the slot
        whose dispatch thread opened the stream (iterate() is called from
        the opening ticket's execution) — because the stream's scan state
        holds that slot's device arrays and only that slot's thread may
        drive its device (slot 0 when opened off the pool)."""
        pin = self.current_slot()
        if pin is None and len(self._threads) > 1:
            pin = 0
        # capture the slot's spawn GENERATION at stream open: chunks
        # submitted after the slot dies/drains and respawns must fail
        # typed [GM-DRAINING] rather than silently resume on a fresh
        # dispatcher (docs/RESILIENCE.md §6)
        gen = None
        if pin is not None:
            with self._cv:
                gen = self._slot_gen.get(pin)
        done = object()
        while True:
            item = self.run(
                lambda: next(it, done), user=user, op=op,
                continuation=True, slot=pin, slot_gen=gen,
            )
            if item is done:
                return
            yield item

    @contextlib.contextmanager
    def admit(self, op: str, user: Optional[str] = None,
              inflight_cap: Optional[int] = None):
        """Local-path admission: wrap one public dataset op. Sheds (typed)
        when the caller's ambient deadline is expired or provably
        unmeetable, and accounts the op into the shared ledger. Reentrant
        (nested public ops account once) and a no-op inside a dispatched
        ticket (the ticket already accounts).

        ``inflight_cap`` bounds CONCURRENT inline admissions (the fleet
        router's admission bound, ``geomesa.fleet.max.inflight`` —
        docs/RESILIENCE.md §7): beyond it the op is rejected typed
        :class:`AdmissionRejectedError` (``[GM-OVERLOADED]``) before any
        work, the inline analog of the bounded dispatch queue."""
        depth = getattr(self._tls, "admit_depth", 0)
        if depth or getattr(self._tls, "in_dispatch", False):
            self._tls.admit_depth = depth + 1
            try:
                yield
            finally:
                self._tls.admit_depth = depth
            return
        user = user or _default_user()
        d = current_deadline()
        rem = d.remaining_s()
        shed = None
        if rem is not None and rem <= 0:
            # inline admission sheds ONLY on an already-expired deadline.
            # An EWMA-estimate check here would livelock: a shed op never
            # executes, so the estimate (inflated by one cold compile)
            # could never decay back under the budget. With no queue in
            # front of an inline op, the in-scan deadline enforcement is
            # the right backstop; estimate shedding stays a QUEUE-path
            # policy (where the wait is real and other traffic keeps the
            # EWMA honest).
            shed = (
                "query shed at admission: deadline already expired before "
                "any work"
            )
        rejected = None
        with self._cv:
            led = self._led(user)
            led.submitted += 1
            led.last_ts = time.time()
            led.weight = config.user_weight(user)
            if shed is not None:
                led.shed += 1
            elif inflight_cap is not None and (
                sum(self._inline_users.values()) >= inflight_cap
            ):
                # checked AND rejected under the SAME lock acquisition
                # as the increment below: two racing admissions at the
                # cap boundary must not both squeeze past it
                led.rejected += 1
                rejected = sum(self._inline_users.values())
            else:
                self._inline_users[user] = \
                    self._inline_users.get(user, 0) + 1
        if rejected is not None:
            metrics.inc(metrics.SERVING_SHED_QUEUE_FULL)
            raise AdmissionRejectedError(rejected)
        if shed is not None:
            metrics.inc(metrics.SERVING_SHED_DEADLINE)
            raise DeadlineShedError(shed)
        self._tls.admit_depth = 1
        self._tls.user = user
        t0 = time.perf_counter()
        ok = True
        try:
            yield
        except BaseException:
            ok = False
            with self._cv:
                self._led(user).errors += 1
            raise
        finally:
            self._tls.admit_depth = 0
            self._tls.user = None
            with self._cv:
                n = self._inline_users.get(user, 0) - 1
                if n > 0:
                    self._inline_users[user] = n
                else:
                    self._inline_users.pop(user, None)
            # failures stay out of the EWMA here too (the _execute_one
            # rule): fast-failing local ops must not deflate the queue
            # path's admission estimate on a shared scheduler. The op's
            # root trace is still OPEN here (admit nests inside it), so
            # its cost ledger reads via the active-trace accessor.
            self._note_service(user, op, time.perf_counter() - t0, ewma=ok,
                               cost=tracing.current_cost() or None)

    # -- dispatch ----------------------------------------------------------
    def start(self) -> "QueryScheduler":
        """Spawn the dispatch-thread pool (idempotent): one thread per
        executor slot, ``geomesa.serving.executors`` wide (default 1 — the
        single dispatch thread, byte-for-byte the pre-pool scheduler).
        The started scheduler becomes the one the process
        serving.queue.depth gauge reads — inline (scratch) schedulers
        never touch the metric. While the pool is wider than one executor
        it owns the devices (one jit thread per device), so the sharded
        partitioned scan stands down (parallel/devices.register_pool)."""
        global _live_sched
        n = self._pool_size()
        from geomesa_tpu.parallel import devices as pdev

        # claim the devices BEFORE any slot thread can dispatch: a sharded
        # scan racing the pool spin-up must already see the pool's width
        pdev.register_pool(self, n)
        with self._cv:
            self._stopped = False
            self._width0 = n
            self._draining.clear()
            for slot in range(n):
                t = self._threads.get(slot)
                if t is None or not t.is_alive():
                    t = threading.Thread(
                        target=self._loop, args=(slot,), daemon=True,
                        name=self.name if slot == 0
                        else f"{self.name}-{slot}",
                    )
                    self._threads[slot] = t
                    self._slot_gen[slot] = self._slot_gen.get(slot, 0) + 1
                    t.start()
                # else: a previous stop()'s join timed out and the old
                # thread is still draining its in-flight query — clearing
                # _stopped re-adopts it as this slot's dispatcher instead
                # of spawning a second one (two dispatch threads on one
                # slot would break the one-jit-thread-per-device rule)
            width = len(self._threads)
        # re-register with the FINAL width: covers re-adopted straggler
        # slots from a timed-out stop, and a last-generation straggler
        # whose exit handshake raced this start() and unregistered the
        # claim made above
        pdev.register_pool(self, width)
        _live_sched = weakref.ref(self)
        metrics.registry().gauge(
            metrics.SERVING_QUEUE_DEPTH, _depth_gauge_value, replace=True
        )
        return self

    def stop(self) -> None:
        """Stop dispatching; queued tickets fail (their callers must not
        block forever on futures nothing will complete)."""
        with self._cv:
            self._stopped = True
            self._width0 = 0  # an intentional stop is not a death: the
            self._draining.clear()  # supervisor must not respawn slots
            stranded = list(self._continuations)
            self._continuations.clear()
            for q in self._queues.values():
                stranded.extend(q)
            self._queues.clear()
            self._pending = 0
            self._cv.notify_all()
            threads = list(self._threads.values())
        for tk in stranded:
            tk.future.set_exception(
                RuntimeError("serving scheduler stopped")
            )
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=5.0)
        # _loop clears its slot's self._threads entry itself (under the
        # lock) as it exits; a timed-out join must leave the reference in
        # place so a later start() re-adopts the still-draining thread
        # rather than racing a second dispatcher against it
        from geomesa_tpu.parallel import devices as pdev

        if any(t.is_alive() and t is not threading.current_thread()
               for t in threads):
            # a join timed out: a slot thread is still draining its
            # in-flight query ON ITS DEVICE, so the pool must keep the
            # devices claimed — releasing now would let a sharded scan
            # fan out onto a device this straggler is still dispatching
            # to. A later start()/stop() cycle (or the straggler's own
            # exit handshake via a fresh stop()) releases them.
            return
        pdev.unregister_pool(self)

    def _target_width(self) -> int:
        """The width the pool SHOULD be running at: the configured width,
        re-clamped to the healthy device count (a cordoned/broken device
        must not keep a dispatch thread — two slots on one surviving
        device would break the one-jit-thread-per-device rule), floored
        at 1 so the pool never supervises itself out of existence."""
        try:
            from geomesa_tpu.parallel.devices import healthy_device_count

            healthy = healthy_device_count()
        except Exception:  # pragma: no cover — defensive
            healthy = self._width0
        return max(1, min(self._width0, healthy))

    def supervise(self, throttle: bool = False) -> Dict[str, Any]:
        """One supervision round (docs/RESILIENCE.md §6): respawn dead
        dispatcher slots (a slot whose thread died via BaseException —
        its pinned continuations were already failed typed by the exit
        backstop), and re-clamp the pool width to the healthy device
        count — slots beyond it are flagged to DRAIN (they exit typed at
        their next wake-up, failing their pinned continuations with
        :class:`DeviceDrainError`). Runs on every :meth:`submit` and
        dispatch wake-up, so a killed dispatcher is back within one
        scheduling round with the admission queue, fair-share ledgers,
        and fusion state untouched (they live on the scheduler, not the
        thread). Idempotent; ``throttle`` (the hot-path callers) skips
        the full health sweep when the thread set looks whole and a
        round ran recently — a DEAD slot (count below width) is always
        repaired immediately, only cordon re-clamps ride the throttle
        window."""
        out: Dict[str, Any] = {"respawned": [], "draining": [], "width": 0}
        if self._width0 <= 0:
            return out
        if throttle:
            # compare against the LAST round's computed target (not the
            # configured width): a cordon-shrunken pool at its clamped
            # width is "whole" and must not pay the sweep per submit
            now = time.monotonic()
            whole = getattr(self, "_width_target", self._width0)
            if len(self._threads) >= whole and not self._draining \
                    and now - self._last_supervise < 0.25:
                return out
            self._last_supervise = now
        with self._cv:
            if self._stopped or self._width0 <= 0:
                return out
            # target is computed AND applied under the lock: a round
            # that computed a stale pre-cordon target outside it could
            # otherwise respawn the very slot a newer round just drained
            # (the classic check-then-act race; _target_width only reads
            # cached jax device handles + breaker states — leaf locks)
            target = self._width_target = self._target_width()
            # drain slots beyond the re-clamped width (never slot 0)
            for slot in list(self._threads):
                if slot >= target and slot not in self._draining:
                    self._draining.add(slot)
                    out["draining"].append(slot)
            # respawn dead slots within it
            for slot in range(target):
                t = self._threads.get(slot)
                if (t is None or not t.is_alive()) \
                        and slot not in self._draining:
                    nt = threading.Thread(
                        target=self._loop, args=(slot,), daemon=True,
                        name=self.name if slot == 0
                        else f"{self.name}-{slot}",
                    )
                    self._threads[slot] = nt
                    self._slot_gen[slot] = self._slot_gen.get(slot, 0) + 1
                    nt.start()
                    out["respawned"].append(slot)
            self._respawns += len(out["respawned"])
            out["width"] = len(self._threads)
            if out["draining"]:
                self._cv.notify_all()  # wake the drained slots to exit
        for slot in out["respawned"]:
            metrics.inc(metrics.SERVING_SLOT_RESPAWN)
            metrics.inc(f"{metrics.SERVING_SLOT_RESPAWN}.{slot}")
        if out["respawned"] or out["draining"]:
            from geomesa_tpu.parallel import devices as pdev

            pdev.register_pool(self, max(out["width"], 1))
        return out

    def _has_work_locked(self, slot: int) -> bool:
        """Is there anything THIS slot may dispatch? (call under _cv)
        Queries are slot-free; continuations only wake their pinned slot."""
        if any(self._queues.values()):
            return True
        return any(t.slot is None or t.slot == slot
                   for t in self._continuations)

    def _loop(self, slot: int = 0):
        self._tls.slot = slot
        try:
            while True:
                # assembled in place so a mid-assembly failure (e.g. a
                # malformed config knob read during the fusion sweep)
                # leaves already-dequeued tickets reachable for the
                # except arm below — their callers must never hang on
                # futures nothing will complete
                group: List[Ticket] = []
                drained: Optional[List[Ticket]] = None
                try:
                    resilience_mod.fault_point("serving.slot.loop",
                                               slot=slot)
                    # a surviving slot's wake-up doubles as a supervision
                    # round: a sibling slot's death is repaired even when
                    # no new submission arrives to trigger it
                    self.supervise(throttle=True)
                    with self._cv:
                        while not self._stopped \
                                and slot not in self._draining \
                                and not self._has_work_locked(slot):
                            # placement reads _idle: only a slot blocked
                            # HERE is worth deferring a fused group to
                            self._idle.add(slot)
                            try:
                                self._cv.wait()
                            finally:
                                self._idle.discard(slot)
                            # the WAITING dispatcher's chaos-kill point:
                            # an idle slot that loses the race for a
                            # ticket re-waits without reaching the
                            # iteration-top fault point, so a seeded
                            # kill must also be able to fire on the
                            # wake itself (tests/test_chaos.py)
                            resilience_mod.fault_point(
                                "serving.slot.loop", slot=slot, wake=True
                            )
                        if slot in self._draining and not self._stopped:
                            drained = self._drain_exit_locked(slot)
                        elif self._stopped:
                            # the exit handshake happens under the lock so
                            # start() can never observe a live-looking
                            # thread that is about to return (it would
                            # fail to spawn a new one)
                            if self._threads.get(slot) is \
                                    threading.current_thread():
                                del self._threads[slot]
                            if not self._threads:
                                # the LAST slot out releases the device
                                # claim — covers the straggler whose
                                # stop()-time join timed out (stop left
                                # the pool registered for exactly this
                                # moment)
                                from geomesa_tpu.parallel import \
                                    devices as pdev

                                pdev.unregister_pool(self)
                            return
                        if drained is None:
                            self._next_group_locked(group, slot)
                            if not group and self._has_work_locked(slot):
                                # everything queued is placement-reserved
                                # for another slot within its grace
                                # window: sleep until a notify or the
                                # window lapses (never busy-spin)
                                self._cv.wait(self._placement_grace_s())
                            self._note_heat_locked(group, slot)
                            self._active_users[slot] = \
                                {t.user for t in group}
                    if drained is not None:
                        # typed drain exit (outside the lock): the pool
                        # width was re-clamped — fail this slot's pinned
                        # continuations with [GM-DRAINING], re-register
                        # the SHRUNKEN device claim, and leave
                        self._fail_drained(slot, drained)
                        with self._cv:
                            width = len(self._threads)
                        from geomesa_tpu.parallel import devices as pdev

                        pdev.register_pool(self, max(width, 1))
                        return
                    if group:
                        with self._cv:
                            self._slot_dispatch[slot] = \
                                self._slot_dispatch.get(slot, 0) + 1
                        metrics.inc(
                            f"{metrics.SERVING_EXECUTOR_DISPATCH}.{slot}"
                        )
                        # slot occupancy (docs/OBSERVABILITY.md): the
                        # serving.slot.occupancy.<slot> gauge reads these
                        # busy intervals
                        with utilization.slot_busy(slot):
                            self._execute_group(group)
                except Exception as e:
                    # a dispatcher must survive anything a single dispatch
                    # can throw (per-ticket errors land on futures in
                    # _execute_one; this arm is for policy/assembly
                    # failures outside that path)
                    log.exception("serving dispatch iteration failed")
                    for t in group:
                        if not t.future.done():
                            t.future.set_exception(e)
                finally:
                    with self._cv:
                        self._active_users.pop(slot, None)
        finally:
            # backstop for a genuinely dying thread (BaseException, e.g.
            # SystemExit): fail what only this slot could have served —
            # and, when it was the LAST slot, everything still queued —
            # so callers never hang on futures nothing will complete
            self._dispatcher_exit(slot)

    def _drain_exit_locked(self, slot: int) -> List[Ticket]:
        """Remove THIS slot from the pool under a width re-clamp (call
        under ``_cv``): unregisters the thread and collects its pinned
        continuations for the caller to fail typed outside the lock."""
        self._draining.discard(slot)
        if self._threads.get(slot) is threading.current_thread():
            del self._threads[slot]
        stranded = [t for t in self._continuations if t.slot == slot]
        for t in stranded:
            self._continuations.remove(t)
        self._pending -= len(stranded)
        return stranded

    def _fail_drained(self, slot: int, stranded: List[Ticket]) -> None:
        """Fail a drained slot's pinned continuations with the typed
        ``[GM-DRAINING]`` contract (docs/RESILIENCE.md §6) and flag their
        traces for tail-sampling keep."""
        metrics.inc(f"{metrics.SERVING_SLOT_DIED}.drained")
        for tk in stranded:
            tracing.mark_slot_died(tk.trace_id, slot, reason="drained")
            if not tk.future.done():
                tk.future.set_exception(DeviceDrainError(
                    f"serving executor slot {slot} drained (pool width "
                    "re-clamped after a device cordon); re-open the stream"
                ))

    def _dispatcher_exit(self, slot: int = 0) -> None:
        last = False
        died = False
        with self._cv:
            # a slot that died while FLAGGED to drain must not leave the
            # stale flag behind: it would block this slot's respawn
            # forever once the width grows back (uncordon)
            self._draining.discard(slot)
            if self._threads.get(slot) is threading.current_thread():
                # still registered at exit = nothing de-registered this
                # thread on purpose (stop()/drain handshakes delete the
                # entry first): a genuine dispatcher DEATH
                del self._threads[slot]
                died = not self._stopped
            last = not self._threads
            if self._threads:
                # surviving slots keep draining queries; only this slot's
                # pinned continuations are stranded
                stranded = [t for t in self._continuations
                            if t.slot == slot]
                for t in stranded:
                    self._continuations.remove(t)
                self._pending -= len(stranded)
            else:
                stranded = list(self._continuations)
                self._continuations.clear()
                for q in self._queues.values():
                    stranded.extend(q)
                self._queues.clear()
                self._pending = 0
        if died:
            # a dispatcher death is never silent (docs/RESILIENCE.md §6):
            # it counts in /metrics, and every stranded stream's trace is
            # flagged slot_died — an always-keep class for PR 7's tail
            # sampling, with a serving.slot.died event under the root
            # span — so the post-mortem trace always exports. An
            # intentional stop()/drain is NOT a death and stays quiet.
            metrics.inc(metrics.SERVING_SLOT_DIED)
            metrics.inc(f"{metrics.SERVING_SLOT_DIED}.{slot}")
        for tk in stranded:
            tracing.mark_slot_died(tk.trace_id, slot, reason="died")
            if not tk.future.done():
                tk.future.set_exception(DeviceDrainError(
                    f"serving executor slot {slot} dispatcher exited; "
                    "re-open the stream"
                    if tk.continuation else
                    "serving dispatch thread exited"
                ))
        if died:
            # prompt repair: the dying dispatcher's last act is a
            # supervision round, so an IDLE pool heals immediately
            # instead of waiting for the next submission to trigger it
            # (stop() zeroes _width0 first, so an intentional shutdown
            # never resurrects itself here)
            try:
                self.supervise()
            except Exception:  # pragma: no cover — defensive
                log.exception("post-death supervision failed")
        if last:
            # a fully-dead pool must release the devices (submit() already
            # raises "not running"); a concurrent start() re-registers its
            # own claim as its final step, so this cannot strand a new
            # generation unclaimed
            from geomesa_tpu.parallel import devices as pdev

            pdev.unregister_pool(self)

    def _users_by_share_locked(self) -> List[str]:
        """Users with pending work in dispatch-preference order (the
        fair-share pick, generalized to a ranking so a slot can fall
        through past a user whose queue is placement-reserved)."""
        users = [u for u, q in self._queues.items() if q]
        if not users:
            return users
        if not config.SERVING_FAIR_SHARE.to_bool():
            # strict FIFO across users
            return sorted(
                users, key=lambda u: min(t.seq for t in self._queues[u])
            )
        # least attained WEIGHTED service first (service_s / weight, so a
        # weight-4 user earns ~4x the service of a weight-1 user under
        # contention — geomesa.serving.user.weight.<user>, captured into
        # the ledger on the submitting thread so scoped overrides apply);
        # FIFO head seq breaks ties so two fresh users interleave in
        # arrival order
        return sorted(
            users,
            key=lambda u: (
                self._led(u).service_s / (self._led(u).weight or 1.0),
                min(t.seq for t in self._queues[u]),
            ),
        )

    @contextlib.contextmanager
    def member_user(self, user: Optional[str]):
        """Temporarily attribute work on THIS thread to ``user`` —
        the distinct-fusion query-at-a-time fallback runs each member's
        full public path on the dispatch thread, whose thread-local user
        is the group PRIMARY's; without this, every member's audit event
        would land on the primary's name (serving/fuse.py)."""
        prev = getattr(self._tls, "user", None)
        self._tls.user = user
        try:
            yield
        finally:
            self._tls.user = prev

    # -- pool-aware fusion placement (docs/SERVING.md §5c) -----------------
    def set_residency_probe(self, fn: Optional[Callable[[str, int], int]]
                            ) -> None:
        """Install the column-residency probe the placement ranking
        consults: ``fn(schema, slot)`` returns the schema's device-
        resident column bytes on that slot's device *right now*.
        GeoDataset wires one over its stores' device caches; without a
        probe the ranking degrades to pure recency (the pre-residency
        "last slot that dispatched the schema" behavior). The probe runs
        under the scheduler lock on dispatch threads — it must be cheap
        metadata reads only (no jit, no locks, no device sync)."""
        with self._cv:
            self._residency_probe = fn

    def _rank_slot_locked(self, schema: str, slot: int) -> Optional[int]:
        """Best candidate slot for ``schema`` — ranked by ACTUAL column
        residency (probe bytes), recency breaking ties — or None when no
        candidate beats dispatching on ``slot`` itself. Candidates are
        the slots that ever scanned the schema; dead slots fall out.
        On wide pools a schema's columns routinely survive on a slot
        that was NOT the last dispatcher (another schema's group ran
        there since) — the probe finds them where recency cannot
        (docs/SERVING.md §9 residency ranking)."""
        heat = self._schema_heat.get(schema)
        if not heat:
            return None
        probe = self._residency_probe
        alive = [s for s in heat if s in self._threads]
        if not alive:
            return None

        # one probe call per candidate (the probe walks device-column
        # caches under the scheduler lock — never re-walk inside max())
        def score(s: int):
            res = 0
            if probe is not None:
                try:
                    res = int(probe(schema, s))
                except Exception:
                    res = 0  # a torn cache walk must never fail dispatch
            return (res, heat.get(s, float("-inf")))

        scores = {s: score(s) for s in set(alive) | {slot}}
        best = max(alive, key=scores.__getitem__)
        if best == slot or scores[best] <= scores[slot]:
            return None  # this slot is already the best (or tied) home
        return best

    def _placement_grace_s(self) -> float:
        g = config.SERVING_PLACEMENT_GRACE_MS.to_int()
        return (50 if g is None else max(g, 0)) / 1e3

    def _defer_ok_locked(self, t: Ticket, slot: int, now: float) -> bool:
        """May THIS slot dispatch ticket ``t``? A placement-deferred
        ticket is reserved for its preferred slot only within the grace
        window — after that, anyone takes it (starvation backstop)."""
        if t.defer_slot is None or t.defer_slot == slot:
            return True
        if t.defer_slot not in self._threads:
            return True  # preferred slot died/drained: anyone serves
        return (now - t.defer_at) > self._placement_grace_s()

    def _defer_for_placement_locked(self, head: Ticket, slot: int,
                                    now: float) -> bool:
        """Defer a fuse-bearing head toward the slot whose device holds
        the most of its schema's columns RIGHT NOW (residency-ranked via
        the probe, recency as tiebreak) — the fused group's device_put is
        then a cache hit instead of a re-upload. Only defers ONCE per
        ticket, only when the preferred slot is alive and IDLE (deferring
        to a busy slot would serialize the pool to save one transfer),
        and records the decision on the FuseSpec for the group span
        (serving/fuse.py)."""
        if (head.fuse is None or head.fuse.schema is None
                or head.continuation or head.defer_slot is not None
                or len(self._threads) <= 1
                or not config.SERVING_PLACEMENT.to_bool()):
            return False
        pref = self._rank_slot_locked(head.fuse.schema, slot)
        if pref is None or pref not in self._idle:
            return False
        head.defer_slot = pref
        head.defer_at = now
        head.fuse.placement = {
            "preferred": pref, "deferred_from": slot,
            "reason": ("column-residency"
                       if self._residency_probe is not None
                       else "column-heat"),
        }
        metrics.inc(metrics.SERVING_PLACEMENT_DEFER)
        self._cv.notify_all()  # wake the preferred (idle) slot
        return True

    def _note_heat_locked(self, group: List[Ticket], slot: int) -> None:
        """Record which slot's device just scanned each fused schema —
        the candidate set (and recency tiebreak) of the residency-ranked
        placement table."""
        for t in group:
            if t.fuse is not None and t.fuse.schema is not None:
                self._schema_heat.setdefault(
                    t.fuse.schema, {}
                )[slot] = time.perf_counter()
                if t.fuse.placement is not None \
                        and "slot" not in t.fuse.placement:
                    t.fuse.placement["slot"] = slot
                    bound = t.fuse.placement.get("preferred") == slot
                    t.fuse.placement["bound"] = bound
                    if bound:
                        metrics.inc(metrics.SERVING_PLACEMENT_BOUND)

    def _next_group_locked(self, group: List[Ticket],
                           slot: int = 0) -> List[Ticket]:
        """Fills ``group`` IN PLACE (and returns it): every ticket is
        appended the moment it leaves a queue, so the dispatch loop can
        fail dequeued tickets' futures if assembly itself throws.
        Continuations dispatch only on their pinned slot (stream
        affinity); queries go to whichever slot asks first."""
        for t in self._continuations:
            if t.slot is None or t.slot == slot:
                self._continuations.remove(t)
                self._pending -= 1
                group.append(t)
                return group
        now = time.perf_counter()
        while True:
            head = None
            for user in self._users_by_share_locked():
                eligible = [
                    t for t in self._queues[user]
                    if self._defer_ok_locked(t, slot, now)
                ]
                if eligible:
                    head = min(eligible, key=Ticket._order_key)
                    break
                # this user's queue is fully placement-reserved for
                # other (idle, column-hot) slots within the grace window
                # — fall through to the next user in fair-share order
                # rather than stalling THIS slot behind another slot's
                # reservation
            if head is None:
                return group
            if not self._defer_for_placement_locked(head, slot, now):
                break
            # head stays queued toward its column-hot slot (it now
            # carries defer_slot, so this slot skips it); loop — not
            # recurse: a deep fuse-bearing backlog must never push the
            # pick past the interpreter's recursion limit
        q = self._queues[user]
        q.remove(head)
        self._pending -= 1
        group.append(head)
        # cap <= 1 disables the sweep entirely (a negative slice bound
        # would otherwise fuse almost everything)
        cap = config.SERVING_FUSION_MAX.to_int()
        cap = 16 if cap is None else cap
        if head.fuse is not None and cap > 1:
            # sweep EVERY user's queue for fusion-compatible members, in
            # submission order: fusion amortizes device work across users,
            # and members removed here are served NOW — ahead of their
            # fair-share turn, which only helps them
            cands: List[Ticket] = []
            for uq in self._queues.values():
                cands.extend(
                    t for t in uq
                    if t.fuse is not None and t.fuse.key == head.fuse.key
                    # the batch executes under the PRIMARY's config
                    # overrides: a member scoped differently could resolve
                    # shape/cache knobs differently and must run alone
                    and t.overrides == head.overrides
                )
            cands.sort(key=lambda t: t.seq)
            for t in cands[: cap - 1]:
                self._queues[t.user].remove(t)
                self._pending -= 1
                group.append(t)  # appended as dequeued — see docstring
        # drop emptied per-user queues: the dict must track users with
        # PENDING work only, or a fuzzing client with unique user headers
        # would grow it (and every dispatch's pick/sweep walk) forever
        for u in {t.user for t in group}:
            if not self._queues.get(u):
                self._queues.pop(u, None)
        return group

    def _shed_ticket(self, t: Ticket) -> None:
        with self._cv:
            self._led(t.user).shed += 1
        metrics.inc(metrics.SERVING_SHED_DEADLINE)
        if t.speculative is not None:
            # the client opted into the typed coarse answer: resolve with
            # it instead of [GM-SHED] (docs/SERVING.md speculative counts)
            self._resolve_speculative(t.future, t.speculative)
            return
        t.future.set_exception(DeadlineShedError(
            f"query shed before dispatch: deadline expired after "
            f"{t.wait_s * 1e3:.0f} ms queued (no device work was done)"
        ))

    def _execute_group(self, group: List[Ticket]) -> None:
        now = time.perf_counter()
        wait_hist = metrics.registry().histogram(metrics.SERVING_QUEUE_WAIT)
        live: List[Ticket] = []
        for t in group:
            t.wait_s = now - t.submitted_at
            if not t.continuation:
                # continuation chunks skip the wait histogram + ledger for
                # the same reason they skip the EWMA: thousands of ~0-wait
                # chunk tickets would collapse the queue-wait p99 exactly
                # when a stream is holding real queries back
                wait_hist.observe(t.wait_s)
                utilization.record_wait(t.wait_s)
                with self._cv:
                    self._led(t.user).wait_s += t.wait_s
            # shed-before-work: a deadline that lapsed while queued is a
            # guaranteed wire timeout — don't burn device time on it.
            # Continuations are exempt (never bounded or shed mid-stream):
            # an accepted stream stays live even past an inherited ambient
            # deadline — in-scan enforcement is its backstop
            if t.deadline.expired and not t.continuation:
                self._shed_ticket(t)
            else:
                live.append(t)
        if not live:
            return
        if len(live) > 1 and live[0].fuse is not None \
                and live[0].fuse.batch is not None:
            if self._execute_fused(live):
                return
        for t in live:
            self._execute_one(t)

    def _execute_fused(self, group: List[Ticket]) -> bool:
        """One device pass for the whole group. False = fall back to
        serial execution (fusion may change latency, never results)."""
        head = group[0]
        t0 = time.perf_counter()
        self._tls.in_dispatch = True
        self._tls.wait_ms = head.wait_s * 1e3
        self._tls.user = head.user
        prev_ov = config.snapshot_overrides()
        config.adopt_overrides(head.overrides)
        tracing.pop_thread_trace()  # clear a previous ticket's residue
        try:
            results = head.fuse.batch(group)
        except BaseException as e:
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit during the batch: relay to
                # every member (the _execute_one invariant) rather than
                # letting it kill the dispatch thread — queued callers
                # would block forever on futures nothing completes — or
                # re-running the batch serially under the same signal
                for t in group:
                    with self._cv:
                        self._led(t.user).errors += 1
                    t.future.set_exception(e)
                return True
            log.warning(
                "fused batch of %d %s queries failed (%r); degrading to "
                "per-query execution", len(group), head.op, e,
            )
            return False
        finally:
            config.adopt_overrides(prev_ov)
            self._tls.in_dispatch = False
            self._tls.wait_ms = 0.0
            self._tls.user = None
        if results is None or len(results) != len(group):
            log.warning(
                "fused batch executor returned %s results for %d members; "
                "degrading to per-query execution",
                "no" if results is None else len(results), len(group),
            )
            return False
        elapsed = time.perf_counter() - t0
        metrics.registry().histogram(
            metrics.SERVING_FUSION_BATCH,
            buckets=metrics.FUSION_BATCH_BUCKETS, unit=None,
        ).observe(float(len(group)))
        # every member counts (primary included) — the same definition the
        # per-user ledger 'fused' field uses, so /metrics and the
        # /debug/queries rollups always agree
        metrics.inc(metrics.SERVING_FUSED, len(group))
        share = elapsed / len(group)
        # the batch ran under ONE trace (the primary's): its cost ledger
        # splits evenly across members, matching the service-time share —
        # a fused member costs 1/N of the device pass it rode
        batch_cost = self._take_cost()
        cost_share = (
            {k: v / len(group) for k, v in batch_cost.items()}
            if batch_cost else None
        )
        for t, r in zip(group, results):
            with self._cv:
                self._led(t.user).fused += 1
            self._note_service(t.user, t.op, share, ewma=False,
                               cost=cost_share)
            if isinstance(r, FusedMemberError):
                t.future.set_exception(r.error)
            else:
                t.future.set_result(r)
        with self._cv:
            # one estimate sample for the whole batch (see
            # _ewma_update_locked): ledgers got their share above
            self._ewma_update_locked(elapsed)
        return True

    def _execute_one(self, t: Ticket) -> None:
        t0 = time.perf_counter()
        self._tls.in_dispatch = True
        self._tls.wait_ms = t.wait_s * 1e3
        self._tls.user = t.user
        prev_ov = config.snapshot_overrides()
        config.adopt_overrides(t.overrides)
        tracing.pop_thread_trace()  # clear a previous ticket's residue
        try:
            out = t.fn()
        except BaseException as e:  # noqa: B036 — relayed to the caller
            with self._cv:
                self._led(t.user).errors += 1
            # failures stay out of the EWMA: a burst of ~ms fast-fail
            # queries would deflate the admission wait estimate exactly
            # when the queue is contended
            self._note_service(t.user, t.op, time.perf_counter() - t0,
                               ewma=False, cost=self._take_cost())
            t.future.set_exception(e)
            return
        finally:
            config.adopt_overrides(prev_ov)
            self._tls.in_dispatch = False
            self._tls.wait_ms = 0.0
            self._tls.user = None
        self._note_service(t.user, t.op, time.perf_counter() - t0,
                           ewma=not t.continuation,
                           cost=self._take_cost())
        t.future.set_result(out)
