"""Serving layer: multi-query scheduling in front of the single dispatch
thread (docs/SERVING.md) — bounded admission, deadline-aware ordering,
per-user fair share, load shedding, and cross-query kernel fusion."""

from geomesa_tpu.serving.scheduler import FuseSpec, QueryScheduler, Ticket

__all__ = ["QueryScheduler", "FuseSpec", "Ticket"]
