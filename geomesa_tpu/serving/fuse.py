"""Cross-query fusion: eligibility keys + micro-batch executors
(docs/SERVING.md).

Two queries may fuse when they would compile to the SAME kernel — same
schema, predicate text, auths, and op shape, which is exactly what the
executor's version-stable kernel tokens key on (docs/PERF.md) — so a fused
group shares the column ``device_put`` and the compiled kernel and differs
only in query *data*. Concretely:

* ``count`` / ``density`` / ``stats`` — members are *repeats* of one
  question (the dominant serving pattern per "Manycore processing of
  repeated range queries", PAPERS.md): the group executes the full path
  ONCE and every member shares the result bit-identically;
* ``density_curve`` — members share layer + filter + level but ask for
  DIFFERENT tile crops (N map clients panning one heatmap layer): the
  group executes one device pass with the per-member CDF gather positions
  stacked over the query axis
  (:meth:`~geomesa_tpu.planning.executor.Executor.density_curve_batch`)
  — the GeoBlocks shared-work shape (PAPERS.md).

Every fused member keeps its own trace span and audit event (hints carry
``fused: true`` and the batch size); results de-interleave bit-identically
versus serial execution because the per-member math is either literally the
same execution (repeat fusion) or exact per-member gathers off one shared
cumsum (tile fusion).

Queries carrying hints that change execution shape per member (sampling,
max_features, sort, properties, explicit index) never fuse.

With an executor POOL (``geomesa.serving.executors`` > 1 —
docs/SERVING.md §10), fusion stays GLOBAL but a group is assembled and
executed entirely by ONE slot's dispatch thread: every member of a batch
runs through the same slot-keyed executor on the same device, so the
shared pass — and therefore every member's result — is bit-identical to
what the single dispatch thread would have produced. Groups never split
across slots; slots parallelize ACROSS groups.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from geomesa_tpu import config, tracing
from geomesa_tpu.serving.scheduler import FusedMemberError, FuseSpec, Ticket

#: opts keys that make a query ineligible for fusion (they change the
#: execution shape or result per member in ways a shared pass can't serve)
_UNFUSABLE_HINTS = (
    "sampling", "sample_by", "max_features", "properties", "sort_by",
    "index",
)

#: the ONLY opts keys a fusable request may carry with a truthy value:
#: routing/identity keys plus the per-op parameters fuse_key folds into
#: the compatibility key. Eligibility is an ALLOW-list — a future
#: result-affecting request key that fuse.py doesn't know about makes the
#: query ineligible (fail safe) instead of silently fusing two queries
#: that differ in it and handing one client another client's answer.
#: NOTE: a polygon ``region`` is deliberately NOT listed — the sidecar
#: folds it into the ecql text BEFORE keying (service._fold_region), so
#: two different polygons key distinctly; a request that somehow still
#: carries a raw ``region`` falls through this allow-list and never fuses.
#: ``speculative_ok`` (docs/SERVING.md speculative counts) never changes
#: a successful result, so carrying it keeps a query fusable.
_FUSABLE_KEYS = frozenset(
    ("op", "name", "schema", "ecql", "auths", "exact", "speculative_ok",
     "bbox", "width", "height", "weight", "level", "stat",
     # join_count parameters (repeat fusion only — docs/JOIN.md)
     "right", "predicate", "distance", "dx", "dy", "right_ecql")
    + _UNFUSABLE_HINTS
)

_MISS = object()


def _auths_key(opts: Dict[str, Any]):
    a = opts.get("auths")
    return None if a is None else tuple(a)


def _structural_key(ds, schema: str, ecql: str) -> Optional[tuple]:
    """The query's structural-template key (filter/template.py), or None
    when it has no batchable viewport slot. Memoized per (schema, ecql)
    on the dataset — this runs on the transport thread, before queueing,
    so the parse must be paid at most once per distinct query text. The
    memo is dropped with the plan cache on schema lifecycle changes."""
    if ds is None or not config.SERVING_FUSION_DISTINCT.to_bool():
        return None
    cache = ds.__dict__.setdefault("_template_key_cache", {})
    ck = (schema, ecql)
    hit = cache.get(ck, _MISS)
    if hit is not _MISS:
        return hit
    out = None
    try:
        from geomesa_tpu.filter import parse_ecql
        from geomesa_tpu.filter import template as ftpl

        st = ds._store(schema)
        t = ftpl.split_literals(parse_ecql(ecql), st.ft)
        out = t.key if t is not None else None
    except Exception:
        out = None
    if len(cache) >= 1024:
        cache.clear()
    cache[ck] = out
    return out


def fuse_key(op: str, schema: str, opts: Dict[str, Any],
             ds=None) -> Optional[tuple]:
    """The fusion-compatibility key for one request, or None when the
    request is ineligible. Equal keys => the members share a compiled
    kernel (the same inputs determine the executor's version-stable
    token) and may coalesce into one device pass.

    With ``ds`` given and ``geomesa.serving.fusion.distinct`` on, a
    count / density / stats request whose ECQL carries batchable viewport
    literals keys on its STRUCTURAL template instead of the literal text
    (docs/SERVING.md "Query-axis batching"): requests differing only in
    BBOX / temporal literals (and, for density, the grid bbox) share a
    key and ride one batched device pass, each member's literals carried
    as payload and de-interleaved bit-identically."""
    if any(opts.get(k) for k in _UNFUSABLE_HINTS):
        return None
    if any(v is not None and v is not False and k not in _FUSABLE_KEYS
           for k, v in opts.items()):
        return None
    ecql = opts.get("ecql", "INCLUDE")
    auths = _auths_key(opts)
    if op == "count":
        exact = bool(opts.get("exact", True))
        skel = _structural_key(ds, schema, ecql) if exact else None
        return ("count", schema,
                ("skel",) + skel if skel is not None else ecql,
                auths, exact)
    if op == "density":
        bbox = opts.get("bbox")
        # distinct-literal density batches only unweighted grids: their
        # cells are exact integer counts, so the batched pass is bit-
        # identical to ANY serial layout (weighted grids stay on the
        # literal-identical repeat path)
        skel = (_structural_key(ds, schema, ecql)
                if opts.get("weight") is None else None)
        if skel is not None:
            # the grid bbox becomes member payload, like the ecql literals
            return ("density", schema, ("skel",) + skel, auths, None,
                    int(opts.get("width", 256)),
                    int(opts.get("height", 256)), None)
        return ("density", schema, ecql, auths,
                tuple(bbox) if bbox is not None else None,
                int(opts.get("width", 256)), int(opts.get("height", 256)),
                opts.get("weight"))
    if op == "density_curve":
        # bbox deliberately NOT in the key: different crops stack into one
        # pass (the tile-fusion path). With a batchable structural
        # template, requests differing only in viewport LITERALS also
        # share the key (docs/SERVING.md "Query-axis batching", curve
        # extension): the group detects distinct members at execution and
        # rides Executor.density_curve_filter_batch, each member's
        # literals AND crop window as kernel data.
        skel = _structural_key(ds, schema, ecql)
        return ("density_curve", schema,
                ("skel",) + skel if skel is not None else ecql,
                auths, int(opts.get("level", 9)), opts.get("weight"))
    if op == "join_count":
        # repeat fusion only: one co-partitioned join serves every
        # identical concurrent request (docs/JOIN.md)
        return ("join_count", schema, opts.get("right"),
                opts.get("predicate"), opts.get("distance"),
                opts.get("dx"), opts.get("dy"), ecql,
                opts.get("right_ecql", "INCLUDE"), auths)
    if op == "stats":
        skel = _structural_key(ds, schema, ecql)
        return ("stats", schema,
                ("skel",) + skel if skel is not None else ecql,
                auths, opts.get("stat"))
    return None


def subscription_key(spec) -> tuple:
    """The standing-subscriber fusion identity (docs/STANDING.md,
    docs/SERVING.md "Subscriber fusion"): subscribers whose specs share
    this key ride ONE standing group — one result, one update ring, one
    delta evaluation per ingest batch, however many watchers. The same
    allow-list philosophy as :func:`fuse_key`: every result-affecting
    spec field is IN the key (viewport bbox as exact float reprs, region
    WKT text, grid dims, pyramid depth, stat spec), so two subscriptions
    fuse iff their results are provably byte-identical forever."""
    return (
        "standing", spec.schema, spec.aggregate,
        tuple(repr(float(v)) for v in spec.bbox),
        spec.region,
        int(spec.width), int(spec.height), int(spec.levels),
        spec.stat_spec,
    )


def make_spec(ds, op: str, schema: str,
              opts: Dict[str, Any]) -> Optional[FuseSpec]:
    """A :class:`FuseSpec` whose batch executor returns RAW results (ints,
    grids, stats). The sidecar wraps these into wire frames; local callers
    (bench, tests) consume them directly."""
    key = fuse_key(op, schema, opts, ds=ds)
    if key is None:
        return None
    return FuseSpec(
        key=("local", op, schema) + key,
        payload=dict(opts),
        batch=lambda tickets: run_batch(ds, op, schema, tickets),
        schema=schema,
    )


def _query_from(opts: Dict[str, Any]):
    from geomesa_tpu.api.dataset import Query

    return Query(ecql=opts.get("ecql", "INCLUDE"), auths=opts.get("auths"))


def _member_span(t: Ticket, op: str, batch_n: int) -> None:
    """A fused non-primary member's OWN root span, joined to the member's
    client trace id when one rode the Flight header — fused queries stay
    individually traceable. Must be called with NO trace active on the
    thread (so the span opens a fresh root under the member's id, not a
    child of the primary's tree)."""
    with tracing.start(f"fused.{op}.member", trace_id=t.trace_id,
                       force=t.trace_id is not None) as sp:
        sp.set(fused=True, fused_batch=batch_n,
               queue_wait_ms=round(t.wait_s * 1e3, 3))


def _member_record(ds, schema: str, t: Ticket, op: str, ecql: str,
                   hits: int, batch_n: int, primary_tid: Optional[str],
                   extra_hints: Optional[Dict[str, Any]] = None) -> None:
    """Per-member bookkeeping for a fused non-primary member: its own root
    span plus its OWN audit event — fused queries stay individually
    attributable."""
    _member_span(t, op, batch_n)
    hints: Dict[str, Any] = {
        "op": op, "fused": True, "fused_batch": batch_n, "user": t.user,
    }
    if t.trace_id is not None:
        hints["trace_id"] = t.trace_id
    if primary_tid is not None and primary_tid != t.trace_id:
        hints["fused_primary"] = primary_tid
    if extra_hints:
        hints.update(extra_hints)
    ds.audit.record(schema, ecql, hints, 0.0, 0.0, hits, user=t.user)


def _placement_attrs(primary: Ticket) -> Dict[str, Any]:
    """The scheduler's pool-aware placement decision for this group (when
    one was made), surfaced as span attributes (docs/SERVING.md §5c)."""
    p = getattr(primary.fuse, "placement", None)
    if not p:
        return {}
    return {f"placement_{k}": v for k, v in p.items()}


def run_batch(ds, op: str, schema: str, tickets: List[Ticket]) -> List[Any]:
    """Execute one fused group, returning one raw result per ticket (in
    order). The primary member runs the full audited public path under its
    own trace; non-primary members record their spans/audits via
    :func:`_member_record`.

    Members may be *repeats* (identical payload: one execution, shared
    result) or *distinct viewports* of one structural template (the
    query-axis megakernel: one batched device pass, per-member literals
    as kernel data — docs/SERVING.md "Query-axis batching")."""
    primary = tickets[0]
    opts = primary.fuse.payload
    ecql = opts.get("ecql", "INCLUDE")
    n_batch = len(tickets)

    if op == "density_curve":
        return _density_curve_batch(ds, schema, tickets)

    if n_batch > 1 and op in ("count", "density", "stats"):
        distinct = any(
            t.fuse.payload.get("ecql", "INCLUDE") != ecql
            for t in tickets[1:]
        )
        if op == "density" and not distinct:
            bb0 = opts.get("bbox")
            distinct = any(
                t.fuse.payload.get("bbox") != bb0 for t in tickets[1:]
            )
        if distinct:
            return _run_distinct(ds, op, schema, tickets)

    # repeat fusion: one execution, shared result (bit-identical by
    # construction — it IS the serial execution, run once)
    with tracing.start(f"fused.{op}", trace_id=primary.trace_id,
                       force=primary.trace_id is not None,
                       fused_batch=n_batch, **_placement_attrs(primary)):
        q = _query_from(opts)
        if op == "count":
            result = ds.count(schema, q, exact=bool(opts.get("exact", True)))
            hits = int(result)
        elif op == "join_count":
            from geomesa_tpu.api.dataset import Query as _Query

            result = ds.join_count(
                schema, opts["right"], predicate=opts["predicate"],
                distance=opts.get("distance"), dx=opts.get("dx"),
                dy=opts.get("dy"), left_query=q,
                # the request's auths must filter BOTH sides' scans
                right_query=_Query(
                    ecql=opts.get("right_ecql", "INCLUDE"),
                    auths=opts.get("auths"),
                ),
            )
            hits = int(result)
        elif op == "density":
            import numpy as np

            result = ds.density(
                schema, q, bbox=opts.get("bbox"),
                width=int(opts.get("width", 256)),
                height=int(opts.get("height", 256)),
                weight=opts.get("weight"),
            )
            hits = int(np.count_nonzero(result))
        elif op == "stats":
            result = ds.stats(schema, opts["stat"], q)
            hits = 0
        else:
            raise ValueError(f"unfusable op {op!r}")
    # each member gets its OWN result object: a caller mutating its grid
    # in place (normalization etc.) must never corrupt another member's —
    # fusion can change latency, never results. Per-member bookkeeping
    # failures (audit path unwritable, say) stay PER-member: the batch
    # already executed, so raising here would trigger the serial fallback
    # and duplicate the device pass + the primary's audit event.
    out: List[Any] = [result]
    for t in tickets[1:]:
        try:
            _member_record(ds, schema, t, op, ecql, hits, n_batch,
                           primary.trace_id)
            out.append(_own_copy(result))
        except Exception as e:
            out.append(FusedMemberError(e))
    return out


def _query_member(ds, opts: Dict[str, Any]):
    from geomesa_tpu.api.dataset import Query

    return Query(ecql=opts.get("ecql", "INCLUDE"), auths=opts.get("auths"))


def _run_distinct(ds, op: str, schema: str,
                  tickets: List[Ticket]) -> List[Any]:
    """Distinct-viewport fusion: one batched device pass serving every
    member's OWN literals (docs/SERVING.md "Query-axis batching"). The
    dataset's ``*_batch`` entry writes one audit event per member; member
    spans open here. When the batch is ineligible (template mismatch a
    key collision can't cause, host-path members, descriptive stats, f32
    band survivors) every member runs query-at-a-time under its own
    trace — fusion changes latency, never results."""
    primary = tickets[0]
    opts = primary.fuse.payload
    n_batch = len(tickets)
    queries = [_query_member(ds, t.fuse.payload) for t in tickets]
    meta = [{"trace_id": t.trace_id, "user": t.user} for t in tickets]
    with tracing.start(f"fused.{op}.distinct", trace_id=primary.trace_id,
                       force=primary.trace_id is not None,
                       fused_batch=n_batch, distinct=True,
                       **_placement_attrs(primary)):
        if op == "count":
            out = ds.count_batch(
                schema, queries, exact=bool(opts.get("exact", True)),
                members=meta,
            )
        elif op == "density":
            out = ds.density_batch(
                schema, queries,
                bboxes=[t.fuse.payload.get("bbox") for t in tickets],
                width=int(opts.get("width", 256)),
                height=int(opts.get("height", 256)),
                weight=None, members=meta,
            )
        else:
            out = ds.stats_batch(schema, opts["stat"], queries,
                                 members=meta)
    if out is None:
        # ineligible: query-at-a-time under each member's own trace —
        # every member keeps its full serial path (audit included)
        out = []
        for t, q in zip(tickets, queries):
            try:
                # each member's serial run must audit under ITS user, not
                # the dispatch thread's (= the primary's) — the
                # individually-attributable contract
                with ds.serving.member_user(t.user), \
                        tracing.start(f"fused.{op}.serial",
                                      trace_id=t.trace_id,
                                      force=t.trace_id is not None):
                    if op == "count":
                        r = ds.count(schema, q,
                                     exact=bool(opts.get("exact", True)))
                    elif op == "density":
                        r = ds.density(
                            schema, q, bbox=t.fuse.payload.get("bbox"),
                            width=int(opts.get("width", 256)),
                            height=int(opts.get("height", 256)),
                        )
                    else:
                        r = ds.stats(schema, opts["stat"], q)
                out.append(r)
            except Exception as e:
                out.append(FusedMemberError(e))
        return out
    # member spans for non-primary members (audits were written by the
    # batch entry); span failures stay per-member — the batch already ran
    for i, t in enumerate(tickets[1:], start=1):
        try:
            _member_span(t, op, n_batch)
        except Exception as e:
            out[i] = FusedMemberError(e)
    return out


def _own_copy(result):
    """An independently-mutable copy of a fused result (ints pass
    through; grids copy; stats deep-copy)."""
    import numpy as np

    if isinstance(result, np.ndarray):
        return result.copy()
    if isinstance(result, (int, float, str, bytes, bool)) or result is None:
        return result
    import copy

    try:
        return copy.deepcopy(result)
    except Exception:  # pragma: no cover — exotic result: share read-only
        return result


def _density_curve_batch(ds, schema: str, tickets: List[Ticket]) -> List[Any]:
    """Tile fusion: one device pass over stacked per-member crops. With
    the structural curve key (docs/SERVING.md "Query-axis batching"),
    members whose ECQL texts DIFFER (same template, distinct viewport
    literals) ride the distinct-filter curve megakernel instead; when
    that batch is ineligible every member runs serially under its own
    trace — fusion changes latency, never results."""
    primary = tickets[0]
    opts = primary.fuse.payload
    level = int(opts.get("level", 9))
    weight = opts.get("weight")
    ecql0 = opts.get("ecql", "INCLUDE")
    members = [
        {"bbox": t.fuse.payload.get("bbox"), "trace_id": t.trace_id,
         "user": t.user}
        for t in tickets
    ]
    distinct = any(
        t.fuse.payload.get("ecql", "INCLUDE") != ecql0
        for t in tickets[1:]
    )
    if distinct:
        return _density_curve_distinct(ds, schema, tickets, level, weight,
                                       members)
    with tracing.start("fused.density_curve", trace_id=primary.trace_id,
                       force=primary.trace_id is not None,
                       fused_batch=len(tickets)):
        # per-member audit events are written by density_curve_batch (it
        # holds the plan + per-member hit counts); only the member spans
        # are opened here, after the primary trace closes
        out = ds.density_curve_batch(
            schema, _query_from(opts), level=level,
            bboxes=[m["bbox"] for m in members], weight=weight,
            members=members,
        )
    # span failures stay per-member (see run_batch): the batch already ran
    for i, t in enumerate(tickets[1:], start=1):
        try:
            _member_span(t, "density_curve", len(tickets))
        except Exception as e:
            out[i] = FusedMemberError(e)
    return out


def _density_curve_distinct(ds, schema: str, tickets: List[Ticket],
                            level: int, weight, members) -> List[Any]:
    """Distinct-filter curve fusion: each member's OWN viewport literals
    and crop window in one batched device pass
    (``GeoDataset.density_curve_filter_batch``); serial per-member
    fallback when ineligible."""
    primary = tickets[0]
    queries = [_query_member(ds, t.fuse.payload) for t in tickets]
    meta = [{"trace_id": t.trace_id, "user": t.user} for t in tickets]
    with tracing.start("fused.density_curve.distinct",
                       trace_id=primary.trace_id,
                       force=primary.trace_id is not None,
                       fused_batch=len(tickets), distinct=True,
                       **_placement_attrs(primary)):
        out = ds.density_curve_filter_batch(
            schema, queries, level=level,
            bboxes=[m["bbox"] for m in members], weight=weight,
            members=meta,
        )
    if out is None:
        out = []
        for t, q in zip(tickets, queries):
            try:
                with ds.serving.member_user(t.user), \
                        tracing.start("fused.density_curve.serial",
                                      trace_id=t.trace_id,
                                      force=t.trace_id is not None):
                    out.append(ds.density_curve(
                        schema, q, level=level,
                        bbox=t.fuse.payload.get("bbox"), weight=weight,
                    ))
            except Exception as e:
                out.append(FusedMemberError(e))
        return out
    for i, t in enumerate(tickets[1:], start=1):
        try:
            _member_span(t, "density_curve", len(tickets))
        except Exception as e:
            out[i] = FusedMemberError(e)
    return out
