"""Columnar feature encoding.

The TPU replacement for the reference's Kryo lazy row serialization
(KryoFeatureSerializer / KryoBufferSimpleFeature, SURVEY.md §2.2): features
are struct-of-arrays. Encoded column names:

* scalar attribute ``a``     -> column ``a`` (int32/int64/float32/float64/bool)
* string attribute ``s``     -> column ``s`` = int32 dictionary codes (-1 = null)
* date attribute ``d``       -> column ``d`` = int64 epoch-ms
* point geometry ``g``       -> columns ``g__x``, ``g__y`` (float64)
* non-point geometry ``g``   -> ``g__xmin/__ymin/__xmax/__ymax`` (float64 bbox)
                                plus host-side object column ``g__wkt``
* feature id                 -> host-side fixed-width bytes column ``__fid__``
                                ('S'; 'U' fallback for non-ASCII ids)

Device uploads additionally carry normalized/fixed-point views and curve keys
(computed by the index layer, see geomesa_tpu/index/).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.utils import geometry as geo


class DictionaryEncoder:
    """Growable string -> int32 code dictionary (Arrow-style).

    The device never sees strings: equality/IN/LIKE predicates are resolved to
    code comparisons at plan time (the analog of the reference's Arrow
    dictionary encoding, geomesa-arrow/.../ArrowDictionary).
    """

    def __init__(self, values: Optional[List[str]] = None):
        self.values: List[str] = list(values or [])
        self._index: Dict[str, int] = {v: i for i, v in enumerate(self.values)}

    def __len__(self):
        return len(self.values)

    def encode(self, vals: Sequence[Optional[str]]) -> np.ndarray:
        out = np.empty(len(vals), dtype=np.int32)
        idx = self._index
        values = self.values
        for i, v in enumerate(vals):
            if v is None:
                out[i] = -1
                continue
            v = str(v)
            code = idx.get(v)
            if code is None:
                code = len(values)
                values.append(v)
                idx[v] = code
            out[i] = code
        return out

    def code_of(self, v: str) -> int:
        """Lookup without growing; -2 if absent (matches nothing, incl. nulls)."""
        return self._index.get(str(v), -2)

    def decode(self, codes: np.ndarray) -> List[Optional[str]]:
        return [None if c < 0 else self.values[c] for c in codes.tolist()]

    def to_list(self) -> List[str]:
        return list(self.values)


@dataclass
class ColumnBatch:
    """A batch of features as columns."""

    columns: Dict[str, np.ndarray]
    n: int

    def __getitem__(self, k):
        return self.columns[k]

    def __contains__(self, k):
        return k in self.columns

    def select(self, mask: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(
            {k: v[mask] for k, v in self.columns.items()}, int(np.sum(mask))
        )

    @staticmethod
    def concat(batches: List["ColumnBatch"],
               fills: Optional[Dict[str, Any]] = None) -> "ColumnBatch":
        """Concatenate batches, UNIONING their column sets: a column missing
        from some batch null-fills that batch's rows. Intersecting to the
        first batch's columns silently dropped late-added columns such as
        ``__vis__`` on reload.

        ``fills`` maps column name -> fill value; derive it from the schema
        with :func:`schema_null_fills` when one is at hand (a dtype alone
        cannot tell a dictionary-coded string, whose null is -1, from a
        plain int, whose null convention is 0). Without a hint: float ->
        NaN, object/str -> None, bool -> False, int32 -> -1 (coded-string
        assumption — code 0 would alias the first REAL dictionary value),
        int64 -> 0, and ``__vis__`` -> 0 = the empty visibility, so
        pre-visibility chunks reload as visible-to-all."""
        if not batches:
            return ColumnBatch({}, 0)
        if len(batches) == 1:  # bulk loads: no copy
            return batches[0]
        keys = dict.fromkeys(k for b in batches for k in b.columns)

        def _fill(name: str, n: int, dtype) -> np.ndarray:
            if fills is not None and name in fills:
                return np.full(n, fills[name], dtype)
            if dtype.kind == "f":
                return np.full(n, np.nan, dtype)
            if dtype.kind in "OUS":
                return np.full(n, None, object)
            if dtype == np.int32 and name != "__vis__":
                return np.full(n, -1, dtype)
            return np.zeros(n, dtype)

        out = {}
        for k in keys:
            dtype = next(
                b.columns[k].dtype for b in batches if k in b.columns
            )
            out[k] = np.concatenate([
                b.columns[k] if k in b.columns else _fill(k, b.n, dtype)
                for b in batches
            ])
        return ColumnBatch(out, sum(b.n for b in batches))


def schema_null_fills(ft: FeatureType) -> Dict[str, Any]:
    """Per-column null-fill values for :meth:`ColumnBatch.concat`, matching
    ``null_columns``' convention: string code -1, int/long/date 0, bool
    False (floats and derived geometry columns fall through to concat's NaN
    default); ``__vis__`` fills the empty-visibility code 0."""
    fills: Dict[str, Any] = {"__vis__": 0}
    for a in ft.attributes:
        if a.is_geom:
            continue
        if a.type == "string":
            fills[a.name] = -1
        elif a.type in ("int32", "int64", "date"):
            fills[a.name] = 0
        elif a.type == "bool":
            fills[a.name] = False
    return fills


def _to_epoch_ms(vals) -> np.ndarray:
    a = np.asarray(vals)
    if a.dtype.kind == "M":  # datetime64
        if a.dtype == np.dtype("datetime64[ms]"):
            return a.view(np.int64)  # same representation, no copy
        return a.astype("datetime64[ms]").astype(np.int64)
    if a.dtype.kind in "iuf":
        return a.astype(np.int64)
    # strings / datetimes / objects -> via numpy datetime parsing
    return np.array(
        [np.datetime64(v, "ms").astype(np.int64) for v in a], dtype=np.int64
    )


def encode_batch(
    ft: FeatureType,
    data: Dict[str, Any],
    dicts: Dict[str, DictionaryEncoder],
    fids: Optional[Sequence[str]] = None,
) -> ColumnBatch:
    """Encode raw attribute arrays into the columnar layout.

    ``data`` maps attribute name -> array-like. Geometry attributes accept:
    separate ``<name>__x``/``<name>__y`` arrays in ``data``, an array of
    (x, y) pairs, Geometry objects, or WKT strings.
    """
    cols: Dict[str, np.ndarray] = {}
    n = None

    def set_n(m):
        nonlocal n
        if n is None:
            n = m
        elif n != m:
            raise ValueError(f"ragged batch: {m} != {n}")

    for a in ft.attributes:
        if a.is_geom:
            xk, yk = a.name + "__x", a.name + "__y"
            if xk in data:
                xs = np.asarray(data[xk], np.float64)
                ys = np.asarray(data[yk], np.float64)
                set_n(len(xs))
                cols[xk], cols[yk] = xs, ys
                continue
            vals = data.get(a.name)
            if vals is None:
                raise KeyError(f"missing geometry attribute {a.name!r}")
            vals = list(vals)
            set_n(len(vals))
            if a.is_point:
                xs = np.empty(len(vals), np.float64)
                ys = np.empty(len(vals), np.float64)
                for i, v in enumerate(vals):
                    if isinstance(v, geo.Point):
                        xs[i], ys[i] = v.x, v.y
                    elif isinstance(v, str):
                        p = geo.parse_wkt(v)
                        xs[i], ys[i] = p.x, p.y
                    else:
                        xs[i], ys[i] = float(v[0]), float(v[1])
                cols[xk], cols[yk] = xs, ys
            else:
                geoms = [
                    v if isinstance(v, geo.Geometry) else geo.parse_wkt(str(v))
                    for v in vals
                ]
                b = np.asarray([g.bounds() for g in geoms], np.float64)
                cols[a.name + "__xmin"] = b[:, 0]
                cols[a.name + "__ymin"] = b[:, 1]
                cols[a.name + "__xmax"] = b[:, 2]
                cols[a.name + "__ymax"] = b[:, 3]
                # centroid-ish reference point for distance/knn ops
                cols[xk] = (b[:, 0] + b[:, 2]) / 2
                cols[yk] = (b[:, 1] + b[:, 3]) / 2
                cols[a.name + "__wkt"] = np.array([g.wkt() for g in geoms], dtype=object)
        elif a.type == "date":
            vals = data.get(a.name)
            if vals is None:
                raise KeyError(f"missing date attribute {a.name!r}")
            enc = _to_epoch_ms(vals)
            set_n(len(enc))
            cols[a.name] = enc
            # Device time representation: (bin, scaled offset) int32 pair —
            # epoch-ms int64 can't ride the TPU int32 fast path (SURVEY §7
            # hard part (g)); temporal predicates compile to pair compares.
            from geomesa_tpu.curves.binned_time import BinnedTime

            bt = BinnedTime(ft.time_period)
            b, off = bt.to_scaled(enc)
            cols[a.name + "__bin"] = b
            cols[a.name + "__off"] = off
        elif a.type == "string":
            vals = data.get(a.name)
            if vals is None:
                raise KeyError(f"missing attribute {a.name!r}")
            vals = list(vals)
            set_n(len(vals))
            d = dicts.setdefault(a.name, DictionaryEncoder())
            cols[a.name] = d.encode(vals)
        elif a.type == "json":
            # stored-JSON attribute (reference kryo-json): raw document
            # text in a host-only object column; jsonPath() predicates
            # parse on demand with a bounded cache
            vals = data.get(a.name)
            if vals is None:
                raise KeyError(f"missing attribute {a.name!r}")
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                out[i] = (
                    None if v is None
                    else v if isinstance(v, str) else json.dumps(v)
                )
            set_n(len(out))
            cols[a.name] = out
        elif a.type == "bool":
            vals = np.asarray(data[a.name]).astype(bool)
            set_n(len(vals))
            cols[a.name] = vals
        else:
            vals = np.asarray(data[a.name]).astype(np.dtype(a.type))
            set_n(len(vals))
            cols[a.name] = vals

    if n is None:
        raise ValueError("empty batch")
    cols["__fid__"] = encode_fids(fids, n)
    return ColumnBatch(cols, n)


def encode_fids(fids, n: int) -> np.ndarray:
    """Feature ids as a fixed-width BYTES ('S') numpy column.

    Object arrays of 10^8+ python strings dominate both ingest time and
    host memory at bulk-load scale; 'S' is one contiguous buffer at 1
    byte/char (vs 4 for 'U' — 128 bytes/row of fids at U32 was the #2 item
    in the round-2 1B-point memory audit). Non-ASCII ids fall back to 'U'.
    Auto-generated ids are random 128-bit hex (Z3FeatureIdGenerator-style
    UUIDs), produced in one urandom+hex pass instead of n uuid4() calls."""
    if fids is None:
        import os as _os

        hexs = _os.urandom(16 * n).hex()
        return np.frombuffer(hexs.encode("ascii"), dtype="S32")
    a = np.asarray(fids)
    if len(a) != n:
        raise ValueError(f"{len(a)} fids for {n} rows")
    if a.dtype.kind == "S":
        return a
    if a.dtype.kind != "U":  # object / numeric: stringify (vectorized in C)
        a = a.astype("U")
    return _u_to_s(a)


def _u_to_s(a: np.ndarray) -> np.ndarray:
    """Fast 'U' -> 'S' for ASCII content: numpy's own U->S cast encodes
    per element (~6s for 20M ids). The native kernel fuses the ASCII
    check and the uint8 narrowing into ONE parallel pass; the numpy
    fallback does the same in separate SIMD passes."""
    w = a.dtype.itemsize // 4
    if w == 0:
        return a.astype("S1")
    cp = np.ascontiguousarray(a).view(np.uint32).reshape(len(a), w)
    from geomesa_tpu import native

    out = native.u32_to_s(cp)
    if out is not None:
        return out.view(f"S{w}").reshape(len(a))
    if not (cp < 128).all():
        return a  # rare non-ASCII ids keep the unicode layout
    return cp.astype(np.uint8).view(f"S{w}").reshape(len(a))


def fid_strs(col: np.ndarray) -> np.ndarray:
    """Fid column -> unicode ('U') view for exports/dedupe/user output.
    Iterating / ``tolist()`` on the result yields ``str``, never bytes.
    Mirror-image SIMD widening of :func:`_u_to_s` — numpy's own S->U cast
    encodes per element, which dominates bulk export paths."""
    a = np.asarray(col)
    if a.dtype.kind != "S":
        return a if a.dtype.kind == "U" else a.astype("U")
    w = a.dtype.itemsize
    if w == 0:
        return a.astype("U1")
    by = np.ascontiguousarray(a).view(np.uint8).reshape(len(a), w)
    from geomesa_tpu import native

    out = native.s_to_u32(by)
    if out is not None:
        return out.view(f"U{w}").reshape(len(a))
    if not (by < 128).all():  # externally-supplied UTF-8 bytes: decode right
        return np.array([s.decode("utf-8", "replace") for s in a.tolist()])
    return by.astype(np.uint32).view(f"U{w}").reshape(len(a))


def decode_batch(
    ft: FeatureType, batch: ColumnBatch, dicts: Dict[str, DictionaryEncoder]
) -> Dict[str, Any]:
    """Columns -> user-facing values (strings decoded, dates as datetime64).

    Attributes projected out of the batch (Query.properties) are skipped."""
    out: Dict[str, Any] = {"__fid__": fid_strs(batch.columns["__fid__"]).tolist()}
    for a in ft.attributes:
        if not a.is_geom and a.name not in batch.columns:
            continue
        if a.is_geom:
            if a.name + "__wkt" in batch.columns:
                out[a.name] = batch.columns[a.name + "__wkt"].tolist()
            elif a.name + "__x" in batch.columns:
                xs = batch.columns[a.name + "__x"]
                ys = batch.columns[a.name + "__y"]
                out[a.name] = list(zip(xs.tolist(), ys.tolist()))
        elif a.type == "date":
            out[a.name] = batch.columns[a.name].astype("datetime64[ms]")
        elif a.type == "string":
            out[a.name] = dicts[a.name].decode(batch.columns[a.name])
        else:
            out[a.name] = batch.columns[a.name]
    return out


def null_columns(ft, attrs, n: int, dicts) -> dict:
    """Columns for ``attrs`` holding ``n`` nulls in this layout's null
    representation (string -> code -1, float -> NaN, int/long -> 0,
    bool -> False, date -> epoch 0 + derived bins; no validity bitmap in
    the fixed-width columnar model). Shared by ``update_schema``'s
    in-place column append and the partition snapshot's lazy schema
    upgrade (GeoMesaDataStore.scala:288-336 parity)."""
    from geomesa_tpu.curves.binned_time import BinnedTime

    cols: dict = {}
    for a in attrs:
        if a.type == "string":
            cols[a.name] = np.full(n, -1, np.int32)
            dicts.setdefault(a.name, DictionaryEncoder())
        elif a.type == "date":
            cols[a.name] = np.zeros(n, np.int64)
            bt = BinnedTime(ft.time_period)
            b, off = bt.to_scaled(cols[a.name])
            cols[a.name + "__bin"] = b
            cols[a.name + "__off"] = off
        elif a.type == "bool":
            cols[a.name] = np.zeros(n, bool)
        elif a.type == "json":
            cols[a.name] = np.full(n, None, dtype=object)
        elif a.type in ("float32", "float64"):
            cols[a.name] = np.full(n, np.nan, np.dtype(a.type))
        else:
            cols[a.name] = np.zeros(n, np.dtype(a.type))
    return cols
