from geomesa_tpu.schema.feature_type import FeatureType, AttributeSpec  # noqa: F401
from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder  # noqa: F401
